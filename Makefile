# Round-end gate and developer entry points.
#
# `make check` is the <5-minute gate to run before every milestone commit:
# fast test subset (compile-heavy tests are marked `slow`) plus a backend
# compile smoke that jits every kernel and its gradient on the attached
# backend (TPU when present) — interpret-mode tests cannot catch Pallas
# tiling legality, so the smoke compiles for real.

PYTHON ?= python

.PHONY: check test slow native bench autotune autotune-quick bench-actor bench-async bench-autotune bench-ckpt bench-dispatch bench-fleet bench-obs bench-paging bench-router bench-precision bench-replay bench-reshard bench-roofline bench-serve bench-serve-overload actor-soak crash-soak fleet-soak fleet-soak-autoscale obs-demo lint perf-gate serve-chaos serve-soak shard-audit clean

check: native lint
	$(PYTHON) -m pytest tests/ -q -m "not slow" -x
	$(PYTHON) tools/smoke_compile.py
	$(PYTHON) tools/obs_demo.py
	$(PYTHON) tools/serve_chaos.py --injections 2
	$(PYTHON) tools/actor_soak.py --kills 2 --actors 2 --quick --no-scale
	$(PYTHON) tools/fleet_soak.py --quick
	$(PYTHON) tools/autotune.py --quick --out /tmp/tuned_profile_quick.json --json
	$(PYTHON) tools/shard_audit.py
	$(PYTHON) tools/perf_gate.py

test: native
	$(PYTHON) -m pytest tests/ -q

slow: native
	$(PYTHON) -m pytest tests/ -q -m slow

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# The dispatch-floor ladder alone (megachunk K in {1, 8, 64}): the lever
# behind runtime.megachunk_factor, runnable on CPU in ~a minute.
bench-dispatch:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_dispatch_floor(), indent=2))"

# The host-offload pipeline alone (runtime.async_pipeline off vs on at
# K in {1, 8}): inter-dispatch gap p50/p99 from the obs trace's dispatch
# spans plus steps/s — the async-readback lever, recorded in BASELINE.md
# "Host-offload pipeline". Runnable on CPU in ~a minute.
bench-async:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_async_pipeline(), indent=2))"

# Telemetry overhead alone (obs.enabled off vs on at K in {1, 8}, with an
# A/A noise-floor control, plus the direct per-sample cost): the <2%
# budget recorded in BASELINE.md "Telemetry overhead".
bench-obs:
	$(PYTHON) -c "import json, bench; \
	r = bench.bench_obs_overhead(); \
	r['per_sample'] = bench.bench_obs_sample_cost(); \
	print(json.dumps(r, indent=2))"

# Zero-to-summary telemetry demo: short obs-enabled training, artifact
# checks, then the `cli obs` summary of the run dir (also part of check).
obs-demo:
	$(PYTHON) tools/obs_demo.py

# Compile-time shard audit (also part of check): every mesh-config in the
# matrix must compile with zero XLA "Involuntary full rematerialization"
# warnings and collective counts within tools/shard_audit_manifest.json.
# Regenerate the manifest after an intentional change with
# `python tools/shard_audit.py --update`.
shard-audit:
	$(PYTHON) tools/shard_audit.py

# The resharding-constraint row alone (parallel.shard_constraints on vs off
# on the forced-8-device host mesh): steps/s + per-dispatch collective
# bytes, recorded in BASELINE.md "Multichip resharding".
bench-reshard:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_reshard(), indent=2))"

# The checkpoint durability tax alone (checkpoint.fsync on vs off, two
# payload sizes): the numbers behind the fsync-on default, recorded in
# BASELINE.md "Checkpoint fsync".
bench-ckpt:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_ckpt_fsync(), indent=2))"

# Roofline telemetry alone (obs.roofline off vs on, with an A/A control):
# the <2% capture+gauge budget plus the captured per-program FLOPs /
# arithmetic intensity / classification, recorded in BASELINE.md
# "Roofline". Runnable on CPU in ~a minute.
bench-roofline:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_roofline(), indent=2))"

# Precision-policy A/B (precision.mode fp32 vs bf16_mixed): reference-MLP
# steps/s + static costs, flagship episode-PPO compile-only static bytes —
# the measured state-bytes reduction behind bf16_mixed, recorded in
# BASELINE.md "Precision". Runnable on CPU in ~a minute (CPU-framed: bf16
# compute is f32-emulated there; see the bench row's note).
bench-precision:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_precision(), indent=2))"

# Serving tier A/B (continuous batching vs the batch=1 closed-loop
# baseline, rate sweep + saturation + the cache-bound episode row): the
# numbers behind BASELINE.md "Serving" and the serve_qps / serve_p99_ms
# perf-gate series. Runnable on CPU in ~a minute; the full soak is
# `python tools/serve_soak.py` (with --strict for the 3x acceptance).
bench-serve:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_serve(), indent=2))"

# Replay data plane A/B (journaled DQN uniform vs PER steps/s, in-chunk
# sum-tree sample latency, journal bytes/record with rotation on, and the
# seeded PER sample-efficiency race): the numbers behind BASELINE.md
# "Replay data plane" and the replay_* / journal_* perf-gate series.
# Runnable on CPU in a few minutes.
bench-replay:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_replay(), indent=2))"

# Perf-regression gate (also part of check): the newest BENCH_*.json row
# per (metric, backend, precision) series must sit within the tolerance
# band of the prior best — steps/s and MFU both gate (tools/perf_gate.py).
perf-gate:
	$(PYTHON) tools/perf_gate.py

# Serving-tier load soak: thousands of synthetic sessions, open-loop rate
# sweep, continuous batching vs the batch=1 server head-to-head; --strict
# enforces the >=3x-QPS-at-equal-or-better-p99 acceptance (ISSUE 8).
serve-soak:
	$(PYTHON) tools/serve_soak.py --strict

# Serve chaos soak: >= 20 seeded fault injections (dispatch exception,
# slow consumer, corrupt swap candidate, queue flood, deadline burst)
# against the real continuous-batching engine, asserting after every one:
# no wedge (every request reaches a terminal outcome), queue depth stays
# <= serve.max_queue, post-restart sessions match fresh sessions bitwise,
# and shed/restart/breaker counters reconcile exactly with the injected
# counts (tools/serve_chaos.py; the 2-injection quick profile runs in
# tier-1 and in `make check`).
serve-chaos:
	$(PYTHON) tools/serve_chaos.py --injections 20

# Serving-tier overload A/B (bounded+shedding engine vs the unbounded
# PR-8 shape at 8x the engine's own saturation rate): shed rate + p99,
# the numbers behind BASELINE.md "Serve under overload".
bench-serve-overload:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_serve_overload(), indent=2))"

# Tiered-session-paging capacity ladder (bench.py bench_session_paging):
# one engine's device arena vs 1x/8x/64x-slots session populations, warm
# host-RAM tier vs the no-warm cold-re-prefill control — the numbers
# behind BASELINE.md "Session tiers" and the session_capacity_qps /
# warm_unpark_ms perf-gate series.
bench-paging:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_session_paging(), indent=2))"

# Actor/learner disaggregation scaling (distrib/): experience produced
# (summed actor rollouts) and ingested by the live learner at N in
# {1,2,4} actor subprocesses vs the single-process train baseline — the
# numbers behind BASELINE.md "Actor/learner disaggregation" and the
# actor_rows_ingested_per_sec perf-gate series. CPU-framed (host-core
# contention); the TPU row rides the item-4 measurement campaign.
bench-actor:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_actor_scaling(), indent=2))"

# Actor-process kill soak: >= 20 seeded SIGKILL/SIGTERM injections into
# LIVE actor subprocesses under a training learner (N=4 pool), asserting
# after every kill that the learner never restarts, journal CRC /
# high-water invariants hold through the segmented reader, and
# membership/restart counters reconcile exactly — plus the mid-soak
# elastic-membership scale() join and the terminal-failure degrade
# (tools/actor_soak.py; the 2-kill quick profile runs in tier-1 via
# tests/test_actor_soak.py and in `make check`).
actor-soak:
	$(PYTHON) tools/actor_soak.py --kills 20 --actors 4

# Fleet kill-test (tools/fleet_soak.py): one cli fleet tier (router +
# N cli serve --listen engine workers + live learner) under closed-loop
# journaling load; whole-engine SIGKILLs mid-ramp, asserting after every
# kill: router answers immediately, zero client requests fail (migration
# through prefill), restart counters reconcile exactly — then the
# flywheel closes (session journals ingested, tag_best republished,
# every engine hot-swaps) and SIGTERM drains the tier with exit 75. The
# quick 1-kill profile rides tier-1 (tests/test_fleet_soak.py) and
# `make check`.
fleet-soak:
	$(PYTHON) tools/fleet_soak.py --engines 3 --kills 3

# Diurnal autoscale profile (tools/fleet_soak.py --autoscale): one
# cli fleet --autoscale tier through a surge/quiet cycle — membership
# grows to the ceiling under queueing load and retires back to the
# floor in silence, zero restart storms, availability burn < 1, clean
# exit-75 drain. The same profile rides tier-1 via
# tests/test_fleet_soak.py::TestAutoscaleSoak.
fleet-soak-autoscale:
	$(PYTHON) tools/fleet_soak.py --autoscale --ceiling 2

# Fleet scale-out bench (bench.py bench_fleet): single-engine saturation
# vs N=2/4 engines behind the router, wire-framed, each engine pinned to
# its own core slice — the numbers behind BASELINE.md "Fleet serving"
# and the fleet_qps / fleet_p99_ms perf-gate series (acceptance: N=4 >=
# 2.5x single-engine saturation).
bench-fleet:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_fleet(), indent=2))"

# Router-ONLY relay throughput: threaded oracle vs the evloop wire
# path against loopback echo engines (ISSUE 16's >=10x acceptance).
bench-router:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_router_relay(), indent=2))"

# Process-kill chaos soak: >= 20 seeded SIGKILL/SIGTERM injections into real
# training subprocesses (journaled DQN config), each followed by --resume,
# plus the bit-flip walk-back scenario — the crash-safety invariants end to
# end (tools/crash_soak.py; the 2-kill quick profile runs in tier-1 via
# tests/test_crash_soak.py).
crash-soak:
	$(PYTHON) tools/crash_soak.py --kills 20

# Offline autotune sweep (tools/autotune.py): successive-halving search
# over the knob registry's train (megachunk K x pipeline depth) and
# serve (max_batch x batch_timeout_ms x max_queue) grids on short
# measured windows, writing the per-host tuned_profile.json that
# `tuning.profile` loads (explicit config > profile > defaults). Add
# `--spec train,serve,distrib --exhaustive` for the acceptance
# comparison against the full hand-sweep grid (BASELINE.md
# "Self-tuning").
autotune:
	$(PYTHON) tools/autotune.py --out tuned_profile.json

# Seconds-scale profile of the same sweep (tiny grid, short windows) —
# wired into `make check` as the end-to-end gate that the sweep ->
# profile -> load path stays green; writes to /tmp, never the repo.
autotune-quick:
	$(PYTHON) tools/autotune.py --quick --out /tmp/tuned_profile_quick.json --json

# Online-controller A/B (bench.py bench_autotune): a ramping open-loop
# arrival schedule where the static default config misses the target
# p99, static arm vs the ServeController arm holding it (or shedding
# within SLO) — the autotune_controller_p99_ms perf-gate row.
bench-autotune:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_autotune(), indent=2))"

# Static guard: no bare scalar device syncs in the orchestrator hot loop.
lint:
	$(PYTHON) tools/lint_hot_loop.py

clean:
	$(MAKE) -C native clean
