# Round-end gate and developer entry points.
#
# `make check` is the <5-minute gate to run before every milestone commit:
# fast test subset (compile-heavy tests are marked `slow`) plus a backend
# compile smoke that jits every kernel and its gradient on the attached
# backend (TPU when present) — interpret-mode tests cannot catch Pallas
# tiling legality, so the smoke compiles for real.

PYTHON ?= python

.PHONY: check test slow native bench bench-dispatch lint clean

check: native lint
	$(PYTHON) -m pytest tests/ -q -m "not slow" -x
	$(PYTHON) tools/smoke_compile.py

test: native
	$(PYTHON) -m pytest tests/ -q

slow: native
	$(PYTHON) -m pytest tests/ -q -m slow

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# The dispatch-floor ladder alone (megachunk K in {1, 8, 64}): the lever
# behind runtime.megachunk_factor, runnable on CPU in ~a minute.
bench-dispatch:
	$(PYTHON) -c "import json, bench; \
	print(json.dumps(bench.bench_dispatch_floor(), indent=2))"

# Static guard: no bare scalar device syncs in the orchestrator hot loop.
lint:
	$(PYTHON) tools/lint_hot_loop.py

clean:
	$(MAKE) -C native clean
