"""Test harness configuration.

Environment reality (discovered, not assumed): the axon sitecustomize imports
and initializes JAX against the real TPU chip at interpreter startup, so
``JAX_PLATFORMS`` cannot be changed here — the unit suite runs on the TPU
when one is attached (honest coverage: the Pallas kernels execute compiled,
not interpreted). Multi-device sharding tests use an explicit 8-device CPU
mesh instead: the CPU PJRT client initializes lazily, so setting
``xla_force_host_platform_device_count`` here — before anything touches
``jax.devices("cpu")`` — still yields 8 virtual devices (the TPU analogue of
the reference's multi-actor-in-one-JVM TestKit strategy, SURVEY.md §4).

Numeric parity assertions need f32 matmuls; the TPU default is bf16-precision
MXU passes, so matmul precision is pinned to "highest" suite-wide (unit tests
check correctness, not throughput).
"""

import os
import tempfile

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compile cache: TPU compiles dominate suite wall time (~20-40s per
# program shape); repeat runs hit the cache and drop from ~15 min to ~2 min.
# User-scoped path so a shared /tmp doesn't leave the second user locked out.
_cache_dir = os.environ.get(
    "SHARETRADE_COMPILE_CACHE",
    os.path.join(tempfile.gettempdir(), f"jax_compile_cache_{os.getuid()}"))
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


@pytest.fixture
def tmp_journal_path(tmp_path):
    return str(tmp_path / "events.journal")


@pytest.fixture(scope="session")
def cpu_devices():
    devices = jax.devices("cpu")
    assert len(devices) >= 8, (
        "expected 8 virtual CPU devices (xla_force_host_platform_device_count)")
    return devices[:8]


@pytest.fixture(scope="session")
def cpu_mesh(cpu_devices):
    """8-device dp mesh on the virtual CPU client for sharding tests."""
    from jax.sharding import Mesh
    return Mesh(np.array(cpu_devices).reshape(8), ("dp",))
