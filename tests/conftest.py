"""Test harness configuration.

Tests run on a *virtual 8-device CPU mesh* — the TPU analogue of the
reference's multi-actor-in-one-JVM TestKit strategy (SURVEY.md §4: no real
cluster; probes at boundaries + fake devices). Real-TPU behavior is exercised
by bench.py and the driver's graft entry, not by the unit suite.

Env vars must be set before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def tmp_journal_path(tmp_path):
    return str(tmp_path / "events.journal")
