"""Async readback & host-offload pipeline tests (runtime.async_pipeline).

The contract under test: with the pipeline ON, the dispatcher hands every
materialization boundary to a background consumer thread — readback, metric
rows, journaling, fault hooks and snapshots all run off the dispatch
critical path — while the OBSERVABLE run is unchanged: bit-identical
TrainState, metric stream and journal contents vs the synchronous path on a
fixed seed; bounded queue depth (backpressure, HBM in flight); consumer
faults attributed to their true chunk index with the same restart/backoff
sequence and flight-recorder forensics; and drain barriers that keep
``get_avg``/``get_std`` and episode completion exact.
"""

import json
import threading
import time

import jax
import numpy as np
import pytest

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.runtime import Orchestrator, ReplyState, run_end_to_end
from sharetrade_tpu.runtime.pipeline import AsyncPipeline, Boundary

WINDOW = 8
#: 256-step episode: long enough that a K=8 megachunk cruises for the first
#: half and the loop then falls back to K=1 near the completion threshold.
PRICES = np.linspace(10.0, 20.0, 264, dtype=np.float32)
#: Deterministic metric keys (the throughput keys from StepTimer are
#: wall-clock and differ between any two runs, sync or not).
DETERMINISTIC_KEYS = ("loss", "env_steps", "updates", "reward_sum",
                      "portfolio_mean", "portfolio_std")


def fast_cfg(tmp_path, *, megachunk=1, algo="qlearn", async_on=True, tag=""):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 4
    cfg.runtime.chunk_steps = 16
    cfg.runtime.checkpoint_every_updates = 64
    cfg.runtime.checkpoint_dir = str(tmp_path / f"ckpts_{tag or async_on}")
    cfg.runtime.backoff_initial_s = 0.01
    cfg.runtime.backoff_max_s = 0.05
    cfg.runtime.max_restarts = 3
    cfg.runtime.metrics_every_chunks = 1   # per-chunk stream for parity
    cfg.runtime.megachunk_factor = megachunk
    cfg.runtime.async_pipeline = async_on
    return cfg


def _assert_states_identical(a, b):
    for la, lb in zip(jax.tree.leaves(jax.device_get(a)),
                      jax.tree.leaves(jax.device_get(b))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestAsyncSyncParity:
    def test_async_bit_identical_to_sync(self, tmp_path):
        """The acceptance row: the same fixed-seed run with the pipeline on
        produces the SAME TrainState, the SAME ordered per-chunk metric
        stream and the same query answers as the synchronous path — the
        pipeline reorders host work, never device results."""
        runs = {}
        for mode in (False, True):
            orch = run_end_to_end(
                fast_cfg(tmp_path, megachunk=8, async_on=mode,
                         tag=f"par_{mode}"), PRICES)
            assert orch.is_everything_done().state is ReplyState.COMPLETED
            assert orch.restarts == 0
            runs[mode] = orch
        _assert_states_identical(runs[False].train_state,
                                 runs[True].train_state)
        for key in DETERMINISTIC_KEYS:
            s_sync = [v for _, v in runs[False].metrics.series(key)]
            s_async = [v for _, v in runs[True].metrics.series(key)]
            assert s_sync == s_async, f"metric stream diverged for {key!r}"
        assert runs[False].get_avg().value == runs[True].get_avg().value
        assert runs[False].get_std().value == runs[True].get_std().value
        # The run actually went through the pipeline, within its depth.
        stats = runs[True].pipeline_stats
        assert stats["boundaries"] > 0
        assert stats["max_depth_seen"] <= 2

    def test_async_dqn_journal_contents_identical(self, tmp_path):
        """DQN journaling through the consumer thread: record-for-record
        identical journal payloads (same framing, same env_steps stamps,
        same transition bytes) as the synchronous path, and the file is
        fully flushed — group-commit batches included — the moment the run
        reports COMPLETED."""
        from sharetrade_tpu.data.journal import iter_framed_records
        payloads = {}
        for mode in (False, True):
            cfg = fast_cfg(tmp_path, megachunk=4, algo="dqn",
                           async_on=mode, tag=f"dqn_{mode}")
            cfg.runtime.chunk_steps = 8
            cfg.learner.journal_replay = True
            cfg.learner.replay_capacity = 4096
            cfg.learner.replay_batch = 8
            cfg.data.journal_dir = str(tmp_path / f"journal_{mode}")
            prices = np.linspace(10.0, 20.0, 72, dtype=np.float32)
            orch = run_end_to_end(cfg, prices)
            assert orch.is_everything_done().state is ReplyState.COMPLETED
            # Read the file BEFORE stop(): completion itself must have
            # flushed every journaled chunk (the durability point).
            payloads[mode] = [
                p for _off, p in iter_framed_records(
                    f"{cfg.data.journal_dir}/transitions.journal")]
            orch.stop()
        assert payloads[True] == payloads[False]
        assert len(payloads[True]) > 0

    def test_invalid_depth_rejected_at_construction(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        cfg.runtime.pipeline_depth = 0
        with pytest.raises(ConfigError, match="pipeline_depth"):
            Orchestrator(cfg)


class TestBackpressure:
    def test_bounded_queue_blocks_producer(self):
        """Unit contract: a producer faster than the consumer parks in
        ``put`` (backpressure) instead of growing the queue — occupancy
        never exceeds the configured depth, and every boundary is still
        consumed exactly once, in order."""
        release = threading.Event()
        seen = []

        def consume(b):
            release.wait(2.0)
            seen.append(b.base)
            return {"env_steps": float(b.base)}

        pl = AsyncPipeline(2, consume)
        producer_done = threading.Event()

        def produce():
            for i in range(8):
                b = Boundary(i, 1, None, None, 0, 1)
                if not pl.try_put(b):
                    pl.put(b)
            producer_done.set()

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        time.sleep(0.2)
        # Consumer parked: at most depth items queued (+1 in-hand), and the
        # producer is blocked well short of 8.
        assert pl.max_depth_seen <= 2
        assert not producer_done.is_set()
        release.set()
        t.join(5.0)
        assert producer_done.is_set()
        assert pl.drain()
        assert seen == list(range(8))        # strict chunk order
        assert pl.processed == pl.enqueued == 8
        pl.shutdown()

    def test_drain_is_a_strict_barrier(self):
        """drain() returns only after every boundary enqueued at call time
        was consumed — the exactness gate the orchestrator puts in front of
        completion checks and query snapshots."""
        gate = threading.Event()
        done = []

        def consume(b):
            gate.wait(2.0)
            done.append(b.base)
            return {"env_steps": float(b.base)}

        pl = AsyncPipeline(4, consume)
        for i in range(3):
            assert pl.try_put(Boundary(i, 1, None, None, 0, 1))
        assert len(done) == 0
        gate.set()
        assert pl.drain()
        assert done == [0, 1, 2]
        pl.shutdown()

    def test_consumer_fault_surfaces_not_hangs(self):
        """A consumer exception parks the pipeline in the error state: the
        original exception object is preserved, drain() reports failure
        instead of blocking, and later boundaries are discarded."""
        boom = RuntimeError("consumer boom")

        def consume(b):
            raise boom

        pl = AsyncPipeline(2, consume)
        assert pl.try_put(Boundary(0, 1, None, None, 0, 1))
        assert not pl.drain(timeout_s=5.0)
        assert pl.error is boom
        assert pl.attention.is_set()
        # Error state: puts are accepted-and-dropped, nothing deadlocks.
        assert pl.try_put(Boundary(1, 1, None, None, 0, 1))
        pl.shutdown()


class TestConsumerFaultParity:
    def _run_chaos(self, tmp_path, *, async_on):
        cfg = fast_cfg(tmp_path, megachunk=4, async_on=async_on,
                       tag=f"chaos_{async_on}")
        cfg.obs.enabled = True
        cfg.obs.dir = str(tmp_path / f"obs_{async_on}")
        seen, fired = [], []

        def chaos(chunk_idx, metrics):
            seen.append(chunk_idx)
            if chunk_idx == 2 and not fired:
                fired.append(1)
                raise RuntimeError("injected mid-megachunk PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        with open(f"{cfg.obs.dir}/flight_recorder.json") as f:
            bundle = json.load(f)
        orch.stop()
        return {
            "restarts": orch.restarts,
            "seen_head": seen[:4],
            "next_chunk": bundle["context"]["next_chunk"],
            "failing_chunk": bundle.get("failing_chunk"),
            "reason": bundle.get("reason"),
        }

    def test_fault_attribution_matches_sync(self, tmp_path):
        """The acceptance row: a fault injected at an inner megachunk index
        via fault_hook — which under the pipeline RAISES ON THE CONSUMER
        THREAD — produces the same flight-recorder dump (failing chunk,
        next_chunk), the same true-chunk attribution/retry order and the
        same restart count as the synchronous path."""
        sync = self._run_chaos(tmp_path, async_on=False)
        asyn = self._run_chaos(tmp_path, async_on=True)
        assert asyn == sync
        assert asyn["restarts"] == 1
        # Inner chunks 0-1 processed from the stacked rows, the fault fired
        # at TRUE index 2, and the restarted loop retried chunk 2.
        assert asyn["seen_head"] == [0, 1, 2, 2]
        assert asyn["next_chunk"] == 2

    def test_restart_budget_parity_under_pipeline(self, tmp_path):
        """A persistent consumer fault consumes the SAME restart budget as
        the synchronous path and lands in the same FAILED terminal."""
        outcomes = {}
        for mode in (False, True):
            cfg = fast_cfg(tmp_path, async_on=mode, tag=f"budget_{mode}")

            def always_fail(chunk_idx, metrics):
                raise RuntimeError("persistent fault")

            orch = Orchestrator(cfg, fault_hook=always_fail)
            orch.send_training_data(PRICES)
            orch.start_training(background=False)
            outcomes[mode] = (orch.is_everything_done().state,
                              orch.restarts)
            orch.stop()
        assert outcomes[True] == outcomes[False]
        assert outcomes[True] == (ReplyState.NOT_COMPUTED,
                                  fast_cfg(tmp_path).runtime.max_restarts + 1)


class TestDrainBarrier:
    def test_completion_exact_under_async(self, tmp_path):
        """Two episodes, K=8, sampling coarser than the run: the pipeline's
        drain barrier near each episode threshold keeps the completion gate
        exact — the run finishes at EXACTLY episodes x horizon env steps
        with exactly the K=1 chunk count, no fused overshoot."""
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path, megachunk=8, async_on=True, tag="exact")
        cfg.runtime.metrics_every_chunks = 1000
        cfg.runtime.episodes = 2
        events_path = str(tmp_path / "events.jsonl")
        orch = Orchestrator(cfg, event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0
        horizon = len(PRICES) - WINDOW
        done = [json.loads(l) for l in open(events_path)
                if json.loads(l)["kind"] == "training_completed"][0]
        assert done["env_steps"] == 2 * horizon       # exact, no overshoot
        chunks_per_episode = -(-horizon // cfg.runtime.chunk_steps)
        assert done["chunks_timed"] == 2 * chunks_per_episode

    def test_queries_drain_to_final_row(self, tmp_path):
        """get_avg/get_std after (and during) an async run answer from a
        drained snapshot: the final values equal the synchronous path's,
        and a completed run's snapshot is the last chunk's row, not a
        stale in-flight one."""
        orch = run_end_to_end(
            fast_cfg(tmp_path, megachunk=8, async_on=True, tag="query"),
            PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        snap = orch.snapshot()
        assert snap["env_steps"] == len(PRICES) - WINDOW
        avg = orch.get_avg()
        assert avg.ok and np.isfinite(avg.value)
        assert avg.value == snap["portfolio_mean"]


@pytest.mark.slow
class TestAsyncSoak:
    def test_k8_512_chunk_soak_completes_exactly(self, tmp_path):
        """The long variant: 512 tiny chunks through the pipeline at K=8 —
        hours of queue churn compressed into one run; completion must stay
        exact and the queue bounded."""
        cfg = fast_cfg(tmp_path, megachunk=8, async_on=True, tag="soak")
        cfg.runtime.chunk_steps = 4
        cfg.runtime.metrics_every_chunks = 8
        prices = np.linspace(10.0, 20.0, 2056, dtype=np.float32)
        orch = run_end_to_end(cfg, prices)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0
        assert int(orch.train_state.env_steps) == len(prices) - WINDOW
        assert orch.pipeline_stats["max_depth_seen"] <= 2
