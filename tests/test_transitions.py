"""Packed binary transition codec + tail reader (data/transitions.py and the
C++ ``stj_read_tail_transitions`` — same semantics, byte-shared format)."""

import numpy as np
import pytest

from sharetrade_tpu.data.journal import Journal
from sharetrade_tpu.data.native import native_available
from sharetrade_tpu.data.transitions import (
    _python_read_tail,
    append_transitions,
    compact_transitions,
    decode_transitions,
    encode_transitions,
    read_tail_transitions,
)


def _batch(n, obs_dim=5, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, obs_dim)).astype(np.float32),
            rng.integers(0, 3, n).astype(np.int32),
            rng.standard_normal(n).astype(np.float32),
            rng.standard_normal((n, obs_dim)).astype(np.float32))


@pytest.fixture
def jpath(tmp_path):
    return str(tmp_path / "transitions.journal")


class TestCodec:
    def test_roundtrip(self):
        obs, act, rew, nxt = _batch(7)
        payload = encode_transitions(obs, act, rew, nxt, env_steps=42)
        out = decode_transitions(payload)
        assert out is not None
        np.testing.assert_array_equal(out[0], obs)
        np.testing.assert_array_equal(out[1], act)
        np.testing.assert_array_equal(out[2], rew)
        np.testing.assert_array_equal(out[3], nxt)
        assert out[4] == 42

    def test_rejects_non_transition_payloads(self):
        assert decode_transitions(b"") is None
        assert decode_transitions(b'{"type":"transitions"}') is None
        # Truncated body: magic ok, sizes wrong.
        payload = encode_transitions(*_batch(4), env_steps=1)
        assert decode_transitions(payload[:-3]) is None

    def test_rejects_inconsistent_shapes(self):
        obs, act, rew, nxt = _batch(4)
        with pytest.raises(ValueError, match="inconsistent"):
            encode_transitions(obs, act[:2], rew, nxt)


class TestTailReader:
    def _write(self, jpath, batches, env_steps):
        with Journal(jpath) as j:
            for b, es in zip(batches, env_steps):
                append_transitions(j, *b, env_steps=es)

    def test_reads_back_in_order(self, jpath):
        batches = [_batch(3, seed=s) for s in range(3)]
        self._write(jpath, batches, [10, 20, 30])
        tail = read_tail_transitions(jpath, 0)
        assert tail is not None
        obs, act, rew, nxt, high = tail
        assert high == 30
        np.testing.assert_array_equal(
            obs, np.concatenate([b[0] for b in batches]))
        np.testing.assert_array_equal(
            nxt, np.concatenate([b[3] for b in batches]))

    def test_tail_bounded_by_max_rows(self, jpath):
        batches = [_batch(4, seed=s) for s in range(5)]
        self._write(jpath, batches, [1, 2, 3, 4, 5])
        obs, act, rew, nxt, high = read_tail_transitions(jpath, 6)
        # Walking back: records 5 and 4 cover >= 6 rows; older ones dropped.
        assert obs.shape[0] == 8
        np.testing.assert_array_equal(
            obs, np.concatenate([batches[3][0], batches[4][0]]))
        assert high == 5

    def test_cutoff_excludes_newer_but_keeps_high_water(self, jpath):
        batches = [_batch(2, seed=s) for s in range(4)]
        self._write(jpath, batches, [5, 10, 15, 20])
        obs, act, rew, nxt, high = read_tail_transitions(
            jpath, 0, cutoff_env_steps=12)
        assert obs.shape[0] == 4          # env_steps 5 and 10 only
        assert high == 20                 # high water sees everything
        np.testing.assert_array_equal(
            obs, np.concatenate([batches[0][0], batches[1][0]]))

    def test_cutoff_excluding_everything_still_returns_high_water(self, jpath):
        """Zero keepable rows must NOT collapse to None: losing high_water
        would re-journal the excluded chunks with duplicate stamps (the
        double-journaling guard, e.g. after compaction dropped old records)."""
        self._write(jpath, [_batch(2, seed=s) for s in range(2)], [50, 60])
        tail = read_tail_transitions(jpath, 0, cutoff_env_steps=10)
        assert tail is not None
        obs, act, rew, nxt, high = tail
        assert obs.shape[0] == 0 and act.shape == (0,)
        assert high == 60
        fb = _python_read_tail(jpath, 0, 10)
        assert fb[0].shape[0] == 0 and fb[4] == 60

    def test_mixed_json_and_binary_log(self, jpath):
        """JSON events and packed records share a journal: replay() yields
        only the JSON events, the tail reader only the packed records."""
        with Journal(jpath) as j:
            j.append({"type": "fetch", "symbol": "MSFT"})
            # Reward bytes crafted to contain "\n7\n": the native replay
            # newline-splits raw payloads, and the fragment b"7" parses as
            # valid (non-dict) JSON — replay must yield dict events only.
            obs, act, _rew, nxt = _batch(1, obs_dim=2)
            rew = np.frombuffer(b"\n7\n\x00", np.float32)
            append_transitions(j, obs, act, rew, nxt, env_steps=7)
            j.append({"type": "fetch", "symbol": "GOOG"})
        events = list(Journal(jpath).replay())
        assert [e["symbol"] for e in events] == ["MSFT", "GOOG"]
        tail = read_tail_transitions(jpath, 0)
        assert tail[0].shape[0] == 1 and tail[4] == 7
        np.testing.assert_array_equal(tail[2], rew)
        if native_available():
            from sharetrade_tpu.data.native import NativeJournal
            assert [e["symbol"] for e in NativeJournal(jpath).replay()] == [
                "MSFT", "GOOG"]

    def test_torn_tail_stops_cleanly(self, jpath):
        batches = [_batch(2, seed=s) for s in range(2)]
        self._write(jpath, batches, [1, 2])
        with open(jpath, "r+b") as f:
            f.seek(0, 2)
            f.truncate(f.tell() - 5)       # rip the last record's tail
        obs, act, rew, nxt, high = read_tail_transitions(jpath, 0)
        assert obs.shape[0] == 2           # only the intact first record
        assert high == 1

    def test_missing_file(self, tmp_path):
        assert read_tail_transitions(str(tmp_path / "nope"), 0) is None

    @pytest.mark.skipif(not native_available(),
                        reason="native journal not built")
    def test_native_matches_python_fallback(self, jpath):
        batches = [_batch(3, seed=s) for s in range(4)]
        self._write(jpath, batches, [3, 6, 9, 12])
        for max_rows, cutoff in [(0, 0), (5, 0), (0, 7), (4, 10)]:
            native = read_tail_transitions(jpath, max_rows,
                                           cutoff_env_steps=cutoff)
            fallback = _python_read_tail(jpath, max_rows, cutoff)
            assert (native is None) == (fallback is None)
            if native is None:
                continue
            for a, b in zip(native, fallback):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_compaction_keeps_tail_and_stamps(self, jpath):
        """Compaction drops only records older than the keep_rows tail and
        preserves record boundaries, so cutoff filtering still works."""
        batches = [_batch(4, seed=s) for s in range(6)]
        with Journal(jpath) as j:
            for b, es in zip(batches, [1, 2, 3, 4, 5, 6]):
                append_transitions(j, *b, env_steps=es)
            import os
            size_before = os.path.getsize(jpath)
            assert compact_transitions(j, keep_rows=8)   # keep last 2 records
            assert os.path.getsize(jpath) < size_before
            # Appends continue cleanly after the rewrite.
            append_transitions(j, *_batch(4, seed=9), env_steps=7)
        obs, act, rew, nxt, high = read_tail_transitions(jpath, 0)
        assert obs.shape[0] == 12 and high == 7
        # Per-record stamps survive: cutoff can still split the kept tail.
        cut, *_rest, high2 = read_tail_transitions(jpath, 0,
                                                   cutoff_env_steps=6)
        assert cut.shape[0] == 8 and high2 == 7

    def test_compaction_noop_when_tail_covers_everything(self, jpath):
        with Journal(jpath) as j:
            append_transitions(j, *_batch(4), env_steps=1)
            assert not compact_transitions(j, keep_rows=100)
        assert read_tail_transitions(jpath, 0)[0].shape[0] == 4

    @pytest.mark.skipif(not native_available(),
                        reason="native journal not built")
    def test_native_journal_appends_binary(self, jpath):
        from sharetrade_tpu.data.native import NativeJournal
        obs, act, rew, nxt = _batch(5, seed=9)
        with NativeJournal(jpath) as nj:
            append_transitions(nj, obs, act, rew, nxt, env_steps=11)
        tail = read_tail_transitions(jpath, 0)
        np.testing.assert_array_equal(tail[0], obs)
        assert tail[4] == 11


class TestIngestReader:
    """read_new_transitions — the learner's actor-feed ingest read
    (disaggregation PR): per-actor cursor streaming over a possibly
    segmented journal, with the no-skip cursor guarantee under max_rows."""

    def _write(self, jpath, stamps, rows_per=4, segment_records=0):
        from sharetrade_tpu.data.transitions import append_transitions
        with Journal(jpath, segment_records=segment_records) as j:
            for i, es in enumerate(stamps):
                append_transitions(j, *_batch(rows_per, seed=i),
                                   env_steps=es)

    def test_floor_filters_and_high_water_advances(self, jpath):
        from sharetrade_tpu.data.transitions import read_new_transitions
        self._write(jpath, [10, 20, 30])
        out = read_new_transitions(jpath, 10, 0)
        assert out[0].shape[0] == 8            # stamps 20, 30
        assert out[4] == 30
        # Cursor at the returned high-water: nothing new next tick.
        out = read_new_transitions(jpath, 30, 0)
        assert out[0].shape[0] == 0
        assert out[4] >= 30

    def test_no_records_returns_none(self, tmp_path):
        from sharetrade_tpu.data.transitions import read_new_transitions
        assert read_new_transitions(
            str(tmp_path / "missing.journal"), 0, 0) is None

    def test_segmented_walk_matches_single_file(self, tmp_path):
        from sharetrade_tpu.data.transitions import read_new_transitions
        flat = str(tmp_path / "flat.journal")
        seg = str(tmp_path / "seg.journal")
        stamps = list(range(10, 110, 10))
        self._write(flat, stamps)
        self._write(seg, stamps, segment_records=3)
        a = read_new_transitions(flat, 40, 0)
        b = read_new_transitions(seg, 40, 0)
        np.testing.assert_array_equal(a[0], b[0])
        assert a[4] == b[4] == 100

    def test_max_rows_keeps_oldest_and_never_skips(self, jpath):
        # THE cursor contract: a capped read must stream the backlog
        # oldest-first, with high-water covering only the KEPT records —
        # keeping the newest instead would advance the cursor past the
        # dropped older rows and lose them forever.
        from sharetrade_tpu.data.transitions import read_new_transitions
        self._write(jpath, [10, 20, 30, 40], rows_per=4)
        seen = []
        cursor = 0
        for _ in range(10):
            out = read_new_transitions(jpath, cursor, 8)
            if out[0].shape[0] == 0:
                break
            seen.append(out[4])
            assert out[0].shape[0] <= 8
            cursor = max(cursor, out[4])
        # Every committed stamp ingested exactly once, in order.
        assert seen == [20, 40]
        assert cursor == 40

    def test_cap_smaller_than_one_record_still_progresses(self, jpath):
        from sharetrade_tpu.data.transitions import read_new_transitions
        self._write(jpath, [10, 20], rows_per=6)
        out = read_new_transitions(jpath, 0, 2)    # cap < record rows
        assert out[0].shape[0] == 6                # whole record kept
        assert out[4] == 10                        # cursor exact
