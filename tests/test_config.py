from sharetrade_tpu.config import FrameworkConfig


def test_defaults_match_reference_constants():
    # Reference hyperparameters: QDecisionPolicyActor.scala:17-22,
    # ShareTradeHelper.scala:20-21, TrainerRouterActor.scala:36.
    cfg = FrameworkConfig()
    assert cfg.env.window == 201
    assert cfg.env.initial_budget == 2400.0
    assert cfg.model.hidden_dim == 200
    assert cfg.model.num_actions == 3
    assert cfg.learner.epsilon == 0.9
    assert cfg.learner.gamma == 0.001
    assert cfg.learner.learning_rate == 0.01
    assert cfg.parallel.num_workers == 10


def test_roundtrip_dict():
    cfg = FrameworkConfig()
    cfg2 = FrameworkConfig.from_dict(cfg.to_dict())
    assert cfg2.to_dict() == cfg.to_dict()


def test_roundtrip_file(tmp_path):
    cfg = FrameworkConfig()
    cfg.learner.gamma = 0.99
    path = str(tmp_path / "cfg.json")
    cfg.save(path)
    loaded = FrameworkConfig.from_file(path)
    assert loaded.learner.gamma == 0.99
    assert loaded.to_dict() == cfg.to_dict()


def test_overrides():
    cfg = FrameworkConfig()
    out = cfg.apply_overrides([
        "learner.gamma=0.95",
        "model.kind=lstm",
        'parallel.mesh_shape={"dp": 4, "tp": 2}',
        "data.csv_path=/tmp/x.csv",
    ])
    assert out.learner.gamma == 0.95
    assert out.model.kind == "lstm"
    assert out.parallel.mesh_shape == {"dp": 4, "tp": 2}
    assert out.data.csv_path == "/tmp/x.csv"
    # original untouched
    assert cfg.learner.gamma == 0.001


def test_override_unknown_key_raises():
    cfg = FrameworkConfig()
    import pytest
    with pytest.raises(KeyError):
        cfg.apply_overrides(["learner.nope=1"])
    with pytest.raises(ValueError):
        cfg.apply_overrides(["learner.gamma"])
