"""Checkpoint manager: atomicity, integrity, quarantine walk-back, retention,
bit-exact resume."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointIntegrityError,
    CheckpointManager,
    verify_checkpoint_files,
)
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.env import trading
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8


def make_agent(algo="qlearn"):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 2
    cfg.runtime.chunk_steps = 4
    env_params = trading.env_from_prices(
        jnp.linspace(10.0, 20.0, 32), window=WINDOW)
    return build_agent(cfg, env_params)


class TestSaveRestore:
    def test_round_trip_bit_exact(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)

        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(int(ts.updates), ts)

        template = agent.init(jax.random.PRNGKey(99))  # different init
        restored, step = mgr.restore(template)
        assert step == int(ts.updates)
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resume_continues_identically(self, tmp_path):
        """Training N chunks straight == training k, checkpoint, restore,
        training N-k: the full state (params/opt/rng/env cursor) round-trips."""
        agent = make_agent()
        step = jax.jit(agent.step)

        ts = agent.init(jax.random.PRNGKey(1))
        for _ in range(4):
            ts, _ = step(ts)
        straight = jax.device_get(ts)

        ts2 = agent.init(jax.random.PRNGKey(1))
        for _ in range(2):
            ts2, _ = step(ts2)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(2, ts2)
        restored, _ = mgr.restore(agent.init(jax.random.PRNGKey(1)))
        for _ in range(2):
            restored, _ = step(restored)
        resumed = jax.device_get(restored)

        for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_retention_prunes_oldest(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in [10, 20, 30, 40]:
            mgr.save(step, ts)
        assert mgr.steps() == [30, 40]

    def test_restore_specific_step(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), keep=5)
        mgr.save(10, ts)
        ts2, _ = jax.jit(agent.step)(ts)
        mgr.save(20, ts2)
        _, step = mgr.restore(ts, step=10)
        assert step == 10

    def test_torn_write_invisible(self, tmp_path):
        # A tmp dir from a crashed writer must not be listed as a checkpoint.
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(5, ts)
        os.makedirs(tmp_path / "tmp-7-12345")
        (tmp_path / "tmp-7-12345" / "state.msgpack").write_bytes(b"partial")
        assert mgr.steps() == [5]
        assert mgr.latest_step() == 5

    def test_async_save_restores_identically(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_async(int(ts.updates), ts)
        assert mgr.wait_pending(timeout=30)
        restored, step = mgr.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == int(ts.updates)
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_metadata(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(7, ts, metadata={"note": "mid-episode"})
        meta = mgr.metadata(7)
        assert meta["step"] == 7 and meta["note"] == "mid-episode"

    def test_fsync_off_still_round_trips(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), fsync=False)
        mgr.save(3, ts)
        restored, step = mgr.restore(agent.init(jax.random.PRNGKey(5)))
        assert step == 3
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# integrity: checksums, verify(), quarantine + walk-back
# ---------------------------------------------------------------------------

def _truncate(path, size):
    with open(path, "r+b") as f:
        f.truncate(size)


def _bitflip(path, frac=0.5):
    size = os.path.getsize(path)
    off = max(0, min(size - 1, int(size * frac)))
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


#: The corruption matrix: (name, mutator(ckpt_dir), expected quarantine
#: reason). Every case must be DETECTED, the checkpoint quarantined (renamed
#: corrupt_*, never deleted), and restore must fall back to the next-oldest
#: intact step.
CORRUPTIONS = [
    ("state_truncated_empty",
     lambda d: _truncate(os.path.join(d, "state.msgpack"), 0),
     "state_checksum"),
    ("state_truncated_1byte",
     lambda d: _truncate(os.path.join(d, "state.msgpack"), 1),
     "state_checksum"),
    ("state_truncated_half",
     lambda d: _truncate(
         os.path.join(d, "state.msgpack"),
         os.path.getsize(os.path.join(d, "state.msgpack")) // 2),
     "state_checksum"),
    ("state_truncated_last_byte",
     lambda d: _truncate(
         os.path.join(d, "state.msgpack"),
         os.path.getsize(os.path.join(d, "state.msgpack")) - 1),
     "state_checksum"),
    ("state_bitflipped",
     lambda d: _bitflip(os.path.join(d, "state.msgpack")),
     "state_checksum"),
    ("state_missing",
     lambda d: os.remove(os.path.join(d, "state.msgpack")),
     "state_missing"),
    ("meta_missing",
     lambda d: os.remove(os.path.join(d, "meta.json")),
     "meta_missing"),
    ("meta_garbled",
     lambda d: open(os.path.join(d, "meta.json"), "w").write("{nope"),
     "meta_garbled"),
    ("meta_bitflipped",
     lambda d: _bitflip(os.path.join(d, "meta.json"), 0.9),
     None),      # garbled JSON or checksum mismatch, depending on the byte
    ("empty_dir",
     lambda d: [os.remove(os.path.join(d, n)) for n in os.listdir(d)],
     None),      # meta and state both gone
]


class TestIntegrity:
    def _three_checkpoints(self, tmp_path, **kwargs):
        """Steps 10 < 20 < 30, each from a distinct train state."""
        agent = make_agent()
        step_fn = jax.jit(agent.step)
        mgr = CheckpointManager(str(tmp_path), keep=5, **kwargs)
        ts = agent.init(jax.random.PRNGKey(0))
        for step in (10, 20, 30):
            ts, _ = step_fn(ts)
            mgr.save(step, ts)
        return agent, mgr

    def test_meta_records_checksums(self, tmp_path):
        _, mgr = self._three_checkpoints(tmp_path)
        meta = mgr.metadata(30)
        integ = meta["integrity"]
        assert integ["algo"] == "sha256"
        assert len(integ["state.msgpack"]) == 64
        assert len(integ["meta_sha256"]) == 64

    def test_verify_accepts_intact(self, tmp_path):
        _, mgr = self._three_checkpoints(tmp_path)
        assert mgr.verify()["step"] == 30
        assert mgr.verify(10)["step"] == 10
        verify_checkpoint_files(os.path.join(str(tmp_path),
                                             "ckpt_0000000020"))

    @pytest.mark.parametrize("name,mutate,reason",
                             CORRUPTIONS, ids=[c[0] for c in CORRUPTIONS])
    def test_corrupt_newest_quarantined_and_walked_back(
            self, tmp_path, name, mutate, reason):
        metrics = MetricsRegistry()
        agent, mgr = self._three_checkpoints(tmp_path, metrics=metrics)
        mutate(os.path.join(str(tmp_path), "ckpt_0000000030"))
        with pytest.raises(CheckpointIntegrityError):
            mgr.verify(30)
        restored, step = mgr.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == 20, "walk-back must serve the next-oldest intact step"
        # Quarantined — renamed aside with the reason, never deleted.
        corrupt = [n for n in os.listdir(tmp_path)
                   if n.startswith("corrupt_0000000030")]
        assert len(corrupt) == 1
        if reason is not None:
            assert reason in corrupt[0]
        assert not os.path.isdir(tmp_path / "ckpt_0000000030")
        assert mgr.steps() == [10, 20]
        assert metrics.counters()["ckpt_quarantined_total"] == 1
        assert metrics.counters()["ckpt_restore_fallbacks_total"] == 1
        # The fallback is reported for the orchestrator's event surface.
        assert mgr.last_restore_report["step"] == 20
        assert mgr.last_restore_report["skipped"][0][0] == 30

    def test_nonfinite_params_rejected(self, tmp_path):
        agent, mgr = self._three_checkpoints(tmp_path)
        ts = agent.init(jax.random.PRNGKey(0))
        poisoned = ts.replace(
            params=jax.tree.map(lambda a: jnp.full_like(a, jnp.nan),
                                ts.params))
        mgr.save(40, poisoned)
        restored, step = mgr.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == 30
        assert any(n.startswith("corrupt_0000000040_nonfinite")
                   for n in os.listdir(tmp_path))

    def test_all_corrupt_raises_corrupt_error(self, tmp_path):
        agent, mgr = self._three_checkpoints(tmp_path)
        for step in (10, 20, 30):
            _bitflip(str(tmp_path / f"ckpt_{step:010d}" / "state.msgpack"))
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(agent.init(jax.random.PRNGKey(9)))
        # FileNotFoundError-compatible: restore-or-reinit arms catch it.
        assert issubclass(CheckpointCorruptError, FileNotFoundError)
        # All three quarantined, none deleted: the bytes are evidence.
        assert len([n for n in os.listdir(tmp_path)
                    if n.startswith("corrupt_")]) == 3

    def test_explicit_corrupt_step_raises_not_substitutes(self, tmp_path):
        agent, mgr = self._three_checkpoints(tmp_path)
        _bitflip(str(tmp_path / "ckpt_0000000030" / "state.msgpack"))
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(agent.init(jax.random.PRNGKey(9)), step=30)

    def test_corrupt_tagged_quarantined(self, tmp_path):
        agent, mgr = self._three_checkpoints(tmp_path)
        ts = agent.init(jax.random.PRNGKey(0))
        mgr.save_tagged("best", ts, metadata={"eval_portfolio": 1.0})
        _bitflip(str(tmp_path / "tag_best" / "state.msgpack"))
        with pytest.raises(CheckpointCorruptError):
            mgr.restore_tagged(agent.init(jax.random.PRNGKey(9)), "best")
        assert any(n.startswith("corrupt_tag_best")
                   for n in os.listdir(tmp_path))

    def test_tagged_overwrite_failure_leaves_live_tag(self, tmp_path,
                                                      monkeypatch):
        """The new payload is staged COMPLETELY before the live tag moves:
        a write failure mid-overwrite (disk full, kill) must leave the
        previous tag readable, not demote it to .old with no primary."""
        agent, mgr = self._three_checkpoints(tmp_path)
        ts = agent.init(jax.random.PRNGKey(0))
        mgr.save_tagged("best", ts, metadata={"v": 1})

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(mgr, "_write_payload_tmp", boom)
        with pytest.raises(OSError):
            mgr.save_tagged("best", ts, metadata={"v": 2})
        _, meta = mgr.restore_tagged(agent.init(jax.random.PRNGKey(9)),
                                     "best")
        assert meta["v"] == 1

    def test_template_mismatch_raises_without_quarantine(self, tmp_path):
        """A checksum-INTACT checkpoint that fails to deserialize is a
        caller/config mismatch, not corruption: restore must raise loudly
        and leave the store untouched — quarantining would rename the
        whole store aside on a model-shape change + --resume."""
        _, mgr = self._three_checkpoints(tmp_path)
        # Structurally different template (DQN carries replay extras the
        # qlearn checkpoints lack) — the config-changed --resume scenario.
        other = make_agent("dqn")
        with pytest.raises(ValueError, match="checksum-intact"):
            mgr.restore(other.init(jax.random.PRNGKey(0)))
        assert mgr.steps() == [10, 20, 30]
        assert not any(n.startswith("corrupt_")
                       for n in os.listdir(tmp_path))

    def test_pre_integrity_checkpoint_still_restores(self, tmp_path):
        """Checkpoints written before checksums existed (no integrity block)
        must restore on structural checks alone — an upgrade must not
        quarantine a healthy old fleet."""
        agent, mgr = self._three_checkpoints(tmp_path)
        meta_path = tmp_path / "ckpt_0000000030" / "meta.json"
        meta = json.loads(meta_path.read_text())
        del meta["integrity"]
        meta_path.write_text(json.dumps(meta))
        _, step = mgr.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == 30


class TestTmpSweep:
    def test_complete_tmp_recovered_not_swept(self, tmp_path):
        """The same-step re-save crash window (_publish removes the old dir
        before the rename): a kill there leaves only the fully-staged tmp.
        The next manager must RECOVER it — it is a durable, checksummed
        checkpoint that merely missed its rename — not sweep it."""
        import shutil
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(5, ts)
        # Simulate the window: staged tmp of a dead pid, published dir gone.
        shutil.copytree(tmp_path / "ckpt_0000000005",
                        tmp_path / "tmp-5-999999999")
        shutil.rmtree(tmp_path / "ckpt_0000000005")
        mgr2 = CheckpointManager(str(tmp_path))
        assert mgr2.steps() == [5]
        assert not (tmp_path / "tmp-5-999999999").exists()
        restored, step = mgr2.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == 5
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dead_pid_tmp_swept_at_init(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        CheckpointManager(str(tmp_path)).save(5, ts)
        # Debris from a crashed writer: a pid that cannot be alive.
        dead = tmp_path / "tmp-7-999999999"
        dead.mkdir()
        (dead / "state.msgpack").write_bytes(b"partial")
        mgr = CheckpointManager(str(tmp_path))
        assert not dead.exists(), "dead-pid tmp debris must be swept"
        assert mgr.steps() == [5]

    def test_live_pid_tmp_untouched(self, tmp_path):
        """A tmp dir of a LIVE pid belongs to a concurrent saver mid-write;
        sweeping it would tear that save."""
        live = tmp_path / f"tmp-9-{os.getpid()}"
        live.mkdir()
        (live / "state.msgpack").write_bytes(b"mid-write")
        CheckpointManager(str(tmp_path))
        assert live.exists()
