"""Checkpoint manager: atomicity, retention, bit-exact resume."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.checkpoint import CheckpointManager
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.env import trading

WINDOW = 8


def make_agent(algo="qlearn"):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 2
    cfg.runtime.chunk_steps = 4
    env_params = trading.env_from_prices(
        jnp.linspace(10.0, 20.0, 32), window=WINDOW)
    return build_agent(cfg, env_params)


class TestSaveRestore:
    def test_round_trip_bit_exact(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)

        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(int(ts.updates), ts)

        template = agent.init(jax.random.PRNGKey(99))  # different init
        restored, step = mgr.restore(template)
        assert step == int(ts.updates)
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resume_continues_identically(self, tmp_path):
        """Training N chunks straight == training k, checkpoint, restore,
        training N-k: the full state (params/opt/rng/env cursor) round-trips."""
        agent = make_agent()
        step = jax.jit(agent.step)

        ts = agent.init(jax.random.PRNGKey(1))
        for _ in range(4):
            ts, _ = step(ts)
        straight = jax.device_get(ts)

        ts2 = agent.init(jax.random.PRNGKey(1))
        for _ in range(2):
            ts2, _ = step(ts2)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(2, ts2)
        restored, _ = mgr.restore(agent.init(jax.random.PRNGKey(1)))
        for _ in range(2):
            restored, _ = step(restored)
        resumed = jax.device_get(restored)

        for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_retention_prunes_oldest(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for step in [10, 20, 30, 40]:
            mgr.save(step, ts)
        assert mgr.steps() == [30, 40]

    def test_restore_specific_step(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path), keep=5)
        mgr.save(10, ts)
        ts2, _ = jax.jit(agent.step)(ts)
        mgr.save(20, ts2)
        _, step = mgr.restore(ts, step=10)
        assert step == 10

    def test_torn_write_invisible(self, tmp_path):
        # A tmp dir from a crashed writer must not be listed as a checkpoint.
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(5, ts)
        os.makedirs(tmp_path / "tmp-7-12345")
        (tmp_path / "tmp-7-12345" / "state.msgpack").write_bytes(b"partial")
        assert mgr.steps() == [5]
        assert mgr.latest_step() == 5

    def test_async_save_restores_identically(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_async(int(ts.updates), ts)
        assert mgr.wait_pending(timeout=30)
        restored, step = mgr.restore(agent.init(jax.random.PRNGKey(9)))
        assert step == int(ts.updates)
        for a, b in zip(jax.tree.leaves(jax.device_get(ts)),
                        jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_metadata(self, tmp_path):
        agent = make_agent()
        ts = agent.init(jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(7, ts, metadata={"note": "mid-episode"})
        meta = mgr.metadata(7)
        assert meta["step"] == 7 and meta["note"] == "mid-episode"
