"""Tracing/profiling subsystem (SURVEY.md §5: absent in reference, required here)."""

import glob
import os

import jax
import jax.numpy as jnp

from sharetrade_tpu.utils.profiling import StepTimer, Tracer


class TestStepTimer:
    def test_first_tick_is_baseline(self):
        t = StepTimer(chunk_steps=10, num_agents=4)
        assert t.tick() == {}
        m = t.tick()
        assert m["chunk_seconds"] > 0
        assert m["agent_steps_per_sec"] > 0
        assert t.summary()["chunks_timed"] == 1.0

    def test_rates_consistent(self):
        t = StepTimer(chunk_steps=100, num_agents=10)
        t.tick()
        m = t.tick()
        assert abs(m["agent_steps_per_sec"] / m["env_steps_per_sec"] - 10.0) < 1e-6


class TestTracer:
    def test_disabled_is_noop(self):
        tracer = Tracer(None)
        with tracer.trace():
            with tracer.span("x"):
                pass  # no profiler started, no error

    def test_device_trace_written(self, tmp_path):
        tracer = Tracer(str(tmp_path))
        with tracer.trace():
            with tracer.span("matmul"):
                x = jnp.ones((64, 64))
                jax.block_until_ready(x @ x)
        # jax.profiler writes xplane protos under plugins/profile/<ts>/.
        found = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
        assert found, f"no xplane trace under {tmp_path}: {os.listdir(tmp_path)}"
