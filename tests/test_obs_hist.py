"""Mergeable histograms (obs/hist.py) + exposition-format export.

The ISSUE-11 contracts pinned here:

- **exact merge**: bucket-wise addition of shard histograms equals the
  histogram of the concatenated sample (the fleet aggregation contract —
  no approximation introduced by the merge itself);
- **bounded-error quantiles**: a histogram quantile is within ONE bucket
  width of the exact nearest-rank sample quantile, under the repo's
  single quantile convention (``serve/engine.py latency_percentiles``,
  whose floored-rank p99 bias this PR fixed);
- **valid exposition output**: ``metrics.prom`` parses under a STRICT
  reader — gauges, counters, and histogram ``_bucket``(cumulative,
  ``le``-labeled, ``+Inf``-terminated)/``_sum``/``_count`` triples.
"""

import json
import os
import random

import numpy as np
import pytest

from sharetrade_tpu.obs.exporter import (
    MetricsExporter,
    PromParseError,
    parse_prom_text,
)
from sharetrade_tpu.obs.hist import (
    DEFAULT_MS_BOUNDS,
    Histogram,
    log_bounds,
    merge,
    quantile_from_snapshot,
)
from sharetrade_tpu.serve.engine import latency_percentiles
from sharetrade_tpu.utils.metrics import MetricsRegistry


class TestBounds:
    def test_log_bounds_deterministic_and_ascending(self):
        a = log_bounds(0.01, 1e5, per_decade=5)
        b = log_bounds(0.01, 1e5, per_decade=5)
        assert a == b                       # bit-identical across calls
        assert all(y > x for x, y in zip(a, a[1:]))
        assert a == DEFAULT_MS_BOUNDS
        assert a[0] <= 0.0100000001 and a[-1] >= 1e5

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError):
            log_bounds(0.0, 10.0)
        with pytest.raises(ValueError):
            log_bounds(10.0, 1.0)
        with pytest.raises(ValueError):
            Histogram(bounds=(1.0, 1.0, 2.0))
        with pytest.raises(ValueError):
            Histogram(bounds=())


class TestHistogram:
    def test_le_bucket_semantics(self):
        h = Histogram(bounds=(1.0, 10.0, 100.0))
        for v in (0.5, 1.0, 1.0001, 100.0, 1000.0):
            h.observe(v)
        # value <= bound (Prometheus le): 1.0 lands in the first bucket,
        # 1.0001 in the second, 1000 in the overflow slot.
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5
        assert h.sum == pytest.approx(0.5 + 1.0 + 1.0001 + 100.0 + 1000.0)

    def test_merge_property_exact(self):
        """Merge of shards == histogram of the concatenation, EXACTLY —
        counts, count, and (integer-valued samples, so float addition is
        exact) sum."""
        rng = random.Random(7)
        shards = []
        everything = []
        for _ in range(5):
            h = Histogram()
            vals = [float(rng.randrange(0, 200_000))
                    for _ in range(rng.randrange(0, 400))]
            for v in vals:
                h.observe(v)
            shards.append(h)
            everything.extend(vals)
        merged = merge(shards)
        reference = Histogram()
        for v in everything:
            reference.observe(v)
        assert merged.snapshot()["counts"] == reference.snapshot()["counts"]
        assert merged.count == reference.count == len(everything)
        assert merged.sum == reference.sum

    def test_merge_refuses_mismatched_layouts(self):
        with pytest.raises(ValueError, match="different bucket bounds"):
            Histogram(bounds=(1.0, 2.0)).merge(Histogram(bounds=(1.0, 3.0)))

    def test_quantile_within_one_bucket_width_of_exact(self):
        """The histogram estimate vs the exact nearest-rank quantile
        (latency_percentiles — ONE convention serve-wide), within the
        width of the bucket holding the exact value."""
        rng = np.random.default_rng(3)
        values = np.exp(rng.normal(1.5, 1.2, size=2000)).astype(np.float64)
        h = Histogram()
        for v in values:
            h.observe(float(v))
        exact = latency_percentiles(values)
        for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
            est = h.quantile(q)
            bounds = h.bounds
            idx = next(i for i, b in enumerate(bounds) if exact[key] <= b)
            lo = bounds[idx - 1] if idx else 0.0
            width = bounds[idx] - lo
            assert abs(est - exact[key]) <= width, (
                f"q={q}: estimate {est} vs exact {exact[key]} "
                f"(bucket width {width})")

    def test_window_delta_equals_interval_histogram(self):
        """Cumulative snapshots subtract into the exact histogram of the
        interval — the serve engine's rolling-gauge mechanism."""
        h = Histogram()
        first = [1.0, 5.0, 40.0]
        second = [2.0, 300.0, 7.0, 0.02]
        for v in first:
            h.observe(v)
        snap0 = h.snapshot()["counts"]
        for v in second:
            h.observe(v)
        delta = [a - b for a, b in zip(h.snapshot()["counts"], snap0)]
        ref = Histogram()
        for v in second:
            ref.observe(v)
        assert delta == ref.snapshot()["counts"]

    def test_nearest_rank_percentile_fix(self):
        """The satellite bugfix: ceil-rank nearest-rank, not the floored
        ``int(q*(n-1))`` that reported p90 as "p99" at n=10."""
        vals = [float(v) for v in range(1, 11)]
        pct = latency_percentiles(vals)
        assert pct["p50_ms"] == 5.0         # ceil(0.5*10) = rank 5
        assert pct["p99_ms"] == 10.0        # ceil(0.99*10) = rank 10 (max)
        assert latency_percentiles([3.25])["p99_ms"] == 3.25
        assert latency_percentiles([])["p99_ms"] == 0.0


class TestRegistryAndExporter:
    def test_attached_histograms_export_and_parse_strictly(self, tmp_path):
        reg = MetricsRegistry()
        reg.record("portfolio_mean", 2400.5)
        reg.inc("restarts_total", 2)
        h = reg.attach_histogram("serve_queue_wait_ms", Histogram())
        for v in (0.5, 3.0, 3.0, 77.0, 1e9):    # 1e9 = overflow bucket
            h.observe(v)
        exporter = MetricsExporter(reg, str(tmp_path), interval_s=60)
        assert exporter.drain()
        prom_text = (tmp_path / "metrics.prom").read_text()
        parsed = parse_prom_text(prom_text)     # STRICT — raises on bad
        assert parsed["gauges"]["sharetrade_portfolio_mean"] == 2400.5
        assert parsed["counters"]["sharetrade_restarts_total"] == 2.0
        hist = parsed["histograms"]["sharetrade_serve_queue_wait_ms"]
        assert hist["count"] == 5.0
        assert hist["buckets"][-1] == ("+Inf", 5)
        cums = [c for _, c in hist["buckets"]]
        assert cums == sorted(cums)             # cumulative, nondecreasing
        assert hist["sum"] == pytest.approx(0.5 + 3.0 + 3.0 + 77.0 + 1e9)
        # ... and the JSONL history carries the raw snapshot the
        # summarizer re-quantiles.
        lines = [json.loads(ln) for ln in
                 (tmp_path / "metrics.jsonl").read_text().splitlines()]
        snap = lines[-1]["histograms"]["serve_queue_wait_ms"]
        assert snap["count"] == 5
        assert quantile_from_snapshot(snap, 0.5) > 0

    def test_histogram_changes_trigger_redrain(self, tmp_path):
        reg = MetricsRegistry()
        h = reg.attach_histogram("h_ms", Histogram())
        exporter = MetricsExporter(reg, str(tmp_path), interval_s=60)
        assert exporter.drain()
        assert not exporter.drain()             # nothing changed
        h.observe(1.0)
        assert exporter.drain()                 # histogram delta counts

    def test_strict_parser_rejections(self):
        ok = "# TYPE m gauge\nm 1.0\n"
        assert parse_prom_text(ok)["gauges"]["m"] == 1.0
        with pytest.raises(PromParseError, match="no preceding TYPE"):
            parse_prom_text("m 1.0\n")
        with pytest.raises(PromParseError, match="non-float"):
            parse_prom_text("# TYPE m gauge\nm abc\n")
        with pytest.raises(PromParseError, match="negative counter"):
            parse_prom_text("# TYPE c counter\nc -1\n")
        with pytest.raises(PromParseError, match="not cumulative"):
            parse_prom_text(
                '# TYPE h histogram\nh_bucket{le="1"} 5\n'
                'h_bucket{le="2"} 3\nh_bucket{le="+Inf"} 5\n'
                "h_sum 1\nh_count 5\n")
        with pytest.raises(PromParseError, match=r"\+Inf"):
            parse_prom_text(
                '# TYPE h histogram\nh_bucket{le="1"} 5\n'
                "h_sum 1\nh_count 5\n")
        with pytest.raises(PromParseError, match="!= _count"):
            parse_prom_text(
                '# TYPE h histogram\nh_bucket{le="+Inf"} 4\n'
                "h_sum 1\nh_count 5\n")
        with pytest.raises(PromParseError, match="malformed sample"):
            parse_prom_text("# TYPE m gauge\n3m&bad 1.0\n")
