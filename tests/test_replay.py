"""Replay data plane (ISSUE 9): device-resident prioritized replay
(ops/sum_tree.py + the DQN PER mode), bounded journal (segment rotation +
retirement), streaming ingest, and their guards.

The pinned claims:

1. **Uniform default is bit-identical to pre-PR** — the golden trajectory
   captured at the pre-data-plane commit
   (tests/golden/replay_uniform_golden.json) reproduces EXACTLY, the same
   contract (and capture recipe) as the precision PR's fp32 golden.
2. **The sum-tree is exact** — after any batched update sequence every
   internal node equals the sum of its two children bit-for-bit (so the
   root IS the total mass), sampled frequencies track priorities, and
   massless (masked / never-written) leaves are never sampled.
3. **Rotation keeps the torn-tail contract per segment** — a crash at ANY
   byte offset of the newest segment recovers an exact record prefix;
   sealed segments are immutable and retirement never touches the
   replay-capacity horizon.
4. **Streaming ingest converges to the batch load** — consuming a feed
   incrementally (partial lines included) yields exactly the series a
   one-shot CSV load of the final file returns.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.data.journal import Journal, segment_paths
from sharetrade_tpu.data.synthetic import synthetic_price_series
from sharetrade_tpu.data.transitions import (
    append_transitions,
    count_transition_rows,
    read_tail_transitions,
    retire_transition_segments,
)
from sharetrade_tpu.env import trading
from sharetrade_tpu.ops import sum_tree

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "replay_uniform_golden.json")


def _tree_digest(tree):
    h = hashlib.sha256()
    for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            key=lambda kv: str(kv[0])):
        a = np.asarray(leaf)
        h.update(str(path).encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _golden_cfg(mode: str = "uniform") -> FrameworkConfig:
    cfg = FrameworkConfig()
    cfg.learner.algo = "dqn"
    cfg.parallel.num_workers = 4
    cfg.env.window = 16
    cfg.runtime.chunk_steps = 25
    cfg.model.hidden_dim = 16
    cfg.learner.replay_capacity = 512
    cfg.learner.replay_batch = 32
    cfg.learner.target_update_every = 10
    cfg.learner.replay_priority = mode
    return cfg


def _golden_env(cfg):
    series = synthetic_price_series(length=256, seed=7)
    return trading.env_from_prices(series.prices, window=cfg.env.window,
                                   initial_budget=cfg.env.initial_budget)


def _tbatch(n, obs_dim=5, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, obs_dim)).astype(np.float32),
            rng.integers(0, 3, n).astype(np.int32),
            rng.standard_normal(n).astype(np.float32),
            rng.standard_normal((n, obs_dim)).astype(np.float32))


# ---------------------------------------------------------------------------
# sum-tree properties
# ---------------------------------------------------------------------------

class TestSumTree:
    def test_leaf_count_power_of_two(self):
        assert sum_tree.leaf_count(1) == 1
        assert sum_tree.leaf_count(2) == 2
        assert sum_tree.leaf_count(3) == 4
        assert sum_tree.leaf_count(4096) == 4096
        assert sum_tree.leaf_count(4097) == 8192
        with pytest.raises(ValueError):
            sum_tree.leaf_count(0)

    def test_total_mass_exact_under_batched_updates(self):
        """After ANY update sequence — duplicates and masks included —
        every internal node equals the sum of its two children
        bit-for-bit, and the whole tree equals a from-scratch rebuild of
        its own leaves. (Exactness is what makes the stratified descent's
        residual-mass arithmetic safe.)"""
        rng = np.random.default_rng(0)
        cap = 256
        tree = sum_tree.from_leaves(
            jnp.asarray(rng.random(cap, dtype=np.float32)))
        for it in range(6):
            b = 32
            idx = rng.integers(0, cap, b).astype(np.int32)
            vals = (rng.random(b) * 3).astype(np.float32).copy()
            for i in range(b):   # duplicate indices carry identical values
                vals[i] = vals[np.flatnonzero(idx == idx[i])[0]]
            mask = jnp.asarray(rng.random(b) > 0.3)
            tree = sum_tree.set_priorities(
                tree, jnp.asarray(idx), jnp.asarray(vals), mask)
            levels = [np.asarray(l) for l in tree.levels]
            for k in range(1, len(levels)):
                paired = levels[k - 1].reshape(-1, 2)
                np.testing.assert_array_equal(
                    levels[k], paired[:, 0] + paired[:, 1],
                    err_msg=f"iteration {it}, level {k}")
            rebuilt = sum_tree.from_leaves(tree.leaves)
            for a, b2 in zip(tree.levels, rebuilt.levels):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b2))

    def test_masked_rows_leave_slots_untouched(self):
        tree = sum_tree.from_leaves(jnp.arange(1.0, 9.0))
        before = np.asarray(tree.leaves).copy()
        tree = sum_tree.set_priorities(
            tree, jnp.asarray([2, 5]), jnp.asarray([100.0, 200.0]),
            mask=jnp.asarray([False, True]))
        after = np.asarray(tree.leaves)
        assert after[2] == before[2]            # masked: untouched
        assert after[5] == 200.0                # unmasked: written
        assert float(tree.total) == float(after.sum())

    def test_sampled_frequencies_match_priorities(self):
        """Empirical stratified-sample frequencies converge to the
        normalized priorities (the PER sampling contract)."""
        priorities = np.zeros(64, np.float32)
        priorities[:16] = np.linspace(0.5, 8.0, 16, dtype=np.float32)
        tree = sum_tree.from_leaves(jnp.asarray(priorities))
        counts = np.zeros(64)
        batch, draws = 32, 300
        sample = jax.jit(lambda t, k: sum_tree.sample_stratified(t, k, batch))
        for d in range(draws):
            idx, probs = sample(tree, jax.random.PRNGKey(d))
            np.add.at(counts, np.asarray(idx), 1)
        freq = counts / counts.sum()
        expect = priorities / priorities.sum()
        # Within-band: absolute 2% everywhere, relative 15% on the
        # heavier-than-average leaves.
        np.testing.assert_allclose(freq, expect, atol=0.02)
        heavy = expect > expect.mean()
        np.testing.assert_allclose(freq[heavy], expect[heavy], rtol=0.15)

    def test_masked_leaves_never_sampled(self):
        """Zero-priority leaves — masked or never written — carry no mass
        and must never come back from the descent (the invalid-slot
        guarantee the replay buffer's size bound relies on)."""
        priorities = np.zeros(128, np.float32)
        live = np.asarray([1, 7, 31, 64, 100])
        priorities[live] = [1.0, 0.25, 3.0, 0.5, 2.0]
        tree = sum_tree.from_leaves(jnp.asarray(priorities))
        sample = jax.jit(lambda t, k: sum_tree.sample_stratified(t, k, 64))
        for d in range(50):
            idx, probs = sample(tree, jax.random.PRNGKey(d))
            assert np.isin(np.asarray(idx), live).all()
            assert (np.asarray(probs) > 0).all()

    def test_empty_tree_samples_gate_to_zero_prob(self):
        tree = sum_tree.create(32)
        idx, probs = sum_tree.sample_stratified(tree, jax.random.PRNGKey(0),
                                                8)
        assert (np.asarray(probs) == 0).all()

    def test_is_weights_normalized_and_zero_safe(self):
        probs = jnp.asarray([0.5, 0.25, 0.0, 0.125])
        w = np.asarray(sum_tree.is_weights(probs, jnp.int32(100),
                                           jnp.float32(0.5)))
        assert w.max() == pytest.approx(1.0)
        assert w[2] == 0.0                      # zero-prob row: 0, not inf
        # Lower probability -> larger weight (the bias correction).
        assert w[3] > w[1] > w[0]


# ---------------------------------------------------------------------------
# uniform default: bit-identical to the pre-data-plane commit
# ---------------------------------------------------------------------------

class TestUniformGolden:
    def test_trajectory_matches_pre_data_plane_golden(self):
        """The golden was captured at the commit BEFORE the replay data
        plane landed (same container, same jax): the default uniform
        sampler must reproduce params/opt/metrics EXACTLY."""
        with open(GOLDEN) as f:
            golden = json.load(f)["dqn"]
        cfg = _golden_cfg("uniform")
        env = _golden_env(cfg)
        agent = build_agent(cfg, env)
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(0))
        for i in range(2):
            ts, metrics = step(ts)
            got = {k: float(np.asarray(v))
                   for k, v in sorted(metrics.items())
                   if np.asarray(v).ndim == 0}
            assert got == golden["metrics"][i]
        assert _tree_digest(ts.params) == golden["params_sha256"]
        assert _tree_digest(ts.opt_state) == golden["opt_state_sha256"]
        assert _tree_digest(ts) == golden["state_sha256"]


# ---------------------------------------------------------------------------
# PER mode
# ---------------------------------------------------------------------------

class TestPerMode:
    def test_unknown_replay_priority_rejected(self):
        cfg = _golden_cfg("prioritized")   # not a valid value
        with pytest.raises(ConfigError, match="replay_priority"):
            build_agent(cfg, _golden_env(cfg))

    def test_capacity_at_most_batch_rejected(self):
        """A push spanning the whole circular buffer has implementation-
        defined slot winners (masked rows alias pos-1) — config error,
        both samplers."""
        for mode in ("uniform", "per"):
            cfg = _golden_cfg(mode)
            cfg.learner.replay_capacity = 4   # == num_workers
            with pytest.raises(ConfigError, match="replay_capacity"):
                build_agent(cfg, _golden_env(cfg))

    def test_per_step_invariants(self):
        """PER training runs: finite loss, the PER gauges in the metric
        dict, live slots carry positive priority, empty slots none, and
        the tree stays exactly consistent after real traced updates."""
        cfg = _golden_cfg("per")
        env = _golden_env(cfg)
        agent = build_agent(cfg, env)
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(0))
        for _ in range(3):
            ts, metrics = step(ts)
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["per_max_priority"]) >= 1.0
        assert 0.0 < float(metrics["per_beta"]) <= 1.0
        size = int(ts.extras.replay.size)
        leaves = np.asarray(ts.extras.per.tree.leaves)
        assert size > 0
        assert (leaves[:size] > 0).all()
        assert (leaves[size:] == 0).all()
        rebuilt = sum_tree.from_leaves(ts.extras.per.tree.leaves)
        for a, b in zip(ts.extras.per.tree.levels, rebuilt.levels):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_per_diverges_from_uniform(self):
        """The prioritized sampler must actually change training (same
        seed, same data — different sample distribution)."""
        outs = {}
        for mode in ("uniform", "per"):
            cfg = _golden_cfg(mode)
            agent = build_agent(cfg, _golden_env(cfg))
            step = jax.jit(agent.step)
            ts = agent.init(jax.random.PRNGKey(0))
            for _ in range(2):
                ts, _m = step(ts)
            outs[mode] = _tree_digest(ts.params)
        assert outs["uniform"] != outs["per"]

    def test_reseed_per_priorities(self):
        """The resume warm-start path: an out-of-band buffer fill reseeds
        live slots at max priority, empty slots at zero."""
        from sharetrade_tpu.agents.dqn import (
            fill_replay_from_arrays, reseed_per_priorities)
        cfg = _golden_cfg("per")
        agent = build_agent(cfg, _golden_env(cfg))
        ts = agent.init(jax.random.PRNGKey(0))
        obs, act, rew, nxt = _tbatch(40, obs_dim=cfg.env.window + 2)
        warm = fill_replay_from_arrays(ts.extras.replay, obs, act, rew, nxt)
        extras = reseed_per_priorities(ts.extras.replace(replay=warm))
        leaves = np.asarray(extras.per.tree.leaves)
        assert (leaves[:40] == float(extras.per.max_priority)).all()
        assert (leaves[40:] == 0).all()
        # Uniform extras pass through untouched.
        cfg_u = _golden_cfg("uniform")
        agent_u = build_agent(cfg_u, _golden_env(cfg_u))
        ts_u = agent_u.init(jax.random.PRNGKey(0))
        assert reseed_per_priorities(ts_u.extras) is ts_u.extras

    def test_per_beta_schedule(self):
        from sharetrade_tpu.agents.base import per_beta
        cfg = FrameworkConfig().learner
        assert float(per_beta(jnp.int32(0), cfg)) == pytest.approx(
            cfg.per_beta0)
        assert float(per_beta(jnp.int32(cfg.per_beta_steps), cfg)) == 1.0
        assert float(per_beta(jnp.int32(10 ** 9), cfg)) == 1.0

    def test_per_checkpoint_roundtrip_exact(self, tmp_path):
        from sharetrade_tpu.checkpoint import CheckpointManager
        cfg = _golden_cfg("per")
        agent = build_agent(cfg, _golden_env(cfg))
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = step(ts)
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
        mgr.save(1, ts)
        restored, _step = mgr.restore(agent.init(jax.random.PRNGKey(0)))
        for a, b in zip(jax.tree.leaves(ts), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# bounded journal: rotation, bounded tail reads, retirement
# ---------------------------------------------------------------------------

class TestSegmentRotation:
    def test_rotation_and_replay_order(self, tmp_journal_path):
        """Events split across sealed segments + the active file replay
        in exact append order."""
        with Journal(tmp_journal_path, segment_records=3) as j:
            for n in range(10):
                j.append({"n": n})
        assert len(segment_paths(tmp_journal_path)) == 3
        with Journal(tmp_journal_path, segment_records=3) as j:
            assert [e["n"] for e in j.replay()] == list(range(10))
            assert len(j) == 10

    def test_tail_reader_walks_only_tail_segments(self, tmp_journal_path):
        j = Journal(tmp_journal_path, segment_records=2)
        for i in range(10):
            append_transitions(j, *_tbatch(2, seed=i), env_steps=i + 1)
        j.flush()
        tail = read_tail_transitions(tmp_journal_path, 4, journal=j)
        obs, act, rew, nxt, high = tail
        assert obs.shape[0] == 4               # newest two records only
        assert high == 10
        # Unbounded read still sees everything, oldest-first.
        full = read_tail_transitions(tmp_journal_path, 0, journal=j)
        assert full[0].shape[0] == 20
        np.testing.assert_array_equal(full[0][-2:], obs[-2:])
        # Cutoff filtering splits across segment boundaries.
        cut = read_tail_transitions(tmp_journal_path, 0,
                                    cutoff_env_steps=5, journal=j)
        assert cut[0].shape[0] == 10 and cut[4] == 10
        j.close()

    def test_retirement_keeps_horizon_and_frees_bytes(self, tmp_journal_path):
        j = Journal(tmp_journal_path, segment_records=2)
        for i in range(12):
            append_transitions(j, *_tbatch(2, seed=i), env_steps=i + 1)
        j.flush()
        seals_before = segment_paths(tmp_journal_path)
        retired, freed = retire_transition_segments(j, keep_rows=6)
        assert retired > 0 and freed > 0
        kept = segment_paths(tmp_journal_path)
        # Never a segment newer than the horizon: the kept set is a
        # SUFFIX of the pre-retirement order, covering >= keep_rows.
        assert kept == seals_before[len(seals_before) - len(kept):]
        rows_kept = (count_transition_rows(tmp_journal_path)
                     + sum(count_transition_rows(p) for p in kept))
        assert rows_kept >= 6
        # The tail (and its high-water) still reads cleanly.
        tail = read_tail_transitions(tmp_journal_path, 0, journal=j)
        assert tail[4] == 12
        # Idempotent once within budget.
        assert retire_transition_segments(j, keep_rows=6)[0] == 0
        j.close()

    def test_compact_payloads_removes_sealed_segments(self, tmp_journal_path):
        """Whole-journal compaction (the orchestrator's fresh-run
        truncation) supersedes sealed segments too."""
        with Journal(tmp_journal_path, segment_records=2) as j:
            for n in range(7):
                j.append({"n": n})
            assert segment_paths(tmp_journal_path)
            j.compact([])
            assert segment_paths(tmp_journal_path) == []
            assert list(j.replay()) == []
            j.append({"n": "post"})
            assert [e["n"] for e in j.replay()] == ["post"]

    def test_torn_tail_property_in_newest_segment(self, tmp_journal_path):
        """Crash the journal at EVERY byte offset of the NEWEST (active)
        segment: recovery must always yield the sealed segments' records
        plus an exact prefix of the active segment — never garbage, never
        a lost sealed record — and appends must continue cleanly."""
        events = [{"n": n, "pad": "x" * (n * 7 % 23)} for n in range(11)]
        with Journal(tmp_journal_path, segment_records=4,
                     fsync_every_records=3) as j:
            for e in events:
                j.append(e)
        seals = segment_paths(tmp_journal_path)
        assert seals                       # rotation actually happened
        # Count sealed records by walking only the sealed files.
        from sharetrade_tpu.data.journal import iter_framed_records
        sealed_records = sum(1 for p in seals
                             for _ in iter_framed_records(p))
        blob = open(tmp_journal_path, "rb").read()
        for cut in range(len(blob) + 1):
            with open(tmp_journal_path, "wb") as f:
                f.write(blob[:cut])
            with Journal(tmp_journal_path, segment_records=4,
                         fsync_every_records=3) as j:
                recovered = list(j.replay())
                # Exact prefix: all sealed events, then a prefix of the
                # active segment's.
                assert recovered == events[:len(recovered)]
                assert len(recovered) >= sealed_records
                j.append({"n": "post-crash"})
                j.flush()
                assert list(j.replay())[-1] == {"n": "post-crash"}

    def test_compact_transitions_on_segmented_journal_retires(
            self, tmp_journal_path):
        """The public compact_transitions must not destroy sealed history:
        on a segmented journal it delegates to segment retirement (the
        keep_rows horizon holds; the active-file-only rewrite would have
        deleted every sealed segment)."""
        from sharetrade_tpu.data.transitions import compact_transitions
        j = Journal(tmp_journal_path, segment_records=2)
        for i in range(10):
            append_transitions(j, *_tbatch(2, seed=i), env_steps=i + 1)
        j.flush()
        assert compact_transitions(j, keep_rows=6)
        tail = read_tail_transitions(tmp_journal_path, 0, journal=j)
        assert tail[0].shape[0] >= 6          # horizon survived
        assert tail[4] == 10
        j.close()

    def test_legacy_json_events_survive_rotation(self, tmp_journal_path):
        """Migration path: a pre-rotation journal holding legacy JSON
        'transitions' events gets sealed into a segment once rotation is
        enabled — the warm-start scan must still find them."""
        from sharetrade_tpu.agents.dqn import (ReplayBuffer,
                                               fill_replay_from_journal)
        with Journal(tmp_journal_path) as j:      # legacy, no rotation
            j.append({"type": "transitions", "env_steps": 5,
                      "obs": [[1.0, 2.0]], "action": [1],
                      "reward": [0.5], "next_obs": [[2.0, 3.0]]})
        j2 = Journal(tmp_journal_path, segment_records=1)
        j2.append({"type": "other"})              # triggers a seal
        j2.flush()
        assert segment_paths(tmp_journal_path)
        warm = fill_replay_from_journal(ReplayBuffer.create(8, 2), j2)
        assert int(warm.size) == 1
        np.testing.assert_allclose(np.asarray(warm.obs[0]), [1.0, 2.0])
        j2.close()

    def test_reopen_continues_rotation(self, tmp_journal_path):
        j = Journal(tmp_journal_path, segment_records=2)
        for n in range(3):
            j.append({"n": n})
        j.close()
        j2 = Journal(tmp_journal_path, segment_records=2)
        for n in range(3, 6):
            j2.append({"n": n})
        j2.close()
        assert len(segment_paths(tmp_journal_path)) >= 2
        with Journal(tmp_journal_path) as j3:
            assert [e["n"] for e in j3.replay()] == list(range(6))


# ---------------------------------------------------------------------------
# streaming ingest
# ---------------------------------------------------------------------------

class TestStreamingIngest:
    def test_tail_parity_with_batch_csv_load(self, tmp_path):
        """Consuming the feed in arbitrary chunks — mid-line cuts
        included — converges to exactly the one-shot CSV load."""
        from sharetrade_tpu.data.ingest import load_price_csv
        from sharetrade_tpu.data.service import (FileTailFeed,
                                                 PriceDataService)
        series = synthetic_price_series(symbol="MSFT", length=80, seed=3)
        feed_path = str(tmp_path / "MSFT.feed")
        blob = "".join(f"{float(p)}, {d}\n"
                       for d, p in zip(series.dates,
                                       series.prices)).encode()
        svc = PriceDataService(
            journal=Journal(str(tmp_path / "ev.journal")),
            provider=lambda s, a, b: series)
        svc.attach_feed("MSFT", FileTailFeed(feed_path))
        cuts = sorted({0, 7, 33, 120, 456, len(blob) // 2, len(blob)})
        rows = 0
        for a, b in zip(cuts, cuts[1:]):
            with open(feed_path, "ab") as f:
                f.write(blob[a:b])
            rows += len(svc.tail("MSFT").series)
        assert len(svc.tail("MSFT").series) == 0   # quiet feed: no delta
        merged = svc.request("MSFT").series
        batch = load_price_csv(feed_path, symbol="MSFT")
        np.testing.assert_array_equal(merged.dates, batch.dates)
        np.testing.assert_allclose(merged.prices, batch.prices)
        assert rows == len(batch)
        svc.close()
        # Recovery: the streamed rows came back from the JOURNAL, with
        # no feed and a provider that must not be called.
        def no_fetch(s, a, b):
            raise AssertionError("recovery must not fetch")
        svc2 = PriceDataService(
            journal=Journal(str(tmp_path / "ev.journal")),
            provider=no_fetch)
        np.testing.assert_array_equal(
            svc2.request("MSFT").series.dates, batch.dates)
        svc2.close()

    def test_restart_does_not_reingest_recovered_rows(self, tmp_path):
        """A restarted consumer's feed offset resets to zero, but rows
        the journal already recovered must NOT come back as delta (nor
        be re-journaled) — only rows appended while the process was
        down do."""
        from sharetrade_tpu.data.service import (FileTailFeed,
                                                 PriceDataService,
                                                 append_feed_rows)
        series = synthetic_price_series(symbol="MSFT", length=30, seed=3)
        feed_path = str(tmp_path / "MSFT.feed")
        jpath = str(tmp_path / "ev.journal")
        first, rest = series.range(end=str(series.dates[19])), series.range(
            start=str(series.dates[20]))
        append_feed_rows(feed_path, first)
        svc = PriceDataService(journal=Journal(jpath),
                               provider=lambda s, a, b: None)
        svc.attach_feed("MSFT", FileTailFeed(feed_path))
        assert len(svc.tail("MSFT").series) == 20
        svc.close()
        # "Restart": new process state, same journal, fresh feed reader;
        # ten new rows landed while it was down.
        append_feed_rows(feed_path, rest)
        svc2 = PriceDataService(journal=Journal(jpath),
                                provider=lambda s, a, b: None)
        svc2.attach_feed("MSFT", FileTailFeed(feed_path))
        delta = svc2.tail("MSFT").series
        assert len(delta) == 10                   # only the new rows
        np.testing.assert_array_equal(delta.dates, rest.dates)
        assert len(svc2.tail("MSFT").series) == 0
        merged = svc2.request("MSFT").series
        np.testing.assert_array_equal(merged.dates, series.dates)
        svc2.close()

    def test_missing_feed_and_unattached_symbol(self, tmp_path):
        from sharetrade_tpu.data.service import (FileTailFeed,
                                                 PriceDataService)
        svc = PriceDataService(journal=Journal(str(tmp_path / "j")),
                               provider=lambda s, a, b: None)
        with pytest.raises(ValueError, match="feed"):
            svc.tail("MSFT")
        svc.attach_feed("MSFT", FileTailFeed(str(tmp_path / "nope.feed")))
        assert len(svc.tail("MSFT").series) == 0   # absent file: empty delta
        svc.close()

    def test_feed_path_config_substitutes_symbol(self, tmp_path):
        from sharetrade_tpu.config import DataConfig
        from sharetrade_tpu.data.service import (PriceDataService,
                                                 append_feed_rows)
        series = synthetic_price_series(symbol="GOOG", length=10, seed=5)
        append_feed_rows(str(tmp_path / "GOOG.feed"), series)
        cfg = DataConfig(feed_path=str(tmp_path / "{symbol}.feed"),
                         journal_dir=str(tmp_path))
        svc = PriceDataService(journal=Journal(str(tmp_path / "j")),
                               provider=lambda s, a, b: None, config=cfg)
        delta = svc.tail("GOOG")
        assert len(delta.series) == 10
        np.testing.assert_allclose(delta.series.prices, series.prices)
        svc.close()


# ---------------------------------------------------------------------------
# orchestrator integration: journaled DQN with rotation, bounded resume
# ---------------------------------------------------------------------------

class TestOrchestratorReplayPlane:
    def _cfg(self, tmp_path, mode):
        cfg = FrameworkConfig()
        cfg.learner.algo = "dqn"
        cfg.learner.journal_replay = True
        cfg.learner.replay_priority = mode
        cfg.learner.replay_capacity = 128
        cfg.learner.replay_batch = 16
        cfg.parallel.num_workers = 4
        cfg.env.window = 8
        cfg.model.hidden_dim = 8
        cfg.runtime.chunk_steps = 8
        cfg.runtime.episodes = 3
        cfg.runtime.checkpoint_every_updates = 32
        cfg.runtime.checkpoint_dir = str(tmp_path / f"ck-{mode}")
        cfg.runtime.keep_best_eval = False
        cfg.data.journal_dir = str(tmp_path / f"journal-{mode}")
        cfg.data.use_native_journal = False
        cfg.data.async_transition_writer = False
        cfg.data.journal_segment_records = 4
        cfg.data.journal_fsync_every_records = 1
        return cfg

    @pytest.mark.parametrize("mode", ["uniform", "per"])
    def test_rotation_resume_and_gauges(self, tmp_path, mode):
        from sharetrade_tpu.runtime.orchestrator import Orchestrator
        cfg = self._cfg(tmp_path, mode)
        prices = synthetic_price_series(length=72, seed=1).prices
        orch = Orchestrator(cfg)
        orch.send_training_data(prices)
        orch.start_training(background=False)
        from sharetrade_tpu.runtime.lifecycle import Phase
        assert orch.lifecycle.phase is Phase.COMPLETED
        jpath = os.path.join(cfg.data.journal_dir, "transitions.journal")
        assert segment_paths(jpath), "rotation never sealed a segment"
        assert (orch.metrics.latest("journal_segments") or 0) >= 1
        orch.stop()

        # Resume: the warm start reads only the tail segments and (in per
        # mode) reseeds the sum-tree over the recovered rows.
        orch2 = Orchestrator(cfg)
        orch2.send_training_data(prices, resume=True)
        size = int(orch2._ts.extras.replay.size)
        assert size > 0
        if mode == "per":
            leaves = np.asarray(orch2._ts.extras.per.tree.leaves)
            assert (leaves[:size] > 0).all()
            assert (leaves[size:] == 0).all()
        orch2.stop()


# ---------------------------------------------------------------------------
# guards: lint check 9, perf-gate direction, cli obs section
# ---------------------------------------------------------------------------

class TestGuards:
    def test_lint_replay_device_path_clean(self):
        import lint_hot_loop
        hits, found = lint_hot_loop.lint_replay_device_path()
        assert hits == [], f"replay device-path lint hits: {hits}"
        required = (set(lint_hot_loop.REPLAY_TREE_FUNCS)
                    | set(lint_hot_loop.REPLAY_DQN_FUNCS)
                    | set(lint_hot_loop.REPLAY_CONSUMER_FUNCS))
        assert required <= found

    def test_lint_replay_pattern_semantics(self):
        import lint_hot_loop
        pat = lint_hot_loop.REPLAY_BLOCK_PATTERN
        assert pat.search("os.fsync(fd)")
        assert pat.search("np.random.uniform(0, 1)")
        assert pat.search("random.random()")
        assert pat.search("journal.append({})")
        assert pat.search("j.append_bytes(payload)")
        assert pat.search("open(path)")
        # jax.random stays legal; dotted open too.
        assert not pat.search("jax.random.split(key)")
        assert not pat.search("k = jax.random.uniform(key, (3,))")

    def test_perf_gate_direction_for_replay_metrics(self):
        from perf_gate import gate, lower_is_better
        assert lower_is_better("journal_bytes_per_record")
        assert lower_is_better("replay_sample_ms")
        assert not lower_is_better("replay_per_steps_per_sec")

        def series(metric, *vals):
            return {(metric, "cpu", "fp32", "value"): [
                {"round": i, "path": f"r{i}", "value": v}
                for i, v in enumerate(vals)]}

        # Bytes/record RISE past the band fails; a drop passes.
        assert not gate(series("journal_bytes_per_record", 100.0, 140.0),
                        {"value": 0.25})["ok"]
        assert gate(series("journal_bytes_per_record", 100.0, 60.0),
                    {"value": 0.25})["ok"]
        # Replay throughput DROP past the band fails.
        assert not gate(series("replay_per_steps_per_sec", 1000.0, 700.0),
                        {"value": 0.25})["ok"]

    def test_cli_obs_replay_section(self, tmp_path):
        from sharetrade_tpu.obs import summarize_run_dir
        run_dir = tmp_path / "obs"
        run_dir.mkdir()
        record = {"ts": 0.0,
                  "gauges": {"replay_size": 128.0, "per_max_priority": 2.5,
                             "per_beta": 0.6, "journal_segments": 3.0},
                  "counters": {"journal_compacted_bytes_total": 4096.0,
                               "journal_segments_retired_total": 2.0}}
        (run_dir / "metrics.jsonl").write_text(json.dumps(record) + "\n")
        summary = summarize_run_dir(str(run_dir))
        replay = summary["replay"]
        assert replay["replay_size"] == 128.0
        assert replay["per_max_priority"] == 2.5
        assert replay["journal_segments"] == 3.0
        assert replay["journal_compacted_bytes_total"] == 4096.0
        assert replay["journal_segments_retired_total"] == 2.0
