"""Multi-host (DCN) bring-up gating — the reference's dormant remoting tier
(build.sbt:13 akka-remote on the classpath, README.md:13 "Akka Clustering
will come later") made explicit and testable.

A REAL 2-process smoke is environmentally blocked here: this host's
interpreter startup binds jax to the single tunneled TPU chip
(JAX_PLATFORMS=cpu is overridden), so two distributed processes would both
claim the same chip. These tests therefore pin the *gating contract* of
``init_distributed`` — which tier fires, with which arguments, and its
idempotence — against a recorded ``jax.distributed.initialize``; the
documented bring-up recipe lives in its docstring (parallel/mesh.py).
"""

import pytest

from sharetrade_tpu.parallel import init_distributed
from sharetrade_tpu.parallel import mesh as mesh_mod


class _Recorder:
    def __init__(self):
        self.calls = []

    def __call__(self, **kwargs):
        self.calls.append(kwargs)


@pytest.fixture
def recorded_initialize(monkeypatch):
    rec = _Recorder()
    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", rec)
    # Ensure the idempotence guard sees "not yet initialized".
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "is_initialized", lambda: False)
    for var in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    return rec


class TestInitDistributedGating:
    def test_single_process_noop(self, recorded_initialize):
        assert init_distributed() is False
        assert recorded_initialize.calls == []

    def test_env_var_triggers_initialize(self, recorded_initialize,
                                         monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        assert init_distributed() is True
        assert recorded_initialize.calls == [{}]  # env-discovered

    def test_megascale_env_var_triggers_initialize(self, recorded_initialize,
                                                   monkeypatch):
        monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        assert init_distributed() is True
        assert recorded_initialize.calls == [{}]

    def test_explicit_args_take_precedence(self, recorded_initialize,
                                           monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "ignored:1")
        init_distributed("host0:8476", num_processes=2, process_id=1)
        assert recorded_initialize.calls == [{
            "coordinator_address": "host0:8476",
            "num_processes": 2, "process_id": 1}]

    def test_idempotent_after_bringup(self, recorded_initialize, monkeypatch):
        # Simulate an already-initialized runtime: no second initialize.
        monkeypatch.setattr(
            mesh_mod.jax.distributed, "is_initialized", lambda: True)
        init_distributed("host0:8476", num_processes=2, process_id=0)
        assert recorded_initialize.calls == []
