"""Multi-host (DCN) bring-up — the reference's dormant remoting tier
(build.sbt:13 akka-remote on the classpath, README.md:13 "Akka Clustering
will come later") made explicit, testable, AND runnable.

Two tiers of coverage:

- ``TestInitDistributedGating`` pins the gating contract of
  ``init_distributed`` (which tier fires, with which arguments, idempotence)
  against a recorded ``jax.distributed.initialize``.
- ``TestTwoProcessSmoke`` runs the real thing: two OS processes, each its
  own jax runtime (CPU backend, gloo standing in for DCN), brought up via
  ``init_distributed`` and running sharded PPO training chunks over a dp
  mesh that SPANS the processes (tools/dist_smoke_worker.py). The in-process
  interpreter here is bound to the tunneled TPU chip by the site hook, so
  the children scrub that hook's trigger from their environment and run
  CPU-only — the same code path a real multi-host TPU pod takes, with DCN
  collectives swapped for gloo.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from sharetrade_tpu.parallel import init_distributed
from sharetrade_tpu.parallel import mesh as mesh_mod

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO_ROOT, "tools", "dist_smoke_worker.py")


class _Recorder:
    def __init__(self):
        self.calls = []

    def __call__(self, **kwargs):
        self.calls.append(kwargs)


@pytest.fixture
def recorded_initialize(monkeypatch):
    rec = _Recorder()
    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", rec)
    # Ensure the idempotence guard sees "not yet initialized". Patched at
    # the framework's version-portable probe, NOT at
    # jax.distributed.is_initialized: the 0.4.x line on this container
    # has no such attribute, and patching it errored every test in this
    # tier at setup since seed (the same AttributeError the probe now
    # shields init_distributed itself from).
    monkeypatch.setattr(
        mesh_mod, "_distributed_initialized", lambda: False)
    for var in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    return rec


class TestInitDistributedGating:
    def test_single_process_noop(self, recorded_initialize):
        assert init_distributed() is False
        assert recorded_initialize.calls == []

    def test_env_var_triggers_initialize(self, recorded_initialize,
                                         monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        assert init_distributed() is True
        assert recorded_initialize.calls == [{}]  # env-discovered

    def test_megascale_env_var_triggers_initialize(self, recorded_initialize,
                                                   monkeypatch):
        monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        assert init_distributed() is True
        assert recorded_initialize.calls == [{}]

    def test_explicit_args_take_precedence(self, recorded_initialize,
                                           monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "ignored:1")
        init_distributed("host0:8476", num_processes=2, process_id=1)
        assert recorded_initialize.calls == [{
            "coordinator_address": "host0:8476",
            "num_processes": 2, "process_id": 1}]

    def test_idempotent_after_bringup(self, recorded_initialize, monkeypatch):
        # Simulate an already-initialized runtime: no second initialize.
        monkeypatch.setattr(
            mesh_mod, "_distributed_initialized", lambda: True)
        init_distributed("host0:8476", num_processes=2, process_id=0)
        assert recorded_initialize.calls == []


@pytest.mark.slow
class TestTwoProcessSmoke:
    """The multi-process training path, executed for real (not mocked)."""

    NPROC = 2

    def _spawn(self, pid: int, port: int, model: str) -> subprocess.Popen:
        env = dict(os.environ)
        # Scrub the site hook's trigger so the child's jax never registers
        # the axon TPU plugin (two processes cannot share the one chip), and
        # drop the parent's 8-virtual-device flag: one CPU device per
        # process makes the global mesh genuinely cross-process.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = ""
        return subprocess.Popen(
            [sys.executable, WORKER, f"127.0.0.1:{port}",
             str(self.NPROC), str(pid), model],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO_ROOT)

    @pytest.mark.parametrize("model", ["mlp", "transformer_episode"])
    def test_sharded_training_across_processes(self, model):
        """Both the MLP family and the flagship episode transformer cross
        the process boundary: for the latter the representative-row trunk
        broadcast and the shared-trunk replay's collectives run over a dp
        mesh spanning two OS processes, which single-process meshes never
        exercise."""
        with socket.socket() as s:  # reserve a free coordinator port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [self._spawn(pid, port, model) for pid in range(self.NPROC)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=600)
                assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
                outs.append(json.loads(out.strip().splitlines()[-1]))
        finally:
            # A failed/timed-out rank must not leak its peer: the survivor
            # blocks forever in the gloo/coordinator barrier, holding the
            # port and hanging the run.
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        assert sorted(o["process_id"] for o in outs) == [0, 1]
        for o in outs:
            assert o["process_count"] == self.NPROC
            assert o["num_devices"] == self.NPROC
            assert o["env_steps"] > 0
        # The dp gradient all-reduce crossed the process boundary and both
        # replicas hold identical post-update parameters.
        assert outs[0]["param_sum"] == outs[1]["param_sum"]
        assert outs[0]["env_steps"] == outs[1]["env_steps"]
