"""Interop tests: the C++ journal backend must be byte-compatible with the
pure-Python one (same framed on-disk format, same torn-tail recovery)."""

import os

import pytest

from sharetrade_tpu.data.journal import Journal
from sharetrade_tpu.data.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native journal not built (make -C native)"
)


def _native(path):
    from sharetrade_tpu.data.native import NativeJournal
    return NativeJournal(path)


def test_python_writes_native_reads(tmp_journal_path):
    with Journal(tmp_journal_path) as j:
        j.append({"n": 1})
        j.append({"n": 2, "s": "héllo"})
    with _native(tmp_journal_path) as nj:
        assert list(nj.replay()) == [{"n": 1}, {"n": 2, "s": "héllo"}]


def test_native_writes_python_reads(tmp_journal_path):
    with _native(tmp_journal_path) as nj:
        nj.append({"a": [1, 2, 3]})
        nj.append({"b": True})
    with Journal(tmp_journal_path) as j:
        assert list(j.replay()) == [{"a": [1, 2, 3]}, {"b": True}]


def test_native_torn_tail_recovery(tmp_journal_path):
    with _native(tmp_journal_path) as nj:
        nj.append({"n": 1})
        nj.append({"n": 2})
    size = os.path.getsize(tmp_journal_path)
    with open(tmp_journal_path, "r+b") as f:
        f.truncate(size - 3)
    # Native open truncates the torn tail and appends continue cleanly.
    with _native(tmp_journal_path) as nj:
        assert [e["n"] for e in nj.replay()] == [1]
        nj.append({"n": 3})
        assert [e["n"] for e in nj.replay()] == [1, 3]
    # And the Python backend agrees on the final bytes.
    with Journal(tmp_journal_path) as j:
        assert [e["n"] for e in j.replay()] == [1, 3]


def test_native_corrupt_length_header_is_torn_tail(tmp_journal_path):
    # A garbage header whose length field claims ~4GB must be treated as a
    # torn tail, not allocated (a bad_alloc would abort the whole process).
    with _native(tmp_journal_path) as nj:
        nj.append({"n": 1})
    with open(tmp_journal_path, "ab") as f:
        f.write(b"\xf0\xff\xff\xff" + b"\xde\xad\xbe\xef" + b"xx")
    with _native(tmp_journal_path) as nj:
        assert [e["n"] for e in nj.replay()] == [1]
        nj.append({"n": 2})
        assert [e["n"] for e in nj.replay()] == [1, 2]
