"""Interop tests: the C++ journal backend must be byte-compatible with the
pure-Python one (same framed on-disk format, same torn-tail recovery)."""

import os

import pytest

from sharetrade_tpu.data.journal import Journal
from sharetrade_tpu.data.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native journal not built (make -C native)"
)


def _native(path):
    from sharetrade_tpu.data.native import NativeJournal
    return NativeJournal(path)


def test_python_writes_native_reads(tmp_journal_path):
    with Journal(tmp_journal_path) as j:
        j.append({"n": 1})
        j.append({"n": 2, "s": "héllo"})
    with _native(tmp_journal_path) as nj:
        assert list(nj.replay()) == [{"n": 1}, {"n": 2, "s": "héllo"}]


def test_native_writes_python_reads(tmp_journal_path):
    with _native(tmp_journal_path) as nj:
        nj.append({"a": [1, 2, 3]})
        nj.append({"b": True})
    with Journal(tmp_journal_path) as j:
        assert list(j.replay()) == [{"a": [1, 2, 3]}, {"b": True}]


def test_native_torn_tail_recovery(tmp_journal_path):
    with _native(tmp_journal_path) as nj:
        nj.append({"n": 1})
        nj.append({"n": 2})
    size = os.path.getsize(tmp_journal_path)
    with open(tmp_journal_path, "r+b") as f:
        f.truncate(size - 3)
    # Native open truncates the torn tail and appends continue cleanly.
    with _native(tmp_journal_path) as nj:
        assert [e["n"] for e in nj.replay()] == [1]
        nj.append({"n": 3})
        assert [e["n"] for e in nj.replay()] == [1, 3]
    # And the Python backend agrees on the final bytes.
    with Journal(tmp_journal_path) as j:
        assert [e["n"] for e in j.replay()] == [1, 3]


def test_native_corrupt_length_header_is_torn_tail(tmp_journal_path):
    # A garbage header whose length field claims ~4GB must be treated as a
    # torn tail, not allocated (a bad_alloc would abort the whole process).
    with _native(tmp_journal_path) as nj:
        nj.append({"n": 1})
    with open(tmp_journal_path, "ab") as f:
        f.write(b"\xf0\xff\xff\xff" + b"\xde\xad\xbe\xef" + b"xx")
    with _native(tmp_journal_path) as nj:
        assert [e["n"] for e in nj.replay()] == [1]
        nj.append({"n": 2})
        assert [e["n"] for e in nj.replay()] == [1, 2]


class TestAsyncWriter:
    """C++ background-thread writer (stj_writer_*): appends become queue
    copies; flush/close/compaction quiesce; the on-disk format stays the
    shared framed log."""

    def _async(self, path, **kw):
        from sharetrade_tpu.data.native import (
            AsyncNativeJournal, async_writer_available)
        if not async_writer_available():
            pytest.skip("async writer not in built .so (make -C native)")
        return AsyncNativeJournal(path, **kw)

    def test_append_flush_read_roundtrip(self, tmp_journal_path):
        with self._async(tmp_journal_path) as aj:
            for n in range(200):
                aj.append({"n": n})
            aj.flush()
            # Python backend reads the flushed bytes directly.
            with Journal(tmp_journal_path) as j:
                assert [e["n"] for e in j.replay()] == list(range(200))
            assert [e["n"] for e in aj.replay()] == list(range(200))

    def test_close_drains_queue(self, tmp_journal_path):
        aj = self._async(tmp_journal_path)
        payload = os.urandom(4096)
        for _ in range(500):
            aj.append_bytes(b"STR0" + payload)   # ~2 MB queued
        aj.close()                               # must drain, not drop
        from sharetrade_tpu.data.journal import iter_framed_records
        records = list(iter_framed_records(tmp_journal_path))
        assert len(records) == 500

    def test_bounded_queue_backpressure(self, tmp_journal_path):
        # A queue budget smaller than one burst: submits must block (not
        # fail, not drop) until the worker drains.
        with self._async(tmp_journal_path, max_queue_bytes=64 << 10) as aj:
            chunk = os.urandom(16 << 10)
            for _ in range(64):                  # 1 MB through a 64 KB queue
                aj.append_bytes(chunk)
            aj.flush()
        from sharetrade_tpu.data.journal import iter_framed_records
        assert len(list(iter_framed_records(tmp_journal_path))) == 64

    def test_write_error_poisons_writer_and_preserves_torn_tail(
            self, tmp_journal_path, tmp_path):
        """After a background write error the writer must go sticky-error and
        STOP appending: frames written past a partially-written (torn) frame
        would be invisible to the framed reader, which stops at the first
        corrupt record. Forced via RLIMIT_FSIZE in a subprocess (writes past
        the cap fail with EFBIG once SIGXFSZ is ignored)."""
        import subprocess
        import sys
        import textwrap
        self._async(str(tmp_path / "probe.journal")).close()  # skip-if-unbuilt
        script = textwrap.dedent("""
            import resource, signal, sys
            sys.path.insert(0, sys.argv[2])
            from sharetrade_tpu.data.native import AsyncNativeJournal
            signal.signal(signal.SIGXFSZ, signal.SIG_IGN)
            aj = AsyncNativeJournal(sys.argv[1])
            aj.append_bytes(b"A" * 64)
            aj.flush()                          # below the cap: lands
            resource.setrlimit(resource.RLIMIT_FSIZE, (4096, resource.getrlimit(
                resource.RLIMIT_FSIZE)[1]))
            aj.append_bytes(b"B" * 16384)       # blows the cap mid-frame
            try:
                aj.flush()
                sys.exit(3)                     # error must surface
            except OSError:
                pass
            try:
                aj.append_bytes(b"C" * 64)      # sticky error or drained-drop
            except OSError:
                pass
            try:
                aj.close()
            except OSError:
                pass
            print("POISONED_OK")
        """)
        proc = subprocess.run(
            [sys.executable, "-c", script, tmp_journal_path,
             os.path.dirname(os.path.dirname(os.path.abspath(__file__)))],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "POISONED_OK" in proc.stdout
        # Recovery sees the pre-error record; the post-error "C" frame was
        # dropped, NOT appended past the torn "B" frame (where the framed
        # reader would never reach it).
        from sharetrade_tpu.data.journal import iter_framed_records
        payloads = [p for _o, p in iter_framed_records(tmp_journal_path)]
        assert payloads and payloads[0] == b"A" * 64
        assert b"C" * 64 not in payloads

    def test_compaction_quiesces_and_resumes(self, tmp_journal_path):
        with self._async(tmp_journal_path) as aj:
            for n in range(10):
                aj.append({"n": n})
            aj.compact([{"n": 9}])
            aj.append({"n": 10})
            assert [e["n"] for e in aj.replay()] == [9, 10]
        with Journal(tmp_journal_path) as j:
            assert [e["n"] for e in j.replay()] == [9, 10]

    def test_torn_tail_recovery_on_open(self, tmp_journal_path):
        with self._async(tmp_journal_path) as aj:
            aj.append({"n": 1})
        with open(tmp_journal_path, "ab") as f:
            f.write(b"\x55\x00\x00\x00garbage")
        with self._async(tmp_journal_path) as aj:
            aj.append({"n": 2})
            assert [e["n"] for e in aj.replay()] == [1, 2]

    def test_transitions_through_async_writer(self, tmp_journal_path):
        import numpy as np
        from sharetrade_tpu.data.transitions import (
            append_transitions, read_tail_transitions)
        with self._async(tmp_journal_path) as aj:
            obs = np.arange(12, dtype=np.float32).reshape(3, 4)
            append_transitions(aj, obs, np.array([0, 1, 2], np.int32),
                               np.array([1.0, 2.0, 3.0], np.float32),
                               obs + 1.0, env_steps=7)
            aj.flush()
            tail = read_tail_transitions(tmp_journal_path, 10)
        assert tail is not None
        np.testing.assert_array_equal(tail[0], obs)
        assert tail[4] == 7

    def test_oversized_payload_does_not_deadlock(self, tmp_journal_path):
        # One payload bigger than the whole queue budget must be admitted
        # when the queue is empty, not wait on an unsatisfiable predicate.
        with self._async(tmp_journal_path, max_queue_bytes=1024) as aj:
            aj.append_bytes(os.urandom(4096))
            aj.flush()
        from sharetrade_tpu.data.journal import iter_framed_records
        assert len(list(iter_framed_records(tmp_journal_path))) == 1

    def test_compaction_sees_queued_records(self, tmp_journal_path):
        # compact_transitions over an async journal must quiesce the writer
        # first: the keep-boundary computed from a stale on-disk snapshot
        # would otherwise drop records still in the queue.
        import numpy as np
        from sharetrade_tpu.data.transitions import (
            append_transitions, compact_transitions, read_tail_transitions)
        with self._async(tmp_journal_path) as aj:
            obs = np.ones((4, 3), np.float32)
            for n in range(8):
                append_transitions(aj, obs * n, np.zeros(4, np.int32),
                                   np.zeros(4, np.float32), obs,
                                   env_steps=n + 1)
            # No flush: records may still be queued when compaction runs.
            compact_transitions(aj, keep_rows=16)   # keep last 4 records
            tail = read_tail_transitions(tmp_journal_path, 0)
        assert tail is not None
        assert tail[4] == 8            # newest record survived
        assert tail[0].shape[0] == 16  # exactly the kept tail
