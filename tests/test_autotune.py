"""Self-tuning runtime (ISSUE 14): tuned profile layer, the online serve
controller's state machine (fake clock, no threads), the adaptive ingest
cadence, lint check 13, and the quick end-to-end sweep.

The controller tests drive :class:`ServeController` through a STUB engine
with a fake clock and synthetic objective series, so the state-machine
contract — bounded step sizes, the hysteresis dead band (no oscillation
on a noisy p99), the overload relax-veto, the rate limit — is pinned
deterministically, independent of host scheduling.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from sharetrade_tpu import tuning
from sharetrade_tpu.config import ConfigError, FrameworkConfig, ServeConfig
from sharetrade_tpu.obs.hist import Histogram
from sharetrade_tpu.serve.controller import ServeController
from sharetrade_tpu.serve.engine import _LiveKnobs
from sharetrade_tpu.utils.metrics import MetricsRegistry


# ---------------------------------------------------------------------------
# profile layer
# ---------------------------------------------------------------------------


def _write_profile(tmp_path, knobs, **kw):
    path = str(tmp_path / "tuned_profile.json")
    tuning.write_profile(path, tuning.build_profile(knobs, **kw))
    return path


class TestTunedProfile:
    def test_roundtrip_and_atomic_write(self, tmp_path):
        path = _write_profile(tmp_path, {"serve.batch_timeout_ms": 0.5},
                              seed=3, objectives={"serve": {"qps": 1.0}})
        doc = tuning.load_profile(path)
        assert doc["knobs"] == {"serve.batch_timeout_ms": 0.5}
        assert doc["schema_version"] == tuning.PROFILE_SCHEMA_VERSION
        assert doc["seed"] == 3
        # Atomic publish: no tmp debris next to the profile.
        assert [p.name for p in tmp_path.iterdir()] == [
            "tuned_profile.json"]

    def test_unknown_knob_refused_at_build(self):
        with pytest.raises(tuning.ProfileError, match="unregistered"):
            tuning.build_profile({"serve.nonsense_knob": 1})

    def test_bad_schema_version_refused(self, tmp_path):
        path = str(tmp_path / "p.json")
        doc = tuning.build_profile({"serve.max_queue": 64})
        doc["schema_version"] = 999
        with open(path, "w") as f:
            json.dump(doc, f)
        with pytest.raises(tuning.ProfileError, match="schema_version"):
            tuning.load_profile(path)

    def test_torn_profile_refused(self, tmp_path):
        path = str(tmp_path / "p.json")
        with open(path, "w") as f:
            f.write('{"knobs": {')
        with pytest.raises(tuning.ProfileError, match="unreadable"):
            tuning.load_profile(path)

    def test_missing_profile_loud(self, tmp_path):
        cfg = FrameworkConfig()
        cfg.tuning.profile = str(tmp_path / "absent.json")
        with pytest.raises(tuning.ProfileError, match="not found"):
            tuning.apply_profile(cfg)

    def test_precedence_explicit_beats_profile_beats_default(
            self, tmp_path):
        path = _write_profile(tmp_path, {"serve.batch_timeout_ms": 0.5,
                                         "runtime.megachunk_factor": 8})
        cfg = FrameworkConfig()
        cfg.tuning.profile = path
        cfg.serve.batch_timeout_ms = 7.0        # explicit: must win
        out = tuning.apply_profile(cfg)
        assert out.serve.batch_timeout_ms == 7.0
        assert out.runtime.megachunk_factor == 8    # profile over default
        assert out.serve.max_queue == 1024          # default untouched
        # Idempotent: a second application changes nothing.
        again = tuning.apply_profile(out)
        assert again.to_dict() == out.to_dict()
        desc = tuning.describe(out)
        assert desc["knobs"]["serve.batch_timeout_ms"]["source"] == \
            "explicit"
        assert desc["knobs"]["runtime.megachunk_factor"]["source"] == \
            "profile"
        assert desc["knobs"]["serve.max_queue"]["source"] == "default"

    def test_explicit_override_at_default_value_beats_profile(
            self, tmp_path):
        """`--set serve.max_queue=1024` (the default VALUE) is still an
        explicit operator decision: apply_overrides memoizes the dotted
        path and the profile must not override it — value-equality alone
        cannot see the pin."""
        path = _write_profile(tmp_path, {"serve.max_queue": 128})
        cfg = FrameworkConfig().apply_overrides(
            [f"tuning.profile={path}", "serve.max_queue=1024"])
        out = tuning.apply_profile(cfg)
        assert out.serve.max_queue == 1024
        assert tuning.describe(out)["knobs"]["serve.max_queue"][
            "source"] == "explicit"
        # Without the pin the same profile applies.
        cfg2 = FrameworkConfig().apply_overrides(
            [f"tuning.profile={path}"])
        assert tuning.apply_profile(cfg2).serve.max_queue == 128

    def test_fingerprint_mismatch_refused_loudly(self, tmp_path):
        path = str(tmp_path / "p.json")
        doc = tuning.build_profile({"runtime.megachunk_factor": 4})
        doc["fingerprint"] = dict(doc["fingerprint"], cpu_count=99999)
        tuning.write_profile(path, doc)
        cfg = FrameworkConfig()
        cfg.tuning.profile = path
        with pytest.raises(tuning.ProfileError, match="different host"):
            tuning.apply_profile(cfg)
        # ProfileError is ConfigError: the supervision decider's STOP verb.
        assert issubclass(tuning.ProfileError, ConfigError)
        cfg.tuning.allow_fingerprint_mismatch = True
        assert tuning.apply_profile(cfg).runtime.megachunk_factor == 4

    def test_orchestrator_applies_profile(self, tmp_path):
        from sharetrade_tpu.runtime.orchestrator import Orchestrator
        path = _write_profile(tmp_path, {"runtime.megachunk_factor": 4})
        cfg = FrameworkConfig()
        cfg.tuning.profile = path
        cfg.runtime.checkpoint_dir = str(tmp_path / "ck")
        orch = Orchestrator(cfg)
        try:
            assert orch.cfg.runtime.megachunk_factor == 4
        finally:
            orch.stop()

    def test_bench_envelope_carries_knob_vector(self):
        import bench
        cfg = FrameworkConfig()
        cfg.runtime.megachunk_factor = 16
        env = bench._result_envelope(cfg)
        assert env["knobs"] == tuning.knob_vector(cfg)
        assert env["knobs"]["runtime.megachunk_factor"] == 16


# ---------------------------------------------------------------------------
# online controller state machine (fake engine, fake clock)
# ---------------------------------------------------------------------------


class FakeEngine:
    """The duck-typed surface ServeController reads/actuates, with the
    REAL engine's clamp semantics (config values are ceilings)."""

    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg
        self.knobs = _LiveKnobs(float(cfg.batch_timeout_ms),
                                int(cfg.max_queue))
        self.registry = MetricsRegistry()
        self.latency_histogram = Histogram()
        self.depth = 0
        self.history: list[_LiveKnobs] = []

    def queue_depth(self) -> int:
        return self.depth

    def set_knobs(self, *, batch_timeout_ms=None, max_queue=None):
        t = min(float(batch_timeout_ms), self.cfg.batch_timeout_ms)
        q = min(int(max_queue), self.cfg.max_queue)
        self.knobs = _LiveKnobs(t, q)
        self.history.append(self.knobs)
        return self.knobs


def make_controller(cfg=None, **kw):
    cfg = cfg or ServeConfig(max_batch=16, slots=64,
                             batch_timeout_ms=8.0, max_queue=512)
    engine = FakeEngine(cfg)
    now = [0.0]
    kw.setdefault("target_p99_ms", 50.0)
    kw.setdefault("interval_s", 1.0)
    ctl = ServeController(engine, clock=lambda: now[0], **kw)
    return engine, ctl, now


def feed_window(engine, p99_ms: float, n: int = 200):
    """Synthesize a completion window whose windowed p99 ~= p99_ms (bulk
    at p99/2, the tail pinned at p99; bucket interpolation keeps the
    estimate within one log-bucket of the intent)."""
    for _ in range(n - max(2, n // 100)):
        engine.latency_histogram.observe(p99_ms * 0.5)
    for _ in range(max(2, n // 100)):
        engine.latency_histogram.observe(p99_ms)


class TestControllerStateMachine:
    def tick(self, engine, ctl, now, p99, dt=1.0):
        now[0] += dt
        if p99 is not None:
            feed_window(engine, p99)
        return ctl.step(now=now[0])

    def test_tighten_is_bounded_per_tick(self):
        engine, ctl, now = make_controller()
        adj = self.tick(engine, ctl, now, 200.0)
        assert adj is not None and adj.action == "tighten"
        # ONE bounded multiplicative step, not a slam to the floor.
        assert adj.batch_timeout_ms == pytest.approx(8.0 * 0.5)
        assert adj.max_queue == 256
        adj2 = self.tick(engine, ctl, now, 200.0)
        assert adj2.batch_timeout_ms == pytest.approx(8.0 * 0.25)
        assert adj2.max_queue == 128

    def test_floors_and_ceilings(self):
        engine, ctl, now = make_controller()
        for _ in range(20):
            self.tick(engine, ctl, now, 500.0)
        assert engine.knobs.batch_timeout_ms == 0.0
        assert engine.knobs.max_queue == 16     # floor = max_batch
        # Recovery relaxes back up, but never past the CONFIG ceilings.
        for _ in range(40):
            self.tick(engine, ctl, now, 1.0)
        assert engine.knobs.batch_timeout_ms == pytest.approx(8.0)
        assert engine.knobs.max_queue == 512

    def test_dead_band_holds(self):
        engine, ctl, now = make_controller()
        # Between rearm (25) and target (50): no action, ever.
        for p99 in (30.0, 45.0, 27.0, 40.0, 35.0):
            assert self.tick(engine, ctl, now, p99) is None
        assert ctl.adjustments == 0

    def test_no_oscillation_on_noisy_p99(self):
        """A noisy p99 hovering around the target must only ever ratchet
        TIGHTER (or hold) — the hysteresis gap means relaxing requires a
        clear recovery below rearm_frac*target, so tighten→relax→tighten
        flapping cannot happen inside the noise band."""
        engine, ctl, now = make_controller()
        rng_series = [48, 53, 47, 52, 49, 55, 46, 51, 44, 56, 48, 53]
        actions = [self.tick(engine, ctl, now, float(p))
                   for p in rng_series]
        assert all(a is None or a.action == "tighten" for a in actions)
        # Knob trajectory is monotone non-increasing through the noise.
        timeouts = [k.batch_timeout_ms for k in engine.history]
        assert timeouts == sorted(timeouts, reverse=True)
        queues = [k.max_queue for k in engine.history]
        assert queues == sorted(queues, reverse=True)

    def test_overload_vetoes_relax(self):
        """With tight admission, a low p99 is the tight knobs' doing:
        relaxing while the window still shed would re-inflate the tail
        (the oscillation the veto kills)."""
        engine, ctl, now = make_controller()
        self.tick(engine, ctl, now, 200.0)      # tighten once
        tightened = engine.knobs
        # Low p99 but the window saw sheds: must HOLD, not relax.
        engine.registry.inc("serve_shed_total", 50)
        assert self.tick(engine, ctl, now, 5.0) is None
        assert engine.knobs == tightened
        # Same low p99 with a clean window: NOW it relaxes.
        adj = self.tick(engine, ctl, now, 5.0)
        assert adj is not None and adj.action == "relax"

    def test_rate_limit_one_adjustment_per_interval(self):
        engine, ctl, now = make_controller()
        self.tick(engine, ctl, now, 200.0, dt=1.0)
        # A second call 0.1s later must not act (and must not consume
        # the histogram window).
        assert self.tick(engine, ctl, now, 200.0, dt=0.1) is None
        assert ctl.adjustments == 1

    def test_no_signal_holds(self):
        engine, ctl, now = make_controller()
        now[0] += 1.0
        assert ctl.step(now=now[0]) is None     # empty window: hold
        assert ctl.adjustments == 0

    def test_adjustments_visible_as_gauges_and_counters(self):
        engine, ctl, now = make_controller()
        self.tick(engine, ctl, now, 200.0)
        counters = engine.registry.counters()
        assert counters["serve_controller_adjustments_total"] == 1
        snap = engine.registry.snapshot()
        assert snap["serve_controller_p99_ms"] > 50.0
        assert snap["serve_controller_target_p99_ms"] == 50.0

    def test_bad_params_refused(self):
        engine = FakeEngine(ServeConfig())
        with pytest.raises(ConfigError):
            ServeController(engine, target_p99_ms=0.0)
        with pytest.raises(ConfigError):
            ServeController(engine, target_p99_ms=50.0, interval_s=0.0)
        with pytest.raises(ConfigError):
            ServeController(engine, target_p99_ms=50.0, shrink=1.5)


# ---------------------------------------------------------------------------
# engine live knobs (the real ServeEngine)
# ---------------------------------------------------------------------------


class TestEngineLiveKnobs:
    @pytest.fixture(scope="class")
    def engine(self):
        import serve_soak
        from sharetrade_tpu.serve import ServeEngine
        model, params, _, _ = serve_soak.build_workload(
            mlp=True, window=8, length=256)
        engine = ServeEngine(
            model, ServeConfig(max_batch=4, slots=16,
                               batch_timeout_ms=5.0, max_queue=64,
                               swap_poll_s=0.0), params)
        yield engine
        engine.stop(drain=False)

    def test_config_is_the_ceiling(self, engine):
        new = engine.set_knobs(batch_timeout_ms=500.0, max_queue=10_000)
        assert new.batch_timeout_ms == 5.0      # clamped to config
        assert new.max_queue == 64
        new = engine.set_knobs(batch_timeout_ms=1.0, max_queue=8)
        assert new == engine.knobs == _LiveKnobs(1.0, 8)
        # The physical ingress bound follows the knob.
        assert engine._q.maxsize == 8
        snap = engine.registry.snapshot()
        assert snap["serve_knob_batch_timeout_ms"] == 1.0
        assert snap["serve_knob_max_queue"] == 8.0
        engine.set_knobs(batch_timeout_ms=5.0, max_queue=64)

    def test_invalid_knobs_refused(self, engine):
        with pytest.raises(ConfigError):
            engine.set_knobs(batch_timeout_ms=-1.0)
        with pytest.raises(ConfigError):
            engine.set_knobs(max_queue=0)

    def test_serving_works_across_knob_changes(self, engine):
        import numpy as np
        engine.set_knobs(batch_timeout_ms=0.5, max_queue=16)
        obs = np.full((10,), 10.0, np.float32)
        handles = [engine.submit(f"knob-{i}", obs) for i in range(8)]
        for h in handles:
            assert h.wait(10.0) is not None
        engine.set_knobs(batch_timeout_ms=5.0, max_queue=64)


# ---------------------------------------------------------------------------
# adaptive ingest cadence (orchestrator)
# ---------------------------------------------------------------------------


class TestAdaptiveIngest:
    def make_orch(self, tmp_path, adaptive=True, every=8):
        from sharetrade_tpu.runtime.orchestrator import Orchestrator
        cfg = FrameworkConfig()
        cfg.learner.algo = "dqn"
        cfg.distrib.num_actors = 1
        cfg.distrib.ingest_every_updates = every
        cfg.distrib.actor_dir = str(tmp_path / "actors")
        cfg.tuning.adaptive_ingest = adaptive
        cfg.runtime.checkpoint_dir = str(tmp_path / "ck")
        return Orchestrator(cfg)

    def test_dry_backoff_and_snap_recovery(self, tmp_path):
        orch = self.make_orch(tmp_path)
        try:
            base = 8
            assert orch._ingest_every == base
            # One or two dry ticks: scheduling noise, no move yet.
            orch._adapt_ingest_cadence(0, False)
            orch._adapt_ingest_cadence(0, False)
            assert orch._ingest_every == base
            orch._adapt_ingest_cadence(0, False)    # third: back off
            assert orch._ingest_every == 2 * base
            for _ in range(10):                      # bounded at 8x base
                orch._adapt_ingest_cadence(0, False)
            assert orch._ingest_every == 8 * base
            # Rows arrive: snap straight back to the configured base.
            orch._adapt_ingest_cadence(100, False)
            assert orch._ingest_every == base
            counters = orch.metrics.counters()
            assert counters["ingest_adjustments_total"] >= 3
            assert orch.metrics.latest(
                "ingest_every_updates_current") == base
        finally:
            orch.stop()

    def test_backlog_tightens_to_floor(self, tmp_path):
        orch = self.make_orch(tmp_path)
        try:
            for _ in range(10):
                orch._adapt_ingest_cadence(4096, True)
            assert orch._ingest_every == 2     # max(1, 8 // 4)
            # Backlog cleared: cadence stays (below base is not "backed
            # off"; it only returns toward base via the dry path).
            orch._adapt_ingest_cadence(10, False)
            assert orch._ingest_every == 2
        finally:
            orch.stop()

    def test_adaptive_off_never_moves(self, tmp_path):
        orch = self.make_orch(tmp_path, adaptive=False)
        try:
            for _ in range(5):
                orch._adapt_ingest_cadence(0, False)
                orch._adapt_ingest_cadence(4096, True)
            assert orch._ingest_every == 8
            assert "ingest_adjustments_total" not in \
                orch.metrics.counters()
        finally:
            orch.stop()


# ---------------------------------------------------------------------------
# lint check 13 + perf-gate direction
# ---------------------------------------------------------------------------


class TestLintAndGate:
    def test_tuned_knob_shadow_semantics(self, tmp_path):
        import lint_hot_loop as lint
        fixture = tmp_path / "serve"
        fixture.mkdir()
        (fixture / "bad.py").write_text(
            "class E:\n"
            "    def f(self):\n"
            "        self.batch_timeout_ms = 2.0\n"
            "        max_queue = 64\n"
            "        # tuned-knob-ok: test fixture escape\n"
            "        self.pipeline_depth = 4\n"
            "        other_name = 3.0\n"
            "        self.max_batch = compute()\n")
        bad, found = lint.lint_tuned_knob_shadows(roots=[fixture])
        lines = sorted(ln for _, ln, _ in bad)
        # Literal assignments to registered leaves flagged (3, 4); the
        # marker-escaped one (6), an unrelated name (7), and a
        # non-literal value (8) stay legal.
        assert lines == [3, 4]
        assert set(lint.TUNED_KNOB_PATHS) <= found | set(
            lint.TUNED_KNOB_PATHS)

    def test_registry_existence_check(self, tmp_path):
        import lint_hot_loop as lint
        empty = tmp_path / "serve2"
        empty.mkdir()
        reg = tmp_path / "not_the_registry.py"
        reg.write_text("KNOBS = ()\n")
        _, found = lint.lint_tuned_knob_shadows(roots=[empty],
                                                registry=reg)
        assert found == set()   # every registered path reported missing

    def test_repo_is_clean(self):
        import lint_hot_loop as lint
        bad, found = lint.lint_tuned_knob_shadows()
        assert bad == []
        assert found == set(lint.TUNED_KNOB_PATHS)

    def test_perf_gate_autotune_directions(self):
        import perf_gate
        assert perf_gate.lower_is_better("autotune_controller_p99_ms")
        assert perf_gate.lower_is_better("autotune_sweep_cost_frac")
        assert perf_gate.lower_is_better("autotune_sweep_cost_s")
        assert not perf_gate.lower_is_better("serve_qps")

    def test_perf_gate_rows_parse_with_knob_vector(self, tmp_path):
        """A bench snapshot carrying the new ``knobs`` envelope block
        still yields exactly its metric rows (the knob dict must not be
        mistaken for a row)."""
        import bench
        import perf_gate
        cfg = FrameworkConfig()
        doc = {**bench._result_envelope(cfg),
               "metric": "autotune_controller_p99_ms", "value": 30.0}
        path = tmp_path / "BENCH_r99.json"
        path.write_text(json.dumps({"n": 99, "parsed": doc}))
        snap = perf_gate.parse_bench_file(str(path))
        assert [r["metric"] for r in snap["rows"]] == [
            "autotune_controller_p99_ms"]


# ---------------------------------------------------------------------------
# manifest + cli obs tuning section
# ---------------------------------------------------------------------------


class TestTuningObservability:
    def test_manifest_and_summary_tuning_section(self, tmp_path):
        from sharetrade_tpu.obs import summarize_run_dir
        from sharetrade_tpu.obs.manifest import write_manifest
        profile = _write_profile(tmp_path,
                                 {"runtime.megachunk_factor": 4})
        cfg = FrameworkConfig()
        cfg.tuning.profile = profile
        cfg = tuning.apply_profile(cfg)
        run_dir = tmp_path / "obs"
        run_dir.mkdir()
        write_manifest(str(run_dir / "manifest.json"), cfg)
        summary = summarize_run_dir(str(run_dir))
        t = summary["tuning"]
        assert t["profile"] == profile
        assert t["knobs"]["runtime.megachunk_factor"]["source"] == \
            "profile"
        assert t["knobs"]["runtime.megachunk_factor"]["value"] == 4
        assert t["knobs"]["serve.max_queue"]["source"] == "default"

    def test_summary_live_controller_gauges(self, tmp_path):
        from sharetrade_tpu.obs import summarize_run_dir
        run_dir = tmp_path / "obs"
        run_dir.mkdir()
        record = {
            "gauges": {"serve_knob_batch_timeout_ms": 0.5,
                       "serve_knob_max_queue": 32.0,
                       "serve_controller_p99_ms": 41.0,
                       "serve_controller_target_p99_ms": 50.0},
            "counters": {"serve_controller_adjustments_total": 7.0},
        }
        (run_dir / "metrics.jsonl").write_text(json.dumps(record) + "\n")
        live = summarize_run_dir(str(run_dir))["tuning"]["live"]
        assert live["serve_batch_timeout_ms"] == 0.5
        assert live["serve_max_queue"] == 32.0
        assert live["controller_adjustments_total"] == 7.0
        assert live["controller_last_p99_ms"] == 41.0


# ---------------------------------------------------------------------------
# quick end-to-end sweep (the make-check profile, train spec only)
# ---------------------------------------------------------------------------


class TestQuickSweep:
    def test_train_sweep_writes_loadable_profile(self, tmp_path):
        import autotune
        out = str(tmp_path / "tuned_profile.json")
        summary = autotune.run_autotune(
            ("train",), quick=True, out_path=out, seed=0,
            log_fn=lambda msg: None)
        assert summary["out"] == out
        assert set(summary["knobs"]) == {"runtime.megachunk_factor",
                                         "runtime.pipeline_depth"}
        # The written profile loads and applies on THIS host.
        cfg = FrameworkConfig()
        cfg.tuning.profile = out
        cfg = tuning.apply_profile(cfg)
        assert cfg.runtime.megachunk_factor == \
            summary["knobs"]["runtime.megachunk_factor"]
        desc = tuning.describe(cfg)
        assert desc["profile_mismatches"] == []


class TestControllerUnderChaos:
    def test_chaos_quick_profile_with_controller_on(self, tmp_path):
        """ISSUE-14 acceptance: the chaos invariants (every request
        terminal, queue bounded, counters reconcile exactly) hold with
        the online controller adjusting LIVE."""
        import serve_chaos
        summary = serve_chaos.run_chaos(
            injections=2, seed=5, workdir=str(tmp_path / "chaos"),
            verbose=False, controller=True)
        assert summary["controller"] is True
        assert summary["decomposition_errors"] == 0
