"""Serving tier (serve/ — ISSUE 8): continuous batching, slot pool, hot
weight swaps, SLO telemetry.

The load-bearing contracts:

- **Parity**: for a fixed request trace, continuous-batched serving is
  BIT-IDENTICAL to threading each session one at a time through
  ``model.apply`` (fp32) — mixed prefill/incremental batches included.
  Batching is a scheduling optimization, never a numerics change.
- **Slot pool**: LRU admission/eviction; an evicted session re-enters COLD
  through the batched prefill and from then on behaves exactly like a
  fresh session fed the same requests (the documented eviction contract).
- **Hot swap**: under load with repeated ``tag_best`` updates every
  response is attributable to exactly ONE checkpoint step (recompute-exact
  — a torn batch cannot pass), and a corrupt candidate is refused without
  interrupting serving.
- **SLO surface**: serve gauges land in ``metrics.prom`` and the ``cli
  obs`` summary grows a serve section.
- **Tooling**: lint check 8 (no blocking host ops in the dispatch
  closure), perf-gate serve series with inverted latency bands, and the
  soak's quick profile all run in tier-1; the full 3x-acceptance soak is
  ``slow``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.agents.base import TrainState
from sharetrade_tpu.checkpoint.manager import CheckpointManager
from sharetrade_tpu.config import ConfigError, ModelConfig, ServeConfig
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.transformer_episode import (
    episode_transformer_policy,
)
from sharetrade_tpu.serve import ServeEngine, SlotPool, WeightSwapWatcher
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8
OBS_DIM = WINDOW + 2


@pytest.fixture(scope="module")
def episode_model():
    return episode_transformer_policy(obs_dim=OBS_DIM, num_layers=2,
                                      num_heads=2, head_dim=8)


@pytest.fixture(scope="module")
def episode_params(episode_model):
    return episode_model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mlp_model():
    return build_model(ModelConfig(kind="mlp", hidden_dim=16), OBS_DIM,
                       head="ac")


@pytest.fixture(scope="module")
def mlp_params(mlp_model):
    return mlp_model.init(jax.random.PRNGKey(1))


@pytest.fixture(scope="module")
def prices():
    rng = np.random.default_rng(7)
    return rng.uniform(10.0, 20.0, 256).astype(np.float32)


def obs_at(prices, start, t, *, budget=2400.0, shares=0.0):
    lo = start + t
    return np.concatenate(
        [prices[lo:lo + WINDOW],
         np.asarray([budget, shares], np.float32)]).astype(np.float32)


class SequentialReference:
    """One-at-a-time ``model.apply`` with carries threaded per session —
    THE parity baseline the acceptance criterion names."""

    def __init__(self, model, params):
        self.model = model
        self.params = params
        self._apply = jax.jit(model.apply)
        self._carries: dict = {}

    def step(self, sid, obs):
        carry = self._carries.get(sid)
        if carry is None:
            carry = self.model.init_carry()
        out, carry = self._apply(self.params, obs, carry)
        self._carries[sid] = carry
        logits = np.asarray(out.logits)
        return int(np.argmax(logits)), logits

    def forget(self, sid):
        self._carries.pop(sid, None)


# ---------------------------------------------------------------------------
# construction / slot pool


def test_config_validation(mlp_model, mlp_params):
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model, ServeConfig(max_batch=8, slots=4),
                    mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model, ServeConfig(max_batch=0), mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1,
                                batch_timeout_ms=-1.0), mlp_params)


def test_slot_pool_lru_and_pinning():
    pool = SlotPool(3)
    slots = {s: pool.admit(s, set())[0] for s in "abc"}
    assert len(set(slots.values())) == 3 and len(pool) == 3
    # 'a' is LRU; touching it promotes it, so 'b' becomes the victim.
    assert pool.lookup("a") == slots["a"]
    slot_d, evicted = pool.admit("d", set())
    assert evicted == "b" and slot_d == slots["b"]
    assert pool.evictions == 1
    assert pool.lookup("b") is None          # evicted sessions are cold
    # Pinning protects the current batch: 'c' is LRU but pinned.
    _, evicted = pool.admit("e", {"c"})
    assert evicted == "a"


# ---------------------------------------------------------------------------
# parity (the acceptance criterion)


def test_parity_mixed_prefill_incremental_episode(episode_model,
                                                  episode_params, prices):
    """Sessions join at staggered ticks, so most ticks mix a cold prefill
    sub-batch with a warm incremental sub-batch at heterogeneous episode
    clocks — every response must be bit-identical to the one-at-a-time
    reference."""
    registry = MetricsRegistry()
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=8, slots=16, batch_timeout_ms=5.0,
                    swap_poll_s=0.0),
        episode_params, registry=registry)
    engine.warmup()
    ref = SequentialReference(episode_model, episode_params)
    try:
        sessions = [(f"s{i}", 3 * i) for i in range(6)]   # staggered starts
        clock: dict[str, int] = {}
        for tick in range(10):
            live = sessions[: 2 + tick]                   # joiners per tick
            pending = []
            for sid, start in live:
                t = clock.get(sid, 0)
                obs = obs_at(prices, start, t, shares=float(t % 3))
                pending.append((sid, obs, engine.submit(sid, obs)))
                clock[sid] = t + 1
            for sid, obs, handle in pending:
                result = handle.wait(30.0)
                assert result is not None, "serve timeout"
                ref_action, ref_logits = ref.step(sid, obs)
                assert result.action == ref_action
                assert np.array_equal(result.logits, ref_logits)
    finally:
        engine.stop()
    counters = registry.counters()
    # The trace really did mix paths: prefills for every join, plus warm
    # incremental traffic.
    assert counters["serve_prefills_total"] == len(sessions)
    assert counters["serve_responses_total"] > counters[
        "serve_prefills_total"]


def test_parity_generic_path_mlp(mlp_model, mlp_params, prices):
    engine = ServeEngine(
        mlp_model, ServeConfig(max_batch=4, slots=8, batch_timeout_ms=5.0),
        mlp_params)
    engine.warmup()
    ref = SequentialReference(mlp_model, mlp_params)
    try:
        for tick in range(5):
            pending = []
            for i in range(6):                # > max_batch: multiple ticks
                obs = obs_at(prices, 5 * i, tick, shares=float(i))
                pending.append((f"u{i}", obs,
                                engine.submit(f"u{i}", obs)))
            for sid, obs, handle in pending:
                result = handle.wait(30.0)
                assert result is not None
                action, logits = ref.step(sid, obs)
                assert result.action == action
                assert np.array_equal(result.logits, logits)
    finally:
        engine.stop()


def test_same_session_requests_stay_sequential(episode_model,
                                               episode_params, prices):
    """Two in-flight requests for one session must not share a batch: the
    second sees the first's carry (deferred to the next tick), matching
    the sequential reference exactly."""
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=8, slots=8, batch_timeout_ms=2.0),
        episode_params)
    engine.warmup()
    ref = SequentialReference(episode_model, episode_params)
    try:
        obs0 = obs_at(prices, 0, 0)
        obs1 = obs_at(prices, 0, 1)
        h0 = engine.submit("dup", obs0)
        h1 = engine.submit("dup", obs1)
        r0, r1 = h0.wait(30.0), h1.wait(30.0)
        assert r0 is not None and r1 is not None
        a0, l0 = ref.step("dup", obs0)
        a1, l1 = ref.step("dup", obs1)
        assert (r0.action, r1.action) == (a0, a1)
        assert np.array_equal(r0.logits, l0)
        assert np.array_equal(r1.logits, l1)
    finally:
        engine.stop()


def test_steady_state_is_one_program_per_tick(episode_model,
                                              episode_params, prices):
    """Once every session is warm, a full tick is ONE batched program:
    batches_total advances by one per tick and prefills stay flat."""
    registry = MetricsRegistry()
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=4, slots=8, batch_timeout_ms=50.0),
        episode_params, registry=registry)
    engine.warmup()
    try:
        sids = [f"w{i}" for i in range(4)]
        for tick in range(2):                 # admit + warm everyone
            handles = [engine.submit(s, obs_at(prices, 4 * i, tick))
                       for i, s in enumerate(sids)]
            assert all(h.wait(30.0) for h in handles)
        counters = registry.counters()
        batches0 = counters["serve_batches_total"]
        prefills0 = counters["serve_prefills_total"]
        for tick in range(2, 5):
            handles = [engine.submit(s, obs_at(prices, 4 * i, tick))
                       for i, s in enumerate(sids)]
            assert all(h.wait(30.0) for h in handles)
        counters = registry.counters()
        assert counters["serve_prefills_total"] == prefills0
        assert counters["serve_batches_total"] == batches0 + 3
    finally:
        engine.stop()


def test_dispatch_fault_fails_batch_not_engine(episode_model,
                                               episode_params, prices):
    """A malformed request (wrong obs length) fails ITS batch — waiters
    unblock with ``error`` set, callbacks fire with None — and the engine
    keeps serving correct, parity-exact answers afterward (the donated
    arena must survive the fault)."""
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=4, slots=8, batch_timeout_ms=2.0),
        episode_params)
    engine.warmup()
    ref = SequentialReference(episode_model, episode_params)
    try:
        # Warm a healthy session first (its slot carry must survive).
        assert engine.submit("ok", obs_at(prices, 0, 0)).wait(30.0)
        failed_cb: list = []
        bad = engine.submit("bad", np.ones(3, np.float32),
                            callback=failed_cb.append)
        assert bad.wait(30.0) is None
        assert bad.error is not None
        assert failed_cb == [None]
        # The engine is still up, and the warm session's state is intact:
        # its next step matches the sequential reference stepped twice.
        ref.step("ok", obs_at(prices, 0, 0))
        obs = obs_at(prices, 0, 1)
        result = engine.submit("ok", obs).wait(30.0)
        assert result is not None
        action, logits = ref.step("ok", obs)
        assert result.action == action
        assert np.array_equal(result.logits, logits)
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# slot eviction / re-prefill


def test_eviction_reprefill_resumes_as_cold_session(episode_model,
                                                    episode_params, prices):
    """Evict a session by admitting others past capacity, then bring it
    back: from re-admission on, its responses are bit-identical to a
    FRESH session fed the same request suffix — the documented slot-pool
    contract (eviction restarts the episode from the request's window)."""
    registry = MetricsRegistry()
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=2, slots=2, batch_timeout_ms=2.0),
        episode_params, registry=registry)
    engine.warmup()
    ref = SequentialReference(episode_model, episode_params)
    try:
        # Warm session A for three steps.
        for t in range(3):
            assert engine.submit("A", obs_at(prices, 0, t)).wait(30.0)
        # Evict A: two other sessions take both slots.
        for sid, start in (("B", 40), ("C", 80)):
            assert engine.submit(sid, obs_at(prices, start, 0)).wait(30.0)
        assert registry.counters()["serve_evictions_total"] >= 1
        # A returns at episode step 3..5; the reference is a FRESH session
        # fed the same suffix (cold restart semantics).
        for t in range(3, 6):
            obs = obs_at(prices, 0, t)
            result = engine.submit("A", obs).wait(30.0)
            assert result is not None
            action, logits = ref.step("A-fresh", obs)
            assert result.action == action
            assert np.array_equal(result.logits, logits)
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# hot weight swaps


def _train_state(params, updates: int) -> TrainState:
    return TrainState(params=params, opt_state=(), carry=(),
                      env_state=(), rng=jax.random.PRNGKey(0),
                      env_steps=jnp.int32(0), updates=jnp.int32(updates))


def test_hot_swap_atomicity_under_load(mlp_model, prices, tmp_path):
    """Sustained load while ``tag_best`` advances four times: every
    response must be attributable to exactly one published step, and its
    logits must recompute EXACTLY under that step's params — a batch that
    mixed two param versions cannot pass."""
    versions = {k: mlp_model.init(jax.random.PRNGKey(10 + k))
                for k in range(1, 5)}
    manager = CheckpointManager(str(tmp_path / "ckpt"), fsync=False)
    manager.save_tagged("best", _train_state(versions[1], 1),
                        metadata={"updates": 1})
    registry = MetricsRegistry()
    engine = ServeEngine(
        mlp_model, ServeConfig(max_batch=4, slots=8, batch_timeout_ms=1.0),
        versions[1], params_step=1, registry=registry)
    engine.warmup()
    watcher = WeightSwapWatcher(
        engine, manager, _train_state(versions[1], 1), tag="best",
        poll_s=60.0, seen_meta={"updates": 1, "saved_at": 0.0})
    results: list = []
    results_lock = threading.Lock()
    stop = threading.Event()

    def load():
        i = 0
        while not stop.is_set():
            obs = obs_at(prices, (i * 3) % 100, 0, shares=float(i % 5))
            handle = engine.submit(f"load{i % 16}", obs)
            result = handle.wait(10.0)
            if result is not None:
                with results_lock:
                    results.append((obs, result))
            i += 1

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(3)]
    for thread in threads:
        thread.start()
    try:
        for k in range(2, 5):
            time.sleep(0.15)
            manager.save_tagged("best", _train_state(versions[k], k),
                                metadata={"updates": k})
            assert watcher.poll_once()
            assert engine.params_step == k
        time.sleep(0.15)
    finally:
        stop.set()
        for thread in threads:
            thread.join(10.0)
        engine.stop()
    assert registry.counters()["serve_swaps_total"] == 3.0
    apply_fn = jax.jit(mlp_model.apply)
    seen_steps = set()
    assert len(results) > 50
    for obs, result in results:
        assert result.params_step in versions, (
            f"response attributed to unpublished step {result.params_step}")
        seen_steps.add(result.params_step)
        out, _ = apply_fn(versions[result.params_step], obs, ())
        assert np.array_equal(result.logits, np.asarray(out.logits)), (
            "response does not recompute under its claimed step — torn "
            "or mixed-params batch")
    assert len(seen_steps) >= 2, "load never spanned a swap"


def test_corrupt_swap_candidate_refused_serving_continues(
        mlp_model, prices, tmp_path):
    v1 = mlp_model.init(jax.random.PRNGKey(21))
    v2 = mlp_model.init(jax.random.PRNGKey(22))
    manager = CheckpointManager(str(tmp_path / "ckpt"), fsync=False)
    registry = MetricsRegistry()
    engine = ServeEngine(
        mlp_model, ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0),
        v1, params_step=1, registry=registry)
    engine.warmup()
    watcher = WeightSwapWatcher(engine, manager, _train_state(v1, 1),
                                tag="best", poll_s=60.0)
    # Publish a candidate, then corrupt its payload in place.
    manager.save_tagged("best", _train_state(v2, 2),
                        metadata={"updates": 2})
    state_path = tmp_path / "ckpt" / "tag_best" / "state.msgpack"
    raw = bytearray(state_path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    state_path.write_bytes(bytes(raw))

    assert watcher.poll_once() is False
    assert watcher.rejected == 1
    assert registry.counters()["serve_swap_rejected_total"] == 1.0
    assert engine.params_step == 1          # serving weights untouched
    # ... and the engine still answers, on the old weights.
    obs = obs_at(prices, 0, 0)
    result = engine.submit("still-up", obs).wait(30.0)
    assert result is not None and result.params_step == 1
    out, _ = jax.jit(mlp_model.apply)(v1, obs, ())
    assert np.array_equal(result.logits, np.asarray(out.logits))
    # The corrupt candidate was quarantined, not deleted.
    assert any(name.startswith("corrupt_")
               for name in os.listdir(tmp_path / "ckpt"))
    engine.stop()


# ---------------------------------------------------------------------------
# SLO telemetry


def test_slo_gauges_reach_metrics_prom(mlp_model, mlp_params, prices,
                                       tmp_path):
    from sharetrade_tpu.config import FrameworkConfig
    from sharetrade_tpu.obs import build_obs, summarize_run_dir

    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "run")
    cfg.obs.export_interval_s = 0.1
    registry = MetricsRegistry()
    obs_bundle = build_obs(cfg, registry)
    engine = ServeEngine(
        mlp_model,
        ServeConfig(max_batch=4, slots=8, batch_timeout_ms=1.0,
                    stats_interval_s=0.05),
        mlp_params, registry=registry, obs=obs_bundle)
    engine.warmup()
    try:
        for tick in range(6):
            handles = [engine.submit(f"m{i}", obs_at(prices, 4 * i, tick))
                       for i in range(4)]
            assert all(h.wait(30.0) for h in handles)
            time.sleep(0.06)
    finally:
        engine.stop()
        obs_bundle.flush()
        obs_bundle.close()
    prom = (tmp_path / "run" / "metrics.prom").read_text()
    for gauge in ("serve_qps", "serve_p50_ms", "serve_p99_ms",
                  "serve_batch_occupancy", "serve_queue_depth"):
        assert f"sharetrade_{gauge}" in prom, f"{gauge} missing from prom"
    assert "sharetrade_serve_requests_total" in prom
    summary = summarize_run_dir(cfg.obs.dir)
    assert "serve" in summary
    assert summary["serve"]["requests_total"] == 24.0
    assert summary["serve"]["qps"] is not None


# ---------------------------------------------------------------------------
# soak / bench / gate / lint satellites


def test_serve_soak_quick_profile():
    """Seconds-scale soak profile: all three phases run and produce sane
    numbers. (The 3x acceptance itself is the slow full-scale soak —
    speed assertions at toy scale measure the CI host, not the engine.)"""
    import serve_soak

    result = serve_soak.run_soak(duration_s=0.5, sessions=32,
                                 rates=(2.0,), max_batch=8, slots=32,
                                 window=WINDOW, length=512, mlp=True)
    assert result["baseline_b1"]["completed"] > 0
    assert result["engine_saturation"]["completed"] > 0
    assert result["rate_sweep"][0]["engine"]["completed"] > 0
    assert result["baseline_b1"]["qps"] > 0
    assert "accepted" in result


@pytest.mark.slow
def test_serve_soak_full_acceptance():
    """The ISSUE 8 acceptance row: on CPU, continuous batching sustains
    >= 3x the batch=1 closed-loop QPS at equal-or-better p99 than the
    batch=1 server under the same offered rate."""
    import serve_soak

    result = serve_soak.run_soak(duration_s=3.0, sessions=2000,
                                 rates=(2.0, 4.0, 8.0), max_batch=64,
                                 mlp=True)
    sweep = [(p["rate_multiple"], round(p["engine"]["qps"]))
             for p in result["rate_sweep"]]
    assert result["accepted"], (
        f"3x acceptance failed: baseline {result['baseline_b1']['qps']:.0f}"
        f" QPS, sweep {sweep}")
    assert result["speedup_saturation"] >= 3.0


def test_perf_gate_serve_series(tmp_path):
    """serve_qps gates lower-is-worse, serve_p99_ms gates HIGHER-is-worse
    (inverted band), both per (metric, backend, precision); single-point
    series seed without failing."""
    from perf_gate import gate, lower_is_better

    assert lower_is_better("serve_p99_ms")
    assert lower_is_better("serve_p50_ms")
    assert not lower_is_better("serve_qps")

    def series(metric, *vals):
        return {(metric, "cpu", "fp32", "value"): [
            {"round": i, "path": f"r{i}", "value": v}
            for i, v in enumerate(vals)]}

    # Throughput drop past 25% fails; within band passes.
    assert not gate(series("serve_qps", 1000.0, 700.0),
                    {"value": 0.25})["ok"]
    assert gate(series("serve_qps", 1000.0, 800.0), {"value": 0.25})["ok"]
    # Latency RISE past 25% fails; a drop (improvement) passes.
    assert not gate(series("serve_p99_ms", 10.0, 13.0),
                    {"value": 0.25})["ok"]
    assert gate(series("serve_p99_ms", 10.0, 12.0), {"value": 0.25})["ok"]
    assert gate(series("serve_p99_ms", 10.0, 2.0), {"value": 0.25})["ok"]
    # Absent history seeds, never fails.
    report = gate(series("serve_qps", 500.0), {"value": 0.25})
    assert report["ok"] and report["checked"] == 0


def test_perf_gate_serve_rows_parse_end_to_end(tmp_path):
    """BENCH-shaped snapshots with serve rows ride the normal gate path:
    the nested p99 row splits into its own series with the inverted
    direction."""
    from perf_gate import run_gate

    def snapshot(n, qps, p99):
        return {"n": n, "parsed": {
            "schema_version": 1, "backend": "cpu", "precision": "fp32",
            "metric": "serve_qps", "value": qps,
            "p99": {"metric": "serve_p99_ms", "value": p99}}}

    for n, qps, p99 in [(1, 1000.0, 10.0), (2, 980.0, 11.0)]:
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(
            json.dumps(snapshot(n, qps, p99)))
    assert run_gate(tmp_path, as_json=True) == 0
    # A p99 regression alone must fail the gate.
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps(snapshot(3, 1000.0, 30.0)))
    assert run_gate(tmp_path, as_json=True) == 1


def test_lint_serve_dispatch_clean():
    """Check 8 on the shipped tree: the dispatch closure is clean and the
    consumer-side functions exist (a rename must update the lint)."""
    import lint_hot_loop

    hits, found = lint_hot_loop.lint_serve_dispatch()
    assert hits == [], f"serve dispatch lint hits: {hits}"
    required = (set(lint_hot_loop.SERVE_DISPATCH_FUNCS)
                | set(lint_hot_loop.SERVE_CONSUMER_FUNCS))
    assert required <= found


# ---------------------------------------------------------------------------
# cli serve preemption contract


def test_cli_serve_sigterm_drains_and_exits_75(tmp_path):
    """``cli serve`` installs the train-style preemption handling: SIGTERM
    drains in-flight requests, flushes metrics, prints its summary, and
    exits 75 (EX_TEMPFAIL)."""
    env = dict(os.environ)
    run_dir = str(tmp_path / "obs")
    proc = subprocess.Popen(
        [sys.executable, "-m", "sharetrade_tpu.cli", "serve",
         "--set", "data.synthetic_length=600",
         "--set", "env.window=32",
         "--set", "serve.max_batch=8", "--set", "serve.slots=16",
         "--set", "serve.stats_interval_s=0.2",
         "--set", "obs.enabled=true", "--set", f"obs.dir={run_dir}",
         "--set", "obs.export_interval_s=0.2",
         "--set", f"runtime.checkpoint_dir={tmp_path / 'ckpt'}",
         "--duration", "60", "--sessions", "32"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=REPO)
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["event"] == "serving_ready"
        time.sleep(1.0)                       # let traffic flow
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=90)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 75, f"expected 75, got {proc.returncode}"
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["preempted"] is True
    assert summary["drained"] is True
    assert summary["completed"] > 0
    # Metrics were flushed on the way out.
    assert os.path.isfile(os.path.join(run_dir, "metrics.prom"))
