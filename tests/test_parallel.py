"""Parallelism tests on the virtual 8-device CPU mesh (conftest cpu_mesh) —
the TPU analogue of the reference's multi-actor-in-one-JVM tests (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.config import FrameworkConfig, ParallelConfig
from sharetrade_tpu.env import trading
from sharetrade_tpu.models.mlp import ac_mlp
from sharetrade_tpu.ops import reference_attention
from sharetrade_tpu.parallel import (
    build_mesh,
    make_parallel_step,
    mlp_tp_rules,
    param_shardings,
    ring_attention,
    train_state_shardings,
)

WINDOW = 8


def tiny_cfg(algo="qlearn", workers=8):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 16
    cfg.parallel.num_workers = workers
    cfg.runtime.chunk_steps = 4
    cfg.learner.unroll_len = 4
    return cfg


class TestMesh:
    def test_default_all_on_dp(self, cpu_devices):
        mesh = build_mesh(ParallelConfig(), devices=cpu_devices)
        assert mesh.shape == {"dp": 8}

    def test_explicit_shape(self, cpu_devices):
        mesh = build_mesh(ParallelConfig(mesh_shape={"dp": 4, "tp": 2}),
                          devices=cpu_devices)
        assert mesh.shape == {"dp": 4, "tp": 2}

    def test_rejects_partial_mesh(self, cpu_devices):
        with pytest.raises(ValueError, match="devices"):
            build_mesh(ParallelConfig(mesh_shape={"dp": 3}), devices=cpu_devices)


class TestDataParallelStep:
    @pytest.mark.parametrize("algo", ["qlearn", "a2c"])
    def test_sharded_step_matches_unsharded(self, cpu_mesh, algo):
        """The dp-sharded chunk must compute the same training trajectory as
        the single-device one — sharding is a layout, not an algorithm."""
        cfg = tiny_cfg(algo)
        env_params = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 64), window=WINDOW)
        agent = build_agent(cfg, env_params)
        ts0 = agent.init(jax.random.PRNGKey(3))

        plain_ts, plain_metrics = jax.jit(agent.step)(ts0)

        place, pstep = make_parallel_step(agent, cpu_mesh)
        ts_sharded = place(agent.init(jax.random.PRNGKey(3)))
        shard_ts, shard_metrics = pstep(ts_sharded)

        for a, b in zip(jax.tree.leaves(plain_ts.params),
                        jax.tree.leaves(shard_ts.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(plain_metrics["portfolio_mean"]),
                                   float(shard_metrics["portfolio_mean"]),
                                   rtol=1e-5)

    def test_dqn_extras_shard_correctly(self, cpu_mesh):
        """DQN on a mesh: target net replicates like params, replay buffer
        does NOT get batch-sharded (its leading dim is capacity, not batch)."""
        cfg = tiny_cfg("dqn")
        cfg.learner.replay_capacity = 128
        cfg.learner.replay_batch = 8
        env_params = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 64), window=WINDOW)
        agent = build_agent(cfg, env_params)
        place, pstep = make_parallel_step(agent, cpu_mesh)
        ts = place(agent.init(jax.random.PRNGKey(0)))
        # Target params (203-like dims) must not be dp-sharded.
        tp_shard = ts.extras.target_params["layer1"]["w"].sharding
        assert tp_shard.spec == P()
        assert ts.extras.replay.obs.sharding.spec == P()
        ts2, metrics = pstep(ts)
        assert int(ts2.env_steps) > 0

    def test_env_state_actually_sharded(self, cpu_mesh):
        cfg = tiny_cfg()
        env_params = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 64), window=WINDOW)
        agent = build_agent(cfg, env_params)
        place, pstep = make_parallel_step(agent, cpu_mesh)
        ts = place(agent.init(jax.random.PRNGKey(0)))
        sh = ts.env_state.budget.sharding
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P("dp")
        ts2, _ = pstep(ts)
        assert ts2.env_state.budget.sharding.spec == P("dp")


class TestTensorParallel:
    def test_tp_sharded_forward_matches_replicated(self, cpu_devices):
        mesh = Mesh(np.array(cpu_devices).reshape(4, 2), ("dp", "tp"))
        model = ac_mlp(obs_dim=WINDOW + 2, hidden_dim=32)
        params = model.init(jax.random.PRNGKey(0))
        obs = jax.random.uniform(jax.random.PRNGKey(1), (WINDOW + 2,))

        want, _ = model.apply(params, obs, ())

        shardings = param_shardings(params, mesh, mlp_tp_rules())
        sharded_params = jax.device_put(params, shardings)
        # Column-split first layer / row-split second: verify placement took.
        w1_shard = sharded_params["torso1"]["w"].sharding
        assert w1_shard.spec == P(None, "tp")

        got, _ = jax.jit(lambda p: model.apply(p, obs, ()))(sharded_params)
        np.testing.assert_allclose(np.asarray(got.logits),
                                   np.asarray(want.logits), rtol=1e-5, atol=1e-6)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cpu_mesh, causal):
        mesh = Mesh(np.asarray(cpu_mesh.devices).reshape(8), ("sp",))
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 2, 64, 16)  # 64 seq over 8 shards = 8 per device
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)

        got = ring_attention(q, k, v, mesh, causal=causal)
        want = reference_attention(
            jax.device_get(q) * 1.0, jax.device_get(k) * 1.0,
            jax.device_get(v) * 1.0, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_seq(self, cpu_mesh):
        mesh = Mesh(np.asarray(cpu_mesh.devices).reshape(8), ("sp",))
        q = jnp.zeros((1, 1, 60, 16))
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, q, q, mesh)

    def test_long_sequence_memory_scales(self, cpu_mesh):
        # Not a perf test — just that a sequence 8x the single-device test
        # still runs sharded (each device holds 64 positions of 512).
        mesh = Mesh(np.asarray(cpu_mesh.devices).reshape(8), ("sp",))
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 512, 16))
        out = ring_attention(q, q, q, mesh, causal=True)
        assert out.shape == q.shape
        assert np.isfinite(np.asarray(out)).all()

    def test_padded_handles_indivisible_seq(self, cpu_mesh):
        from sharetrade_tpu.parallel.ring_attention import ring_attention_padded
        mesh = Mesh(np.asarray(cpu_mesh.devices).reshape(8), ("sp",))
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 2, 61, 16)   # 61 not divisible by 8: pads to 64
        q, k, v = (jax.random.normal(kx, shape) for kx in (kq, kk, kv))
        got = ring_attention_padded(q, k, v, mesh, causal=True)
        want = reference_attention(q, k, v, causal=True)
        assert got.shape == q.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    """all_to_all head<->sequence re-partition (parallel/ulysses.py)."""

    def _mesh(self, cpu_devices, n=8):
        return Mesh(np.asarray(cpu_devices[:n]).reshape(n), ("sp",))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cpu_devices, causal):
        from sharetrade_tpu.parallel import ulysses_attention
        mesh = self._mesh(cpu_devices)
        key = jax.random.PRNGKey(3)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 8, 64, 16)   # heads 8 == sp, seq 64 divisible
        q, k, v = (jax.random.normal(kx, shape) for kx in (kq, kk, kv))
        got = ulysses_attention(q, k, v, mesh, causal=causal,
                                use_pallas=False)
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_ring(self, cpu_devices):
        from sharetrade_tpu.parallel import ulysses_attention
        mesh = self._mesh(cpu_devices)
        q = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 128, 16))
        got = ulysses_attention(q, q, q, mesh, causal=True, use_pallas=False)
        want = ring_attention(q, q, q, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_heads(self, cpu_devices):
        from sharetrade_tpu.parallel import ulysses_attention
        mesh = self._mesh(cpu_devices)
        q = jnp.zeros((1, 4, 64, 16))   # 4 heads, sp=8
        with pytest.raises(ValueError, match="heads divisible"):
            ulysses_attention(q, q, q, mesh)

    def test_padded_handles_indivisible_seq(self, cpu_devices):
        from sharetrade_tpu.parallel import ulysses_attention_padded
        mesh = self._mesh(cpu_devices)
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 8, 61, 16)   # 61 pads to 64
        q, k, v = (jax.random.normal(kx, shape) for kx in (kq, kk, kv))
        got = ulysses_attention_padded(q, k, v, mesh, causal=True,
                                       use_pallas=False)
        want = reference_attention(q, k, v, causal=True)
        assert got.shape == q.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_differentiates(self, cpu_devices):
        from sharetrade_tpu.parallel import ulysses_attention
        mesh = self._mesh(cpu_devices, n=2)
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 32, 8))

        def loss(q):
            return jnp.sum(ulysses_attention(q, q, q, mesh, causal=True,
                                             use_pallas=False) ** 2)

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.linalg.norm(g)) > 0


class TestPartitionedTransformer:
    """The sp/pp mechanisms reached through the PUBLIC config surface
    (model.attention='ring', model.pipeline_blocks) — the round-1 gap of
    parallelism-mechanisms-that-nothing-uses (VERDICT round 1, weak #5)."""

    OBS_DIM = 32  # window 30 + (budget, shares); seq 31 pads to 32 for sp=8

    def _model(self, cpu_devices, mesh_shape, axes, **cfg_kw):
        from sharetrade_tpu.config import ModelConfig
        from sharetrade_tpu.models import build_model
        mesh = Mesh(np.asarray(cpu_devices).reshape(mesh_shape), axes)
        cfg = ModelConfig(kind="transformer", num_heads=2, head_dim=16,
                          **cfg_kw)
        return build_model(cfg, self.OBS_DIM, mesh=mesh), mesh

    def _obs(self, batch=4):
        key = jax.random.PRNGKey(5)
        prices = jax.random.uniform(key, (batch, self.OBS_DIM - 2),
                                    minval=40.0, maxval=60.0)
        extras = jnp.tile(jnp.array([[2400.0, 3.0]]), (batch, 1))
        return jnp.concatenate([prices, extras], axis=1)

    def test_ring_attention_matches_flash(self, cpu_devices):
        ring_model, _ = self._model(cpu_devices, (2, 4), ("dp", "sp"),
                                    attention="ring", num_layers=2)
        flash_model, _ = self._model(cpu_devices, (2, 4), ("dp", "sp"),
                                     attention="flash", num_layers=2)
        params = ring_model.init(jax.random.PRNGKey(0))
        obs = self._obs()
        got, _ = ring_model.apply_batch(params, obs, ())
        want, _ = flash_model.apply_batch(params, obs, ())
        np.testing.assert_allclose(np.asarray(got.logits),
                                   np.asarray(want.logits),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_attention_matches_flash(self, cpu_devices):
        uly_model, _ = self._model(cpu_devices, (4, 2), ("dp", "sp"),
                                   attention="ulysses", num_layers=2)
        flash_model, _ = self._model(cpu_devices, (4, 2), ("dp", "sp"),
                                     attention="flash", num_layers=2)
        params = uly_model.init(jax.random.PRNGKey(0))
        obs = self._obs()
        got, _ = uly_model.apply_batch(params, obs, ())
        want, _ = flash_model.apply_batch(params, obs, ())
        np.testing.assert_allclose(np.asarray(got.logits),
                                   np.asarray(want.logits),
                                   rtol=2e-4, atol=2e-5)

    def test_pipelined_blocks_match_loop(self, cpu_devices):
        pp_model, _ = self._model(cpu_devices, (2, 4), ("dp", "pp"),
                                  pipeline_blocks=True, num_layers=4)
        loop_model, _ = self._model(cpu_devices, (2, 4), ("dp", "pp"),
                                    num_layers=4)
        # Same init keys -> same values; pp stores blocks stacked.
        pp_params = pp_model.init(jax.random.PRNGKey(0))
        loop_params = loop_model.init(jax.random.PRNGKey(0))
        obs = self._obs()
        got, _ = pp_model.apply_batch(pp_params, obs, ())
        want, _ = loop_model.apply_batch(loop_params, obs, ())
        np.testing.assert_allclose(np.asarray(got.logits),
                                   np.asarray(want.logits),
                                   rtol=2e-4, atol=2e-5)

    def test_moe_ffn_sharded_matches_single_device(self, cpu_devices):
        from sharetrade_tpu.config import ModelConfig
        from sharetrade_tpu.models import build_model
        ep_model, _ = self._model(cpu_devices, (2, 4), ("dp", "ep"),
                                  moe_experts=4, num_layers=2)
        # Same config WITHOUT a mesh: single-device moe_apply path.
        cfg = ModelConfig(kind="transformer", num_heads=2, head_dim=16,
                          moe_experts=4, num_layers=2)
        local_model = build_model(cfg, self.OBS_DIM)
        params = ep_model.init(jax.random.PRNGKey(0))
        obs = self._obs()
        got, _ = ep_model.apply_batch(params, obs, ())
        want, _ = local_model.apply_batch(params, obs, ())
        np.testing.assert_allclose(np.asarray(got.logits),
                                   np.asarray(want.logits),
                                   rtol=2e-4, atol=2e-5)

    def test_config_rejects_mesh_without_axis(self, cpu_devices):
        with pytest.raises(ValueError, match="sp"):
            self._model(cpu_devices, (8,), ("dp",), attention="ring")
        with pytest.raises(ValueError, match="pp"):
            self._model(cpu_devices, (8,), ("dp",), pipeline_blocks=True)

    @pytest.mark.parametrize("attention", ["ring", "ulysses"])
    def test_config_rejects_sp_attention_plus_pipeline(self, cpu_devices,
                                                       attention):
        """Nested shard_maps must fail loudly at construction, not with an
        obscure trace-time mesh error."""
        with pytest.raises(ValueError, match="pipeline_blocks is unsupported"):
            self._model(cpu_devices, (2, 2, 2), ("dp", "sp", "pp"),
                        attention=attention, pipeline_blocks=True,
                        num_layers=2)


class TestShardedHeal:
    """Per-agent kill-and-heal UNDER A DP MESH (round-4 verdict #7): the
    heal's device_get/_place round-trips must compose with donated,
    sharded buffers — the interaction that can only break sharded. The
    unsharded twin lives in tests/test_runtime.py TestPerAgentRecovery."""

    def test_kill_and_heal_on_dp_mesh(self, tmp_path, cpu_devices):
        from sharetrade_tpu.runtime import Orchestrator, ReplyState
        cfg = tiny_cfg(workers=8)
        cfg.runtime.chunk_steps = 8   # 4 chunks: poison at 1, detect at 2
        cfg.parallel.mesh_shape = {"dp": 4}
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
        poisoned = []

        def chaos(chunk_idx, metrics):
            if chunk_idx == 1 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                budget = np.asarray(
                    jax.device_get(ts.env_state.budget)).copy()
                budget[5] = np.nan       # one row on dp shard 2 corrupted
                orch._ts = orch._place(ts.replace(
                    env_state=ts.env_state.replace(
                        budget=jnp.asarray(budget))))

        mesh = build_mesh(cfg.parallel, devices=cpu_devices[:4])
        orch = Orchestrator(cfg, mesh=mesh, fault_hook=chaos)
        prices = np.linspace(10.0, 20.0, 40, dtype=np.float32)  # 32 steps
        orch.send_training_data(prices)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        # Healed in place on the mesh: no restart, no rollback.
        assert orch.restarts == 0
        assert orch.agent_heals == 1
        snap = orch.snapshot()
        assert snap["unhealthy_workers"] == 0
        assert snap["trained_workers"] == 8
        assert orch.get_avg().ok and np.isfinite(orch.get_avg().value)
        # The healed state is still dp-sharded (a heal that silently
        # replicated the batch would "pass" while undoing the mesh).
        spec = orch.train_state.env_state.budget.sharding.spec
        assert "dp" in jax.tree.leaves(tuple(spec)), spec


@pytest.mark.slow
class TestPartitionedTrainingEndToEnd:
    """Full PPO training through the Orchestrator with the partitioned
    transformer selected purely via config — sp and pp are reachable from
    the public surface, not bespoke harnesses."""

    def _cfg(self, tmp_path, mesh_shape):
        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.model.kind = "transformer"
        cfg.model.num_heads = 2
        cfg.model.head_dim = 16
        cfg.env.window = 30
        cfg.parallel.num_workers = 4
        cfg.parallel.mesh_shape = mesh_shape
        cfg.learner.unroll_len = 8
        cfg.runtime.chunk_steps = 8
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
        return cfg

    def _run(self, cfg, cpu_devices):
        from sharetrade_tpu.runtime import Orchestrator, ReplyState
        mesh = build_mesh(cfg.parallel, devices=cpu_devices)
        orch = Orchestrator(cfg, mesh=mesh)
        prices = np.linspace(10.0, 20.0, 54, dtype=np.float32)  # 24 steps
        orch.send_training_data(prices)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.get_avg().ok
        assert np.isfinite(orch.get_avg().value)
        return orch

    def test_ring_attention_via_config(self, tmp_path, cpu_devices):
        cfg = self._cfg(tmp_path, {"dp": 2, "sp": 4})
        cfg.model.attention = "ring"
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_ulysses_attention_via_config(self, tmp_path, cpu_devices):
        cfg = self._cfg(tmp_path, {"dp": 4, "sp": 2})   # sp divides 2 heads
        cfg.model.attention = "ulysses"
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_pipelined_transformer_via_config(self, tmp_path, cpu_devices):
        cfg = self._cfg(tmp_path, {"dp": 2, "pp": 4})
        cfg.model.pipeline_blocks = True
        cfg.model.num_layers = 4
        self._run(cfg, cpu_devices)

    def test_moe_transformer_via_config(self, tmp_path, cpu_devices):
        cfg = self._cfg(tmp_path, {"dp": 2, "ep": 4})
        cfg.model.moe_experts = 4
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_topk_moe_transformer_via_config(self, tmp_path, cpu_devices):
        """Capacity-dispatch top-k experts reachable from the same surface."""
        cfg = self._cfg(tmp_path, {"dp": 2, "ep": 4})
        cfg.model.moe_experts = 4
        cfg.model.moe_top_k = 2
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_a2a_moe_transformer_via_config(self, tmp_path, cpu_devices):
        """The all_to_all token-dispatch variant — the pattern whose
        communication volume scales — reachable via model.moe_dispatch."""
        cfg = self._cfg(tmp_path, {"dp": 2, "ep": 4})
        cfg.model.moe_experts = 4
        cfg.model.moe_top_k = 2
        cfg.model.moe_dispatch = "a2a"
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_episode_moe_a2a_via_config(self, tmp_path, cpu_devices):
        """Episode mode composes with expert parallelism: the flagship
        model class with its FFN dispatched all_to_all over ep — the
        round-3 capability cliff (EP existed only on the 10-100x slower
        window path) removed."""
        cfg = self._cfg(tmp_path, {"dp": 2, "ep": 4})
        cfg.model.seq_mode = "episode"
        cfg.model.moe_experts = 4
        cfg.model.moe_top_k = 2
        cfg.model.moe_dispatch = "a2a"
        cfg.model.num_layers = 2
        self._run(cfg, cpu_devices)

    def test_episode_pipeline_via_config(self, tmp_path, cpu_devices):
        """Episode mode composes with pipeline parallelism: banded blocks
        as GPipe stages (positions ride the pipeline state; K/V and aux
        escape as pipeline sides)."""
        cfg = self._cfg(tmp_path, {"dp": 2, "pp": 4})
        cfg.model.seq_mode = "episode"
        cfg.model.pipeline_blocks = True
        cfg.model.num_layers = 4
        self._run(cfg, cpu_devices)

    def test_episode_tp_shards_block_params_via_config(self, tmp_path,
                                                       cpu_devices):
        """tp × episode proven, not presumed: the episode trunk's qkv
        weight must actually shard over tp through the public surface."""
        cfg = self._cfg(tmp_path, {"dp": 2, "tp": 4})
        cfg.model.seq_mode = "episode"
        cfg.model.num_layers = 2
        orch = self._run(cfg, cpu_devices)
        w = orch.train_state.params["blocks"][0]["qkv"]["w"]
        spec = w.sharding.spec
        assert "tp" in jax.tree.leaves(tuple(spec)), spec
        orch.stop()

    @pytest.mark.parametrize("kind", ["mlp", "transformer"])
    def test_tp_axis_actually_shards_params_via_config(self, tmp_path,
                                                       cpu_devices, kind):
        """A tp axis in parallel.mesh_shape must shard the Megatron-split
        weights through the public Orchestrator surface, not silently
        replicate them."""
        cfg = self._cfg(tmp_path, {"dp": 2, "tp": 4})
        cfg.model.kind = kind
        cfg.model.num_layers = 2
        orch = self._run(cfg, cpu_devices)
        params = orch.train_state.params
        if kind == "transformer":
            w = params["blocks"][0]["qkv"]["w"]       # column-parallel
        else:
            w = params["torso1"]["w"]                 # column-parallel
        spec = w.sharding.spec
        assert "tp" in jax.tree.leaves(tuple(spec)), spec
        orch.stop()


class TestEpisodeSequenceParallel:
    """Halo-exchange banded attention (parallel/episode_sp.py): the episode
    transformer's tick sequence sharded over sp with a single neighbor
    ppermute instead of a full ring."""

    def test_halo_matches_reference_banded(self, cpu_devices):
        from sharetrade_tpu.parallel.episode_sp import (
            halo_banded_attention_sharded)
        from sharetrade_tpu.ops.attention import reference_attention
        mesh = Mesh(np.asarray(cpu_devices).reshape(8), ("sp",))
        window = 9
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(kk, (2, 2, 128, 16))
                   for kk in jax.random.split(key, 3))
        attend = halo_banded_attention_sharded(mesh, use_pallas=False)
        got = attend(q, k, v, window)
        want = reference_attention(q, k, v, causal=True, local_window=window)
        # EXACT over the whole sequence, including the first window-1
        # positions: shard 0's zero-halo contamination is corrected by the
        # local-prefix pass (episode_sp.py), so the sharded function matches
        # the reference for any caller, not just ones that discard the head.
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    def test_rejects_shard_shorter_than_band(self, cpu_devices):
        from sharetrade_tpu.parallel.episode_sp import (
            halo_banded_attention_sharded)
        mesh = Mesh(np.asarray(cpu_devices).reshape(8), ("sp",))
        q = jnp.zeros((1, 1, 32, 16))      # 4 per shard < window-1
        attend = halo_banded_attention_sharded(mesh, use_pallas=False)
        with pytest.raises(ValueError, match="halo band"):
            attend(q, q, q, window=9)

    def test_sp_replay_matches_local_replay(self, cpu_devices):
        """Same params: the sp-sharded episode replay must equal the local
        banded replay on every observable (per-step) output."""
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.agents.rollout import (
            collect_rollout, replay_forward)
        from sharetrade_tpu.env import trading

        def make(attention, mesh):
            cfg = FrameworkConfig()
            cfg.learner.algo = "ppo"
            cfg.model.kind = "transformer"
            cfg.model.seq_mode = "episode"
            cfg.model.attention = attention
            cfg.model.num_layers = 2
            cfg.model.num_heads = 2
            cfg.model.head_dim = 16
            cfg.env.window = 16
            cfg.parallel.num_workers = 4
            cfg.learner.unroll_len = 34
            cfg.runtime.chunk_steps = 34
            env = trading.make_trading_env(
                jnp.linspace(10.0, 20.0, 64), window=16)
            return build_agent(cfg, env, mesh=mesh), env

        mesh = Mesh(np.asarray(cpu_devices).reshape(4, 2), ("dp", "sp"))
        local_agent, env = make("flash", mesh)
        sp_agent, _ = make("ring", mesh)
        ts = local_agent.init(jax.random.PRNGKey(0))
        ts, traj, _, init_carry = collect_rollout(
            local_agent.model, env, ts, 34, 4)
        logits_local, values_local, _ = replay_forward(
            local_agent.model, ts.params, traj, init_carry)
        logits_sp, values_sp, _ = replay_forward(
            sp_agent.model, ts.params, traj, init_carry)
        np.testing.assert_allclose(np.asarray(logits_sp),
                                   np.asarray(logits_local),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(values_sp),
                                   np.asarray(values_local),
                                   rtol=2e-4, atol=2e-5)

    def test_episode_ring_requires_sp_mesh(self, cpu_devices):
        from sharetrade_tpu.config import ModelConfig
        from sharetrade_tpu.models import build_model
        cfg = ModelConfig(kind="transformer", seq_mode="episode",
                          attention="ring", num_heads=2, head_dim=16)
        with pytest.raises(ValueError, match="sp"):
            build_model(cfg, 18)

    @pytest.mark.slow
    def test_episode_sp_training_via_config(self, tmp_path, cpu_devices):
        """Full PPO training through the Orchestrator: episode mode + sp
        halo attention selected purely via config."""
        from sharetrade_tpu.runtime import Orchestrator, ReplyState
        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.attention = "ring"
        cfg.model.num_layers = 2
        cfg.model.num_heads = 2
        cfg.model.head_dim = 16
        cfg.env.window = 16
        cfg.parallel.num_workers = 4
        cfg.parallel.mesh_shape = {"dp": 4, "sp": 2}
        cfg.learner.unroll_len = 8
        cfg.runtime.chunk_steps = 8
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
        mesh = build_mesh(cfg.parallel, devices=cpu_devices)
        orch = Orchestrator(cfg, mesh=mesh)
        orch.send_training_data(np.linspace(10.0, 20.0, 40, dtype=np.float32))
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.get_avg().ok and np.isfinite(orch.get_avg().value)
