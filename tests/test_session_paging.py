"""Tiered session paging + fleet autoscaler (ISSUE 18).

The load-bearing contracts:

- **Warm bitwise oracle**: a session evicted to the host-RAM warm tier
  and paged back in CONTINUES — its responses are bit-identical to a
  never-evicted session fed the same requests (device_get → host numpy
  → device_put → batched scatter install is an exact byte round trip).
  This is the tier's whole claim; the PR-8 cold-restart contract stays
  pinned for everything the warm tier does not hold.
- **Bounded warm store**: byte-budgeted + session-bounded LRU; overflow
  demotes stalest-first to cold, an over-budget carry is refused (that
  session pages straight to cold), and demoted/refused sessions resume
  under the documented COLD semantics (fresh-session bitwise).
- **Autoscaler discipline**: the membership controller is the PR-14
  pattern applied to ``EnginePool.scale`` — windowed signals out of the
  telemetry history ring, asymmetric hysteresis (one noisy window scales
  up, 2x quiet windows scale down, dead band holds), bounded ±1 steps
  under a cooldown, config floor/ceiling — all driven here with stubbed
  rows, a stub pool, and a fake clock (no subprocesses).
- **Tooling**: lint check 17 (warm tier bounded in code; the dispatch-
  thread paging functions inherit the host-op ban) fixture-tested like
  checks 10-16; the ``cli obs`` "sessions" section; EnginePool.scale's
  spawn/retire mechanics on stub children.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.config import ConfigError, ModelConfig, ServeConfig
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.transformer_episode import (
    episode_transformer_policy,
)
from sharetrade_tpu.serve import ServeEngine
from sharetrade_tpu.serve.engine import WarmStore
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8
OBS_DIM = WINDOW + 2


@pytest.fixture(scope="module")
def episode_model():
    return episode_transformer_policy(obs_dim=OBS_DIM, num_layers=2,
                                      num_heads=2, head_dim=8)


@pytest.fixture(scope="module")
def episode_params(episode_model):
    return episode_model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def prices():
    rng = np.random.default_rng(7)
    return rng.uniform(10.0, 20.0, 256).astype(np.float32)


def obs_at(prices, start, t, *, budget=2400.0, shares=0.0):
    lo = start + t
    return np.concatenate(
        [prices[lo:lo + WINDOW],
         np.asarray([budget, shares], np.float32)]).astype(np.float32)


class SequentialReference:
    """One-at-a-time ``model.apply`` with carries threaded per session —
    the parity baseline (same as tests/test_serve.py)."""

    def __init__(self, model, params):
        self.model = model
        self.params = params
        self._apply = jax.jit(model.apply)
        self._carries: dict = {}

    def step(self, sid, obs):
        carry = self._carries.get(sid)
        if carry is None:
            carry = self.model.init_carry()
        out, carry = self._apply(self.params, obs, carry)
        self._carries[sid] = carry
        logits = np.asarray(out.logits)
        return int(np.argmax(logits)), logits


def _engine(model, params, *, slots=2, max_batch=2, warm_bytes=1 << 20,
            warm_max_sessions=4096, registry=None):
    engine = ServeEngine(
        model,
        ServeConfig(max_batch=max_batch, slots=slots, batch_timeout_ms=2.0,
                    warm_bytes=warm_bytes,
                    warm_max_sessions=warm_max_sessions),
        params, registry=registry or MetricsRegistry())
    engine.warmup()
    return engine


def _carry_nbytes(model) -> int:
    return sum(int(np.asarray(leaf).size) * np.asarray(leaf).dtype.itemsize
               for leaf in jax.tree.leaves(model.init_carry()))


# ---------------------------------------------------------------------------
# WarmStore unit semantics (single-owner LRU, bytes + session bounds)


class TestWarmStore:
    def test_lru_demotes_stalest_first_and_hits_refresh(self):
        store = WarmStore(max_bytes=300, max_sessions=64)
        for i, sid in enumerate(("a", "b", "c")):
            assert store.put(sid, rows=sid.upper(), nbytes=100,
                             steps=i + 1) == []
        assert store.bytes == 300 and len(store) == 3
        # A hit removes the entry and hands back the carry WITH its
        # park-time step stamp (the adoption clock travels with the
        # carry — ISSUE 20)...
        assert store.pop("a") == ("A", 1)
        assert store.bytes == 200
        # ...and re-parking makes it the FRESHEST: the next overflow
        # demotes b (now stalest) as a full (sid, rows, nbytes, steps)
        # entry — exactly what the spill tier seals to disk.
        assert store.put("a", "A2", 100, steps=4) == []
        assert store.put("d", "D", 100) == [("b", "B", 100, 2)]
        assert store.demotions == 1
        assert store.pop("b") is None           # demoted = cold
        assert store.pop("a") == ("A2", 4)

    def test_byte_budget_refuses_oversize_carry(self):
        store = WarmStore(max_bytes=100, max_sessions=64)
        assert store.put("big", "X", 101) == []
        assert store.refusals == 1
        assert len(store) == 0 and store.bytes == 0
        assert store.put("junk", "Y", 0) == []  # degenerate size: refused
        assert store.refusals == 2

    def test_session_bound_demotes_even_under_byte_budget(self):
        store = WarmStore(max_bytes=1 << 20, max_sessions=2)
        store.put("a", "A", 10)
        store.put("b", "B", 10)
        assert store.put("c", "C", 10) == [("a", "A", 10, 0)]
        assert len(store) == 2 and store.bytes == 20

    def test_reput_same_session_replaces_bytes(self):
        store = WarmStore(max_bytes=250, max_sessions=64)
        store.put("a", "A", 100)
        store.put("a", "A2", 200)               # replace, not accumulate
        assert store.bytes == 200 and len(store) == 1
        assert store.pop("a") == ("A2", 0)


def test_slot_pool_lru_order_and_pinned_exemption():
    """The hot tier's eviction choice feeds the warm tier: admit picks
    the OLDEST unpinned session — a session pinned by the current batch
    is never the victim even when it is the LRU — so the sid handed to
    the page-out path is exactly the LRU-order victim."""
    from sharetrade_tpu.serve.engine import SlotPool
    pool = SlotPool(capacity=3)
    for sid in ("a", "b", "c"):
        slot, evicted = pool.admit(sid, pinned=set())
        assert evicted is None
    pool.lookup("a")                            # refresh: order b, c, a
    _slot, evicted = pool.admit("d", pinned=set())
    assert evicted == "b"                       # oldest unpinned
    # 'c' is now the LRU but sits in the current batch: exempt.
    _slot, evicted = pool.admit("e", pinned={"c"})
    assert evicted == "a"
    assert pool.evictions == 2


# ---------------------------------------------------------------------------
# engine-level paging (the bitwise oracles)


def test_config_validation():
    model = build_model(ModelConfig(kind="mlp", hidden_dim=16), OBS_DIM,
                        head="ac")
    params = model.init(jax.random.PRNGKey(1))
    with pytest.raises(ConfigError):
        ServeEngine(model, ServeConfig(warm_bytes=-1), params)
    with pytest.raises(ConfigError):
        ServeEngine(model, ServeConfig(warm_max_sessions=0), params)


def test_warm_unpark_is_bitwise_uninterrupted(episode_model,
                                              episode_params, prices):
    """THE acceptance oracle: evict a session into the warm tier, page
    it back in, and its continuation is bit-identical to a session that
    was never evicted — NOT the cold fresh-restart the PR-8 contract
    gives demoted sessions."""
    registry = MetricsRegistry()
    engine = _engine(episode_model, episode_params, registry=registry)
    ref = SequentialReference(episode_model, episode_params)
    try:
        for t in range(3):
            obs = obs_at(prices, 0, t)
            result = engine.submit("A", obs).wait(30.0)
            assert result is not None
            action, logits = ref.step("A", obs)
            assert np.array_equal(result.logits, logits)
        # Evict A: B and C take both slots; A's carry pages out through
        # the consumer readback into the warm store.
        for sid, start in (("B", 40), ("C", 80)):
            assert engine.submit(sid, obs_at(prices, start, 0)).wait(30.0)
        # A returns: warm hit, batched scatter re-install, and steps 3..5
        # CONTINUE the uninterrupted reference bit-for-bit.
        for t in range(3, 6):
            obs = obs_at(prices, 0, t)
            result = engine.submit("A", obs).wait(30.0)
            assert result is not None
            action, logits = ref.step("A", obs)
            assert result.action == action
            assert np.array_equal(result.logits, logits)
        counters = registry.counters()
        assert counters["serve_warm_parks_total"] >= 1
        assert counters["serve_warm_hits_total"] >= 1
    finally:
        engine.stop()


def test_warm_overflow_demotes_to_cold_restart(episode_model,
                                               episode_params, prices):
    """A warm store sized for exactly ONE carry: the second park demotes
    the first session to cold, which then resumes under the documented
    cold contract (bitwise-fresh); the still-warm session continues
    bitwise-uninterrupted."""
    nbytes = _carry_nbytes(episode_model)
    registry = MetricsRegistry()
    engine = _engine(episode_model, episode_params, warm_bytes=nbytes,
                     registry=registry)
    ref = SequentialReference(episode_model, episode_params)
    try:
        for t in range(3):
            obs = obs_at(prices, 0, t)
            assert engine.submit("A", obs).wait(30.0)
            ref.step("A", obs)
        obs_b = obs_at(prices, 40, 0)
        assert engine.submit("B", obs_b).wait(30.0)
        ref.step("B", obs_b)
        # C evicts A (parked: warm holds A); D evicts B (parked: A is
        # demoted — one-carry budget).
        assert engine.submit("C", obs_at(prices, 80, 0)).wait(30.0)
        assert engine.submit("D", obs_at(prices, 120, 0)).wait(30.0)
        # B pages back WARM: continues the uninterrupted reference.
        obs = obs_at(prices, 40, 1)
        result = engine.submit("B", obs).wait(30.0)
        assert result is not None
        _, logits = ref.step("B", obs)
        assert np.array_equal(result.logits, logits)
        # A was demoted: returns COLD — bitwise a fresh session fed the
        # same suffix.
        for t in range(3, 5):
            obs = obs_at(prices, 0, t)
            result = engine.submit("A", obs).wait(30.0)
            assert result is not None
            action, logits = ref.step("A-fresh", obs)
            assert result.action == action
            assert np.array_equal(result.logits, logits)
        assert registry.counters()["serve_warm_demotions_total"] >= 1
    finally:
        engine.stop()


def test_undersized_budget_refuses_and_stays_cold(episode_model,
                                                  episode_params, prices):
    """``warm_bytes`` smaller than one carry: every park is refused, and
    eviction keeps the exact PR-8 cold-restart behavior."""
    registry = MetricsRegistry()
    engine = _engine(episode_model, episode_params, warm_bytes=1,
                     registry=registry)
    ref = SequentialReference(episode_model, episode_params)
    try:
        for t in range(3):
            assert engine.submit("A", obs_at(prices, 0, t)).wait(30.0)
        for sid, start in (("B", 40), ("C", 80)):
            assert engine.submit(sid, obs_at(prices, start, 0)).wait(30.0)
        for t in range(3, 5):
            obs = obs_at(prices, 0, t)
            result = engine.submit("A", obs).wait(30.0)
            assert result is not None
            action, logits = ref.step("A-fresh", obs)
            assert result.action == action
            assert np.array_equal(result.logits, logits)
        assert engine._warm.refusals >= 1
        assert registry.counters().get("serve_warm_hits_total", 0) == 0
    finally:
        engine.stop()


def test_warm_disabled_for_stateless_model(prices):
    """A stateless (empty-carry) model never enables the warm tier even
    with a budget configured — there is nothing to park."""
    model = build_model(ModelConfig(kind="mlp", hidden_dim=16), OBS_DIM,
                        head="ac")
    params = model.init(jax.random.PRNGKey(1))
    registry = MetricsRegistry()
    engine = _engine(model, params, warm_bytes=1 << 20, registry=registry)
    try:
        assert engine._warm_enabled is False
        for sid, start in (("A", 0), ("B", 40), ("C", 80)):
            assert engine.submit(sid, obs_at(prices, start, 0)).wait(30.0)
        counters = registry.counters()
        assert counters.get("serve_warm_parks_total", 0) == 0
        assert counters.get("serve_warm_misses_total", 0) == 0
    finally:
        engine.stop()


def test_sessions_gauges_published(episode_model, episode_params, prices):
    """The paging surface publishes its population/economics gauges
    through the registry (the Prometheus/`cli obs` surface)."""
    registry = MetricsRegistry()
    engine = _engine(episode_model, episode_params, registry=registry)
    try:
        for sid, start in (("A", 0), ("B", 40), ("C", 80)):
            assert engine.submit(sid, obs_at(prices, start, 0)).wait(30.0)
        # A's park rides the consumer readback into the inbox; the NEXT
        # dispatch commits it to the warm store — drive one hot request.
        assert engine.submit("C", obs_at(prices, 80, 1)).wait(30.0)
        engine._publish_stats(force=True)
        gauges = {k: registry.latest(k)
                  for k in ("serve_sessions_hot", "serve_warm_sessions",
                            "serve_warm_bytes", "serve_warm_budget_bytes",
                            "serve_warm_econ_ms_per_mb")}
        assert gauges["serve_sessions_hot"] == 2.0      # slots=2, full
        assert gauges["serve_warm_sessions"] == 1.0     # A parked
        assert gauges["serve_warm_bytes"] > 0
        assert gauges["serve_warm_budget_bytes"] == float(1 << 20)
        assert gauges["serve_warm_econ_ms_per_mb"] is not None
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# autoscaler decision discipline (stubbed rows, stub pool, fake clock)


class StubPool:
    def __init__(self, target=2, live=2):
        self.target = target
        self._live = live
        self.scaled: list[int] = []

    def live_count(self):
        return self._live

    def scale(self, n):
        self.scaled.append(n)
        self.target = n


def _fleet_cfg(tmp_path, **kw):
    from sharetrade_tpu.config import FrameworkConfig
    cfg = FrameworkConfig()
    cfg.fleet.dir = str(tmp_path / "fleet")
    cfg.fleet.num_engines = kw.pop("num_engines", 4)
    cfg.fleet.autoscale = True
    cfg.fleet.min_engines = kw.pop("min_engines", 1)
    cfg.fleet.autoscale_interval_s = kw.pop("interval", 0.01)
    cfg.fleet.autoscale_cooldown_s = kw.pop("cooldown", 0.0)
    cfg.fleet.autoscale_window = kw.pop("window", 3)
    for k, v in kw.items():
        setattr(cfg.fleet, k, v)
    return cfg


def _rows(n, *, burn=0.0, depth=0.0, engines=2.0, overload=0.0):
    return [{"ts": float(i), "fleet_slo_availability_burn": burn,
             "fleet_queue_depth": depth, "fleet_engines_live": engines,
             "fleet_overload": overload} for i in range(n)]


class TestAutoscalerDecide:
    def _scaler(self, tmp_path, pool=None, **kw):
        from sharetrade_tpu.fleet.autoscale import EngineAutoscaler
        clock = {"t": 1000.0}
        scaler = EngineAutoscaler(pool or StubPool(),
                                  _fleet_cfg(tmp_path, **kw).fleet,
                                  clock=lambda: clock["t"])
        return scaler, clock

    def test_validation(self, tmp_path):
        from sharetrade_tpu.fleet.autoscale import EngineAutoscaler
        with pytest.raises(ConfigError):
            EngineAutoscaler(StubPool(),
                             _fleet_cfg(tmp_path, min_engines=0).fleet)
        with pytest.raises(ConfigError):
            EngineAutoscaler(StubPool(),
                             _fleet_cfg(tmp_path, num_engines=2,
                                        min_engines=3).fleet)
        with pytest.raises(ConfigError):
            EngineAutoscaler(StubPool(),
                             _fleet_cfg(tmp_path, interval=0.0).fleet)

    def test_dead_band_holds(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        # Between the low and high thresholds: neither up nor down.
        rows = _rows(6, burn=0.5, depth=2.0)
        assert scaler.decide(rows, current=2) is None

    def test_up_on_sustained_burn_bounded_step(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        rows = _rows(3, burn=1.5)
        decision = scaler.decide(rows, current=2)
        assert decision is not None
        target, reason = decision
        assert target == 3                      # ONE engine, never more
        assert "burn" in reason

    def test_one_bad_poll_is_noise(self, tmp_path):
        """Windowed MEAN smooths a transient: one above-threshold poll
        in an otherwise-quiet window holds (a spike big enough to drag
        the whole mean over the line is, by definition, not noise)."""
        scaler, _ = self._scaler(tmp_path)
        rows = _rows(2) + _rows(1, burn=2.0)    # mean 0.67 < burn_high 1.0
        assert scaler.decide(rows, current=2) is None

    def test_up_on_queue_depth_per_engine(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        # Aggregate depth 20 over 2 engines = 10/engine >= 8.0 default.
        rows = _rows(3, depth=20.0, engines=2.0)
        target, reason = scaler.decide(rows, current=2)
        assert target == 3 and "queue" in reason

    def test_up_on_overload_majority(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        rows = _rows(1) + _rows(2, overload=1.0)
        target, reason = scaler.decide(rows, current=2)
        assert target == 3 and "overload" in reason

    def test_ceiling_and_floor_clamp(self, tmp_path):
        scaler, _ = self._scaler(tmp_path, num_engines=4)
        assert scaler.decide(_rows(3, burn=5.0), current=4) is None
        assert scaler.decide(_rows(6), current=1) is None   # at floor

    def test_down_needs_double_quiet_window(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        assert scaler.decide(_rows(3), current=2) is None   # 3 < 2*3 rows
        target, reason = scaler.decide(_rows(6), current=2)
        assert target == 1 and "quiet" in reason

    def test_down_vetoed_by_any_noisy_row(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        rows = _rows(5) + _rows(1, burn=0.5)    # one row above burn_low
        assert scaler.decide(rows, current=2) is None

    def test_missing_gauges_read_as_quiet(self, tmp_path):
        scaler, _ = self._scaler(tmp_path)
        rows = [{"ts": float(i)} for i in range(6)]
        target, _reason = scaler.decide(rows, current=2)
        assert target == 1

    def test_step_applies_cooldown_and_writes_state(self, tmp_path):
        pool = StubPool(target=2, live=2)
        scaler, clock = self._scaler(tmp_path, pool=pool, cooldown=10.0)
        rows = _rows(3, burn=2.0)
        clock["t"] += 1.0
        applied = scaler.step(rows=rows)
        assert applied is not None and applied.target == 3
        assert pool.scaled == [3]
        # Within the cooldown: pressure persists but no second apply.
        clock["t"] += 1.0
        assert scaler.step(rows=rows) is None
        assert pool.scaled == [3]
        # Past the cooldown the next bounded step lands.
        clock["t"] += 10.0
        applied = scaler.step(rows=rows)
        assert applied is not None and applied.target == 4
        with open(os.path.join(scaler.dir, "fleet_autoscale.json"),
                  encoding="utf-8") as f:
            state = json.load(f)
        assert state["target"] == 4 and state["decisions"] == 2
        assert state["last_decision"]["action"] == "up"

    def test_interval_rate_limits_reads(self, tmp_path):
        pool = StubPool()
        scaler, clock = self._scaler(tmp_path, pool=pool, interval=5.0)
        rows = _rows(3, burn=2.0)
        clock["t"] += 1.0                       # < interval since init
        assert scaler.step(rows=rows) is None
        clock["t"] += 5.0
        assert scaler.step(rows=rows) is not None

    def test_reads_history_ring_from_disk(self, tmp_path):
        from sharetrade_tpu.fleet.autoscale import EngineAutoscaler
        from sharetrade_tpu.obs.tsdb import FLEET_HISTORY_FILE, TsdbRing
        cfg = _fleet_cfg(tmp_path, window=2)
        os.makedirs(cfg.fleet.dir, exist_ok=True)
        ring = TsdbRing(os.path.join(cfg.fleet.dir, FLEET_HISTORY_FILE))
        for row in _rows(4, burn=3.0, engines=2.0):
            ring.append(row)
        ring.close()
        pool = StubPool()
        clock = {"t": 1000.0}
        scaler = EngineAutoscaler(pool, cfg.fleet, clock=lambda: clock["t"])
        clock["t"] += 1.0
        applied = scaler.step()
        assert applied is not None and applied.action == "up"
        assert pool.scaled == [3]


# ---------------------------------------------------------------------------
# EnginePool.scale mechanics (stub children, no jax bring-up)


_HEALTHY_STUB = r"""
import json, sys, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a): pass
    def do_GET(self):
        body = json.dumps({"ok": True, "queue_depth": 0, "overload": 0,
                           "params_step": 1, "swaps_total": 0}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
print(json.dumps({"event": "engine_listening", "host": "127.0.0.1",
                  "port": srv.server_address[1]}), flush=True)
srv.serve_forever()
"""


def _stub_spawn(script: str):
    def spawn(engine_id: str, log_path: str):
        with open(log_path, "ab") as log_f:
            return subprocess.Popen([sys.executable, "-c", script],
                                    stdout=log_f,
                                    stderr=subprocess.STDOUT)
    return spawn


def _pump(pool, predicate, timeout_s=15.0, desc="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pool.poll_once()
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {desc}")


def test_engine_pool_scale_up_down(tmp_path):
    """scale() grows by spawning supervised engines and shrinks by
    retiring the NEWEST members (drain via SIGTERM, classified retired
    — not crashed — by the reaper); scale_events counts both."""
    from sharetrade_tpu.fleet import EnginePool
    cfg = _fleet_cfg(tmp_path, num_engines=1)
    pool = EnginePool(cfg, spawn_fn=_stub_spawn(_HEALTHY_STUB))
    pool.target = 1
    with pool._lock:
        pool._spawn_new_locked()
    try:
        _pump(pool, lambda: "e0" in pool.endpoints(), desc="e0 listening")
        pool.scale(3)
        assert pool.target == 3
        _pump(pool, lambda: len(pool.endpoints()) == 3,
              desc="scale-up to 3 listening")
        restarts_before = pool.restarts_total
        pool.scale(1)
        _pump(pool, lambda: pool.counts()["alive"] == 1
              and pool.counts().get("retired", 0) == 2,
              desc="scale-down retires the two newest")
        # Retirements are NOT crashes: no respawn, no restart count.
        assert pool.restarts_total == restarts_before
        assert pool.scale_events == 2
        assert "e0" in pool.endpoints()
    finally:
        pool.kill_all()
        pool.stop(grace_s=2.0)


def test_engine_pool_scale_refused_when_quiesced(tmp_path):
    from sharetrade_tpu.fleet import EnginePool
    cfg = _fleet_cfg(tmp_path, num_engines=1)
    pool = EnginePool(cfg, spawn_fn=_stub_spawn(_HEALTHY_STUB))
    try:
        pool.quiesce()
        pool.scale(3)
        assert pool.target != 3 or pool.counts()["alive"] == 0
        assert pool.scale_events == 0
    finally:
        pool.kill_all()
        pool.stop(grace_s=2.0)


# ---------------------------------------------------------------------------
# lint check 17 fixture semantics


def test_lint_warm_tier_semantics(tmp_path):
    """Fixture semantics: an unbounded WarmStore (no popitem loop
    conditioned on the budget) is flagged unless the class carries
    ``warm-tier-ok``; the dispatch-thread paging functions inherit the
    check-8 host-op ban with the ``serve-host-ok`` escape; bounded +
    clean code passes."""
    import pathlib

    import lint_hot_loop

    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "engine.py").write_text(
        "class WarmStore:\n"
        "    def put(self, sid, rows, nbytes):\n"
        "        self._lru[sid] = rows\n"        # no eviction at all
        "        return []\n\n"
        "def _drain_park_inbox(self):\n"
        "    x = jax.device_get(rows)\n"          # host op on dispatch
        "def _install_parked(self, rows, slots):\n"
        "    print('installing')\n")
    hits, found = lint_hot_loop.lint_warm_tier(
        target=bad / "engine.py")
    assert found == {"WarmStore", "_drain_park_inbox", "_install_parked"}
    assert {(name, ln) for name, ln, _ in hits} == {
        ("WarmStore", 1), ("_drain_park_inbox", 7),
        ("_install_parked", 9)}

    good = tmp_path / "good"
    good.mkdir()
    (good / "engine.py").write_text(
        "class WarmStore:\n"
        "    def put(self, sid, rows, nbytes):\n"
        "        self._lru[sid] = (rows, nbytes)\n"
        "        while (self.bytes > self.max_bytes\n"
        "               or len(self._lru) > self.max_sessions):\n"
        "            self._lru.popitem(last=False)\n"
        "        return []\n\n"
        "def _drain_park_inbox(self):\n"
        "    self._warm.put('s', 1, 2)\n"
        "def _install_parked(self, rows, slots):\n"
        "    return self._install_fn(self._pool, rows, slots)\n")
    hits, found = lint_hot_loop.lint_warm_tier(target=good / "engine.py")
    assert hits == []

    marked = tmp_path / "marked"
    marked.mkdir()
    (marked / "engine.py").write_text(
        "# warm-tier-ok: bound lives in the caller's byte ledger\n"
        "class WarmStore:\n"
        "    def put(self, sid, rows, nbytes):\n"
        "        self._lru[sid] = rows\n\n"
        "def _drain_park_inbox(self):\n"
        "    x = jax.device_get(r)  # serve-host-ok: fixture\n"
        "def _install_parked(self):\n"
        "    pass\n")
    hits, _found = lint_hot_loop.lint_warm_tier(
        target=marked / "engine.py")
    assert hits == []


def test_lint_check17_clean_on_real_engine():
    import lint_hot_loop
    hits, found = lint_hot_loop.lint_warm_tier()
    assert hits == []
    assert {"WarmStore", "_drain_park_inbox", "_install_parked"} <= found


# ---------------------------------------------------------------------------
# cli obs "sessions" section


def test_obs_sessions_section(tmp_path):
    """`cli obs` grows a sessions section: tier populations, warm
    hit/miss, bytes vs budget, economics gauge — plus the autoscaler
    state file folded in as sessions.autoscaler."""
    from sharetrade_tpu.config import FrameworkConfig
    from sharetrade_tpu.obs import build_obs, summarize_run_dir

    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "run")
    registry = MetricsRegistry()
    bundle = build_obs(cfg, registry)
    registry.record_many({
        "serve_sessions_hot": 16.0, "serve_warm_sessions": 48.0,
        "serve_warm_bytes": 6144.0, "serve_warm_budget_bytes": 65536.0,
        "serve_warm_econ_ms_per_mb": 12.5})
    registry.inc("serve_warm_parks_total", 80)
    registry.inc("serve_warm_hits_total", 60)
    registry.inc("serve_warm_misses_total", 20)
    registry.inc("serve_warm_demotions_total", 4)
    registry.inc("serve_prefills_total", 24)
    bundle.flush()
    bundle.close()
    with open(os.path.join(cfg.obs.dir, "fleet_autoscale.json"), "w",
              encoding="utf-8") as f:
        json.dump({"ts": 0.0, "target": 3, "actual": 3, "floor": 1,
                   "ceiling": 4, "decisions": 2,
                   "last_decision": {"action": "up", "from": 2, "to": 3,
                                     "reason": "burn"}}, f)
    summary = summarize_run_dir(cfg.obs.dir)
    sessions = summary["sessions"]
    assert sessions["hot"] == 16.0
    assert sessions["warm"] == 48.0
    assert sessions["warm_hit_rate"] == 0.75
    assert sessions["warm_demotions_total"] == 4.0
    assert sessions["econ_ms_per_mb"] == 12.5
    assert sessions["autoscaler"]["target"] == 3
    assert sessions["autoscaler"]["last_decision"]["action"] == "up"
