"""Actor-process kill soak (tools/actor_soak.py) — REAL learner + actor
subprocesses, real SIGKILLs, driven in-process.

The quick profile (2 kills into an N=2 pool, no scale/terminal scenarios)
is the tier-1 guard for the disaggregation contract: an actor process
dying NEVER restarts the learner, every actor journal reads cleanly
through the segmented CRC reader after each kill with a monotone
high-water, the pool's restart counter reconciles exactly with the
injected kills, and the learner actually trains on ingested actor
experience before the SIGTERM drain (exit 75, no leaked actor
processes). The full soak — 20 seeded injections into an N=4 pool plus
the mid-run elastic-membership ``scale()`` join and the
terminal-failure degrade — is the ``slow``-marked variant (also
``make actor-soak``).
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import actor_soak  # noqa: E402


class TestQuickSoak:
    def test_two_kills_learner_never_restarts(self, tmp_path):
        summary = actor_soak.run_soak(
            kills=2, actors=2, seed=0, workdir=str(tmp_path),
            sigterm_every=2, terminal_failure=False, scale_test=False,
            verbose=False)
        # Both injections landed and the pool counted exactly them (the
        # reconciliation inside run_soak also asserted, after EVERY kill,
        # that the learner pid/started_at never changed).
        assert summary["injected"] == 2
        assert summary["final_status"]["restarts_total"] == 2
        assert summary["final_status"]["failed"] == 0
        # The learner trained on actor experience, and the drain retired
        # every member (exit 75 + no leaked pids checked in stop()).
        assert summary["rows_ingested"] > 0
        states = [a["state"]
                  for a in summary["final_status"]["actors"].values()]
        assert states and all(s == "retired" for s in states)
        # Committed transitions survived the kills: a recovered per-actor
        # high-water exists for every member that journaled.
        assert summary["high_water"]
        assert all(hw > 0 for hw in summary["high_water"].values())


@pytest.mark.slow
class TestFullSoak:
    def test_twenty_seeded_kills_scale_and_terminal_failure(self, tmp_path):
        summary = actor_soak.run_soak(
            kills=20, actors=4, seed=0, workdir=str(tmp_path),
            sigterm_every=3, terminal_failure=True, scale_test=True,
            verbose=True)
        assert summary["injected"] >= 20
        assert summary["scaled"] is True
        assert summary["terminal_failed_actor"]
        assert summary["final_status"]["failed"] == 1
        assert summary["rows_ingested"] > 0
