"""Sharding-consistency gate (round 8): the partitioned step program must
keep its carries on their canonical shardings — proven at compile time.

The failure mode under test: GSPMD bridging two program regions by fully
replicating a tensor and re-slicing it under a transposed mesh layout. XLA
logs ``Involuntary full rematerialization`` (C++ LOG(WARNING) → stderr,
which pytest's ``capfd`` captures at the fd level) and the step pays a full
all-gather + repartition of e.g. the episode carry's ``hist`` buffer every
chunk. ``parallel/sharding.py`` pins the carry/env_state seams with
``with_sharding_constraint`` and routes every placement through ONE
canonical NamedSharding per (mesh, spec); these tests compile the
issue-named configs (dp2×tp2, dp2×sp2) on the forced-8-device host platform
(conftest) and assert the log stays clean, the pins cost nothing, and the
megachunk metrics stay shard-resident until readback.
"""

import importlib.util
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.env import trading
from sharetrade_tpu.parallel import (
    canonical_sharding,
    jit_parallel_step,
    make_parallel_step,
    mlp_tp_rules,
)

REMAT = "Involuntary full rematerialization"
TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _shard_audit():
    spec = importlib.util.spec_from_file_location(
        "shard_audit", TOOLS / "shard_audit.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ppo_mlp_cfg(workers=8):
    cfg = FrameworkConfig()
    cfg.learner.algo = "ppo"
    cfg.env.window = 8
    cfg.model.hidden_dim = 16
    cfg.parallel.num_workers = workers
    cfg.runtime.chunk_steps = 4
    cfg.learner.unroll_len = 4
    return cfg


def _build(cfg, mesh, *, rules=None, mega=1, constrain=True, series=64):
    env = trading.env_from_prices(
        jnp.linspace(10.0, 20.0, series), window=cfg.env.window)
    agent = build_agent(cfg, env, mesh=mesh)
    ts = agent.init(jax.random.PRNGKey(0))
    sh, fn = jit_parallel_step(agent, mesh, ts, param_rules=rules,
                               megachunk_factor=mega, constrain=constrain)
    return jax.device_put(ts, sh), fn


class TestCanonicalShardings:
    def test_one_object_per_mesh_and_spec(self, cpu_mesh):
        """The canonical-spec contract is structural: every layer asking for
        (mesh, spec) holds the IDENTICAL NamedSharding object."""
        a = canonical_sharding(cpu_mesh, P("dp"))
        b = canonical_sharding(cpu_mesh, P("dp"))
        assert a is b
        assert canonical_sharding(cpu_mesh) is canonical_sharding(cpu_mesh)

    def test_place_and_step_output_specs_agree(self, cpu_mesh):
        """A placed (fresh/restored/healed) state and a stepped state must
        sit on the same specs — a divergence here is exactly the
        involuntary reshard the audit gates (paid on the first chunk after
        every recovery)."""
        cfg = _ppo_mlp_cfg()
        env = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 64), window=cfg.env.window)
        agent = build_agent(cfg, env, mesh=cpu_mesh)
        place, step = make_parallel_step(agent, cpu_mesh)
        ts = place(agent.init(jax.random.PRNGKey(0)))
        ts2, _ = step(ts)
        placed = jax.tree.map(lambda l: l.sharding.spec,
                              (ts.carry, ts.env_state))
        stepped = jax.tree.map(lambda l: l.sharding.spec,
                               (ts2.carry, ts2.env_state))
        assert placed == stepped


class TestNoInvoluntaryRemat:
    """The issue-named config matrix entries, compiled in-process with the
    fd-level stderr capture watching the XLA SPMD log."""

    def test_dp2_tp2_step_compiles_clean(self, cpu_devices, capfd):
        mesh = Mesh(np.asarray(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
        ts, fn = _build(_ppo_mlp_cfg(), mesh, rules=mlp_tp_rules())
        fn.lower(ts).compile()
        assert REMAT not in capfd.readouterr().err

    def test_dp4_sp2_episode_step_compiles_clean(self, cpu_devices, capfd):
        """The round-8 motivating case, at the EXACT shapes that reproduced
        MULTICHIP's ``ts.carry['hist']`` [4,1,2]→[1,2,4] warning: PPO's
        permuted minibatch gather of the dp-sharded episode carry colliding
        with the sp halo-attention's transposed-mesh spec. Fixed by the
        rollout→update replicate seam (agents/ppo.py) + the canonical
        carry pins."""
        mesh = Mesh(np.asarray(cpu_devices[:8]).reshape(4, 2), ("dp", "sp"))
        cfg = _ppo_mlp_cfg(workers=8)
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.attention = "ring"
        cfg.model.num_layers = 2
        cfg.model.num_heads = 2
        cfg.model.head_dim = 8
        cfg.env.window = 14
        cfg.parallel.mesh_shape = {"dp": 4, "sp": 2}
        ts, fn = _build(cfg, mesh, series=40)
        fn.lower(ts).compile()
        assert REMAT not in capfd.readouterr().err

    def test_dp2_sp4_window_ring_step_compiles_clean(self, cpu_devices,
                                                     capfd):
        """The second reproducer: window-mode ring attention, where the
        minibatch gathers themselves carried the involuntary-remat (8
        warnings at agents/ppo.py's x[:, idx] sites before the fix)."""
        mesh = Mesh(np.asarray(cpu_devices[:8]).reshape(2, 4), ("dp", "sp"))
        cfg = _ppo_mlp_cfg(workers=4)
        cfg.model.kind = "transformer"
        cfg.model.attention = "ring"
        cfg.model.num_layers = 1
        cfg.model.num_heads = 2
        cfg.model.head_dim = 8
        cfg.env.window = 14
        cfg.parallel.mesh_shape = {"dp": 2, "sp": 4}
        ts, fn = _build(cfg, mesh, series=40)
        fn.lower(ts).compile()
        assert REMAT not in capfd.readouterr().err

    def test_constrained_collectives_no_worse(self, cpu_devices):
        """The carry pin must be free: per-op collective counts of the
        constrained program <= the unconstrained one (a version-robust
        relative check; the absolute ceilings live in the audit manifest)."""
        audit = _shard_audit()
        mesh = Mesh(np.asarray(cpu_devices[:4]).reshape(2, 2), ("dp", "tp"))
        counts = {}
        for constrain in (True, False):
            ts, fn = _build(_ppo_mlp_cfg(), mesh, rules=mlp_tp_rules(),
                            constrain=constrain)
            counts[constrain] = audit.collective_counts(
                fn.lower(ts).compile().as_text())
        for op, n in counts[True].items():
            assert n <= counts[False][op], (op, counts)


class TestGoldenCollectiveCounts:
    def test_counts_within_manifest_ceiling(self, cpu_devices):
        """Golden check against the checked-in audit manifest — pinned to
        the toolchain that measured it (collective counts are partitioner-
        version dependent; under a different jax the audit tool still gates
        on remat, and this test steps aside)."""
        audit = _shard_audit()
        manifest = json.loads(
            (TOOLS / "shard_audit_manifest.json").read_text())
        if manifest.get("jax_version") != jax.__version__:
            pytest.skip(
                f"manifest measured under jax {manifest.get('jax_version')}, "
                f"running {jax.__version__}; counts are not comparable")
        spec = next(c for c in audit.CONFIGS if c["name"] == "dp8_qlearn")
        ts, fn = audit._child_build(spec)
        counts = audit.collective_counts(fn.lower(ts).compile().as_text())
        ceiling = manifest["configs"]["dp8_qlearn"]["collectives"]
        for op, n in counts.items():
            assert n <= ceiling.get(op, 0), (op, counts, ceiling)


class TestMegachunkMetricsStaySharded:
    def test_stacked_transitions_keep_dp(self, cpu_devices):
        """The round-8 satellite fix: the fused program's stacked
        ``(K, T, B, ...)`` transition metrics must come back SHARD-RESIDENT
        (GSPMD-chosen) — the old forced-replicate out-sharding inserted an
        all-gather inside the megachunk for every journaled chunk."""
        mesh = Mesh(np.asarray(cpu_devices[:4]).reshape(4), ("dp",))
        cfg = FrameworkConfig()
        cfg.learner.algo = "dqn"
        cfg.env.window = 8
        cfg.model.hidden_dim = 16
        cfg.parallel.num_workers = 8
        cfg.runtime.chunk_steps = 4
        cfg.learner.unroll_len = 4
        cfg.learner.replay_capacity = 64
        cfg.learner.replay_batch = 8
        cfg.learner.journal_replay = True
        ts, fn = _build(cfg, mesh, mega=4)
        ts2, metrics = fn(ts)
        obs_spec = metrics["transitions"]["obs"].sharding.spec
        assert "dp" in jax.tree.leaves(tuple(obs_spec)), obs_spec
        # Scalar chunk metrics remain host-readable as before: ONE batched
        # device_get materializes the whole (K,)-stacked row set.
        host = jax.device_get({k: v for k, v in metrics.items()
                               if k != "transitions"})
        assert np.asarray(host["env_steps"]).shape == (4,)
