"""Process-kill chaos soak (tools/crash_soak.py) — REAL subprocesses, real
SIGKILL/SIGTERM, driven in-process.

The quick profile (2 kills, qlearn, no journal) is the tier-1 guard: it
proves a killed training process always resumes from an intact checkpoint,
a SIGTERM drains into the ``tag_preempt`` emergency checkpoint with the
distinct exit code, a bit-flipped resume source is quarantined and walked
back past, and no tmp debris accumulates. The full randomized soak — 20
seeded injections over the journaled DQN config — is the ``slow``-marked
variant (also ``make crash-soak``).
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import crash_soak  # noqa: E402


class TestQuickSoak:
    def test_two_kills_resume_preempt_and_walkback(self, tmp_path):
        summary = crash_soak.run_soak(
            kills=2, seed=1, algo="qlearn", workdir=str(tmp_path),
            sigterm_every=2, corruption=True, verbose=False)
        # One hard SIGKILL and one graceful SIGTERM landed...
        assert [k["signal"] for k in summary["kills"]] \
            == ["SIGKILL", "SIGTERM"]
        # ...every relaunch resumed, the TERM produced the preemption exit
        # code + emergency checkpoint, and the bit-flipped sources were
        # quarantined (never deleted) while training still completed.
        assert summary["resumes"] >= 2
        assert summary["sigterm_preempts"] == 1
        assert summary["quarantined"] >= 1
        assert summary["final_result"]["env_steps"] > 0


@pytest.mark.slow
class TestFullSoak:
    def test_twenty_seeded_injections_journaled_dqn(self, tmp_path):
        summary = crash_soak.run_soak(
            kills=20, seed=0, algo="dqn", workdir=str(tmp_path),
            sigterm_every=3, corruption=True, verbose=True)
        assert summary["resumes"] >= 20
        assert summary["sigterm_preempts"] >= 6
        assert summary["quarantined"] >= 1
