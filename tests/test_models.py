"""Model zoo: shapes, reference-parity properties, transform-friendliness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.config import ModelConfig
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.mlp import ac_mlp, q_mlp

OBS_DIM = 203


def _obs(key):
    return jax.random.uniform(key, (OBS_DIM,), minval=0.0, maxval=100.0)


class TestQMLPParity:
    """Architecture parity with QDecisionPolicyActor.scala:38-50."""

    def test_param_shapes_match_reference_graph(self):
        model = q_mlp(parity=True)
        params = model.init(jax.random.PRNGKey(0))
        assert params["layer1"]["w"].shape == (203, 200)  # w1
        assert params["layer2"]["w"].shape == (200, 3)    # w2
        # Biases are tf.constant in the reference -> not trainable params.
        assert "b" not in params["layer1"] and "b" not in params["layer2"]
        n = sum(p.size for p in jax.tree.leaves(params))
        assert n == 203 * 200 + 200 * 3  # ~41.2k (SURVEY.md §6)

    def test_output_relu_clamps_at_zero(self):
        # Reference: q = relu(...) — Q-values can never go negative.
        model = q_mlp(parity=True)
        params = model.init(jax.random.PRNGKey(1))
        out, _ = model.apply(params, _obs(jax.random.PRNGKey(2)), ())
        assert out.logits.shape == (3,)
        assert bool(jnp.all(out.logits >= 0.0))

    def test_forward_matches_hand_computed(self):
        model = q_mlp(obs_dim=4, hidden_dim=2, num_actions=3, parity=True)
        params = {"layer1": {"w": jnp.ones((4, 2))},
                  "layer2": {"w": jnp.ones((2, 3)) * 0.5}}
        obs = jnp.array([1.0, 2.0, 3.0, 4.0])
        out, _ = model.apply(params, obs, ())
        # h = relu(10 + 0.1) = 10.1 each; q = relu(10.1*2*0.5 + 0.1) = 10.2
        np.testing.assert_allclose(np.asarray(out.logits), [10.2] * 3, rtol=1e-6)

    def test_non_parity_has_trainable_biases_and_no_output_relu(self):
        model = q_mlp(parity=False)
        params = model.init(jax.random.PRNGKey(0))
        assert "b" in params["layer1"] and "b" in params["layer2"]


class TestHeads:
    @pytest.mark.parametrize("kind", ["mlp", "lstm", "transformer"])
    def test_build_apply_shapes(self, kind):
        cfg = ModelConfig(kind=kind, hidden_dim=32, num_layers=1,
                          num_heads=2, head_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        out, carry = model.apply(params, _obs(jax.random.PRNGKey(1)),
                                 model.init_carry())
        assert out.logits.shape == (3,)
        assert out.value.shape == ()
        assert jnp.isfinite(out.logits).all()

    def test_lstm_carry_evolves_and_affects_output(self):
        cfg = ModelConfig(kind="lstm", hidden_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(1))
        out1, carry1 = model.apply(params, obs, model.init_carry())
        out2, carry2 = model.apply(params, obs, carry1)
        assert not np.allclose(np.asarray(carry1[0]), np.asarray(carry2[0]))
        assert not np.allclose(np.asarray(out1.logits), np.asarray(out2.logits))

    def test_transformer_scale_invariance(self):
        # Price normalization: scaling the whole window (and budget) by 10x
        # must leave the policy's decision unchanged.
        cfg = ModelConfig(kind="transformer", num_layers=1, num_heads=2, head_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        prices = jnp.linspace(50.0, 60.0, 201)
        obs1 = jnp.concatenate([prices, jnp.array([2400.0, 3.0])])
        obs2 = jnp.concatenate([prices * 10, jnp.array([24000.0, 3.0])])
        out1, _ = model.apply(params, obs1, ())
        out2, _ = model.apply(params, obs2, ())
        np.testing.assert_allclose(np.asarray(out1.logits),
                                   np.asarray(out2.logits), rtol=1e-4)

    def test_vmap_over_agent_batch(self):
        model = ac_mlp(OBS_DIM, 32)
        params = model.init(jax.random.PRNGKey(0))
        obs_batch = jax.random.uniform(jax.random.PRNGKey(1), (8, OBS_DIM))
        outs, _ = jax.vmap(lambda o: model.apply(params, o, ()))(obs_batch)
        assert outs.logits.shape == (8, 3)

    def test_gradients_flow(self):
        model = ac_mlp(OBS_DIM, 16)
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(1))

        def loss(p):
            out, _ = model.apply(p, obs, ())
            return jnp.sum(out.logits ** 2) + out.value ** 2

        grads = jax.grad(loss)(params)
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(norms)) and any(n > 0 for n in norms)

    def test_bfloat16_compute(self):
        """bf16 compute now arrives via the precision policy's compute
        copy (precision.py) — model.dtype='bfloat16' is a migration error
        (it silently put optimizer state in bf16; tests/test_precision.py
        covers the error path). Forwards compute in the dtype of the
        params they are handed; heads cast back to f32 for numerics
        downstream (TD targets etc)."""
        from sharetrade_tpu.precision import PrecisionPolicy
        cfg = ModelConfig(kind="mlp", hidden_dim=32)
        model = build_model(cfg, OBS_DIM)
        params = PrecisionPolicy(mode="bf16_mixed").cast_compute(
            model.init(jax.random.PRNGKey(0)))
        assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(params))
        out, _ = model.apply(params, _obs(jax.random.PRNGKey(1)), ())
        assert out.logits.dtype == jnp.float32


class TestEpisodeMode:
    """Episode-mode transformer (models/transformer_episode.py): the
    incremental K/V-cache rollout and the banded-replay training pass must
    compute the same function of the same tick stream."""

    WINDOW = 16                  # ticks; obs_dim = WINDOW + 2

    def _setup(self, num_layers=2, unroll=8, num_agents=3, algo="ppo",
               **model_kw):
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.config import FrameworkConfig
        from sharetrade_tpu.env import trading

        cfg = FrameworkConfig()
        cfg.learner.algo = algo
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.num_layers = num_layers
        cfg.model.num_heads = 2
        cfg.model.head_dim = 16
        for k, v in model_kw.items():
            setattr(cfg.model, k, v)
        cfg.env.window = self.WINDOW
        cfg.parallel.num_workers = num_agents
        cfg.learner.unroll_len = unroll
        cfg.runtime.chunk_steps = unroll
        prices = 10.0 + jnp.cumsum(
            jax.random.normal(jax.random.PRNGKey(9), (64,)) * 0.1)
        env = trading.make_trading_env(
            jnp.abs(prices) + 5.0, window=cfg.env.window)
        agent = build_agent(cfg, env)
        return cfg, agent, env

    def test_rollout_replay_parity_across_chunks(self):
        """Replayed logp/value must match what the rollout recorded — for
        the FIRST chunk (prefill path) and a SECOND chunk (carry crosses
        the unroll boundary: cache + tick history + absolute positions)."""
        from sharetrade_tpu.agents.rollout import collect_rollout, replay_forward

        _, agent, env = self._setup()
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))

        for chunk in range(2):
            init_carry = ts.carry
            ts, traj, _, carry_out = collect_rollout(
                model, env, ts, 8, agent.num_agents)
            assert carry_out is init_carry  # replay starts from unroll start
            logits, values, _ = replay_forward(
                model, ts.params, traj, init_carry)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), traj.action[..., None],
                axis=-1)[..., 0]
            np.testing.assert_allclose(
                np.asarray(logp), np.asarray(traj.logp), atol=2e-4,
                err_msg=f"chunk {chunk} logp mismatch")
            np.testing.assert_allclose(
                np.asarray(values), np.asarray(traj.value), atol=2e-4,
                err_msg=f"chunk {chunk} value mismatch")

    def test_precomputed_trunk_matches_incremental_stepping(self):
        """The precomputed-rollout pair (apply_rollout_trunk + head) must
        compute the same per-step outputs AND hand off the same carry as
        prefill + incremental cache stepping — an off-by-one in q_pos, the
        tick series, or the ring-cache roll would silently train every
        episode-mode run on shifted prices."""
        _, agent, env = self._setup(num_agents=2)
        model = agent.model
        params = model.init(jax.random.PRNGKey(3))
        n_agents, t_len = 2, 6
        from sharetrade_tpu.agents.base import batched_carry, batched_reset

        # Incremental: prefill at t=0 then T-1 cache steps, Hold actions.
        state = batched_reset(env, n_agents)
        carry = batched_carry(model, n_agents)
        inc_logits, inc_values, obs_seq = [], [], []
        for _ in range(t_len):
            obs = jax.vmap(env.observe)(state)
            outs, carry = model.apply_batch(params, obs, carry)
            inc_logits.append(outs.logits)
            inc_values.append(outs.value)
            obs_seq.append(obs)
            state, _ = jax.vmap(env.step)(
                state, jnp.full((n_agents,), 2, jnp.int32))  # Hold

        # Trunk: same episode start, ticks read off the future windows.
        state0 = batched_reset(env, n_agents)
        carry0 = batched_carry(model, n_agents)
        obs0 = jax.vmap(env.observe)(state0)
        ticks = jnp.stack(
            [o[:, self.WINDOW - 1] for o in obs_seq[1:]]
            + [jax.vmap(env.observe)(state)[:, self.WINDOW - 1]], axis=1)
        hn_base, carry_tr = model.apply_rollout_trunk(
            params, obs0, ticks, carry0)
        for i in range(t_len):
            outs = model.apply_rollout_head(params, hn_base[:, i], obs_seq[i])
            np.testing.assert_allclose(
                np.asarray(outs.logits), np.asarray(inc_logits[i]),
                atol=3e-4, err_msg=f"step {i} logits")
            np.testing.assert_allclose(
                np.asarray(outs.value), np.asarray(inc_values[i]),
                atol=3e-4, err_msg=f"step {i} value")

        # Carry handoff: identical ring-layout cache, history, and cursor.
        assert int(carry_tr["t"][0]) == int(carry["t"][0])
        np.testing.assert_allclose(np.asarray(carry_tr["hist"]),
                                   np.asarray(carry["hist"]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(carry_tr["k"]),
                                   np.asarray(carry["k"]), atol=3e-4)
        np.testing.assert_allclose(np.asarray(carry_tr["v"]),
                                   np.asarray(carry["v"]), atol=3e-4)

    @pytest.mark.slow
    def test_shared_trunk_replay_matches_per_agent_unroll(self):
        """apply_unroll_shared (trunk once, per-agent heads) must produce
        the same logits/values AND the same parameter gradients as the
        per-agent apply_unroll — the linearity argument (B identical trunk
        paths pulled back by per-agent cotangents == one shared path pulled
        back by their sum) checked numerically, with distinct per-agent
        loss weights so the cotangents genuinely differ."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        w_agent = jnp.asarray([0.3, 1.7, 0.9])

        for chunk in range(2):   # prefill chunk AND a carry-crossing chunk
            init_carry = ts.carry
            ts, traj, _, _ = collect_rollout(model, env, ts, 8, 3)

            l_sh, v_sh, _ = model.apply_unroll_shared(
                ts.params, traj.obs, init_carry)
            l_pa, v_pa, _ = model.apply_unroll(ts.params, traj.obs, init_carry)
            np.testing.assert_allclose(np.asarray(l_sh), np.asarray(l_pa),
                                       atol=3e-4, err_msg=f"chunk {chunk}")
            np.testing.assert_allclose(np.asarray(v_sh), np.asarray(v_pa),
                                       atol=3e-4, err_msg=f"chunk {chunk}")

            def loss(params, fwd):
                logits, values, _ = fwd(params, traj.obs, init_carry)
                lp = jax.nn.log_softmax(logits)
                return (jnp.sum(lp[..., 0] * w_agent[None, :])
                        + jnp.sum(jnp.square(values) * w_agent[None, :]))

            g_sh = jax.grad(loss)(ts.params, model.apply_unroll_shared)
            g_pa = jax.grad(loss)(ts.params, model.apply_unroll)
            for p_sh, p_pa in zip(jax.tree.leaves(g_sh),
                                  jax.tree.leaves(g_pa)):
                # rtol accommodates backend reduction-order noise (TPU
                # measured ~3e-7, CPU ~5e-5 relative); a genuinely wrong
                # gradient path diverges by O(1) relative.
                np.testing.assert_allclose(
                    np.asarray(p_sh), np.asarray(p_pa),
                    rtol=1e-4, atol=5e-3,
                    err_msg=f"gradient mismatch (chunk {chunk})")

    @pytest.mark.slow
    def test_shared_trunk_replay_skips_zeroed_quarantine_rows(self):
        """A quarantined row's stored obs is all-zero; the shared replay
        must elect a live representative (not the zeroed row) and stay
        finite everywhere."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        init_carry = ts.carry
        ts, traj, _, _ = collect_rollout(model, env, ts, 8, 3)
        zeroed = traj._replace(
            obs=traj.obs.at[:, 0].set(0.0),
            active=traj.active.at[:, 0].set(0.0))

        l_sh, v_sh, _ = model.apply_unroll_shared(
            ts.params, zeroed.obs, init_carry)
        l_pa, v_pa, _ = model.apply_unroll(ts.params, traj.obs, init_carry)
        assert np.isfinite(np.asarray(l_sh)).all()
        assert np.isfinite(np.asarray(v_sh)).all()
        # Healthy rows replay exactly as if the zeroed row were absent.
        np.testing.assert_allclose(np.asarray(l_sh[:, 1:]),
                                   np.asarray(l_pa[:, 1:]), atol=3e-4)
        np.testing.assert_allclose(np.asarray(v_sh[:, 1:]),
                                   np.asarray(v_pa[:, 1:]), atol=3e-4)

    @pytest.mark.slow
    def test_shared_trunk_replay_skips_mid_unroll_quarantined_row(self):
        """The NORMAL fault timing: a row quarantined mid-unroll has real
        early-step obs but a zero-sanitized tail. Electing on step 0 alone
        would pick it (row 0 wins argmax) and eps-clamp its zeroed tail
        into finite garbage inside every healthy agent's trunk; the
        election must scan the WHOLE trajectory and skip it."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        init_carry = ts.carry
        ts, traj, _, _ = collect_rollout(model, env, ts, 8, 3)
        # Row 0 healthy through step 3, zeroed from step 4 onward.
        zeroed = traj._replace(
            obs=traj.obs.at[4:, 0].set(0.0),
            active=traj.active.at[4:, 0].set(0.0))

        l_sh, v_sh, _ = model.apply_unroll_shared(
            ts.params, zeroed.obs, init_carry)
        l_pa, v_pa, _ = model.apply_unroll(ts.params, traj.obs, init_carry)
        assert np.isfinite(np.asarray(l_sh)).all()
        assert np.isfinite(np.asarray(v_sh)).all()
        # Healthy rows replay exactly as if the poisoned row were absent —
        # fails if the zero-tailed row 0 was elected representative.
        np.testing.assert_allclose(np.asarray(l_sh[:, 1:]),
                                   np.asarray(l_pa[:, 1:]), atol=3e-4)
        np.testing.assert_allclose(np.asarray(v_sh[:, 1:]),
                                   np.asarray(v_pa[:, 1:]), atol=3e-4)

    def test_quarantined_representative_row_does_not_corrupt_trunk(self):
        """The shared-trunk rollout elects a HEALTHY representative row: a
        quarantined row's cursor freezes while the broadcast carry keeps
        advancing, so electing it (the old fixed row 0) would feed every
        healthy agent windows from a stale cursor with desynced RoPE
        positions. Poison row 0, roll two more chunks, and compare the
        healthy rows' trajectories against an unpoisoned twin."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        ts, *_ = collect_rollout(model, env, ts, 8, 3)   # chunk A: healthy
        twin = ts

        budget = np.asarray(ts.env_state.budget).copy()
        budget[0] = np.nan                               # row 0 poisoned
        ts = ts.replace(env_state=ts.env_state.replace(
            budget=jnp.asarray(budget)))

        for _ in range(2):                               # chunks B, C
            ts, traj_p, _, _ = collect_rollout(model, env, ts, 8, 3)
            twin, traj_t, _, _ = collect_rollout(model, env, twin, 8, 3)
            np.testing.assert_allclose(
                np.asarray(traj_p.obs[:, 1:]), np.asarray(traj_t.obs[:, 1:]),
                atol=1e-5, err_msg="healthy rows fed stale-cursor windows")
            np.testing.assert_array_equal(np.asarray(traj_p.action[:, 1:]),
                                          np.asarray(traj_t.action[:, 1:]))
        np.testing.assert_array_equal(np.asarray(ts.env_state.t[1:]),
                                      np.asarray(twin.env_state.t[1:]))

    def test_nan_carry_row_not_elected_representative(self):
        """election_health ANDs model-carry finiteness into the election:
        a row with a finite wallet but a NaN carry (K/V cache) must not be
        elected — its carry would broadcast into the shared trunk and
        poison every agent's windows, escalating a one-row fault to a
        full-batch corruption."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        ts, *_ = collect_rollout(model, env, ts, 8, 3)   # chunk A: healthy
        twin = ts

        k = np.asarray(ts.carry["k"]).copy()
        k[0] = np.nan                                    # row 0 carry poisoned
        ts = ts.replace(carry={**ts.carry, "k": jnp.asarray(k)})

        poisoned_carry = ts.carry
        ts, traj_p, _, _ = collect_rollout(model, env, ts, 8, 3)
        twin, traj_t, _, _ = collect_rollout(model, env, twin, 8, 3)
        assert np.isfinite(np.asarray(traj_p.obs)).all(), \
            "NaN carry broadcast into the shared trunk"
        np.testing.assert_allclose(
            np.asarray(traj_p.obs[:, 1:]), np.asarray(traj_t.obs[:, 1:]),
            atol=1e-5, err_msg="healthy rows corrupted by NaN-carry rep")
        np.testing.assert_array_equal(np.asarray(traj_p.action[:, 1:]),
                                      np.asarray(traj_t.action[:, 1:]))

        # Replay-side election must skip the NaN-carry row too: every
        # row's stored obs is healthy, so an obs-only election would tie
        # at count T and elect poisoned row 0 into the ONE shared pass.
        l_sh, v_sh, _ = model.apply_unroll_shared(
            ts.params, traj_t.obs, poisoned_carry)
        assert np.isfinite(np.asarray(l_sh[:, 1:])).all(), \
            "replay elected the NaN-carry representative"
        assert np.isfinite(np.asarray(v_sh[:, 1:])).all()

    def test_greedy_eval_trunk_matches_incremental(self):
        """Orchestrator.evaluate()'s precomputed-trunk greedy replay must
        reproduce the per-step incremental greedy rollout (same argmax
        actions, same rewards, same final portfolio)."""
        from sharetrade_tpu.agents.rollout import greedy_rollout_precomputed

        _, agent, env = self._setup()
        model = agent.model
        params = model.init(jax.random.PRNGKey(5))

        final_t, rewards_t = greedy_rollout_precomputed(model, env, params)

        state, carry = env.reset(), model.init_carry()
        rewards_i = []
        for _ in range(env.num_steps):
            obs = env.observe(state)
            out, carry = model.apply(params, obs, carry)
            action = jnp.argmax(out.logits).astype(jnp.int32)
            state, r = env.step(state, action)
            rewards_i.append(float(r))

        np.testing.assert_allclose(np.asarray(rewards_t),
                                   np.asarray(rewards_i), atol=1e-3)
        np.testing.assert_allclose(float(env.portfolio_value(final_t)),
                                   float(env.portfolio_value(state)),
                                   rtol=1e-5)

    def test_single_layer_no_history(self):
        # L=1: hist_len == 0 — the zero-width history path.
        from sharetrade_tpu.agents.rollout import collect_rollout, replay_forward

        _, agent, env = self._setup(num_layers=1)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(1))
        init_carry = ts.carry
        ts, traj, _, _ = collect_rollout(model, env, ts, 8, agent.num_agents)
        logits, values, _ = replay_forward(model, ts.params, traj, init_carry)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), traj.action[..., None], axis=-1)[..., 0]
        np.testing.assert_allclose(np.asarray(logp), np.asarray(traj.logp),
                                   atol=2e-4)

    def test_ppo_training_step_runs(self):
        _, agent, _ = self._setup()
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(2))
        ts, metrics = step(ts)
        assert int(ts.env_steps) == 8
        assert np.isfinite(float(metrics["loss"]))
        ts, metrics = step(ts)   # second chunk crosses the carry boundary
        assert np.isfinite(float(metrics["loss"]))

    def test_portfolio_state_reaches_the_heads(self):
        # Same prices, different budget in the observation -> different
        # logits (the head-side portfolio injection is live).
        _, agent, _ = self._setup()
        model = agent.model
        params = model.init(jax.random.PRNGKey(3))
        carry = jax.tree.map(lambda x: x[None], model.init_carry())
        obs = jnp.concatenate(
            [jnp.linspace(10.0, 12.0, self.WINDOW), jnp.array([100.0, 3.0])]
        )[None]
        out1, _ = model.apply_batch(params, obs, carry)
        obs2 = obs.at[0, self.WINDOW].set(2400.0)
        out2, _ = model.apply_batch(params, obs2, carry)
        assert not np.allclose(np.asarray(out1.logits),
                               np.asarray(out2.logits))

    @pytest.mark.slow
    def test_episode_moe_rollout_replay_parity_and_training(self):
        """Episode mode composes with MoE: the FFN routes through the
        shared dispatch (models/ffn.py). Dense-mask top-1 is per-token
        exact, so rollout (precomputed trunk + heads), banded replay, AND
        the incremental prefill must all agree; a jitted PPO chunk trains
        with finite loss and a live aux term."""
        from sharetrade_tpu.agents.rollout import (
            collect_rollout, replay_forward)

        _, agent, env = self._setup(moe_experts=4)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        assert "moe" in model.init(
            jax.random.PRNGKey(1))["blocks"][0]   # FFN is actually MoE

        for chunk in range(2):
            init_carry = ts.carry
            ts, traj, _, _ = collect_rollout(model, env, ts, 8, 3)
            logits, values, aux = replay_forward(
                model, ts.params, traj, init_carry)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), traj.action[..., None],
                axis=-1)[..., 0]
            np.testing.assert_allclose(
                np.asarray(logp), np.asarray(traj.logp), atol=3e-4,
                err_msg=f"moe chunk {chunk} logp")
            assert float(aux) > 0.0   # balance loss is live

        ts2 = agent.init(jax.random.PRNGKey(2))
        ts2, metrics = jax.jit(agent.step)(ts2)
        assert np.isfinite(float(metrics["loss"]))

    def test_factored_rollout_head_matches_exact(self):
        """rollout_head_factored (trunk terms hoisted, tiny portfolio term
        in-scan) must equal apply_rollout_head exactly up to float
        reassociation — the linearity split is algebraic, not an
        approximation."""
        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        params = model.init(jax.random.PRNGKey(7))
        t_len, bsz, d = 5, 3, model.num_actions
        key = jax.random.PRNGKey(8)
        hn_base = jax.random.normal(key, (t_len + 1, 32))  # d_model=2*16
        base_l, base_v, pf_fn = model.rollout_head_factored(params, hn_base)
        assert base_l.shape == (t_len + 1, d)
        assert base_v.shape == (t_len + 1,)
        obs = jnp.abs(jax.random.normal(
            jax.random.PRNGKey(9), (bsz, model.obs_dim))) * 30.0 + 1.0
        for i in range(t_len + 1):
            exact = model.apply_rollout_head(
                params, jnp.broadcast_to(hn_base[i], (bsz, 32)), obs)
            d_l, d_v = pf_fn(obs)
            np.testing.assert_allclose(
                np.asarray(base_l[i][None] + d_l), np.asarray(exact.logits),
                rtol=1e-5, atol=1e-5, err_msg=f"row {i} logits")
            np.testing.assert_allclose(
                np.asarray(base_v[i] + d_v), np.asarray(exact.value),
                rtol=1e-5, atol=1e-5, err_msg=f"row {i} value")

    def test_remat_blocks_matches_exact(self):
        """model.remat_blocks must be numerically a no-op — identical
        replay outputs AND parameter gradients, only the residual-memory
        profile changes (the HBM lever for the d>=1024 tier)."""
        from sharetrade_tpu.agents.rollout import collect_rollout

        _, agent, env = self._setup(num_agents=3)
        model = agent.model
        ts = agent.init(jax.random.PRNGKey(0))
        init_carry = ts.carry
        ts, traj, _, _ = collect_rollout(model, env, ts, 8, 3)

        _, agent_r, _ = self._setup(num_agents=3, remat_blocks=True)
        model_r = agent_r.model

        def loss(params, fwd):
            logits, values, _ = fwd(params, traj.obs, init_carry)
            return (jnp.sum(jax.nn.log_softmax(logits)[..., 0])
                    + jnp.sum(jnp.square(values)))

        l_e, v_e, _ = model.apply_unroll(ts.params, traj.obs, init_carry)
        l_r, v_r, _ = model_r.apply_unroll(ts.params, traj.obs, init_carry)
        np.testing.assert_allclose(np.asarray(l_r), np.asarray(l_e),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(v_r), np.asarray(v_e),
                                   atol=1e-5)
        g_e = jax.grad(loss)(ts.params, model.apply_unroll)
        g_r = jax.grad(loss)(ts.params, model_r.apply_unroll)
        for p_e, p_r in zip(jax.tree.leaves(g_e), jax.tree.leaves(g_r)):
            # rtol 5e-5, not 1e-5: remat recomputes the block forward
            # inside the backward pass, and XLA fuses/reassociates that
            # recompute differently from the saved-activation path, so
            # gradients agree only to a few float32 ulps (observed max
            # rel diff ~1.2e-5 on CPU) — a compiler-scheduling artifact,
            # not a math difference; the primal outputs above stay at
            # the tight tolerance.
            np.testing.assert_allclose(np.asarray(p_r), np.asarray(p_e),
                                       rtol=5e-5, atol=1e-5)

    def test_episode_pp_b1_pipelines_sequence_chunks(self, cpu_devices,
                                                     monkeypatch):
        """The B=1 replay pass pipelines along the SEQUENCE: banded-halo
        carries stream chunk-to-chunk through the stages, so >1 microbatch
        is in flight (round-4 weak #4: these passes ran m=1 — a full
        pipeline bubble), with parity against the unpartitioned forward."""
        from jax.sharding import Mesh
        from sharetrade_tpu.models.transformer_episode import (
            episode_transformer_policy)
        from sharetrade_tpu.parallel import pipeline as pipeline_mod
        from sharetrade_tpu.parallel.pipeline import stack_stage_params

        mesh = Mesh(np.array(cpu_devices[:2]).reshape(2), ("pp",))
        obs_dim = self.WINDOW + 2
        base = episode_transformer_policy(
            obs_dim, 3, num_layers=2, num_heads=2, head_dim=16,
            use_pallas=False)
        piped = episode_transformer_policy(
            obs_dim, 3, num_layers=2, num_heads=2, head_dim=16,
            use_pallas=False, pp_mesh=mesh)
        params = base.init(jax.random.PRNGKey(3))
        params_pp = dict(params)
        params_pp["blocks"] = stack_stage_params(params["blocks"])

        seen_m = []
        real = pipeline_mod.pipeline_apply

        def spy(stage_fn, sp, mb, *a, **k):
            seen_m.append(mb.shape[0])
            return real(stage_fn, sp, mb, *a, **k)

        monkeypatch.setattr(pipeline_mod, "pipeline_apply", spy)

        t_len = 8
        win = jnp.linspace(10.0, 12.0, self.WINDOW)
        obs_row = jnp.concatenate(
            [win, jnp.asarray([20.0, 0.0])])[None]        # (1, obs_dim)
        obs_t = jnp.broadcast_to(obs_row, (t_len, 1, obs_dim))
        carry1 = jax.tree.map(lambda x: x[None], base.init_carry())

        l_b, v_b, _ = base.apply_unroll(params, obs_t, carry1)
        l_p, v_p, _ = piped.apply_unroll(params_pp, obs_t, carry1)
        np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_b),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_b),
                                   rtol=2e-4, atol=2e-4)
        assert seen_m and max(seen_m) > 1, \
            f"B=1 replay ran a full-bubble pipeline (microbatches: {seen_m})"

    @pytest.mark.slow
    def test_remat_blocks_under_pp_matches_exact(self, cpu_devices):
        """remat_blocks under pp (per-(stage, tick) checkpointing) must be
        a numeric no-op for outputs AND gradients."""
        from jax.sharding import Mesh
        from sharetrade_tpu.models.transformer_episode import (
            episode_transformer_policy)
        from sharetrade_tpu.parallel.pipeline import stack_stage_params

        mesh = Mesh(np.array(cpu_devices[:2]).reshape(2), ("pp",))
        obs_dim = self.WINDOW + 2
        kw = dict(num_layers=2, num_heads=2, head_dim=16, use_pallas=False)
        base = episode_transformer_policy(obs_dim, 3, **kw)
        piped = episode_transformer_policy(obs_dim, 3, pp_mesh=mesh, **kw)
        piped_r = episode_transformer_policy(
            obs_dim, 3, pp_mesh=mesh, remat_blocks=True, **kw)
        params = base.init(jax.random.PRNGKey(3))
        params_pp = dict(params)
        params_pp["blocks"] = stack_stage_params(params["blocks"])

        t_len = 8
        win = jnp.linspace(10.0, 12.0, self.WINDOW)
        obs_row = jnp.concatenate([win, jnp.asarray([20.0, 0.0])])[None]
        obs_t = jnp.broadcast_to(obs_row, (t_len, 1, obs_dim))
        carry1 = jax.tree.map(lambda x: x[None], base.init_carry())

        def loss(p, fwd):
            logits, values, _ = fwd(p, obs_t, carry1)
            return (jnp.sum(jax.nn.log_softmax(logits)[..., 0])
                    + jnp.sum(jnp.square(values)))

        l_p, v_p, _ = piped.apply_unroll(params_pp, obs_t, carry1)
        l_r, v_r, _ = piped_r.apply_unroll(params_pp, obs_t, carry1)
        np.testing.assert_allclose(np.asarray(l_r), np.asarray(l_p),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v_r), np.asarray(v_p),
                                   rtol=1e-5, atol=1e-5)
        g_p = jax.grad(loss)(params_pp, piped.apply_unroll)
        g_r = jax.grad(loss)(params_pp, piped_r.apply_unroll)
        for a, b in zip(jax.tree.leaves(g_p), jax.tree.leaves(g_r)):
            # rtol accommodates recompute-order noise (the checkpointed
            # backward re-fuses differently than the stored-residual one;
            # measured ~5e-5 relative on CPU); a wrong remat diverges by
            # O(1) relative.
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-2)

        # The BATCH-microbatch path (bsz divisible by the stage count) on a
        # dp x pp mesh, with dp-sharded microbatches so the checkpointed
        # stage_fn includes the pmean(aux, b_axis) branch — the path even
        # production agent batches take.
        mesh2 = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "pp"))
        kw2 = dict(kw, pp_mesh=mesh2, pp_batch_axis="dp")
        piped2 = episode_transformer_policy(obs_dim, 3, **kw2)
        piped2_r = episode_transformer_policy(
            obs_dim, 3, remat_blocks=True, **kw2)
        bsz = 4
        rows = jnp.stack([win * (1.0 + 0.2 * b) for b in range(bsz)])
        obs_rows = jnp.concatenate(
            [rows, jnp.full((bsz, 1), 20.0), jnp.zeros((bsz, 1))], axis=-1)
        obs_t4 = jnp.broadcast_to(obs_rows, (t_len, bsz, obs_dim))
        carry4 = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (bsz,) + x.shape),
            base.init_carry())

        def loss4(p, fwd):
            logits, values, _ = fwd(p, obs_t4, carry4)
            return (jnp.sum(jax.nn.log_softmax(logits)[..., 0])
                    + jnp.sum(jnp.square(values)))

        l_p4, v_p4, _ = piped2.apply_unroll(params_pp, obs_t4, carry4)
        l_r4, v_r4, _ = piped2_r.apply_unroll(params_pp, obs_t4, carry4)
        np.testing.assert_allclose(np.asarray(l_r4), np.asarray(l_p4),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v_r4), np.asarray(v_p4),
                                   rtol=1e-5, atol=1e-5)
        g_p4 = jax.grad(loss4)(params_pp, piped2.apply_unroll)
        g_r4 = jax.grad(loss4)(params_pp, piped2_r.apply_unroll)
        for a, b in zip(jax.tree.leaves(g_p4), jax.tree.leaves(g_r4)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-2)

    @pytest.mark.slow
    def test_episode_pipeline_matches_unpartitioned(self, cpu_devices):
        """Episode × pp: the pipelined banded forward (positions riding the
        state, K/V + aux escaping as pipeline sides) must reproduce the
        unpartitioned model — logits/values of the replay AND the trunk's
        carry handoff — for both a multi-microbatch agent batch and the
        batch-of-1 trunk pass."""
        from jax.sharding import Mesh
        from sharetrade_tpu.agents.rollout import collect_rollout
        from sharetrade_tpu.models.transformer_episode import (
            episode_transformer_policy)
        from sharetrade_tpu.parallel.pipeline import stack_stage_params

        mesh = Mesh(np.array(cpu_devices[:2]).reshape(2), ("pp",))
        obs_dim = self.WINDOW + 2
        base = episode_transformer_policy(
            obs_dim, 3, num_layers=2, num_heads=2, head_dim=16,
            use_pallas=False)
        piped = episode_transformer_policy(
            obs_dim, 3, num_layers=2, num_heads=2, head_dim=16,
            use_pallas=False, pp_mesh=mesh)
        params = base.init(jax.random.PRNGKey(3))
        params_pp = dict(params)
        params_pp["blocks"] = stack_stage_params(params["blocks"])

        _, agent, env = self._setup(num_agents=4)
        ts = agent.init(jax.random.PRNGKey(0))
        init_carry = ts.carry
        ts, traj, _, _ = collect_rollout(base, env, ts, 6, 4)

        l_b, v_b, _ = base.apply_unroll(params, traj.obs, init_carry)
        l_p, v_p, _ = piped.apply_unroll(params_pp, traj.obs, init_carry)
        np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_b),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_b),
                                   rtol=2e-4, atol=2e-4)

        # Trunk pass (B=1, single microbatch) + carry handoff via sides.
        state1 = jax.tree.map(lambda x: x[:1], ts.env_state)
        carry1 = jax.tree.map(lambda x: x[:1], ts.carry)
        obs1 = jax.vmap(env.observe)(state1)
        ticks = jnp.broadcast_to(
            jnp.linspace(11.0, 12.0, 6, dtype=jnp.float32)[None], (1, 6))
        hn_b, carry_b = base.apply_rollout_trunk(params, obs1, ticks, carry1)
        hn_p, carry_p = piped.apply_rollout_trunk(
            params_pp, obs1, ticks, carry1)
        np.testing.assert_allclose(np.asarray(hn_p), np.asarray(hn_b),
                                   rtol=2e-4, atol=2e-4)
        for key in ("k", "v", "hist"):
            np.testing.assert_allclose(
                np.asarray(carry_p[key]), np.asarray(carry_b[key]),
                rtol=2e-4, atol=2e-4, err_msg=f"carry[{key}]")
        assert int(carry_p["t"][0]) == int(carry_b["t"][0])

        # dp × pp: microbatches dp-sharded, so the K/V pipeline sides must
        # carry EACH shard's own rows (a replicated side spec would hand
        # one shard's cache to every agent). Rows are made deliberately
        # distinct — the lockstep env's identical rows would mask that.
        mesh2 = Mesh(np.array(cpu_devices[:4]).reshape(2, 2), ("dp", "pp"))
        piped2 = episode_transformer_policy(
            obs_dim, 3, num_layers=2, num_heads=2, head_dim=16,
            use_pallas=False, pp_mesh=mesh2, pp_batch_axis="dp")
        t_len, bsz = 6, 4
        base_win = jnp.linspace(10.0, 12.0, self.WINDOW)
        rows = jnp.stack([base_win * (1.0 + 0.2 * b) for b in range(bsz)])
        obs_rows = jnp.concatenate(
            [rows, jnp.full((bsz, 1), 20.0), jnp.zeros((bsz, 1))], axis=-1)
        obs_t = jnp.broadcast_to(obs_rows, (t_len, bsz, obs_dim))
        carry4 = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (bsz,) + x.shape),
            base.init_carry())
        l_b4, v_b4, _ = base.apply_unroll(params, obs_t, carry4)
        l_p4, v_p4, _ = piped2.apply_unroll(params_pp, obs_t, carry4)
        np.testing.assert_allclose(np.asarray(l_p4), np.asarray(l_b4),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg="dp-sharded pipelined replay")
        ticks4 = jnp.stack(
            [jnp.linspace(11.0, 12.0, t_len) * (1.0 + 0.2 * b)
             for b in range(bsz)])
        hn_b4, carry_b4 = base.apply_rollout_trunk(
            params, obs_rows, ticks4, carry4)
        hn_p4, carry_p4 = piped2.apply_rollout_trunk(
            params_pp, obs_rows, ticks4, carry4)
        np.testing.assert_allclose(np.asarray(hn_p4), np.asarray(hn_b4),
                                   rtol=2e-4, atol=2e-4)
        for key in ("k", "v"):
            np.testing.assert_allclose(
                np.asarray(carry_p4[key]), np.asarray(carry_b4[key]),
                rtol=2e-4, atol=2e-4,
                err_msg=f"dp-sharded K/V side carry[{key}]")

    def test_episode_mode_rejects_non_transformer_kinds(self):
        from sharetrade_tpu.config import ModelConfig as MC
        with pytest.raises(ValueError, match="transformer mode"):
            build_model(MC(kind="lstm", seq_mode="episode"), 18)
        with pytest.raises(ValueError, match="seq_mode"):
            build_model(MC(kind="mlp", seq_mode="epsiode"), 18)



    def test_a2c_and_pg_episode_replay(self):
        # replay_forward's apply_unroll dispatch serves every on-policy
        # learner, not just PPO.
        for algo in ("a2c", "pg"):
            _, agent, _ = self._setup(algo=algo)
            ts = agent.init(jax.random.PRNGKey(4))
            ts, metrics = jax.jit(agent.step)(ts)
            assert np.isfinite(float(metrics["loss"])), algo
            assert int(ts.env_steps) > 0

    def test_evaluate_and_resume_roundtrip(self, tmp_path):
        """Episode-mode carry (K/V cache + tick history + step counter)
        through the full runtime: train, checkpoint, restore bit-exact,
        greedy-evaluate (the per-step incremental path end to end)."""
        from sharetrade_tpu.config import FrameworkConfig
        from sharetrade_tpu.runtime import Orchestrator, ReplyState

        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.num_layers = 2
        cfg.model.num_heads = 2
        cfg.model.head_dim = 16
        cfg.env.window = self.WINDOW
        cfg.parallel.num_workers = 3
        cfg.learner.unroll_len = 8
        cfg.runtime.chunk_steps = 8
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
        cfg.runtime.checkpoint_every_updates = 8

        prices = np.linspace(10.0, 20.0, self.WINDOW + 24, dtype=np.float32)
        orch = Orchestrator(cfg)
        orch.send_training_data(prices)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        avg = orch.get_avg().value
        ev = orch.evaluate()
        assert np.isfinite(ev["eval_portfolio"])

        resumed = Orchestrator(cfg)
        resumed.send_training_data(prices, resume=True)
        carry = resumed.train_state.carry
        assert int(np.asarray(carry["t"])[0]) > 0      # cursor restored
        assert carry["k"].shape[0] == 3                # per-agent cache
        resumed.start_training(background=False)
        assert resumed.get_avg().ok
        assert resumed.get_avg().value == pytest.approx(avg, rel=1e-5)


class TestTCN:
    """Dilated causal conv tick policy (models/tcn.py)."""

    def _model(self, obs_dim=OBS_DIM, channels=16):
        return build_model(
            ModelConfig(kind="tcn", hidden_dim=channels), obs_dim)

    def test_shapes_and_finite(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        out, carry = model.apply(params, _obs(jax.random.PRNGKey(1)), ())
        assert out.logits.shape == (3,) and out.value.shape == ()
        assert np.isfinite(np.asarray(out.logits)).all()
        assert carry == ()

    def test_receptive_field_covers_window(self):
        # Perturbing the OLDEST tick must reach the summary (last) position:
        # the dilation stack is auto-sized to cover the full window.
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(2))
        base, _ = model.apply(params, obs, ())
        pert, _ = model.apply(params, obs.at[0].mul(3.0), ())
        assert not np.allclose(np.asarray(base.logits),
                               np.asarray(pert.logits))

    def test_scale_invariance(self):
        # Tokens are rel/log-ret (shared with the transformer): scaling the
        # whole window and budget by 10x leaves the decision unchanged.
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        prices = jnp.linspace(50.0, 60.0, 201)
        obs1 = jnp.concatenate([prices, jnp.array([2400.0, 3.0])])
        obs2 = jnp.concatenate([prices * 10, jnp.array([24000.0, 3.0])])
        out1, _ = model.apply(params, obs1, ())
        out2, _ = model.apply(params, obs2, ())
        np.testing.assert_allclose(np.asarray(out1.logits),
                                   np.asarray(out2.logits), rtol=1e-3)

    def test_causal_padding_limits_receptive_field(self):
        # A deliberately SHALLOW stack (1 block, kernel 3, dilation 1) has a
        # 3-tick receptive field at the summary position. Perturbing ticks
        # OUTSIDE it must not change the output — with anti-causal (right)
        # padding the summary would instead depend on padding, not on the
        # latest ticks, and the in-field perturbation check would fail.
        from sharetrade_tpu.models.tcn import tcn_policy
        obs_dim = 34                      # window 32
        model = tcn_policy(obs_dim, channels=8, num_blocks=1)
        params = model.init(jax.random.PRNGKey(0))
        obs = jax.random.uniform(jax.random.PRNGKey(5), (obs_dim,),
                                 minval=10.0, maxval=20.0)
        base, _ = model.apply(params, obs, ())
        # Ticks 0..27 are beyond the receptive field of the last position
        # EXCEPT through the log-return of tick 28... conv taps cover ticks
        # {29, 30, 31}; tick-29's log-return reads tick 28 too. Perturb
        # strictly earlier ticks only:
        # (tick 5 affects only the rel/log-ret features of ticks 5 and 6,
        # both outside the field, so any output change would mean the conv
        # reads positions it must not)
        pert_far, _ = model.apply(params, obs.at[5].mul(2.0), ())
        np.testing.assert_allclose(np.asarray(base.logits),
                                   np.asarray(pert_far.logits), atol=1e-5)
        # An in-field tick must, by contrast, change the output:
        pert_near, _ = model.apply(params, obs.at[30].mul(2.0), ())
        assert not np.allclose(np.asarray(base.logits),
                               np.asarray(pert_near.logits))

    def test_portfolio_reaches_heads(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(3))
        out1, _ = model.apply(params, obs, ())
        out2, _ = model.apply(params, obs.at[OBS_DIM - 2].set(9999.0), ())
        assert not np.allclose(np.asarray(out1.logits),
                               np.asarray(out2.logits))

    def test_gradients_flow(self):
        model = self._model(channels=8)
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(4))

        def loss(p):
            out, _ = model.apply(p, obs, ())
            return jnp.sum(out.logits ** 2) + out.value ** 2

        grads = jax.grad(loss)(params)
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(norms)) and any(n > 0 for n in norms)

    @pytest.mark.slow
    def test_ppo_training_step(self):
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.config import FrameworkConfig
        from sharetrade_tpu.env import trading

        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.model.kind = "tcn"
        cfg.model.hidden_dim = 16
        cfg.env.window = 32
        cfg.parallel.num_workers = 4
        cfg.learner.unroll_len = 8
        cfg.runtime.chunk_steps = 8
        env_params = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 80), window=cfg.env.window)
        agent = build_agent(cfg, env_params)
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(0))
        ts, metrics = step(ts)
        assert np.isfinite(float(metrics["loss"]))
        assert int(ts.env_steps) == 8

    def test_value_based_algos_reject_tcn(self):
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.config import FrameworkConfig
        from sharetrade_tpu.env import trading

        cfg = FrameworkConfig()
        cfg.learner.algo = "dqn"
        cfg.model.kind = "tcn"
        env_params = trading.env_from_prices(
            jnp.linspace(10.0, 20.0, 250), window=201)
        with pytest.raises(ValueError, match="mlp"):
            build_agent(cfg, env_params)
