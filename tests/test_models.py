"""Model zoo: shapes, reference-parity properties, transform-friendliness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.config import ModelConfig
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.mlp import ac_mlp, q_mlp

OBS_DIM = 203


def _obs(key):
    return jax.random.uniform(key, (OBS_DIM,), minval=0.0, maxval=100.0)


class TestQMLPParity:
    """Architecture parity with QDecisionPolicyActor.scala:38-50."""

    def test_param_shapes_match_reference_graph(self):
        model = q_mlp(parity=True)
        params = model.init(jax.random.PRNGKey(0))
        assert params["layer1"]["w"].shape == (203, 200)  # w1
        assert params["layer2"]["w"].shape == (200, 3)    # w2
        # Biases are tf.constant in the reference -> not trainable params.
        assert "b" not in params["layer1"] and "b" not in params["layer2"]
        n = sum(p.size for p in jax.tree.leaves(params))
        assert n == 203 * 200 + 200 * 3  # ~41.2k (SURVEY.md §6)

    def test_output_relu_clamps_at_zero(self):
        # Reference: q = relu(...) — Q-values can never go negative.
        model = q_mlp(parity=True)
        params = model.init(jax.random.PRNGKey(1))
        out, _ = model.apply(params, _obs(jax.random.PRNGKey(2)), ())
        assert out.logits.shape == (3,)
        assert bool(jnp.all(out.logits >= 0.0))

    def test_forward_matches_hand_computed(self):
        model = q_mlp(obs_dim=4, hidden_dim=2, num_actions=3, parity=True)
        params = {"layer1": {"w": jnp.ones((4, 2))},
                  "layer2": {"w": jnp.ones((2, 3)) * 0.5}}
        obs = jnp.array([1.0, 2.0, 3.0, 4.0])
        out, _ = model.apply(params, obs, ())
        # h = relu(10 + 0.1) = 10.1 each; q = relu(10.1*2*0.5 + 0.1) = 10.2
        np.testing.assert_allclose(np.asarray(out.logits), [10.2] * 3, rtol=1e-6)

    def test_non_parity_has_trainable_biases_and_no_output_relu(self):
        model = q_mlp(parity=False)
        params = model.init(jax.random.PRNGKey(0))
        assert "b" in params["layer1"] and "b" in params["layer2"]


class TestHeads:
    @pytest.mark.parametrize("kind", ["mlp", "lstm", "transformer"])
    def test_build_apply_shapes(self, kind):
        cfg = ModelConfig(kind=kind, hidden_dim=32, num_layers=1,
                          num_heads=2, head_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        out, carry = model.apply(params, _obs(jax.random.PRNGKey(1)),
                                 model.init_carry())
        assert out.logits.shape == (3,)
        assert out.value.shape == ()
        assert jnp.isfinite(out.logits).all()

    def test_lstm_carry_evolves_and_affects_output(self):
        cfg = ModelConfig(kind="lstm", hidden_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(1))
        out1, carry1 = model.apply(params, obs, model.init_carry())
        out2, carry2 = model.apply(params, obs, carry1)
        assert not np.allclose(np.asarray(carry1[0]), np.asarray(carry2[0]))
        assert not np.allclose(np.asarray(out1.logits), np.asarray(out2.logits))

    def test_transformer_scale_invariance(self):
        # Price normalization: scaling the whole window (and budget) by 10x
        # must leave the policy's decision unchanged.
        cfg = ModelConfig(kind="transformer", num_layers=1, num_heads=2, head_dim=16)
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        prices = jnp.linspace(50.0, 60.0, 201)
        obs1 = jnp.concatenate([prices, jnp.array([2400.0, 3.0])])
        obs2 = jnp.concatenate([prices * 10, jnp.array([24000.0, 3.0])])
        out1, _ = model.apply(params, obs1, ())
        out2, _ = model.apply(params, obs2, ())
        np.testing.assert_allclose(np.asarray(out1.logits),
                                   np.asarray(out2.logits), rtol=1e-4)

    def test_vmap_over_agent_batch(self):
        model = ac_mlp(OBS_DIM, 32)
        params = model.init(jax.random.PRNGKey(0))
        obs_batch = jax.random.uniform(jax.random.PRNGKey(1), (8, OBS_DIM))
        outs, _ = jax.vmap(lambda o: model.apply(params, o, ()))(obs_batch)
        assert outs.logits.shape == (8, 3)

    def test_gradients_flow(self):
        model = ac_mlp(OBS_DIM, 16)
        params = model.init(jax.random.PRNGKey(0))
        obs = _obs(jax.random.PRNGKey(1))

        def loss(p):
            out, _ = model.apply(p, obs, ())
            return jnp.sum(out.logits ** 2) + out.value ** 2

        grads = jax.grad(loss)(params)
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(norms)) and any(n > 0 for n in norms)

    def test_bfloat16_compute(self):
        cfg = ModelConfig(kind="mlp", hidden_dim=32, dtype="bfloat16")
        model = build_model(cfg, OBS_DIM)
        params = model.init(jax.random.PRNGKey(0))
        out, _ = model.apply(params, _obs(jax.random.PRNGKey(1)), ())
        # Heads cast back to f32 for numerics downstream (TD targets etc).
        assert out.logits.dtype == jnp.float32
