"""Telemetry subsystem (obs/): span trace, metrics export, flight recorder.

The acceptance contract of the obs PR: an obs-enabled run produces a
Perfetto-loadable trace, a metrics JSONL + Prometheus textfile, and a run
manifest; a run killed via the fault seam additionally dumps a forensic
flight-recorder bundle naming the failing chunk; disabled obs writes ZERO
files and keeps the hot loop structurally instrumentation-free. The
satellite surfaces (registry ring caps + counters, batched record_many,
StepTimer history cap, the extended hot-loop lint) are pinned here too.
"""

import json
import os
import time

import numpy as np
import pytest

from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.obs import (
    FlightRecorder,
    Obs,
    SpanTracer,
    build_obs,
    read_trace,
    summarize_run_dir,
)
from sharetrade_tpu.obs.trace import _NULL_CTX
from sharetrade_tpu.runtime import Orchestrator, Phase, ReplyState
from sharetrade_tpu.utils.metrics import MetricsRegistry
from sharetrade_tpu.utils.profiling import StepTimer

WINDOW = 8
PRICES = np.linspace(10.0, 20.0, 72, dtype=np.float32)  # 64-step episode


def obs_cfg(tmp_path, *, enabled=True, algo="qlearn"):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 4
    cfg.runtime.chunk_steps = 16
    cfg.runtime.checkpoint_every_updates = 32
    cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
    cfg.runtime.backoff_initial_s = 0.01
    cfg.runtime.backoff_max_s = 0.05
    cfg.runtime.max_restarts = 2
    cfg.obs.enabled = enabled
    cfg.obs.dir = str(tmp_path / "obs")
    cfg.obs.export_interval_s = 0.1
    return cfg


class TestSpanTracer:
    def test_spans_and_instants_written(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tracer = SpanTracer(path, flush_every=1)
        with tracer.span("alpha", chunk=3):
            time.sleep(0.002)
        tracer.instant("marker", reason="x")
        tracer.close()
        events = read_trace(path)
        assert len(events) == 2
        span = next(e for e in events if e["ph"] == "X")
        assert span["name"] == "alpha"
        assert span["dur"] > 0
        # The Perfetto/chrome trace-event required keys per event.
        for ev in events:
            for key in ("name", "ph", "ts", "pid", "tid"):
                assert key in ev
        assert span["args"] == {"chunk": 3}

    def test_unterminated_file_is_loadable(self, tmp_path):
        """Crash realism: the writer never appends the closing bracket —
        the spec makes it optional, and read_trace must cope."""
        path = str(tmp_path / "trace.jsonl")
        tracer = SpanTracer(path, flush_every=1)
        with tracer.span("s"):
            pass
        tracer.flush()   # no close(): simulates a killed process
        raw = open(path).read()
        assert raw.startswith("[") and not raw.rstrip().endswith("]")
        assert read_trace(path)[0]["name"] == "s"

    def test_disabled_writes_nothing_and_is_shared_nullctx(self, tmp_path):
        tracer = SpanTracer(None)
        assert tracer.span("x") is _NULL_CTX  # no per-call allocation
        with tracer.span("x"):
            pass
        tracer.instant("y")
        tracer.close()
        assert list(tmp_path.iterdir()) == []


class TestMetricsRegistrySatellites:
    def test_series_ring_cap(self):
        reg = MetricsRegistry(max_points=4)
        for i in range(10):
            reg.record("m", float(i))
        series = reg.series("m")
        assert len(series) == 4
        assert [v for _, v in series] == [6.0, 7.0, 8.0, 9.0]
        assert reg.latest("m") == 9.0

    def test_unbounded_when_cap_disabled(self):
        reg = MetricsRegistry(max_points=0)
        for i in range(10):
            reg.record("m", float(i))
        assert len(reg.series("m")) == 10

    def test_record_many_single_timestamp(self):
        """One lock/one clock read per row: every key in a record_many batch
        carries the identical timestamp."""
        reg = MetricsRegistry()
        reg.record_many({"a": 1.0, "b": 2.0, "c": 3.0})
        stamps = {reg.series(k)[0][0] for k in ("a", "b", "c")}
        assert len(stamps) == 1
        assert reg.snapshot() == {"a": 1.0, "b": 2.0, "c": 3.0}

    def test_counters_monotonic_and_separate_from_gauges(self):
        reg = MetricsRegistry()
        assert reg.inc("restarts_total") == 1.0
        assert reg.inc("restarts_total", 2) == 3.0
        reg.record("gauge", 5.0)
        assert reg.counters() == {"restarts_total": 3.0}
        assert "restarts_total" not in reg.snapshot()


class TestStepTimerCap:
    def test_history_ring_bounded_summary_exact(self):
        t = StepTimer(chunk_steps=10, num_agents=2, max_history=3)
        for _ in range(8):
            t.tick()
        assert len(t.history) == 3          # ring evicted the old entries
        s = t.summary()
        assert s["chunks_timed"] == 7.0     # ...but totals saw every tick
        assert s["total_seconds"] > 0

    def test_uncapped_default_keeps_list_behavior(self):
        t = StepTimer(chunk_steps=10, num_agents=2)
        for _ in range(5):
            t.tick()
        assert len(t.history) == 4


class TestFlightRecorder:
    def test_ring_capacity_and_dump_bundle(self, tmp_path):
        fr = FlightRecorder(capacity=3)
        for i in range(6):
            fr.record("chunk_metrics", chunk=i, loss=float(i))
        fr.record("lifecycle", frm="training", to="failed")
        path = fr.dump(str(tmp_path / "bundle.json"), reason="test",
                       error="boom")
        bundle = json.load(open(path))
        assert bundle["reason"] == "test"
        assert bundle["failing_chunk"] == 5   # newest chunk_metrics record
        assert bundle["context"] == {"error": "boom"}
        assert len(bundle["events"]) == 3     # ring bound, not the 7 records


class TestObsRun:
    def test_enabled_run_produces_all_artifacts(self, tmp_path):
        cfg = obs_cfg(tmp_path)
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()

        run_dir = cfg.obs.dir
        names = sorted(os.listdir(run_dir))
        assert names == ["manifest.json", "metrics.jsonl", "metrics.prom",
                         "trace.jsonl"]

        manifest = json.load(open(os.path.join(run_dir, "manifest.json")))
        assert manifest["config_hash"]
        assert manifest["backend"]
        assert manifest["config"]["runtime"]["chunk_steps"] == 16

        events = read_trace(os.path.join(run_dir, "trace.jsonl"))
        span_names = {e["name"] for e in events if e["ph"] == "X"}
        # The orchestrator phase decomposition the ISSUE names.
        assert {"dispatch", "readback", "host_process",
                "checkpoint_save"} <= span_names
        assert "phase:completed" in {
            e["name"] for e in events if e["ph"] == "i"}

        lines = [json.loads(ln) for ln in
                 open(os.path.join(run_dir, "metrics.jsonl"))]
        assert lines and "env_steps" in lines[-1]["gauges"]
        assert lines[-1]["counters"]["episodes_completed_total"] == 1.0
        prom = open(os.path.join(run_dir, "metrics.prom")).read()
        assert "# TYPE sharetrade_env_steps gauge" in prom
        assert "sharetrade_episodes_completed_total 1.0" in prom

        summary = summarize_run_dir(run_dir)
        assert summary["manifest"]["config_hash"] == manifest["config_hash"]
        assert summary["trace"]["dispatch"]["count"] >= 1
        assert summary["metrics"]["prom_file"]
        assert "flight_recorder" not in summary   # healthy run: no bundle

    def test_disabled_means_zero_files(self, tmp_path):
        cfg = obs_cfg(tmp_path, enabled=False)
        orch = Orchestrator(cfg)
        # Structural zero-cost: inert facade, shared null context, and the
        # run dir is never even created.
        assert not orch.obs.enabled
        assert orch.obs.span("dispatch") is _NULL_CTX
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()
        assert not os.path.exists(cfg.obs.dir)

    def test_flight_recorder_dumped_on_supervision_trip(self, tmp_path):
        """Fault-injection acceptance: killing the run via fault_hook must
        leave a bundle naming the failing chunk, carrying the last-K chunk
        metric rows and the worker_failed event.

        Checkpointing is OFF and the restart budget zero: the non-slow tier
        deliberately avoids the CPU checkpoint save/restore interleavings
        (the known writer-thread wobble every supervision test in
        test_runtime.py quarantines under `slow`), so the single trip kills
        the run deterministically; the heal-and-complete restore variant
        below is slow-marked for the same reason."""
        cfg = obs_cfg(tmp_path)
        cfg.runtime.checkpoint_every_updates = 0
        cfg.runtime.max_restarts = 0

        def chaos(chunk_idx, metrics):
            if chunk_idx == 2:
                raise RuntimeError("injected PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        orch.stop()
        bundle = json.load(open(
            os.path.join(cfg.obs.dir, "flight_recorder.json")))
        assert bundle["reason"] == "supervision"
        assert bundle["failing_chunk"] == 2
        assert bundle["context"]["verb"] == "restart"
        rows = [e for e in bundle["events"] if e["kind"] == "chunk_metrics"]
        assert [r["chunk"] for r in rows] == [0, 1, 2]  # last-K incl. failer
        assert all("loss" in r and "env_steps" in r for r in rows)
        failed = [e for e in bundle["events"]
                  if e["kind"] == "event" and e["event"] == "worker_failed"]
        assert failed and "PoisonPill" in failed[0]["error"]
        assert summarize_run_dir(cfg.obs.dir)[
            "flight_recorder"]["failing_chunk"] == 2

    @pytest.mark.slow
    def test_heal_and_complete_keeps_bundle(self, tmp_path):
        """The restore path end to end: trip → dump → checkpoint restore →
        heal → COMPLETED, bundle left behind. Slow tier, like every other
        restore-exercising supervision test (CPU restore interleavings)."""
        cfg = obs_cfg(tmp_path)
        fail_at = {2}

        def chaos(chunk_idx, metrics):
            if chunk_idx in fail_at:
                fail_at.discard(chunk_idx)   # fire once, not on the replay
                raise RuntimeError("injected PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()
        bundle = json.load(open(
            os.path.join(cfg.obs.dir, "flight_recorder.json")))
        assert bundle["reason"] == "supervision"
        assert bundle["context"]["verb"] == "restart"
        # The CPU replay can wobble into a second trip after the restore
        # (the latest bundle wins), so pin the invariants, not the count:
        # a real failing chunk is named and the bundle matches the summary.
        assert bundle["failing_chunk"] >= 2
        assert summarize_run_dir(cfg.obs.dir)["flight_recorder"]["events"] \
            == len(bundle["events"])

    def test_flight_recorder_knob_off_means_no_bundle(self, tmp_path):
        """obs.flight_recorder=false disables the ring AND the dump — a
        failing run leaves the other artifacts but no bundle."""
        cfg = obs_cfg(tmp_path)
        cfg.obs.flight_recorder = False
        cfg.runtime.checkpoint_every_updates = 0   # non-slow-tier rule
        cfg.runtime.max_restarts = 0

        def always_fail(chunk_idx, metrics):
            raise RuntimeError("persistent failure")

        orch = Orchestrator(cfg, fault_hook=always_fail)
        assert not orch.obs._flight_on
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        orch.stop()
        assert not os.path.exists(
            os.path.join(cfg.obs.dir, "flight_recorder.json"))
        assert os.path.isfile(os.path.join(cfg.obs.dir, "trace.jsonl"))
        assert orch.obs.flight.snapshot() == []   # ring never fed

    def test_fatal_run_keeps_bundle_and_counters(self, tmp_path):
        # Checkpointing off: the one restart recovers via the REINIT path
        # (no checkpoint to restore, no writer threads — the non-slow-tier
        # rule above), which still exercises dump → backoff warning →
        # recovery → second dump → budget exhausted.
        cfg = obs_cfg(tmp_path)
        cfg.runtime.checkpoint_every_updates = 0
        cfg.runtime.max_restarts = 1

        def always_fail(chunk_idx, metrics):
            raise RuntimeError("persistent failure")

        orch = Orchestrator(cfg, fault_hook=always_fail)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        orch.stop()
        bundle = json.load(open(
            os.path.join(cfg.obs.dir, "flight_recorder.json")))
        assert bundle["failing_chunk"] == 0
        # Lifecycle transitions and WARNING+ logs rode along in the ring.
        kinds = {e["kind"] for e in bundle["events"]}
        assert {"chunk_metrics", "lifecycle", "event", "log"} <= kinds
        # The exporter's final drain captured the monotonic counters.
        prom = open(os.path.join(cfg.obs.dir, "metrics.prom")).read()
        assert "sharetrade_restarts_total 2.0" in prom


class TestCliObs:
    def test_obs_command_summarizes_run_dir(self, tmp_path, capsys):
        from sharetrade_tpu import cli
        cfg = obs_cfg(tmp_path)
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        orch.stop()
        assert cli.main(["obs", "--dir", cfg.obs.dir]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["manifest"]["config_hash"]
        assert out["trace"]["dispatch"]["count"] >= 1

    def test_obs_command_rejects_missing_dir(self, tmp_path):
        from sharetrade_tpu import cli
        assert cli.main(["obs", "--dir", str(tmp_path / "nope")]) == 1


class TestLintExtension:
    def test_lints_pass_on_tree(self):
        import importlib.util
        import pathlib
        tool = (pathlib.Path(__file__).resolve().parent.parent
                / "tools" / "lint_hot_loop.py")
        spec = importlib.util.spec_from_file_location("lint_hot_loop", tool)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.lint_device_host_calls() == []
        bad, found = mod.lint_hot_loop_syncs()
        assert bad == [] and found == {"_run_supervised"}

    def test_jit_pattern_semantics(self):
        import importlib.util
        import pathlib
        tool = (pathlib.Path(__file__).resolve().parent.parent
                / "tools" / "lint_hot_loop.py")
        spec = importlib.util.spec_from_file_location("lint_hot_loop2", tool)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        hits = mod.JIT_PATTERN.search
        assert hits("t = time.time()")
        assert hits("log.info('x')")
        assert hits("print(x)")
        assert not hits("jax.debug.print('{}', x)")   # sanctioned in-jit
        assert not hits("pprint(x)")


class TestObsFacade:
    def test_build_obs_disabled_creates_nothing(self, tmp_path):
        cfg = obs_cfg(tmp_path, enabled=False)
        obs = build_obs(cfg, MetricsRegistry())
        assert isinstance(obs, Obs) and not obs.enabled
        obs.record("chunk_metrics", chunk=1)     # dropped, not buffered
        assert obs.dump_flight(reason="x") is None
        obs.flush()
        obs.close()
        assert not os.path.exists(cfg.obs.dir)

    def test_log_handler_detached_on_close(self, tmp_path):
        import logging
        cfg = obs_cfg(tmp_path)
        root = logging.getLogger("sharetrade")
        before = list(root.handlers)
        obs = build_obs(cfg, MetricsRegistry())
        assert len(root.handlers) == len(before) + 1
        obs.close()
        assert root.handlers == before


class TestPreemptionObs:
    def test_preemption_artifacts(self, tmp_path):
        """Satellite contract of the durability PR: a preempted obs-enabled
        run leaves a `preemption_drain` span, an `emergency_checkpoint`
        instant, and a flight bundle with reason "preemption"."""
        cfg = obs_cfg(tmp_path)
        cfg.runtime.episodes = 200          # long run: cannot complete
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        orch.start_training(background=True)
        deadline = time.monotonic() + 30
        while not orch.snapshot() and time.monotonic() < deadline:
            time.sleep(0.02)
        orch.request_preempt()
        assert orch.wait(timeout=30)
        assert orch.preempted
        orch.stop()

        events = read_trace(os.path.join(cfg.obs.dir, "trace.jsonl"))
        names = {e["name"] for e in events}
        assert "preemption_drain" in names
        assert "emergency_checkpoint" in names
        bundle = json.load(open(os.path.join(cfg.obs.dir,
                                             "flight_recorder.json")))
        assert bundle["reason"] == "preemption"

    def test_restore_fallback_counters_exported(self, tmp_path):
        """The walk-back counters flow through the existing exporter into
        the Prometheus textfile."""
        cfg = obs_cfg(tmp_path)
        from sharetrade_tpu.runtime import run_end_to_end
        orch = run_end_to_end(cfg, PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()
        ckpt_dir = cfg.runtime.checkpoint_dir
        newest = sorted(n for n in os.listdir(ckpt_dir)
                        if n.startswith("ckpt_"))[-1]
        from test_checkpoint import _bitflip   # the one corruption helper
        _bitflip(os.path.join(ckpt_dir, newest, "state.msgpack"))

        cfg2 = obs_cfg(tmp_path)
        cfg2.obs.dir = str(tmp_path / "obs2")
        orch2 = Orchestrator(cfg2)
        orch2.send_training_data(PRICES, resume=True)
        orch2.obs.flush()
        prom = open(os.path.join(cfg2.obs.dir, "metrics.prom")).read()
        assert "sharetrade_ckpt_restore_fallbacks_total 1" in prom
        assert "sharetrade_ckpt_quarantined_total 1" in prom
        orch2.stop()
