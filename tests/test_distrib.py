"""ActorPool supervision-ladder unit tests (distrib/pool.py) — the
PR-5/PR-10 contract at PROCESS granularity, driven deterministically.

The kill soak (tests/test_actor_soak.py, tools/actor_soak.py) proves the
topology end to end with real ``cli actor``/``cli learner`` processes;
these tests pin the supervisor's LADDER with cheap stub children via the
``spawn_fn`` hook (no jax bring-up): reap classification, seeded
exponential backoff, the terminal FAILED state and graceful degrade,
streak reset on a healthy heartbeat, elastic ``scale()`` both ways, the
out-of-process ``scale`` control file, quiesce-on-preempt, the
heartbeat-timeout wedge kill, and the status.json/gauge export the kill
test reconciles against.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.distrib.actor import (
    HEARTBEAT_FILE, read_heartbeat, write_heartbeat)
from sharetrade_tpu.distrib.pool import ActorPool, read_status


def _sleeper(actor_id, workdir):
    return subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"])


def _crasher(actor_id, workdir):
    return subprocess.Popen([sys.executable, "-c", "raise SystemExit(3)"])


def make_pool(tmp_path, spawn_fn, *, registry=None, **distrib):
    cfg = FrameworkConfig()
    cfg.distrib.actor_dir = str(tmp_path / "actors")
    # The supervise thread must never race the test's poll_once() steps:
    # park it on a first wait() longer than any test.
    cfg.distrib.supervise_interval_s = 300.0
    cfg.distrib.actor_backoff_jitter = 0.0
    for key, value in distrib.items():
        setattr(cfg.distrib, key, value)
    return ActorPool(cfg, registry=registry, spawn_fn=spawn_fn)


def wait_exit(pool, ids=None):
    """Block until the named children (default: all) have actually
    exited (a crasher's exit is asynchronous; the reap must not race
    it)."""
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if all(h.proc is None or h.proc.poll() is not None
               for aid, h in pool._actors.items()
               if ids is None or aid in ids):
            return
        time.sleep(0.01)
    raise AssertionError("stub children did not exit in time")


def stamp_rolling(pool, handle):
    write_heartbeat(
        os.path.join(pool.dir, handle.actor_id, HEARTBEAT_FILE),
        pid=handle.pid, actor_id=handle.actor_id, env_steps=8,
        episodes=0, chunks=1, rows=8, params_step=0, phase="rolling")


@pytest.fixture
def cleanup_pools():
    pools = []
    yield pools
    for pool in pools:
        pool.stop(grace_s=5.0)


class TestSupervisionLadder:
    def test_negative_restart_budget_rejected(self, tmp_path):
        with pytest.raises(ConfigError):
            make_pool(tmp_path, _sleeper, max_actor_restarts=-1)

    def test_crash_backs_off_then_respawns(self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _crasher, actor_backoff_initial_s=30.0,
                         max_actor_restarts=5).start(1)
        cleanup_pools.append(pool)
        wait_exit(pool)
        pool.poll_once()
        (h,) = pool._actors.values()
        assert h.state == "backoff"
        assert h.last_rc == 3
        assert pool.restarts_total == 1
        assert h.respawn_at > time.monotonic()   # 30 s out, not yet due
        pid_before = h.pid
        pool.poll_once()                          # still inside backoff
        assert h.state == "backoff" and h.pid == pid_before

    def test_backoff_schedule_doubles_to_cap(self, tmp_path,
                                             cleanup_pools):
        pool = make_pool(tmp_path, _crasher, actor_backoff_initial_s=0.0,
                         max_actor_restarts=10).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        delays = []
        for _ in range(4):
            wait_exit(pool)
            before = time.monotonic()
            pool.poll_once()          # reap -> backoff (delay from streak)
            delays.append(h.respawn_at - before)
            pool.poll_once()          # 0-initial backoff: respawn now
            assert h.state == "starting"
        # initial_s=0 collapses every delay to 0 but the STREAK still
        # climbed; re-run the math the pool used to prove the ladder.
        assert [h.restarts, h.streak] == [4, 4]

    def test_terminal_failure_degrades_onto_survivors(
            self, tmp_path, cleanup_pools):
        registry = _Registry()
        pool = make_pool(tmp_path, _crasher, actor_backoff_initial_s=0.0,
                         max_actor_restarts=2,
                         registry=registry).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        for _ in range(12):
            if h.state == "failed":
                break
            wait_exit(pool)
            pool.poll_once()
        assert h.state == "failed"
        assert h.streak == 3                      # budget 2, third strike
        assert pool.counts()["failed"] == 1
        pid_at_failure = h.pid
        pool.poll_once()                          # a corpse never respawns
        assert h.state == "failed" and h.pid == pid_at_failure
        assert registry.counters["actor_restarts_total"] == 3.0
        assert registry.gauges["actors_failed"] == 1.0

    def test_rolling_heartbeat_resets_streak(self, tmp_path,
                                             cleanup_pools):
        # One crash, then the respawn proves healthy: the streak must
        # reset so an occasional crash never accumulates to terminal.
        pool = make_pool(tmp_path, _crasher, actor_backoff_initial_s=0.0,
                         max_actor_restarts=5).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        wait_exit(pool)
        pool.poll_once()
        pool.poll_once()
        assert h.streak == 1 and h.state == "starting"
        pool._spawn_fn = _sleeper
        wait_exit(pool)
        pool.poll_once()                          # crash 2 -> respawn as
        pool.poll_once()                          # a healthy sleeper
        assert h.streak == 2
        stamp_rolling(pool, h)
        pool.poll_once()
        assert h.state == "alive" and h.streak == 0

    def test_stale_previous_incarnation_heartbeat_ignored(
            self, tmp_path, cleanup_pools):
        # The dead incarnation's rolling stamp must not mark the fresh
        # spawn healthy: _spawn_locked clears it, and the pid check
        # guards the race besides.
        pool = make_pool(tmp_path, _sleeper,
                         actor_backoff_initial_s=0.0).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        stamp_rolling(pool, h)
        hb_path = os.path.join(pool.dir, h.actor_id, HEARTBEAT_FILE)
        assert read_heartbeat(hb_path) is not None
        h.proc.kill()
        wait_exit(pool)
        pool.poll_once()                          # crash -> backoff
        pool.poll_once()                          # respawn (0 backoff)
        assert read_heartbeat(hb_path) is None    # stamp wiped on spawn
        pool.poll_once()
        assert h.state == "starting"              # not falsely alive


class TestElasticMembership:
    def test_scale_up_and_down(self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper).start(2)
        cleanup_pools.append(pool)
        assert pool.counts()["alive"] == 2
        pool.scale(3)
        assert pool.counts()["alive"] == 3
        pool.scale(1)
        retiring = [aid for aid, h in pool._actors.items()
                    if h.state == "retiring"]
        wait_exit(pool, retiring)                 # SIGTERM'd sleepers die
        pool.poll_once()
        counts = pool.counts()
        assert counts["alive"] == 1 and counts["retired"] == 2
        # Retiring exits are NOT crashes: no restart counted.
        assert pool.restarts_total == 0

    def test_scale_file_drives_live_pool(self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper).start(1)
        cleanup_pools.append(pool)
        with open(os.path.join(pool.dir, "scale"), "w") as f:
            f.write("3\n")
        pool.poll_once()
        assert pool.target == 3
        assert pool.counts()["alive"] == 3
        assert pool.scale_events == 1

    def test_stale_scale_file_does_not_undo_api_scale(self, tmp_path,
                                                      cleanup_pools):
        # The control file is applied ONCE per written value: a lingering
        # file must not re-override a later programmatic scale() on
        # every supervise tick.
        pool = make_pool(tmp_path, _sleeper).start(1)
        cleanup_pools.append(pool)
        with open(os.path.join(pool.dir, "scale"), "w") as f:
            f.write("2")
        pool.poll_once()
        assert pool.target == 2
        pool.scale(4)
        pool.poll_once()                          # file still says 2
        assert pool.target == 4
        assert pool.counts()["alive"] == 4

    def test_negative_scale_file_ignored(self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper).start(1)
        cleanup_pools.append(pool)
        with open(os.path.join(pool.dir, "scale"), "w") as f:
            f.write("-3")
        pool.poll_once()                          # must not raise/spam
        assert pool.target == 1

    def test_failed_actor_excluded_from_target(self, tmp_path,
                                               cleanup_pools):
        # Replacing a corpse: scale(n) counts LIVE members only, so the
        # same target respawns a fresh actor next to the failed one.
        pool = make_pool(tmp_path, _crasher, actor_backoff_initial_s=0.0,
                         max_actor_restarts=0).start(1)
        cleanup_pools.append(pool)
        wait_exit(pool)
        pool.poll_once()
        assert pool.counts()["failed"] == 1
        pool._spawn_fn = _sleeper
        pool.scale(1)
        counts = pool.counts()
        assert counts["alive"] == 1 and counts["failed"] == 1
        assert len(pool._actors) == 2             # a0 corpse + a1 fresh

    def test_quiesce_classifies_exits_as_retirement(
            self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper).start(2)
        cleanup_pools.append(pool)
        pool.quiesce()
        for h in pool._actors.values():
            h.proc.kill()
        wait_exit(pool)
        pool.poll_once()
        assert pool.counts()["retired"] == 2
        assert pool.restarts_total == 0           # a drain, not a storm

    def test_quiesced_pool_refuses_scale(self, tmp_path, cleanup_pools):
        # A scale request (or control-file write) landing inside the
        # learner's drain window must not spawn fresh actors into a
        # dying run.
        pool = make_pool(tmp_path, _sleeper).start(1)
        cleanup_pools.append(pool)
        pool.quiesce()
        pool.scale(3)
        assert len(pool._actors) == 1
        assert pool.scale_events == 0

    def test_kill_all_leaves_no_live_children(self, tmp_path,
                                              cleanup_pools):
        # The hard-exit teardown (os._exit skips every finally): no
        # actor may outlive it unsupervised.
        pool = make_pool(tmp_path, _sleeper).start(3)
        cleanup_pools.append(pool)
        pids = [h.pid for h in pool._actors.values()]
        pool.kill_all()
        wait_exit(pool)
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)
        pool.poll_once()                          # ...and the reaps are
        assert pool.restarts_total == 0           # drains, not crashes


class TestHeartbeatTimeout:
    def test_wedged_actor_killed_into_restart_ladder(
            self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper, heartbeat_timeout_s=5.0,
                         actor_backoff_initial_s=30.0).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        stamp_rolling(pool, h)
        pool.poll_once()
        assert h.state == "alive"
        # Age the stamp past the timeout: presumed wedged, killed, and
        # the DEATH feeds the normal crash ladder on the next reap.
        hb_path = os.path.join(pool.dir, h.actor_id, HEARTBEAT_FILE)
        hb = read_heartbeat(hb_path)
        hb["time"] = time.time() - 60.0
        with open(hb_path, "w") as f:
            json.dump(hb, f)
        pool.poll_once()                          # kill
        wait_exit(pool)
        pool.poll_once()                          # reap as crash
        assert h.state == "backoff"
        assert pool.restarts_total == 1

    def test_wedged_during_bringup_also_killed(self, tmp_path,
                                               cleanup_pools):
        # An actor that stamps once and then hangs BEFORE reaching the
        # rolling phase must not escape the timeout contract.
        pool = make_pool(tmp_path, _sleeper, heartbeat_timeout_s=5.0,
                         actor_backoff_initial_s=30.0).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        write_heartbeat(
            os.path.join(pool.dir, h.actor_id, HEARTBEAT_FILE),
            pid=h.pid, actor_id=h.actor_id, env_steps=0, episodes=0,
            chunks=0, rows=0, params_step=0, phase="starting")
        hb_path = os.path.join(pool.dir, h.actor_id, HEARTBEAT_FILE)
        hb = read_heartbeat(hb_path)
        hb["time"] = time.time() - 60.0
        with open(hb_path, "w") as f:
            json.dump(hb, f)
        pool.poll_once()                          # still STARTING: kill
        wait_exit(pool)
        pool.poll_once()
        assert h.state == "backoff"
        assert pool.restarts_total == 1

    def test_timeout_zero_observes_only(self, tmp_path, cleanup_pools):
        pool = make_pool(tmp_path, _sleeper,
                         heartbeat_timeout_s=0.0).start(1)
        cleanup_pools.append(pool)
        (h,) = pool._actors.values()
        stamp_rolling(pool, h)
        hb_path = os.path.join(pool.dir, h.actor_id, HEARTBEAT_FILE)
        hb = read_heartbeat(hb_path)
        hb["time"] = time.time() - 3600.0
        with open(hb_path, "w") as f:
            json.dump(hb, f)
        pool.poll_once()
        assert h.proc.poll() is None              # still running
        assert h.heartbeat_age_s > 3000           # ...but the age exports


class TestStatusExport:
    def test_status_json_names_every_member(self, tmp_path,
                                            cleanup_pools):
        registry = _Registry()
        pool = make_pool(tmp_path, _sleeper, registry=registry).start(2)
        cleanup_pools.append(pool)
        pool.poll_once()
        status = read_status(pool.dir)
        assert status["pid"] == os.getpid()
        assert status["target"] == 2
        assert sorted(status["actors"]) == ["a0", "a1"]
        for rec in status["actors"].values():
            assert rec["pid"] and rec["state"] in ("starting", "alive")
        assert registry.gauges["actors_alive"] == 2.0
        assert registry.gauges["actors_failed"] == 0.0

    def test_torn_or_absent_status_reads_none(self, tmp_path):
        assert read_status(str(tmp_path)) is None
        with open(tmp_path / "status.json", "w") as f:
            f.write('{"pid": 12')                 # torn
        assert read_status(str(tmp_path)) is None


class _Registry:
    """MetricsRegistry duck-type: last-value gauges + monotone counters."""

    def __init__(self):
        self.gauges = {}
        self.counters = {}

    def record(self, name, value):
        self.gauges[name] = value

    def inc(self, name, value=1.0):
        self.counters[name] = self.counters.get(name, 0.0) + value
