"""Pipeline (pp) and expert (ep) parallelism on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from sharetrade_tpu.models.core import dense, dense_init
from sharetrade_tpu.parallel import (
    init_moe_params,
    moe_apply,
    moe_apply_sharded,
    moe_apply_topk,
    moe_apply_topk_a2a,
    moe_apply_topk_sharded,
    pipeline_apply,
    stack_stage_params,
)


@pytest.fixture
def pp_mesh(cpu_devices):
    return Mesh(np.array(cpu_devices[:4]).reshape(4), ("pp",))


@pytest.fixture
def ep_mesh(cpu_devices):
    return Mesh(np.array(cpu_devices).reshape(8), ("ep",))


class TestPipeline:
    def test_matches_sequential(self, pp_mesh):
        """4 pipelined stages over 8 microbatches == applying the stages
        back-to-back on one device."""
        dim, micro, mb = 16, 8, 4
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        per_stage = [dense_init(k, dim, dim) for k in keys]
        stacked = stack_stage_params(per_stage)

        def stage_fn(params, x):
            return jax.nn.relu(dense(params, x))

        x = jax.random.normal(jax.random.PRNGKey(1), (micro, mb, dim))
        got = pipeline_apply(stage_fn, stacked, x, pp_mesh)

        want = x
        for p in per_stage:
            want = jax.vmap(jax.vmap(lambda t, p=p: stage_fn(p, t)))(want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_jits_and_differentiates(self, pp_mesh):
        dim, micro, mb = 8, 4, 2
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        stacked = stack_stage_params([dense_init(k, dim, dim) for k in keys])

        def stage_fn(params, x):
            return jnp.tanh(dense(params, x))

        x = jax.random.normal(jax.random.PRNGKey(1), (micro, mb, dim))

        @jax.jit
        def loss(params):
            return jnp.sum(pipeline_apply(stage_fn, params, x, pp_mesh) ** 2)

        grads = jax.grad(loss)(stacked)
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(norms)) and all(n > 0 for n in norms)


class TestMoE:
    def test_sharded_matches_reference(self, ep_mesh):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                                 in_dim=16, hidden_dim=32)
        tokens = jax.random.normal(jax.random.PRNGKey(1), (24, 16))
        want, aux_want = moe_apply(params, tokens)
        got, aux_got = moe_apply_sharded(params, tokens, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_routing_actually_selects_experts(self):
        params = init_moe_params(jax.random.PRNGKey(2), num_experts=4,
                                 in_dim=8, hidden_dim=16)
        tokens = jax.random.normal(jax.random.PRNGKey(3), (64, 8))
        choice = np.asarray(jnp.argmax(tokens @ params["gate"], axis=-1))
        assert len(set(choice.tolist())) > 1  # multiple experts in play

    def test_rejects_indivisible_experts(self, ep_mesh):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=6,
                                 in_dim=8, hidden_dim=8)
        tokens = jnp.zeros((4, 8))
        with pytest.raises(ValueError, match="divisible"):
            moe_apply_sharded(params, tokens, ep_mesh)

    def test_aux_loss_gradient_flows_to_gate(self):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=4,
                                 in_dim=8, hidden_dim=16)
        tokens = jax.random.normal(jax.random.PRNGKey(1), (32, 8))

        def loss(p):
            out, aux = moe_apply(p, tokens)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        assert float(jnp.linalg.norm(g["gate"])) > 0


class TestTopKMoE:
    """Capacity-bucketed top-k dispatch (the O(k·N/E)-per-expert scheme)."""

    def _params_tokens(self, num_experts=4, n=48, dim=8, seed=0):
        params = init_moe_params(jax.random.PRNGKey(seed),
                                 num_experts=num_experts, in_dim=dim,
                                 hidden_dim=16)
        tokens = jax.random.normal(jax.random.PRNGKey(seed + 1), (n, dim))
        return params, tokens

    def test_top1_no_drop_matches_dense_mask(self):
        """With k=1 and capacity for every token, the dispatch scheme must
        reproduce the exact dense-mask top-1 result."""
        params, tokens = self._params_tokens()
        want, _ = moe_apply(params, tokens)
        got, _ = moe_apply_topk(params, tokens, top_k=1,
                                capacity_factor=4.0)   # cap >= N: no drops
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_top2_second_pick_contributes(self):
        params, tokens = self._params_tokens()
        out1, _ = moe_apply_topk(params, tokens, top_k=1, capacity_factor=4.0)
        out2, _ = moe_apply_topk(params, tokens, top_k=2, capacity_factor=4.0)
        assert float(jnp.max(jnp.abs(out2 - out1))) > 1e-6

    @pytest.mark.parametrize("n", [64, 50])   # 50: N % group_size != 0
    def test_grouped_matches_ungrouped_when_no_drops(self, n):
        """Grouping (including the zero-padded final group for indivisible
        N) must not change results when capacity is ample."""
        params, tokens = self._params_tokens(n=n)
        want, aux_want = moe_apply_topk(params, tokens, top_k=2,
                                        capacity_factor=4.0, group_size=None)
        got, aux_got = moe_apply_topk(params, tokens, top_k=2,
                                      capacity_factor=4.0, group_size=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_aux_reaches_training_loss(self):
        """The balance term must be visible to learners: a top-k MoE
        transformer's replay_forward reports a positive aux that moves when
        the gate moves (the capacity-dispatch drop-collapse guard)."""
        from sharetrade_tpu.agents.rollout import StepData, replay_forward
        from sharetrade_tpu.config import ModelConfig
        from sharetrade_tpu.models import build_model
        cfg = ModelConfig(kind="transformer", num_heads=2, head_dim=8,
                          num_layers=1, moe_experts=4, moe_top_k=2)
        obs_dim = 18
        model = build_model(cfg, obs_dim)
        params = model.init(jax.random.PRNGKey(0))
        t, b = 2, 3
        obs = jax.random.uniform(jax.random.PRNGKey(1), (t, b, obs_dim))
        z = jnp.zeros((t, b))
        traj = StepData(obs=obs, action=z.astype(jnp.int32), logp=z,
                        value=z, reward=z, active=z + 1.0)
        _, _, aux = replay_forward(model, params, traj, ())
        assert float(aux) > 0.0
        g = jax.grad(lambda p: replay_forward(model, p, traj, ())[2])(params)
        gate_norm = sum(float(jnp.linalg.norm(b["moe"]["gate"]))
                        for b in g["blocks"])
        assert gate_norm > 0.0

    def test_capacity_actually_drops(self):
        """A starved capacity factor must zero some tokens' outputs (static
        buffers drop overflow picks instead of resizing)."""
        params, tokens = self._params_tokens(n=256)
        full, _ = moe_apply_topk(params, tokens, top_k=1, capacity_factor=4.0)
        starved, _ = moe_apply_topk(params, tokens, top_k=1,
                                    capacity_factor=0.05)
        zero_rows = np.sum(np.all(np.asarray(starved) == 0.0, axis=-1))
        assert zero_rows > 0
        assert np.all(np.isfinite(np.asarray(starved)))
        assert float(jnp.max(jnp.abs(full - starved))) > 1e-6

    def test_sharded_matches_reference(self, ep_mesh):
        params, tokens = self._params_tokens(num_experts=8, n=48, dim=16)
        want, aux_want = moe_apply_topk(params, tokens, top_k=2,
                                        capacity_factor=2.0)
        got, aux_got = moe_apply_topk_sharded(params, tokens, ep_mesh,
                                              top_k=2, capacity_factor=2.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_a2a_matches_reference_in_no_drop_regime(self, ep_mesh):
        """all_to_all dispatch groups tokens per source shard, so it only
        equals the global-routing reference when nothing drops."""
        params, tokens = self._params_tokens(num_experts=8, n=64, dim=16)
        want, aux_want = moe_apply_topk(params, tokens, top_k=2,
                                        capacity_factor=8.0, group_size=8)
        got, aux_got = moe_apply_topk_a2a(params, tokens, ep_mesh, top_k=2,
                                          capacity_factor=8.0, group_size=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_a2a_rejects_indivisible_tokens(self, ep_mesh):
        params, tokens = self._params_tokens(num_experts=8, n=12, dim=16)
        with pytest.raises(ValueError, match="divisible"):
            moe_apply_topk_a2a(params, tokens, ep_mesh)

    def test_a2a_n_valid_pads_like_group_padding(self, ep_mesh):
        """Callers with an indivisible token count zero-pad to a multiple of
        ep and pass n_valid: pad rows must claim no buffer slots and leave
        the balance stats identical to the unpadded reference."""
        params, tokens = self._params_tokens(num_experts=8, n=60, dim=16)
        want, aux_want = moe_apply_topk(params, tokens, top_k=2,
                                        capacity_factor=8.0, group_size=8)
        padded = jnp.pad(tokens, ((0, 4), (0, 0)))       # 60 -> 64 = 8 shards
        got, aux_got = moe_apply_topk_a2a(params, padded, ep_mesh, top_k=2,
                                          capacity_factor=8.0, group_size=8,
                                          n_valid=60)
        np.testing.assert_allclose(np.asarray(got[:60]), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_a2a_dispatch_config_validation(self):
        """moe_dispatch='a2a' without top-k or without an ep mesh must fail
        loudly at build time, not silently fall back to psum."""
        from sharetrade_tpu.config import ModelConfig
        from sharetrade_tpu.models import build_model
        cfg = ModelConfig(kind="transformer", num_heads=2, head_dim=8,
                          num_layers=1, moe_experts=4, moe_dispatch="a2a")
        with pytest.raises(ValueError, match="moe_top_k"):
            build_model(cfg, 18)
        cfg.moe_top_k = 2
        with pytest.raises(ValueError, match="ep"):
            build_model(cfg, 18)       # no mesh at all
        cfg.moe_dispatch = "bogus"
        with pytest.raises(ValueError, match="moe_dispatch"):
            build_model(cfg, 18)

    def test_gradients_flow_through_dispatch(self):
        params, tokens = self._params_tokens()

        def loss(p):
            out, aux = moe_apply_topk(p, tokens, top_k=2, capacity_factor=4.0)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for name in ("gate", "w_in", "w_out"):
            assert float(jnp.linalg.norm(g[name])) > 0, name
