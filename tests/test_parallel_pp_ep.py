"""Pipeline (pp) and expert (ep) parallelism on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from sharetrade_tpu.models.core import dense, dense_init
from sharetrade_tpu.parallel import (
    init_moe_params,
    moe_apply,
    moe_apply_sharded,
    pipeline_apply,
    stack_stage_params,
)


@pytest.fixture
def pp_mesh(cpu_devices):
    return Mesh(np.array(cpu_devices[:4]).reshape(4), ("pp",))


@pytest.fixture
def ep_mesh(cpu_devices):
    return Mesh(np.array(cpu_devices).reshape(8), ("ep",))


class TestPipeline:
    def test_matches_sequential(self, pp_mesh):
        """4 pipelined stages over 8 microbatches == applying the stages
        back-to-back on one device."""
        dim, micro, mb = 16, 8, 4
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        per_stage = [dense_init(k, dim, dim) for k in keys]
        stacked = stack_stage_params(per_stage)

        def stage_fn(params, x):
            return jax.nn.relu(dense(params, x))

        x = jax.random.normal(jax.random.PRNGKey(1), (micro, mb, dim))
        got = pipeline_apply(stage_fn, stacked, x, pp_mesh)

        want = x
        for p in per_stage:
            want = jax.vmap(jax.vmap(lambda t, p=p: stage_fn(p, t)))(want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_jits_and_differentiates(self, pp_mesh):
        dim, micro, mb = 8, 4, 2
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        stacked = stack_stage_params([dense_init(k, dim, dim) for k in keys])

        def stage_fn(params, x):
            return jnp.tanh(dense(params, x))

        x = jax.random.normal(jax.random.PRNGKey(1), (micro, mb, dim))

        @jax.jit
        def loss(params):
            return jnp.sum(pipeline_apply(stage_fn, params, x, pp_mesh) ** 2)

        grads = jax.grad(loss)(stacked)
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(norms)) and all(n > 0 for n in norms)


class TestMoE:
    def test_sharded_matches_reference(self, ep_mesh):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                                 in_dim=16, hidden_dim=32)
        tokens = jax.random.normal(jax.random.PRNGKey(1), (24, 16))
        want, aux_want = moe_apply(params, tokens)
        got, aux_got = moe_apply_sharded(params, tokens, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_got), float(aux_want), rtol=1e-5)

    def test_routing_actually_selects_experts(self):
        params = init_moe_params(jax.random.PRNGKey(2), num_experts=4,
                                 in_dim=8, hidden_dim=16)
        tokens = jax.random.normal(jax.random.PRNGKey(3), (64, 8))
        choice = np.asarray(jnp.argmax(tokens @ params["gate"], axis=-1))
        assert len(set(choice.tolist())) > 1  # multiple experts in play

    def test_rejects_indivisible_experts(self, ep_mesh):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=6,
                                 in_dim=8, hidden_dim=8)
        tokens = jnp.zeros((4, 8))
        with pytest.raises(ValueError, match="divisible"):
            moe_apply_sharded(params, tokens, ep_mesh)

    def test_aux_loss_gradient_flows_to_gate(self):
        params = init_moe_params(jax.random.PRNGKey(0), num_experts=4,
                                 in_dim=8, hidden_dim=16)
        tokens = jax.random.normal(jax.random.PRNGKey(1), (32, 8))

        def loss(p):
            out, aux = moe_apply(p, tokens)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        assert float(jnp.linalg.norm(g["gate"])) > 0
