"""Pallas flash-attention kernel vs the XLA reference.

On the CPU test mesh the kernel runs through the Pallas interpreter
(``use_pallas=True`` forces the kernel path; ``interpret=True`` is selected
automatically off-TPU), so the exact code that executes on TPU is what is
checked numerically here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.ops import flash_attention, reference_attention


def _rand_qkv(key, batch=2, heads=2, seq=64, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, heads, seq, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


def test_kernel_matches_reference_fast():
    """One small parity case kept in the fast `make check` gate so a numeric
    regression in the kernel cannot ship on a green gate (the full seq/causal
    sweep below is `slow`)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), seq=64)
    got = flash_attention(q, k, v, causal=True, use_pallas=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("seq", [64, 128, 201, 256])
@pytest.mark.parametrize("causal", [True, False])
def test_kernel_matches_reference(seq, causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), seq=seq)
    got = flash_attention(q, k, v, causal=causal, use_pallas=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_unaligned_head_dim_padding():
    # head_dim 48 < lane width 128: exercises the D-padding path.
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), seq=96, d=48)
    got = flash_attention(q, k, v, causal=True, use_pallas=True)
    want = reference_attention(q, k, v, causal=True)
    assert got.shape == q.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_causality():
    # Perturbing a future key/value must not change earlier outputs.
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), seq=64)
    base = flash_attention(q, k, v, causal=True, use_pallas=True)
    k2 = k.at[:, :, 40:, :].add(100.0)
    v2 = v.at[:, :, 40:, :].add(-50.0)
    pert = flash_attention(q, k2, v2, causal=True, use_pallas=True)
    np.testing.assert_allclose(np.asarray(base[:, :, :40]),
                               np.asarray(pert[:, :, :40]), atol=1e-5)
    assert not np.allclose(np.asarray(base[:, :, 40:]), np.asarray(pert[:, :, 40:]))


@pytest.mark.slow
def test_gradients_match_reference():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), seq=64, d=32)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, use_pallas=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(2, 4, 202, 64), (1, 1, 64, 32), (3, 2, 256, 128)])
def test_grad_compiles_on_backend(shape):
    """AOT-compile jax.grad of the kernel on the attached backend.

    BlockSpec tiling legality only surfaces in real Mosaic lowering — the
    interpreter accepts layouts the TPU compiler rejects, which is exactly how
    round 1 shipped a backward that failed to lower for every bh > 1 shape.
    On a CPU-only host this degrades to interpret mode (still checks tracing).
    """
    b, h, t, d = shape
    x = jnp.zeros((b, h, t, d), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, use_pallas=True))

    jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x).compile()


def test_cross_attention_kv_longer_than_q():
    # Non-causal cross-attention with kv_len != q_len: real keys beyond
    # q_len must participate, padding beyond kv_len must not.
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 2, 4, 32))
    k = jax.random.normal(kk, (1, 2, 16, 32))
    v = jax.random.normal(kv, (1, 2, 16, 32))
    got = flash_attention(q, k, v, causal=False, use_pallas=True)
    want = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_causal_rejects_mismatched_lengths():
    q = jnp.zeros((1, 1, 4, 32))
    k = jnp.zeros((1, 1, 8, 32))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, k, causal=True, use_pallas=True)


def test_bfloat16_path():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), seq=128, d=64, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, use_pallas=True)
    want = reference_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=3e-2, rtol=3e-2)


class TestLocalWindow:
    """Banded (sliding-window) attention — the episode-mode primitive."""

    def test_banded_matches_dense_mask_fast(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(6), seq=64)
        got = flash_attention(q, k, v, causal=True, local_window=16,
                              use_pallas=True)
        want = reference_attention(q, k, v, causal=True, local_window=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_band_semantics_hand_check(self):
        # window=1: each query attends only itself -> output == v.
        q, k, v = _rand_qkv(jax.random.PRNGKey(7), seq=8, d=32)
        got = flash_attention(q, k, v, causal=True, local_window=1,
                              use_pallas=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(v), atol=2e-5)

    def test_window_covering_sequence_equals_causal(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(8), seq=64)
        banded = flash_attention(q, k, v, causal=True, local_window=64,
                                 use_pallas=True)
        causal = flash_attention(q, k, v, causal=True, use_pallas=True)
        np.testing.assert_allclose(np.asarray(banded), np.asarray(causal),
                                   atol=1e-6)

    def test_keys_outside_band_are_invisible(self):
        # Perturbing a key/value outside every query's band changes nothing
        # for queries whose band excludes it.
        q, k, v = _rand_qkv(jax.random.PRNGKey(9), seq=64)
        w = 8
        base = flash_attention(q, k, v, causal=True, local_window=w,
                               use_pallas=True)
        k2 = k.at[:, :, 10, :].add(100.0)
        v2 = v.at[:, :, 10, :].add(-50.0)
        pert = flash_attention(q, k2, v2, causal=True, local_window=w,
                               use_pallas=True)
        # Queries 18+ have bands starting at >= 11: unaffected.
        np.testing.assert_allclose(np.asarray(base[:, :, 18:]),
                                   np.asarray(pert[:, :, 18:]), atol=1e-5)
        assert not np.allclose(np.asarray(base[:, :, 10:18]),
                               np.asarray(pert[:, :, 10:18]))

    @pytest.mark.slow
    @pytest.mark.parametrize("seq,window", [(256, 64), (403, 202), (512, 256)])
    def test_banded_matches_reference(self, seq, window):
        # 403 = the episode-mode replay span for window 202, unroll 202.
        q, k, v = _rand_qkv(jax.random.PRNGKey(10), seq=seq)
        got = flash_attention(q, k, v, causal=True, local_window=window,
                              use_pallas=True)
        want = reference_attention(q, k, v, causal=True, local_window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_banded_gradients_match_reference(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(11), seq=96, d=32)

        def loss_kernel(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, local_window=24, use_pallas=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(
                q, k, v, causal=True, local_window=24) ** 2)

        g_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_kernel, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_banded_grad_compiles_on_backend(self):
        x = jnp.zeros((2, 2, 433, 64), jnp.float32)  # W=202 span, T=232

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           local_window=202, use_pallas=True))

        jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(x, x, x).compile()

    def test_rejects_noncausal_band(self):
        q = jnp.zeros((1, 1, 8, 32))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, causal=False, local_window=4,
                            use_pallas=True)

    @pytest.mark.slow
    def test_streaming_path_matches_reference_long_seq(self):
        # 8192 x d128 crosses the _STREAM_KV_ELEMS dispatch threshold, so
        # this exercises the STREAMING banded kernels (K/V one block per
        # grid step) against the dense-mask reference — short-seq tests
        # above cover the full-KV banded path.
        from sharetrade_tpu.ops import attention as att
        q, k, v = _rand_qkv(jax.random.PRNGKey(12), batch=1, heads=1,
                            seq=8192, d=128)
        assert 8192 * 128 > att._STREAM_KV_ELEMS
        got = flash_attention(q, k, v, causal=True, local_window=202,
                              use_pallas=True)
        want = reference_attention(q, k, v, causal=True, local_window=202)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_streaming_gradients_match_reference_long_seq(self):
        from sharetrade_tpu.ops import attention as att
        q, k, v = _rand_qkv(jax.random.PRNGKey(13), batch=1, heads=1,
                            seq=8192, d=128)
        assert 8192 * 128 > att._STREAM_KV_ELEMS

        def loss_kernel(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, local_window=202, use_pallas=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(
                q, k, v, causal=True, local_window=202) ** 2)

        g_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_kernel, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)
