"""Multi-asset portfolio environment (BASELINE.json config 4 capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.data.ingest import align_series, from_rows
from sharetrade_tpu.env import make_portfolio_env, make_trading_env

WINDOW = 4


def two_asset_env(budget=100.0):
    prices = jnp.stack([jnp.arange(1.0, 11.0),        # asset 0: 1..10
                        jnp.arange(10.0, 110.0, 10.0)])  # asset 1: 10..100
    return make_portfolio_env(prices, window=WINDOW, initial_budget=budget)


class TestSingleAssetEquivalence:
    def test_matches_trading_env_exactly(self):
        """A=1 portfolio env reproduces the single-asset env step-for-step
        (obs layout, feasibility, rewards, final portfolio)."""
        prices = jnp.linspace(5.0, 15.0, 30)
        single = make_trading_env(prices, window=WINDOW, initial_budget=40.0)
        multi = make_portfolio_env(prices, window=WINDOW, initial_budget=40.0)
        assert multi.num_actions == 3 and multi.obs_dim == single.obs_dim
        assert multi.num_steps == single.num_steps

        s1, s2 = single.reset(), multi.reset()
        key = jax.random.PRNGKey(0)
        actions = jax.random.randint(key, (multi.num_steps,), 0, 3)
        for a in np.asarray(actions):
            np.testing.assert_allclose(np.asarray(single.observe(s1)),
                                       np.asarray(multi.observe(s2)), rtol=1e-6)
            s1, r1 = single.step(s1, jnp.int32(a))
            s2, r2 = multi.step(s2, jnp.int32(a))
            assert float(r1) == pytest.approx(float(r2))
        assert float(single.portfolio_value(s1)) == pytest.approx(
            float(multi.portfolio_value(s2)))


class TestPortfolioSemantics:
    def test_obs_layout(self):
        env = two_asset_env()
        obs = env.observe(env.reset())
        assert obs.shape == (2 * WINDOW + 1 + 2,)
        np.testing.assert_allclose(obs[:WINDOW], [1, 2, 3, 4])       # asset 0
        np.testing.assert_allclose(obs[WINDOW:2 * WINDOW], [10, 20, 30, 40])
        np.testing.assert_allclose(obs[2 * WINDOW:], [100.0, 0.0, 0.0])

    def test_buy_each_asset_against_shared_budget(self):
        env = two_asset_env(budget=60.0)
        s = env.reset()
        s, _ = env.step(s, jnp.int32(1))   # buy asset 1 at 50 -> budget 10
        assert float(s.budget) == 10.0
        np.testing.assert_allclose(np.asarray(s.shares), [0.0, 1.0])
        s, _ = env.step(s, jnp.int32(0))   # buy asset 0 at 6 -> budget 4
        assert float(s.budget) == 4.0
        np.testing.assert_allclose(np.asarray(s.shares), [1.0, 1.0])
        s2, _ = env.step(s, jnp.int32(1))  # asset 1 costs 70 > 4: degrades to Hold
        np.testing.assert_allclose(np.asarray(s2.shares), [1.0, 1.0])

    def test_sell_requires_holding_that_asset(self):
        env = two_asset_env()
        s = env.reset()
        s, _ = env.step(s, jnp.int32(0))   # buy asset 0 at 5
        s2, _ = env.step(s, jnp.int32(3))  # sell asset 1: none held -> Hold
        np.testing.assert_allclose(np.asarray(s2.shares),
                                   np.asarray(s.shares))
        s3, _ = env.step(s, jnp.int32(2))  # sell asset 0 at 6
        assert float(s3.budget) == float(s.budget) + 6.0
        np.testing.assert_allclose(np.asarray(s3.shares), [0.0, 0.0])

    def test_hold_marks_whole_portfolio(self):
        env = two_asset_env()
        s = env.reset()
        s, _ = env.step(s, jnp.int32(0))        # 1 share asset 0 at 5
        s, _ = env.step(s, jnp.int32(1))        # 1 share asset 1 at 60
        _, r = env.step(s, jnp.int32(4))        # hold; prices -> 7, 70
        # Both holdings appreciate: (7-6) + (70-60) = 11.
        assert float(r) == pytest.approx(11.0)

    def test_reward_telescopes(self):
        env = two_asset_env(budget=55.0)
        key = jax.random.PRNGKey(3)
        actions = jax.random.randint(key, (env.num_steps,), 0, env.num_actions)

        def body(s, a):
            ns, r = env.step(s, a)
            return ns, r

        final, rewards = jax.lax.scan(body, env.reset(), actions)
        np.testing.assert_allclose(
            float(env.portfolio_value(final)),
            55.0 + float(jnp.sum(rewards)), rtol=1e-5)


@pytest.mark.slow
class TestPortfolioTraining:
    @pytest.mark.parametrize("algo", ["qlearn", "ppo"])
    def test_agents_train_on_two_assets(self, algo):
        cfg = FrameworkConfig()
        cfg.learner.algo = algo
        cfg.env.window = WINDOW
        cfg.model.hidden_dim = 16
        cfg.parallel.num_workers = 4
        cfg.runtime.chunk_steps = 8
        cfg.learner.unroll_len = 8
        prices = jnp.stack([jnp.linspace(10.0, 20.0, 64),
                            jnp.linspace(50.0, 40.0, 64)])
        env = make_portfolio_env(prices, window=WINDOW)
        agent = build_agent(cfg, env)
        ts = agent.init(jax.random.PRNGKey(0))
        ts2, metrics = jax.jit(agent.step)(ts)
        assert int(ts2.env_steps) > 0
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["portfolio_mean"]))

    def test_window_transformer_trains_on_two_assets(self):
        """The sequence-model capability cliff removed (round 4): the
        window transformer tokenizes the portfolio observation as per-asset
        blocks (PARITY.md "Model-family boundaries") and trains end-to-end
        over the 2-asset env with the widened 2A+1 action head."""
        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.env.window = WINDOW
        cfg.model.kind = "transformer"
        cfg.model.num_layers = 1
        cfg.model.num_heads = 2
        cfg.model.head_dim = 8
        cfg.parallel.num_workers = 4
        cfg.runtime.chunk_steps = 8
        cfg.learner.unroll_len = 8
        prices = jnp.stack([jnp.linspace(10.0, 20.0, 64),
                            jnp.linspace(50.0, 40.0, 64)])
        env = make_portfolio_env(prices, window=WINDOW)
        agent = build_agent(cfg, env)
        assert "asset" in agent.model.init(jax.random.PRNGKey(1))
        ts = agent.init(jax.random.PRNGKey(0))
        ts2, metrics = jax.jit(agent.step)(ts)
        assert int(ts2.env_steps) > 0
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["portfolio_mean"]))

    def test_transformer_tokenization_distinguishes_assets(self):
        """Holding a share of asset 0 vs asset 1 must produce different
        logits — the asset embeddings and per-asset portfolio tokens make
        the policy asset-aware, not just wider."""
        from sharetrade_tpu.models import build_model
        from sharetrade_tpu.config import ModelConfig

        env = two_asset_env()
        model = build_model(
            ModelConfig(kind="transformer", num_layers=1, num_heads=2,
                        head_dim=8),
            env.obs_dim, num_actions=env.num_actions, num_assets=2)
        params = model.init(jax.random.PRNGKey(0))
        s = env.reset()
        obs_a = env.observe(s.replace(
            shares=jnp.asarray([1.0, 0.0])))[None]
        obs_b = env.observe(s.replace(
            shares=jnp.asarray([0.0, 1.0])))[None]
        out_a, _ = model.apply_batch(params, obs_a, ())
        out_b, _ = model.apply_batch(params, obs_b, ())
        assert not np.allclose(np.asarray(out_a.logits),
                               np.asarray(out_b.logits))

    def test_episode_mode_multiasset_rejected_with_pointer(self):
        """The episode-transformer boundary is declared, not silent
        (PARITY.md): multi-asset configs get a clear error naming the
        supported alternative."""
        cfg = FrameworkConfig()
        cfg.learner.algo = "ppo"
        cfg.env.window = WINDOW
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        env = two_asset_env()
        with pytest.raises(ValueError, match="PARITY.md"):
            build_agent(cfg, env)


class TestRolloutDispatch:
    def test_trunk_capable_model_on_multiasset_env_uses_generic_path(
            self, monkeypatch):
        """The precomputed-rollout fast path hard-codes the single-asset
        obs layout (window | budget | shares); a trunk-capable model over a
        multi-asset env must fall back to the generic per-step loop instead
        of assembling malformed observations."""
        from sharetrade_tpu.agents import rollout as rmod
        from sharetrade_tpu.agents.base import (
            TrainState, batched_carry, batched_reset)
        from sharetrade_tpu.models.transformer_episode import (
            episode_transformer_policy)

        env = two_asset_env()
        model = episode_transformer_policy(
            env.obs_dim, env.num_actions, num_layers=1, num_heads=2,
            head_dim=8)
        assert model.apply_rollout_trunk is not None
        monkeypatch.setattr(
            rmod, "_collect_rollout_precomputed",
            lambda *a, **k: pytest.fail(
                "precomputed fast path taken for a multi-asset env"))
        k = jax.random.PRNGKey(0)
        ts = TrainState(
            params=model.init(k), opt_state=None,
            carry=batched_carry(model, 2), env_state=batched_reset(env, 2),
            rng=jax.random.PRNGKey(1), env_steps=jnp.int32(0),
            updates=jnp.int32(0))
        _, traj, _, _ = rmod.collect_rollout(model, env, ts, 3, 2)
        assert traj.obs.shape == (3, 2, env.obs_dim)
        assert np.isfinite(np.asarray(traj.obs)).all()


class TestAlignSeries:
    def test_inner_join_on_dates(self):
        a = from_rows("A", [("2020-01-01", 1.0), ("2020-01-02", 2.0),
                            ("2020-01-03", 3.0)])
        b = from_rows("B", [("2020-01-02", 20.0), ("2020-01-03", 30.0),
                            ("2020-01-04", 40.0)])
        mat = align_series([a, b])
        np.testing.assert_allclose(mat, [[2.0, 3.0], [20.0, 30.0]])

    def test_disjoint_dates_rejected(self):
        a = from_rows("A", [("2020-01-01", 1.0)])
        b = from_rows("B", [("2021-01-01", 2.0)])
        with pytest.raises(ValueError, match="no common dates"):
            align_series([a, b])
