"""Precision policy (precision.py, ops/fused_update.py): the bf16-compute /
fp32-master tier and its contracts.

The four pinned claims:

1. **fp32 default is bit-identical to the pre-policy code** — the policy
   helpers are structural identities, and a fixed-seed qlearn/PPO
   trajectory reproduces the golden captured at the commit BEFORE the
   policy landed (tests/golden/precision_fp32_golden.json) exactly.
2. **bf16_mixed keeps fp32 masters** — params and optimizer state stay
   f32 through training and checkpoints; the reference MLP converges
   within a pinned band of the fp32 run.
3. **Checkpoints hold fp32 masters and refuse mode mismatches** — the
   round-trip is exact, and a store saved under one precision.mode
   raises a loud ValueError under another (flax from_bytes would
   otherwise silently deserialize wrong-dtype leaves).
4. **The fused optimizer update is optax-exact** — bitwise in fp32 for
   adagrad/adam/sgd; bf16 gradients differ only by their quantization.
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.data.synthetic import synthetic_price_series
from sharetrade_tpu.env import trading
from sharetrade_tpu.ops.fused_update import fused_apply
from sharetrade_tpu.precision import FP32, PrecisionPolicy, policy_from_config

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "precision_fp32_golden.json")


def _tree_digest(tree):
    h = hashlib.sha256()
    for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            key=lambda kv: str(kv[0])):
        a = np.asarray(leaf)
        h.update(str(path).encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _small_cfg(algo: str, mode: str = "fp32") -> FrameworkConfig:
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.precision.mode = mode
    cfg.parallel.num_workers = 4
    cfg.env.window = 16
    cfg.runtime.chunk_steps = 25
    cfg.learner.unroll_len = 25
    cfg.model.hidden_dim = 16
    return cfg


def _small_env(cfg):
    series = synthetic_price_series(length=256, seed=7)
    return trading.env_from_prices(series.prices, window=cfg.env.window,
                                   initial_budget=cfg.env.initial_budget)


# ---------------------------------------------------------------------------
# the policy object
# ---------------------------------------------------------------------------

class TestPolicy:
    def test_fp32_helpers_are_object_identities(self):
        """The structural bit-identity guarantee: fp32 mode returns THE
        SAME OBJECT, so the traced program cannot differ from pre-policy
        code even by a no-op cast."""
        tree = {"w": jnp.ones((3, 2)), "n": jnp.int32(4)}
        assert FP32.cast_compute(tree) is tree
        assert FP32.grads_to_master(tree) is tree
        assert FP32.cast_carry(tree) is tree
        assert not FP32.mixed and not FP32.use_fused_update

    def test_bf16_casts_float_leaves_only(self):
        pol = PrecisionPolicy(mode="bf16_mixed")
        tree = {"w": jnp.ones((3, 2)), "n": jnp.int32(4)}
        cast = pol.cast_compute(tree)
        assert cast["w"].dtype == jnp.bfloat16
        assert cast["n"].dtype == jnp.int32
        back = pol.grads_to_master(cast)
        assert back["w"].dtype == jnp.float32
        assert pol.mixed and pol.use_fused_update

    def test_model_carry_hook_wins(self):
        """The episode transformer's mixed-dtype carry: K/V follow the
        compute dtype, ``hist`` (raw prices) stays f32."""
        from sharetrade_tpu.models.transformer_episode import (
            episode_transformer_policy)
        pol = PrecisionPolicy(mode="bf16_mixed")
        model = episode_transformer_policy(10, 3, num_layers=2, num_heads=2,
                                           head_dim=8)
        carry = pol.cast_carry(model.init_carry(), model)
        assert carry["k"].dtype == jnp.bfloat16
        assert carry["v"].dtype == jnp.bfloat16
        assert carry["hist"].dtype == jnp.float32
        assert carry["t"].dtype == jnp.int32

    def test_unknown_mode_rejected(self):
        with pytest.raises(ConfigError, match="precision.mode"):
            PrecisionPolicy(mode="fp16")
        with pytest.raises(ConfigError, match="fused_update"):
            PrecisionPolicy(fused_update="maybe")

    def test_policy_from_config(self):
        cfg = FrameworkConfig()
        assert not policy_from_config(cfg.precision).mixed
        cfg.precision.mode = "bf16_mixed"
        assert policy_from_config(cfg.precision).mixed

    def test_old_dtype_knob_raises_migration_error(self):
        """Satellite: model.dtype='bfloat16' (the whole-model cast that
        silently put optimizer state in bf16) must fail loudly, naming the
        replacement knob."""
        from sharetrade_tpu.models import build_model
        cfg = FrameworkConfig()
        cfg.model.dtype = "bfloat16"
        with pytest.raises(ConfigError, match="precision.mode"):
            build_model(cfg.model, 18)
        cfg.model.dtype = "float16"
        with pytest.raises(ConfigError, match="unknown model.dtype"):
            build_model(cfg.model, 18)


# ---------------------------------------------------------------------------
# fp32 default: bit-identical to the pre-policy commit (golden trajectory)
# ---------------------------------------------------------------------------

class TestFp32Golden:
    @pytest.mark.parametrize("algo,chunks", [("qlearn", 2), ("ppo", 1)])
    def test_trajectory_matches_pre_policy_golden(self, algo, chunks):
        """The golden file was captured at the commit BEFORE the precision
        policy landed (same container, same jax): the default fp32 mode
        must reproduce params/opt/metrics EXACTLY — not approximately."""
        with open(GOLDEN) as f:
            golden = json.load(f)[algo]
        cfg = _small_cfg(algo)
        env = _small_env(cfg)
        agent = build_agent(cfg, env)
        step = jax.jit(agent.step)
        ts = agent.init(jax.random.PRNGKey(0))
        for i in range(chunks):
            ts, metrics = step(ts)
            got = {k: float(np.asarray(v)) for k, v in sorted(metrics.items())
                   if np.asarray(v).ndim == 0}
            assert got == golden["metrics"][i]
        assert _tree_digest(ts.params) == golden["params_sha256"]
        assert _tree_digest(ts.opt_state) == golden["opt_state_sha256"]
        assert _tree_digest(ts) == golden["state_sha256"]


# ---------------------------------------------------------------------------
# bf16_mixed: masters stay fp32; reference MLP converges within a band
# ---------------------------------------------------------------------------

class TestBf16Mixed:
    def test_masters_stay_fp32_and_convergence_band(self):
        """The reference-shape MLP (hidden 200 — the real architecture,
        shortened series) trained one 200-update chunk in both modes on
        one seed: masters stay f32, and the bf16 run's loss curve and
        final portfolio stats sit within a pinned band of fp32 — the
        bf16 quantization moves rounding, not the learning dynamics."""
        results = {}
        for mode in ("fp32", "bf16_mixed"):
            cfg = FrameworkConfig()
            cfg.learner.algo = "qlearn"
            cfg.precision.mode = mode
            cfg.parallel.num_workers = 4
            cfg.env.window = 32
            cfg.model.hidden_dim = 200
            cfg.runtime.chunk_steps = 200
            series = synthetic_price_series(length=300, seed=3)
            env = trading.env_from_prices(series.prices,
                                          window=cfg.env.window)
            agent = build_agent(cfg, env)
            ts = agent.init(jax.random.PRNGKey(0))
            ts, metrics = jax.jit(agent.step)(ts)
            for leaf in jax.tree.leaves(ts.params):
                assert leaf.dtype == jnp.float32
            for leaf in jax.tree.leaves(ts.opt_state):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    assert leaf.dtype == jnp.float32
            results[mode] = (ts, {k: float(np.asarray(v))
                                  for k, v in metrics.items()
                                  if np.asarray(v).ndim == 0})
        m32, m16 = results["fp32"][1], results["bf16_mixed"][1]
        assert np.isfinite(m16["loss"])
        # Loss scale tracks squared portfolio-value errors (large); the
        # band is generous but pins "same training dynamics" — a wrong
        # master/update dtype diverges by orders of magnitude, not 20%.
        assert m16["loss"] == pytest.approx(m32["loss"], rel=0.2)
        assert m16["portfolio_mean"] == pytest.approx(
            m32["portfolio_mean"], rel=0.05)
        # Master weights stay close leaf-by-leaf (bf16 rounding noise
        # accumulated over 200 adagrad updates, not a different optimum).
        for a, b in zip(jax.tree.leaves(results["fp32"][0].params),
                        jax.tree.leaves(results["bf16_mixed"][0].params)):
            denom = np.maximum(np.abs(np.asarray(a)), 1e-3)
            rel = np.abs(np.asarray(a) - np.asarray(b)) / denom
            assert float(np.median(rel)) < 0.05

    def test_bf16_megachunk_parity(self):
        """K fused chunks == K host chunks under bf16_mixed (the same
        traced-body guarantee megachunks pin for fp32)."""
        from sharetrade_tpu.agents.base import megachunk_step
        cfg = _small_cfg("qlearn", "bf16_mixed")
        env = _small_env(cfg)
        agent = build_agent(cfg, env)
        single = jax.jit(agent.step)
        fused = jax.jit(megachunk_step(agent.step, 2))
        ts_a = agent.init(jax.random.PRNGKey(0))
        ts_b = agent.init(jax.random.PRNGKey(0))
        for _ in range(2):
            ts_a, _ = single(ts_a)
        ts_b, _ = fused(ts_b)
        for a, b in zip(jax.tree.leaves(ts_a), jax.tree.leaves(ts_b)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_compute_copy_drives_forward_dtype(self):
        """models compute in the dtype of the params they are HANDED:
        fp32 masters -> f32 activations; the policy's bf16 copy -> bf16
        internals with f32 heads (the ops/attention.py accumulation
        convention extended to models/*)."""
        from sharetrade_tpu.models.core import compute_dtype
        from sharetrade_tpu.models.mlp import ac_mlp
        pol = PrecisionPolicy(mode="bf16_mixed")
        model = ac_mlp(18, 16)
        params = model.init(jax.random.PRNGKey(0))
        assert compute_dtype(params) == jnp.float32
        params_c = pol.cast_compute(params)
        assert compute_dtype(params_c) == jnp.bfloat16
        out, _ = model.apply(params_c, jnp.ones((18,)), ())
        assert out.logits.dtype == jnp.float32   # heads stay f32
        assert np.isfinite(np.asarray(out.logits)).all()


# ---------------------------------------------------------------------------
# fused optimizer update vs the optax pair
# ---------------------------------------------------------------------------

def _opt_pair(name):
    return {"adagrad": optax.adagrad(0.01), "adam": optax.adam(0.01),
            "sgd": optax.sgd(0.01)}[name]


class TestFusedUpdate:
    params = {
        "a": jax.random.normal(jax.random.PRNGKey(0), (37, 13)),
        "b": {"w": jax.random.normal(jax.random.PRNGKey(1), (200,)),
              "s": jnp.float32(0.5)},
    }
    grads = jax.tree.map(lambda x: x * 0.37 + 0.01, params)

    @pytest.mark.parametrize("name", ["adagrad", "adam", "sgd"])
    def test_fp32_bitwise_vs_optax(self, name):
        opt = _opt_pair(name)
        st = opt.init(self.params)
        p_ref, st_ref = self.params, st
        p_f, st_f = self.params, st
        for _ in range(3):       # counts/moments exercise multi-step state
            u, st_ref = opt.update(self.grads, st_ref, p_ref)
            p_ref = optax.apply_updates(p_ref, u)
            p_f, st_f = fused_apply(name, 0.01, self.grads, st_f, p_f)
        for ref, got in zip(jax.tree.leaves((p_ref, st_ref)),
                            jax.tree.leaves((p_f, st_f))):
            np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    @pytest.mark.parametrize("name", ["adagrad", "adam", "sgd"])
    def test_pallas_kernel_interpret_parity(self, name):
        """The Pallas kernel path (interpret mode — the CPU stand-in for
        the TPU compile) agrees with optax to ~1 ulp: interpret mode
        evaluates ops singly, so XLA's FMA contraction of the fused
        chain is the only allowed divergence."""
        opt = _opt_pair(name)
        st = opt.init(self.params)
        u, st_ref = opt.update(self.grads, st, self.params)
        p_ref = optax.apply_updates(self.params, u)
        p_i, st_i = fused_apply(name, 0.01, self.grads, st, self.params,
                                interpret=True)
        for ref, got in zip(jax.tree.leaves((p_ref, st_ref)),
                            jax.tree.leaves((p_i, st_i))):
            np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                       rtol=3e-7, atol=1e-7)

    @pytest.mark.parametrize("name", ["adagrad", "adam", "sgd"])
    def test_bf16_grads_within_tolerance(self, name):
        """bf16 gradients: the fused update (upcast inside the pass)
        equals the optax pair fed explicitly-upcast grads — the only
        divergence is the gradient's own bf16 quantization upstream."""
        opt = _opt_pair(name)
        st = opt.init(self.params)
        g16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), self.grads)
        p_f, st_f = fused_apply(name, 0.01, g16, st, self.params,
                                compute_dtype=jnp.bfloat16)
        u, _ = opt.update(jax.tree.map(lambda x: x.astype(jnp.float32), g16),
                          st, self.params)
        p_ref = optax.apply_updates(self.params, u)
        for a, b in zip(jax.tree.leaves(p_f), jax.tree.leaves(p_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_emit_compute_is_recast_of_new_masters(self):
        st = optax.adagrad(0.01).init(self.params)
        p_new, _, p_c = fused_apply("adagrad", 0.01, self.grads, st,
                                    self.params,
                                    compute_dtype=jnp.bfloat16,
                                    emit_compute=True)
        for m, c in zip(jax.tree.leaves(p_new), jax.tree.leaves(p_c)):
            assert c.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(m, dtype=np.float32).astype(jnp.bfloat16),
                np.asarray(c))

    def test_in_jit_trace(self):
        """The fused path must trace inside the agents' jitted steps (the
        real call context) — counts as traced scalars included. Tolerance
        is ~1 ulp, not bitwise: XLA may FMA-contract the jitted fused
        chain differently from the eagerly-dispatched optax reference
        (op-for-op identity is pinned by test_fp32_bitwise_vs_optax,
        where both sides run under the same execution regime)."""
        opt = optax.adam(0.01)
        st = opt.init(self.params)

        @jax.jit
        def step(p, s, g):
            return fused_apply("adam", 0.01, g, s, p, use_pallas=False)

        p1, s1 = step(self.params, st, self.grads)
        u, s_ref = opt.update(self.grads, st, self.params)
        p_ref = optax.apply_updates(self.params, u)
        for a, b in zip(jax.tree.leaves((p1, s1)),
                        jax.tree.leaves((p_ref, s_ref))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-7, atol=1e-7)

    def test_unsupported_optimizer_raises(self):
        with pytest.raises(ValueError, match="fused update"):
            fused_apply("rmsprop", 0.01, self.grads, (), self.params)


# ---------------------------------------------------------------------------
# checkpoints: fp32 masters always; mode mismatches refused
# ---------------------------------------------------------------------------

class TestCheckpointPrecision:
    def _trained_state(self, mode):
        cfg = _small_cfg("ppo", mode)
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.num_layers = 2
        cfg.model.num_heads = 2
        cfg.model.head_dim = 8
        env = _small_env(cfg)
        agent = build_agent(cfg, env)
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)
        return agent, ts

    def test_round_trip_restores_fp32_masters_exactly(self, tmp_path):
        from sharetrade_tpu.checkpoint import CheckpointManager
        agent, ts = self._trained_state("bf16_mixed")
        mgr = CheckpointManager(str(tmp_path), precision_mode="bf16_mixed")
        mgr.save(7, ts, metadata={"episode": 0})
        meta = mgr.metadata(7)
        assert meta["precision_mode"] == "bf16_mixed"
        template = agent.init(jax.random.PRNGKey(0))
        restored, step = mgr.restore(template)
        assert step == 7
        for a, b in zip(jax.tree.leaves(ts.params),
                        jax.tree.leaves(restored.params)):
            assert b.dtype == jnp.float32      # fp32 masters, always
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the compute-dtype carry survives too (K/V bf16, hist f32)
        assert restored.carry["k"].dtype == jnp.bfloat16
        assert restored.carry["hist"].dtype == jnp.float32

    def test_mode_mismatch_refused_loudly(self, tmp_path):
        from sharetrade_tpu.checkpoint import CheckpointManager
        agent, ts = self._trained_state("bf16_mixed")
        CheckpointManager(str(tmp_path),
                          precision_mode="bf16_mixed").save(3, ts)
        wrong = CheckpointManager(str(tmp_path), precision_mode="fp32")
        template = agent.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="precision.mode"):
            wrong.restore(template)
        # the store is untouched (config mismatch, not corruption)
        assert not [p for p in os.listdir(tmp_path)
                    if p.startswith("corrupt_")]

    def test_pre_policy_checkpoints_read_as_fp32(self, tmp_path):
        """A checkpoint with NO recorded mode (every pre-PR store) is
        fp32: restorable under fp32 config, refused under bf16_mixed."""
        from sharetrade_tpu.checkpoint import CheckpointManager
        cfg = _small_cfg("qlearn")
        env = _small_env(cfg)
        agent = build_agent(cfg, env)
        ts = agent.init(jax.random.PRNGKey(0))
        CheckpointManager(str(tmp_path)).save(1, ts)   # no mode stamped
        ok = CheckpointManager(str(tmp_path), precision_mode="fp32")
        restored, _ = ok.restore(agent.init(jax.random.PRNGKey(0)))
        bad = CheckpointManager(str(tmp_path), precision_mode="bf16_mixed")
        with pytest.raises(ValueError, match="precision.mode"):
            bad.restore(agent.init(jax.random.PRNGKey(0)))


# ---------------------------------------------------------------------------
# satellites: perf-gate precision series split, lint check 7
# ---------------------------------------------------------------------------

class TestPerfGateSplit:
    def test_precision_splits_series(self, tmp_path):
        """A bf16_mixed row never gates against fp32 history: a 10x
        apparent 'regression' across precisions stays ungated."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import perf_gate
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "parsed": {"metric": "m", "value": 1000.0,
                               "schema_version": 1, "backend": "cpu",
                               "precision": "fp32"}}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "n": 2, "parsed": {"metric": "m", "value": 100.0,
                               "schema_version": 1, "backend": "cpu",
                               "precision": "bf16_mixed"}}))
        assert perf_gate.run_gate(tmp_path) == 0
        # same precision still gates
        (tmp_path / "BENCH_r03.json").write_text(json.dumps({
            "n": 3, "parsed": {"metric": "m", "value": 100.0,
                               "schema_version": 1, "backend": "cpu",
                               "precision": "fp32"}}))
        assert perf_gate.run_gate(tmp_path) == 1

    def test_legacy_rows_default_to_fp32_series(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import perf_gate
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "parsed": {"metric": "m", "value": 100.0}}))  # legacy
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "n": 2, "parsed": {"metric": "m", "value": 99.0,
                               "schema_version": 1, "backend": "tpu",
                               "precision": "fp32"}}))
        series = perf_gate.collect_series([
            perf_gate.parse_bench_file(str(tmp_path / "BENCH_r01.json")),
            perf_gate.parse_bench_file(str(tmp_path / "BENCH_r02.json"))])
        assert ("m", "tpu", "fp32", "value") in series
        assert len(series[("m", "tpu", "fp32", "value")]) == 2


class TestLintCheck7:
    def test_repo_is_clean(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import lint_hot_loop
        assert lint_hot_loop.lint_precision_casts() == []

    def test_pattern_semantics(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import lint_hot_loop
        pat = lint_hot_loop.PRECISION_PATTERN
        # receiver casts on params/grads: flagged
        assert pat.search('p = ts.params.astype(jnp.bfloat16)')
        assert pat.search('g = grads.astype(jnp.float32)')
        assert pat.search('w = params["w"].astype(dtype)')
        assert pat.search(
            'jax.tree.map(lambda x: x.astype(d), grads)')
        # activation casts that merely mention params: not flagged
        assert not pat.search(
            'logits = dense(params["policy"], h).astype(jnp.float32)')
        assert not pat.search('x = obs.astype(compute_dtype(params))')
        assert not pat.search('tokens = tokenize(obs).astype(dtype)')
