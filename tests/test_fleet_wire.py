"""Sans-IO wire core + evloop backend (fleet/proto.py, fleet/evloop.py
— ISSUE 16).

The load-bearing contracts:

- **Torn reads are invisible**: a parser fed the SAME byte stream split
  at EVERY offset (including one byte at a time) emits the same message
  sequence — framing is a pure state machine, never "hope recv returned
  a whole request".
- **Pipelining**: N messages in one chunk come back as N events in
  order; partial tails stay buffered across feeds.
- **Bounded buffering**: an oversized head or declared body raises
  :class:`ProtocolError` (status 400) instead of buffering unboundedly;
  malformed framing is refused with the same class.
- **Differential oracle**: the threaded and evloop wire backends answer
  the SAME request stream with BYTE-IDENTICAL response streams — the
  blocking stdlib path is retained exactly so the event-loop rewrite
  can be diffed against it.
- **Non-blocking discipline is linted**: check 15 keeps blocking socket
  idioms and per-connection threads out of the evloop path, and keeps
  fleet/proto.py free of I/O imports entirely.
- **Trace headers ride the same frame** (ISSUE 17): ``X-Trace-Id``/
  ``X-Parent-Span`` canonicalize identically through torn reads, a bad
  id is dropped rather than relayed, replies NEVER echo trace headers —
  so the differential oracle holds byte-identically with tracing on AND
  off — and check 16 keeps span emission on the evloop/router hot path
  a bounded buffered append.
- **The native parser is indistinguishable** (ISSUE 19): the C
  extension behind ``proto.set_backend("native")`` replays seeded
  byte-split/pipelined/malformed corpora with event streams and
  ``ProtocolError`` status+detail EXACTLY equal to the Python oracle's,
  renders byte-identically, degrades loudly to "py" when the extension
  is missing, and check 18 confines the binding surface to
  fleet/proto.py with the GIL released in wire.cc.
"""

from __future__ import annotations

import json
import os
import socket
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.fleet import ServeFrontend
from sharetrade_tpu.fleet import proto, wire
from sharetrade_tpu.fleet.evloop import EvloopFrontend
from sharetrade_tpu.fleet.frontend import ThreadedServeFrontend
from sharetrade_tpu.utils.metrics import MetricsRegistry


# ---- corpus ---------------------------------------------------------

def _request_corpus() -> list[bytes]:
    submit = json.dumps({"session": "s-1", "obs": [1.0, 2.0, 3.0]})
    return [
        proto.render_request("GET", wire.HEALTH_PATH, "h:1"),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1",
                             submit.encode(),
                             headers={wire.DEADLINE_HEADER: "250"}),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1",
                             b"\x00binary body\xff",
                             headers={"Connection": "close"}),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1",
                             submit.encode(),
                             headers={proto.TRACE_HEADER: "ab12cd34ef56ab78",
                                      proto.PARENT_HEADER: "1f.2"}),
        proto.render_request("GET", wire.METRICS_PATH, "h:1"),
    ]


def _response_corpus() -> list[bytes]:
    return [
        proto.render_response(200, b'{"ok": true}'),
        proto.render_response(503, b'{"error": "engine_failed"}',
                              keep_alive=False),
        proto.render_response(200, b"metrics text",
                              content_type="text/plain; version=0.0.4",
                              extra_headers={"X-Probe": "1"}),
        proto.render_response(400, b""),
    ]


def _req_key(r: proto.Request) -> tuple:
    return (r.method, r.target, sorted(r.headers.items()), r.body,
            r.keep_alive)


def _resp_key(r: proto.Response) -> tuple:
    return (r.status, sorted(r.headers.items()), r.body)


class TestSansIOParsers:
    def test_request_stream_torn_at_every_offset(self):
        blob = b"".join(_request_corpus())
        reference = [_req_key(r)
                     for r in proto.RequestParser().feed(blob)]
        assert len(reference) == len(_request_corpus())
        for split in range(1, len(blob)):
            p = proto.RequestParser()
            got = p.feed(blob[:split]) + p.feed(blob[split:])
            assert [_req_key(r) for r in got] == reference, split
            assert not p.pending_bytes()

    def test_response_stream_torn_at_every_offset(self):
        blob = b"".join(_response_corpus())
        reference = [_resp_key(r)
                     for r in proto.ResponseParser().feed(blob)]
        assert len(reference) == len(_response_corpus())
        for split in range(1, len(blob)):
            p = proto.ResponseParser()
            got = p.feed(blob[:split]) + p.feed(blob[split:])
            assert [_resp_key(r) for r in got] == reference, split

    def test_one_byte_at_a_time(self):
        blob = b"".join(_request_corpus())
        reference = [_req_key(r)
                     for r in proto.RequestParser().feed(blob)]
        p = proto.RequestParser()
        got = []
        for i in range(len(blob)):
            got.extend(p.feed(blob[i:i + 1]))
        assert [_req_key(r) for r in got] == reference
        assert not p.pending_bytes()

    def test_pending_bytes_mid_message(self):
        blob = _request_corpus()[1]
        p = proto.RequestParser()
        assert not p.pending_bytes()
        assert p.feed(blob[:len(blob) - 1]) == []
        assert p.pending_bytes()        # mid-body: not pool-reusable
        assert len(p.feed(blob[len(blob) - 1:])) == 1
        assert not p.pending_bytes()

    def test_keep_alive_folding(self):
        def parse(version, connection=None):
            head = [f"GET / {version}", "Host: h"]
            if connection:
                head.append(f"Connection: {connection}")
            raw = ("\r\n".join(head) + "\r\n\r\n").encode()
            return proto.RequestParser().feed(raw)[0].keep_alive

        assert parse("HTTP/1.1") is True
        assert parse("HTTP/1.1", "close") is False
        assert parse("HTTP/1.0") is False
        assert parse("HTTP/1.0", "keep-alive") is True

    @pytest.mark.parametrize("raw", [
        b"GARBAGE\r\n\r\n",                       # no 3-part line
        b"GET /x HTTP/2\r\n\r\n",                 # unsupported version
        b"GET /x HTTP/1.1\r\nNoColonHere\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: xyz\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: -5\r\n\r\n",
    ])
    def test_malformed_requests_raise_400(self, raw):
        with pytest.raises(proto.ProtocolError) as exc:
            proto.RequestParser().feed(raw)
        assert exc.value.status == 400

    def test_oversized_head_refused_before_terminator(self):
        p = proto.RequestParser()
        with pytest.raises(proto.ProtocolError):
            p.feed(b"GET /x HTTP/1.1\r\nX: "
                   + b"a" * (proto.MAX_HEAD_BYTES + 8))

    def test_oversized_declared_body_refused(self):
        raw = (f"POST /x HTTP/1.1\r\nContent-Length: "
               f"{proto.MAX_BODY_BYTES + 1}\r\n\r\n").encode()
        with pytest.raises(proto.ProtocolError):
            proto.RequestParser().feed(raw)

    def test_response_requires_content_length(self):
        with pytest.raises(proto.ProtocolError) as exc:
            proto.ResponseParser().feed(b"HTTP/1.1 200 OK\r\n\r\n")
        assert "Content-Length" in exc.value.detail

    def test_render_request_is_the_fleet_client_frame(self):
        raw = proto.render_request("POST", "/v1/submit", "10.0.0.1:80",
                                   b"{}", headers={"X-Deadline-Ms": "9"})
        assert raw == (b"POST /v1/submit HTTP/1.1\r\n"
                       b"Host: 10.0.0.1:80\r\n"
                       b"Content-Length: 2\r\n"
                       b"X-Deadline-Ms: 9\r\n\r\n{}")


class TestTraceHeaders:
    """X-Trace-Id / X-Parent-Span canonicalization (ISSUE 17): ONE
    framing definition, bad ids dropped rather than relayed."""

    def test_roundtrip_through_the_parser(self):
        raw = proto.render_request(
            "POST", wire.SUBMIT_PATH, "h:1", b"{}",
            headers={proto.TRACE_HEADER: "DEADbeef00112233",
                     proto.PARENT_HEADER: "abc.1f"})
        req = proto.RequestParser().feed(raw)[0]
        assert proto.trace_context(req.headers) == \
            ("DEADbeef00112233", "abc.1f")
        # Torn at every offset: the context survives identically.
        for split in range(1, len(raw)):
            p = proto.RequestParser()
            got = p.feed(raw[:split]) + p.feed(raw[split:])
            assert proto.trace_context(got[0].headers) == \
                ("DEADbeef00112233", "abc.1f"), split

    def test_absent_context_is_none(self):
        req = proto.RequestParser().feed(
            proto.render_request("GET", wire.HEALTH_PATH, "h:1"))[0]
        assert proto.trace_context(req.headers) is None

    @pytest.mark.parametrize("trace_id", [
        "", "zz99", "a" * 65, "ab cd", "ab\tcd", "<script>"])
    def test_invalid_trace_id_never_relayed(self, trace_id):
        assert proto.trace_context({"x-trace-id": trace_id}) is None

    def test_invalid_parent_dropped_trace_kept(self):
        assert proto.trace_context(
            {"x-trace-id": "ab12", "x-parent-span": "not~valid"}) \
            == ("ab12", "")
        assert proto.trace_context(
            {"x-trace-id": "ab12", "x-parent-span": "f" * 65}) \
            == ("ab12", "")

    def test_stdlib_message_headers_resolve_case_insensitively(self):
        # The threaded front-end hands trace_context an
        # email.message.Message (BaseHTTPRequestHandler.headers) whose
        # .get is case-insensitive — same answer as the parsed dict.
        from email.message import Message
        msg = Message()
        msg["X-Trace-Id"] = "ab12cd34"
        msg["X-Parent-Span"] = "3.c"
        assert proto.trace_context(msg) == ("ab12cd34", "3.c")

    def test_replies_never_carry_trace_headers(self):
        raw = proto.render_response(
            200, b"{}", extra_headers={"X-Probe": "1"})
        resp = proto.ResponseParser().feed(raw)[0]
        assert "x-trace-id" not in resp.headers
        assert "x-parent-span" not in resp.headers


# ---- the differential oracle ---------------------------------------


class StubBackend:
    """Deterministic inline backend: replies are a pure function of the
    request, so the two wire backends' response streams must be
    byte-identical."""

    def serve_request(self, session, obs, deadline_ms):
        vals = [float(x) for x in obs]
        return {"session": session, "action": len(vals) % 3,
                "logits": vals[:3], "value": sum(vals),
                "params_step": 7, "latency_ms": 0.25,
                "stages": {"queue_ms": 0.1}}

    def health(self):
        return {"ok": True, "failed": False, "queue_depth": 0,
                "overload": 0.0, "params_step": 7, "swaps_total": 0}


def _scripted_stream() -> tuple[bytes, int]:
    """One connection's worth of requests covering every front-end
    reply path that is deterministic across backends; returns
    ``(payload, expected_response_count)``."""
    ok = json.dumps({"session": "d-1", "obs": [1.0, 2.0, 3.0]}).encode()
    reqs = [
        proto.render_request("GET", wire.HEALTH_PATH, "h:1"),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1", ok),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1",
                             b"not json at all"),
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1",
                             b'{"obs": [1.0]}'),      # missing session
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1", ok,
                             headers={wire.DEADLINE_HEADER: "soon"}),
        proto.render_request("GET", "/nope", "h:1"),
        proto.render_request("POST", "/nope", "h:1", b"ignored body"),
        # pipelined burst: three submits in one segment
        proto.render_request("POST", wire.SUBMIT_PATH, "h:1", ok) * 3,
    ]
    return b"".join(reqs), 10


def _drive(host: str, port: int, payload: bytes, n_responses: int,
           chunk: int | None = None) -> bytes:
    sock = socket.create_connection((host, port), timeout=30.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(30.0)
    if chunk is None:
        sock.sendall(payload)
    else:
        for i in range(0, len(payload), chunk):
            sock.sendall(payload[i:i + chunk])
    parser = proto.ResponseParser()
    raw = bytearray()
    got = 0
    while got < n_responses:
        data = sock.recv(1 << 16)
        if not data:
            break
        raw += data
        got += len(parser.feed(data))
    sock.close()
    assert got == n_responses
    return bytes(raw)


class TestDifferentialOracle:
    def test_threaded_and_evloop_answer_byte_identically(self):
        payload, n = _scripted_stream()
        streams = {}
        for backend in ("threaded", "evloop"):
            fe = ServeFrontend(StubBackend(), MetricsRegistry(),
                               wire_backend=backend).start()
            try:
                streams[backend] = _drive(fe.host, fe.port, payload, n)
                # ...and torn delivery must not change a byte either.
                torn = _drive(fe.host, fe.port, payload, n, chunk=7)
                assert torn == streams[backend]
            finally:
                fe.stop()
        assert streams["threaded"] == streams["evloop"]

    def test_byte_identity_holds_with_tracing_on_and_off(self, tmp_path):
        """ISSUE 17 acceptance: replies never echo trace headers, so
        turning tracing ON (frontend mints + journals spans, requests
        may carry inbound context) changes ZERO reply bytes on either
        backend — all four (backend x tracing) streams are identical.
        StubBackend has no ``wire_traced`` attr, so the front-ends must
        also never hand it a tctx kwarg (that inversion would 500)."""
        from sharetrade_tpu.fleet.wire import WireTracer
        from sharetrade_tpu.obs import collect
        from sharetrade_tpu.obs.trace import SpanJournal, SpanSink

        payload, n = _scripted_stream()
        traced_req = proto.render_request(
            "POST", wire.SUBMIT_PATH, "h:1",
            json.dumps({"session": "d-1", "obs": [1.0, 2.0, 3.0]}).encode(),
            headers={proto.TRACE_HEADER: "ab12cd34ef56ab78",
                     proto.PARENT_HEADER: "1f.2"})
        payload = traced_req + payload
        n += 1
        streams: dict = {}
        for mode in ("off", "on"):
            for backend in ("threaded", "evloop"):
                sink = tracer = None
                if mode == "on":
                    sink = SpanSink(SpanJournal(
                        str(tmp_path / f"spans-{backend}"), "fleet"))
                    tracer = WireTracer(sink, mint=True)
                fe = ServeFrontend(StubBackend(), MetricsRegistry(),
                                   wire_backend=backend,
                                   tracer=tracer).start()
                try:
                    streams[(mode, backend)] = _drive(
                        fe.host, fe.port, payload, n)
                finally:
                    fe.stop()
                    if sink is not None:
                        sink.close()
        assert len(set(streams.values())) == 1
        # ...and tracing-on actually journaled: every POST got a
        # frontend hop span on both backends (the evloop additionally
        # traces GETs); the inbound context threads through intact
        # while untraced requests were minted fresh unique ids.
        posts = sum(1 for r in proto.RequestParser().feed(payload)
                    if r.method == "POST"
                    and r.target == wire.SUBMIT_PATH)
        for backend in ("threaded", "evloop"):
            spans = collect.read_span_dir(
                str(tmp_path / f"spans-{backend}"))
            fronts = [s for s in spans if s["name"] == "frontend"]
            assert len(fronts) >= posts
            assert len({s["span"] for s in fronts}) == len(fronts)
            assert len({s["trace"] for s in fronts}) == len(fronts)
            inbound = [s for s in fronts
                       if s["trace"] == "ab12cd34ef56ab78"]
            assert len(inbound) == 1 and inbound[0]["parent"] == "1f.2"

    def test_tracing_off_emits_zero_headers_and_files(self, tmp_path):
        """obs.enabled=false default: no tracer → the backend sees no
        trace context even when the CLIENT sends headers, and nothing
        span-shaped is ever written."""
        seen: list = []

        class Recorder(StubBackend):
            wire_traced = True

            def serve_request(self, session, obs, deadline_ms,
                              tctx=None):
                seen.append(tctx)
                return super().serve_request(session, obs, deadline_ms)

        payload = proto.render_request(
            "POST", wire.SUBMIT_PATH, "h:1",
            json.dumps({"session": "d-1", "obs": [1.0]}).encode(),
            headers={proto.TRACE_HEADER: "ab12cd34ef56ab78"})
        for backend in ("threaded", "evloop"):
            fe = ServeFrontend(Recorder(), MetricsRegistry(),
                               wire_backend=backend).start()
            try:
                _drive(fe.host, fe.port, payload, 1)
            finally:
                fe.stop()
        assert seen == [None, None]
        assert list(tmp_path.iterdir()) == []

    def test_wire_backend_knob(self):
        reg = MetricsRegistry()
        fe = ServeFrontend(StubBackend(), reg, wire_backend="threaded")
        assert isinstance(fe, ThreadedServeFrontend)
        fe2 = ServeFrontend(StubBackend(), reg)     # default: evloop
        assert isinstance(fe2, EvloopFrontend)
        with pytest.raises(ValueError):
            ServeFrontend(StubBackend(), reg, wire_backend="carrier")


class TestEvloopSocketEdges:
    def test_oversized_head_gets_400_and_close(self):
        fe = ServeFrontend(StubBackend(), MetricsRegistry(),
                           wire_backend="evloop").start()
        try:
            sock = socket.create_connection((fe.host, fe.port),
                                            timeout=30.0)
            sock.settimeout(30.0)
            sock.sendall(b"GET /healthz HTTP/1.1\r\nX-Pad: "
                         + b"a" * (proto.MAX_HEAD_BYTES + 64))
            raw = bytearray()
            while True:
                data = sock.recv(1 << 16)
                if not data:        # server closed after the refusal
                    break
                raw += data
            sock.close()
            resp = proto.ResponseParser().feed(bytes(raw))[0]
            assert resp.status == 400
            assert resp.headers.get("connection") == "close"
        finally:
            fe.stop()

    def test_draining_refusal_matches_threaded_wording(self):
        payload = proto.render_request(
            "POST", wire.SUBMIT_PATH, "h:1",
            json.dumps({"session": "x", "obs": [1.0, 2.0]}).encode())
        bodies = {}
        for backend in ("threaded", "evloop"):
            fe = ServeFrontend(StubBackend(), MetricsRegistry(),
                               wire_backend=backend).start()
            try:
                sock = socket.create_connection((fe.host, fe.port),
                                                timeout=30.0)
                sock.settimeout(30.0)
                parser = proto.ResponseParser()

                def roundtrip() -> proto.Response:
                    sock.sendall(payload)
                    resps: list = []
                    while not resps:
                        data = sock.recv(1 << 16)
                        if not data:
                            break
                        resps.extend(parser.feed(data))
                    return resps[0]

                # One served request FIRST: the connection is then
                # accepted and keep-alive before the listener closes.
                assert roundtrip().status == wire.STATUS_OK
                assert fe.drain(timeout_s=5.0)
                resp = roundtrip()
                sock.close()
                bodies[backend] = (resp.status, resp.body)
            finally:
                fe.stop()
        assert bodies["threaded"] == bodies["evloop"]
        assert bodies["evloop"][0] == wire.STATUS_UNAVAILABLE


class TestEvloopLint:
    def test_lint_evloop_sansio_semantics(self, tmp_path):
        import lint_hot_loop
        pkg = tmp_path / "pkg"
        (pkg / "fleet").mkdir(parents=True)
        (pkg / "fleet" / "evloop.py").write_text(
            "import socket, threading, time\n"
            "def bad(s):\n"
            "    s.sendall(b'x')\n"
            "    time.sleep(1)\n"
            "def ok(s):\n"
            "    # evloop-block-ok: test probe\n"
            "    s.sendall(b'x')\n"
            "    t = threading.Thread()  # evloop-block-ok: runner\n")
        (pkg / "fleet" / "proto.py").write_text(
            "import socket\n"
            "from selectors import DefaultSelector\n")
        block, imports = lint_hot_loop.lint_evloop_sansio(root=pkg)
        assert [(r, ln) for r, ln, _ in block] \
            == [("fleet/evloop.py", 3), ("fleet/evloop.py", 4)]
        assert [(r, ln) for r, ln, _ in imports] \
            == [("fleet/proto.py", 1), ("fleet/proto.py", 2)]
        # The real tree is clean (the repo-level invariant).
        real_block, real_imports = lint_hot_loop.lint_evloop_sansio()
        assert real_block == [] and real_imports == []


class TestSpanEmissionLint:
    def test_lint_span_emission_semantics(self, tmp_path):
        import lint_hot_loop
        pkg = tmp_path / "pkg"
        (pkg / "fleet").mkdir(parents=True)
        (pkg / "fleet" / "evloop.py").write_text(
            "import json\n"
            "from collections import deque\n"
            "def emit(tctx, out):\n"
            "    line = json.dumps({'span': tctx})\n"   # per-event dumps
            "    out.append(line)\n"
            "def build():\n"
            "    span_buf = []\n"                       # unbounded list
            "    trace_ring = deque()\n"                # maxlen-less
            "    other_ring = deque()\n"                # not span-named
            "    # trace-buffer-ok: drained every flush\n"
            "    span_ok = []\n"                        # marker-exempt
            "    spans2 = deque([], 128)\n"             # bounded
            "    return span_buf, trace_ring, span_ok, spans2\n")
        (pkg / "fleet" / "router.py").write_text(
            "import json\n"
            "def fine(status):\n"
            "    return json.dumps({'gauges': status})\n")  # no span ctx
        hits = lint_hot_loop.lint_span_emission(root=pkg)
        assert [(rel, ln) for rel, ln, _ in hits] == [
            ("fleet/evloop.py", 4), ("fleet/evloop.py", 7),
            ("fleet/evloop.py", 8)]
        # The real tree is clean (the repo-level invariant).
        assert lint_hot_loop.lint_span_emission() == []


# ---- the native wire backend (ISSUE 19) ----------------------------


needs_native = pytest.mark.skipif(
    not proto.native_available(),
    reason="native wire extension not built (make -C native)")


def _drive_chunks(parser_factory, chunks, key):
    """Feed ``chunks`` into a fresh parser; returns (event keys before
    any error, (status, detail) of the ProtocolError or None). Events
    completed in the same feed() call as an error are discarded by
    BOTH implementations — the driver mirrors that by catching per
    call."""
    p = parser_factory()
    events, err = [], None
    for chunk in chunks:
        try:
            events.extend(p.feed(chunk))
        except proto.ProtocolError as exc:
            err = (exc.status, exc.detail)
            break
    return [key(ev) for ev in events], err


def _random_splits(rng, blob, n_cuts):
    cuts = sorted(rng.sample(range(1, len(blob)), min(n_cuts,
                                                      len(blob) - 1)))
    chunks, prev = [], 0
    for cut in cuts + [len(blob)]:
        chunks.append(blob[prev:cut])
        prev = cut
    return chunks


def _fuzz_request_corpus(rng) -> list[bytes]:
    """Valid, malformed, oversized, and trace-header request blobs —
    the satellite's four corpus classes, seeded."""
    blobs = []
    methods = ["GET", "POST", "PUT", "PATCH"]
    for _ in range(30):
        n = rng.randrange(1, 4)     # pipelined burst of n messages
        parts = []
        for _ in range(n):
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 64)))
            headers = {}
            if rng.random() < 0.5:
                headers[proto.TRACE_HEADER] = rng.choice(
                    ["ab12cd34ef56ab78", "DEADbeef", "1a2f.3c",
                     "not~a~trace", "z" * 70])
            if rng.random() < 0.3:
                headers[proto.PARENT_HEADER] = rng.choice(
                    ["1f.2", "zz", "a" * 65])
            if rng.random() < 0.3:
                headers["X-Deadline-Ms"] = str(rng.randrange(1, 5000))
            if rng.random() < 0.2:
                headers["Connection"] = rng.choice(
                    ["close", "keep-alive", "Keep-Alive", "CLOSE"])
            parts.append(proto.py_render_request(
                rng.choice(methods), f"/p/{rng.randrange(100)}",
                "h:1", body, headers=headers or None))
        blobs.append(b"".join(parts))
    # hand-built heads: HTTP/1.0 folding, duplicate headers
    # (last-wins), padded values, underscored and signed
    # Content-Lengths, a µ header name (lowers OUTSIDE latin-1)
    blobs += [
        b"GET / HTTP/1.0\r\nHost: h\r\n\r\n",
        b"GET / HTTP/1.0\r\nConnection: Keep-Alive\r\n\r\n",
        b"POST /d HTTP/1.1\r\nX-N: 1\r\nX-N: 2\r\n"
        b"Content-Length: 2\r\n\r\nhi",
        b"POST /d HTTP/1.1\r\nContent-Length:   2  \r\n\r\nhi",
        b"POST /d HTTP/1.1\r\nContent-Length: +1_0\r\n\r\n" + b"a" * 10,
        b"GET /u HTTP/1.1\r\n\xb5Name: micro\r\nX-\xc0: caps\r\n\r\n",
    ]
    # malformed: bad request lines, versions, header lines, lengths
    blobs += [
        b"GARBAGE\r\n\r\n",
        b"ONE TWO THREE FOUR\r\n\r\n",
        b"GET /x HTTP/2\r\n\r\n",
        b"GET /x HTTP/1.1\r\nNoColonHere\r\n\r\n",
        b"GET /x HTTP/1.1\r\n  : empty-name\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: xyz\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: -5\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: 1__0\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: 5_\r\n\r\n",
        b"GET /x HTTP/1.1\r\nContent-Length: \xa07\r\n\r\n",
        (f"POST /x HTTP/1.1\r\nContent-Length: "
         f"{proto.MAX_BODY_BYTES + 1}\r\n\r\n").encode(),
        b"GET /x HTTP/1.1\r\nX: " + b"a" * (proto.MAX_HEAD_BYTES + 8),
        b"\r\nGET / HTTP/1.1\r\n\r\n",
    ]
    # mutations: valid frames with one random head byte flipped
    for _ in range(40):
        raw = bytearray(proto.py_render_request(
            rng.choice(methods), "/m", "h:1", b"xyz",
            headers={"X-K": "v"}))
        pos = rng.randrange(0, min(len(raw), 40))
        raw[pos] = rng.randrange(256)
        blobs.append(bytes(raw))
    return blobs


def _fuzz_response_corpus(rng) -> list[bytes]:
    blobs = []
    for _ in range(20):
        n = rng.randrange(1, 4)
        parts = []
        for _ in range(n):
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 64)))
            parts.append(proto.py_render_response(
                rng.choice([200, 400, 404, 429, 500, 503, 504, 299]),
                body,
                keep_alive=rng.random() < 0.8,
                extra_headers=({"X-Probe": str(rng.randrange(10))}
                               if rng.random() < 0.4 else None)))
        blobs.append(b"".join(parts))
    blobs += [
        b"HTTP/1.1 200 OK\r\n\r\n",                 # no Content-Length
        b"NOPE 200 OK\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 2x0 OK\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 2_0 OK\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 -1 Odd\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 200 OK with spaced reason\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 200 OK\r\nContent-Length: bad\r\n\r\n",
    ]
    for _ in range(30):
        raw = bytearray(proto.py_render_response(200, b"body"))
        pos = rng.randrange(0, min(len(raw), 30))
        raw[pos] = rng.randrange(256)
        blobs.append(bytes(raw))
    return blobs


@needs_native
class TestNativeDifferentialFuzz:
    """Satellite 2: seeded random byte-split + pipelined burst corpora
    through BOTH parsers — event streams exactly equal, ProtocolError
    status AND detail exactly equal."""

    def _native(self):
        return proto._NATIVE      # skipif guarantees it loaded

    def test_request_parsers_agree_on_fuzzed_streams(self):
        import random
        rng = random.Random(0x57_17e)
        stw = self._native()
        for blob in _fuzz_request_corpus(rng):
            for _ in range(4):
                chunks = _random_splits(rng, blob, rng.randrange(0, 9))
                got_py = _drive_chunks(proto.PyRequestParser, chunks,
                                       _req_key)
                got_c = _drive_chunks(stw.RequestParser, chunks,
                                      _req_key)
                assert got_c == got_py, blob

    def test_response_parsers_agree_on_fuzzed_streams(self):
        import random
        rng = random.Random(0xbeef)
        stw = self._native()
        for blob in _fuzz_response_corpus(rng):
            for _ in range(4):
                chunks = _random_splits(rng, blob, rng.randrange(0, 9))
                got_py = _drive_chunks(proto.PyResponseParser, chunks,
                                       _resp_key)
                got_c = _drive_chunks(stw.ResponseParser, chunks,
                                      _resp_key)
                assert got_c == got_py, blob

    def test_renderers_agree_byte_for_byte(self):
        import random
        rng = random.Random(0x12e7de2)
        stw = self._native()
        for _ in range(60):
            method = rng.choice(["GET", "POST", "DELETE"])
            target = f"/t/{rng.randrange(1000)}"
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 50)))
            headers = ({f"X-H{rng.randrange(5)}": f"v{rng.randrange(9)}",
                        "X-Trace-Id": "ab12"}
                       if rng.random() < 0.7 else None)
            assert stw.render_request(method, target, "h:1", body,
                                      headers=headers) \
                == proto.py_render_request(method, target, "h:1", body,
                                           headers=headers)
        for _ in range(60):
            status = rng.choice([200, 400, 404, 429, 500, 503, 504,
                                 299, 101])
            body = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 50)))
            ct = rng.choice(["application/json",
                             "text/plain; version=0.0.4"])
            ka = rng.random() < 0.7
            extra = ({"X-Probe": "1"} if rng.random() < 0.4 else None)
            assert stw.render_response(status, body, ct,
                                       keep_alive=ka,
                                       extra_headers=extra) \
                == proto.py_render_response(status, body, ct,
                                            keep_alive=ka,
                                            extra_headers=extra)

    def test_empty_headers_dict_and_bytearray_feed(self):
        stw = self._native()
        assert stw.render_request("GET", "/", "h:1", b"", headers={}) \
            == proto.py_render_request("GET", "/", "h:1", b"",
                                       headers={})
        raw = bytearray(proto.py_render_request("GET", "/", "h:1"))
        assert len(stw.RequestParser().feed(raw)) == 1


class TestNativeBackendDispatch:
    """Satellite 1: the proto_backend seam — native default when
    built, loud Python fallback when not, live-backend gauge."""

    def _pin(self, monkeypatch):
        # set_backend rebinds module globals outside monkeypatch's
        # sight; no-op patches record the originals for teardown.
        for name in ("RequestParser", "ResponseParser",
                     "render_request", "render_response",
                     "proto_backend", "_NATIVE", "_NATIVE_ERROR",
                     "_FALLBACK_LOGGED"):
            monkeypatch.setattr(proto, name, getattr(proto, name))

    @needs_native
    def test_native_is_the_default_when_built(self):
        assert proto.proto_backend == "native"
        assert proto.RequestParser is proto._NATIVE.RequestParser
        assert proto.render_response is proto._NATIVE.render_response
        assert proto.native_load_error() == ""

    def test_set_backend_py_and_back(self, monkeypatch):
        self._pin(monkeypatch)
        assert proto.set_backend("py") == "py"
        assert proto.proto_backend == "py"
        assert proto.RequestParser is proto.PyRequestParser
        assert proto.render_request is proto.py_render_request

    def test_unknown_backend_refused(self):
        with pytest.raises(ValueError, match="proto_backend"):
            proto.set_backend("carrier")

    def test_missing_extension_degrades_loudly_once(self, monkeypatch):
        import logging
        self._pin(monkeypatch)
        monkeypatch.setattr(proto, "_NATIVE", None)
        monkeypatch.setattr(proto, "_NATIVE_ERROR", "forced by test")
        monkeypatch.setattr(proto, "_FALLBACK_LOGGED", False)
        # The repo's "sharetrade" root logger is propagate=False, so
        # caplog's root handler never sees it — attach directly.
        records: list[logging.LogRecord] = []

        class _Sink(logging.Handler):
            def emit(self, record):
                records.append(record)

        logger = logging.getLogger("sharetrade.fleet.proto")
        sink = _Sink(level=logging.WARNING)
        logger.addHandler(sink)
        try:
            assert proto.set_backend("native") == "py"
            assert proto.proto_backend == "py"
            assert proto.RequestParser is proto.PyRequestParser
            assert proto.native_available() is False
            assert proto.native_load_error() == "forced by test"
            assert len(records) == 1
            msg = records[0].getMessage()
            assert "falling back" in msg
            assert "forced by test" in msg
            # ONE loud line per process, not one per request/frontend.
            assert proto.set_backend("native") == "py"
            assert len(records) == 1
        finally:
            logger.removeHandler(sink)

    @pytest.mark.parametrize("backend", ["threaded", "evloop"])
    def test_live_backend_gauge_recorded(self, backend):
        reg = MetricsRegistry()
        fe = ServeFrontend(StubBackend(), reg,
                           wire_backend=backend).start()
        try:
            want = 1.0 if proto.proto_backend == "native" else 0.0
            assert reg.latest("fleet_proto_backend_native") == want
        finally:
            fe.stop()

    @needs_native
    def test_evloop_py_and_native_answer_byte_identically(self,
                                                          monkeypatch):
        self._pin(monkeypatch)
        payload, n = _scripted_stream()
        streams = {}
        for pb in ("py", "native"):
            proto.set_backend(pb)
            fe = ServeFrontend(StubBackend(), MetricsRegistry(),
                               wire_backend="evloop").start()
            try:
                streams[pb] = _drive(fe.host, fe.port, payload, n)
            finally:
                fe.stop()
        proto.set_backend("native")
        assert streams["py"] == streams["native"]


class TestEvloopInternalsMetrics:
    """Satellite 3: the selector thread's internals land in the shared
    registry (→ /metrics and fleet_status.json)."""

    def test_open_conns_gauge_tracks_the_connection(self):
        import time
        reg = MetricsRegistry()
        fe = ServeFrontend(StubBackend(), reg,
                           wire_backend="evloop").start()
        try:
            assert reg.latest("fleet_evloop_open_conns") == 0.0
            payload, n = _scripted_stream()
            _drive(fe.host, fe.port, payload, n)
            deadline = time.monotonic() + 5.0
            while (reg.latest("fleet_evloop_open_conns") != 0.0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            # it went up on accept and back to zero on close
            series = [v for _, v in
                      (reg.snapshot_series("fleet_evloop_open_conns")
                       if hasattr(reg, "snapshot_series") else [])]
            assert reg.latest("fleet_evloop_open_conns") == 0.0
        finally:
            fe.stop()

    def test_deadline_expiry_counter_fires_on_engine_timeout(self):
        class WedgedBackend(StubBackend):
            request_timeout_s = 0.05

            def submit_async(self, session, obs, deadline_ms, done):
                class Handle:
                    result = None
                    error = None
                return Handle()     # never signals: the wheel must fire

        reg = MetricsRegistry()
        fe = ServeFrontend(WedgedBackend(), reg,
                           wire_backend="evloop").start()
        try:
            body = json.dumps({"session": "w", "obs": [1.0]}).encode()
            raw = _drive(fe.host, fe.port,
                         proto.render_request("POST", wire.SUBMIT_PATH,
                                              "h:1", body), 1)
            resp = proto.ResponseParser().feed(raw)[0]
            assert resp.status == wire.STATUS_UNAVAILABLE
            assert reg.counters().get(
                "fleet_evloop_deadline_expiries_total") == 1.0
        finally:
            fe.stop()


class TestNativeWireLint:
    def test_lint_native_wire_semantics(self, tmp_path):
        import lint_hot_loop
        pkg = tmp_path / "pkg"
        (pkg / "fleet").mkdir(parents=True)
        (pkg / "fleet" / "proto.py").write_text(
            "import stwire\n")      # the ONE sanctioned seam: exempt
        (pkg / "fleet" / "evloop.py").write_text(
            "import stwire\n"
            "def load(path):\n"
            "    from importlib.machinery import ExtensionFileLoader\n"
            "    # native-wire-ok: test probe\n"
            "    import stwire as sw\n"
            "    return sw\n"
            "# stwire in a comment is prose, not a binding\n")
        wire_cc = tmp_path / "wire.cc"
        wire_cc.write_text(
            "// Py_BEGIN_ALLOW_THREADS in prose does not count\n"
            "static int core() {\n"
            "  Py_BEGIN_ALLOW_THREADS\n"
            "  Py_END_ALLOW_THREADS\n"
            "  return 0;\n"
            "}\n")
        binding, gil, imports = lint_hot_loop.lint_native_wire(
            root=pkg, wire_cc=wire_cc)
        assert [(r, ln) for r, ln, _ in binding] \
            == [("fleet/evloop.py", 1), ("fleet/evloop.py", 3)]
        assert gil == [] and imports == []
        # no GIL release at all
        wire_cc.write_text("static int core() { return 0; }\n")
        _, gil, _ = lint_hot_loop.lint_native_wire(root=pkg,
                                                   wire_cc=wire_cc)
        assert len(gil) == 1 and "Py_BEGIN_ALLOW_THREADS" in gil[0][2]
        # unbalanced pairing
        wire_cc.write_text("Py_BEGIN_ALLOW_THREADS\n"
                           "Py_BEGIN_ALLOW_THREADS\n"
                           "Py_END_ALLOW_THREADS\n")
        _, gil, _ = lint_hot_loop.lint_native_wire(root=pkg,
                                                   wire_cc=wire_cc)
        assert len(gil) == 1 and "unbalanced" in gil[0][2]
        # missing wire.cc is itself a failure
        _, gil, _ = lint_hot_loop.lint_native_wire(
            root=pkg, wire_cc=tmp_path / "absent.cc")
        assert len(gil) == 1 and "missing" in gil[0][2]
        # The real tree is clean (the repo-level invariant).
        rb, rg, ri = lint_hot_loop.lint_native_wire()
        assert rb == [] and rg == [] and ri == []
