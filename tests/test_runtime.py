"""Orchestrator lifecycle, mid-run queries, supervision & chaos tests.

Mirrors TrainerRouterActorSpec (SURVEY.md §4): the ML backend is stubbed at
the same seam (``step_override`` = the anonymous-subclass ``train()``
override), lifecycle queries are asserted in every phase, and failures are
injected mid-run to assert self-healing.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.checkpoint import CheckpointManager
from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.runtime import (
    ESCALATE, RESUME, STOP, Orchestrator, Phase, QueryReply, ReplyState,
    run_end_to_end,
)
from sharetrade_tpu.runtime.lifecycle import Lifecycle

WINDOW = 8
PRICES = np.linspace(10.0, 20.0, 72, dtype=np.float32)  # 64-step episode


def fast_cfg(tmp_path, algo="qlearn"):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 4
    cfg.runtime.chunk_steps = 16
    cfg.runtime.checkpoint_every_updates = 32
    cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
    cfg.runtime.backoff_initial_s = 0.01
    cfg.runtime.backoff_max_s = 0.05
    cfg.runtime.max_restarts = 3
    return cfg


class TestLifecycleFSM:
    def test_legal_path(self):
        lc = Lifecycle()
        for phase in [Phase.READY, Phase.TRAINING, Phase.TRAINED,
                      Phase.COMPLETED, Phase.READY]:
            lc.to(phase)
        assert lc.phase is Phase.READY

    def test_illegal_transition_rejected(self):
        lc = Lifecycle()
        with pytest.raises(RuntimeError, match="illegal"):
            lc.to(Phase.TRAINED)


class TestQueriesPerPhase:
    """The reply protocol per phase (TrainerRouterActorSpec:46-79)."""

    def test_before_data(self, tmp_path):
        orch = Orchestrator(fast_cfg(tmp_path))
        assert orch.is_everything_done().state is ReplyState.NO_TRAINING_DATA
        assert orch.get_avg().state is ReplyState.NO_TRAINING_DATA
        assert orch.get_std().state is ReplyState.NO_TRAINING_DATA

    def test_start_training_stashed_until_data(self, tmp_path):
        # StartTraining before data must not crash and must fire once data
        # arrives (stash/unstashAll, TrainerRouterActor.scala:75-81).
        orch = Orchestrator(fast_cfg(tmp_path))
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.AWAITING_DATA
        orch.send_training_data(PRICES)  # unstashes; runs inline to completion
        assert orch.is_everything_done().state is ReplyState.COMPLETED

    def test_after_data_before_training(self, tmp_path):
        orch = Orchestrator(fast_cfg(tmp_path))
        orch.send_training_data(PRICES)
        assert orch.is_everything_done().state is ReplyState.TRAINING_NOT_COMPLETED
        assert orch.get_avg().state is ReplyState.NOT_COMPUTED

    def test_completed_serves_results(self, tmp_path):
        orch = run_end_to_end(fast_cfg(tmp_path), PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        avg, std = orch.get_avg(), orch.get_std()
        assert avg.ok and std.ok
        assert avg.value > 0 and std.value >= 0
        assert repr(avg).startswith("Result(")


class TestSampledMetrics:
    def test_fault_detected_at_next_sample_within_bound(self, tmp_path):
        """The sampled-metrics contract (config.RuntimeConfig
        .metrics_every_chunks): a persistent fault (non-finite loss)
        surfacing on an UNSAMPLED chunk is not seen there — the fast path
        materializes nothing — but MUST be caught at the next sample,
        bounding detection latency at metrics_every_chunks chunks; the
        run then restores and completes."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.metrics_every_chunks = 3
        calls, restarts_seen = [], []

        def fake_step(ts):
            calls.append(1)
            restarts_seen.append(orch.restarts)
            n = len(calls)
            # Persistent poison from call 2 until the restore (detection
            # at the call-3 sample bounds it); finite again afterwards.
            loss = float("nan") if 2 <= n <= 3 else 0.1
            return ts, {"env_steps": float(min(16 * n, 64)),
                        "updates": float(n), "loss": loss,
                        "portfolio_mean": 10.0, "portfolio_std": 0.0,
                        "trained_workers": 4.0, "unhealthy_workers": 0.0}

        orch = Orchestrator(cfg, step_override=fake_step)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 1, "non-finite loss was never detected"
        # Calls 1-3 all ran BEFORE the restart: the poisoned call-2 chunk
        # was dispatched on the fast path (undetected there — with
        # metrics_every_chunks=1 the restart would land before call 3),
        # and the call-3 sample caught it.
        assert restarts_seen[2] == 0
        assert restarts_seen[-1] == 1

    def test_completion_exact_with_sampling_coarser_than_run(self, tmp_path):
        """The sampled-metrics fast path (metrics_every_chunks > run
        length): chunks dispatch with NO host materialization between
        samples, yet the host-side env_steps upper bound must sample the
        completion chunk — the run completes at the exact episode
        threshold, runs exactly the right number of chunks, and serves
        queries afterwards."""
        import json
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path)
        cfg.runtime.metrics_every_chunks = 1000   # coarser than the run
        cfg.runtime.episodes = 2
        events_path = str(tmp_path / "events.jsonl")
        orch = Orchestrator(cfg, event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0
        events = [json.loads(l) for l in open(events_path)]
        done = [e for e in events if e["kind"] == "training_completed"][0]
        horizon = orch.env.num_steps
        assert done["env_steps"] == 2 * horizon       # exact, no overshoot
        chunks_per_episode = -(-horizon // cfg.runtime.chunk_steps)
        assert done["chunks_timed"] == 2 * chunks_per_episode
        avg = orch.get_avg()
        assert avg.ok and np.isfinite(avg.value)


@pytest.mark.slow
class TestMidRunQueries:
    def test_query_during_training_not_blocking(self, tmp_path):
        """GetAvg mid-run answers from the latest snapshot without stopping
        the device loop (TrainerRouterActorSpec:81-95)."""
        cfg = fast_cfg(tmp_path)
        gate = threading.Event()
        seen_mid_run: list[QueryReply] = []

        def slow_step(ts):
            gate.wait(5)
            import sharetrade_tpu.agents as agents_mod
            return real_step(ts)

        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        # Build the real step AFTER data arrival, wrap it with a gate.
        real_step = jax.jit(orch.agent.step)
        orch._step_fn = slow_step

        orch.start_training(background=True)
        time.sleep(0.05)
        seen_mid_run.append(orch.is_everything_done())
        seen_mid_run.append(orch.get_avg())
        gate.set()
        assert orch.wait(30)
        assert seen_mid_run[0].state is ReplyState.TRAINING_NOT_COMPLETED
        # First chunk hadn't finished: NotComputed is the honest mid-run reply.
        assert seen_mid_run[1].state is ReplyState.NOT_COMPUTED
        assert orch.get_avg().ok


@pytest.mark.slow
class TestSupervision:
    def test_fault_injection_heals_and_completes(self, tmp_path):
        """Kill the trainer mid-run; it must restart with backoff, restore
        from checkpoint, and still complete (TrainerRouterActorSpec:97-115)."""
        cfg = fast_cfg(tmp_path)
        fail_at = {1}

        def chaos(chunk_idx, metrics):
            if chunk_idx in fail_at:
                fail_at.discard(chunk_idx)
                raise RuntimeError("injected PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 1
        assert orch.get_avg().ok

    def test_restart_budget_exhaustion_fails(self, tmp_path):
        cfg = fast_cfg(tmp_path)

        def always_fail(chunk_idx, metrics):
            raise RuntimeError("persistent failure")

        orch = Orchestrator(cfg, fault_hook=always_fail)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        assert orch.restarts == cfg.runtime.max_restarts + 1
        assert orch.is_everything_done().state is ReplyState.NOT_COMPUTED

    def test_error_policy_stop(self, tmp_path):
        cfg = fast_cfg(tmp_path)

        def bad(chunk_idx, metrics):
            from sharetrade_tpu.config import ConfigError
            raise ConfigError("bad input")  # policy: stop (IllegalArgument analogue)

        orch = Orchestrator(cfg, fault_hook=bad)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        assert orch.restarts == 0  # stopped, not restarted

    def test_plain_value_error_restarts_not_stops(self, tmp_path):
        """A transient in-loop ValueError (JAX retrace/shape wobble) takes
        the RESTART path; only ConfigError maps to STOP — a run must not
        permanently fail on an error class that healing can fix."""
        cfg = fast_cfg(tmp_path)
        hits = []

        def flaky(chunk_idx, metrics):
            if not hits:
                hits.append(1)
                raise ValueError("transient retrace wobble")

        orch = Orchestrator(cfg, fault_hook=flaky)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 1   # restarted once, then completed

    def test_error_policy_resume(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        hits = []

        def flaky(chunk_idx, metrics):
            if chunk_idx == 0 and not hits:
                hits.append(1)
                raise ArithmeticError("transient")  # policy: resume

        orch = Orchestrator(cfg, fault_hook=flaky)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0  # resumed in place


@pytest.mark.slow
class TestPerAgentRecovery:
    """The reference heals ONE dead child while the other nine keep training
    (TrainerRouterActor.scala:141-146). Here: learners quarantine non-finite
    rows on-device, the orchestrator respawns just those rows — survivors
    keep every step of progress (no checkpoint rollback)."""

    def test_one_poisoned_agent_heals_without_rollback(self, tmp_path):
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path)
        events_path = str(tmp_path / "events.jsonl")
        poisoned = []

        def chaos(chunk_idx, metrics):
            if chunk_idx == 1 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
                budget[2] = np.nan          # one agent's wallet corrupted
                orch._ts = ts.replace(env_state=ts.env_state.replace(
                    budget=jnp.asarray(budget)))

        orch = Orchestrator(cfg, fault_hook=chaos,
                            event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        # Healed in place: zero full restarts, one row respawn, agent 2.
        assert orch.restarts == 0
        assert orch.agent_heals == 1
        import json
        events = [json.loads(l) for l in open(events_path)]
        kinds = [e["kind"] for e in events]
        assert "agents_healed" in kinds
        assert next(e for e in events
                    if e["kind"] == "agents_healed")["agents"] == [2]
        # Survivors kept their progress: nothing was restored/reinit'd, and
        # the respawned agent retrained its episode (updates ran PAST the
        # horizon instead of rolling back to a checkpoint).
        assert "restored" not in kinds and "reinitialized" not in kinds
        horizon = len(PRICES) - WINDOW
        assert int(orch.train_state.updates) > horizon
        snap = orch.snapshot()
        assert snap["unhealthy_workers"] == 0
        assert snap["trained_workers"] == cfg.parallel.num_workers
        assert orch.get_avg().ok and np.isfinite(orch.get_avg().value)

    def test_heal_budget_escalates_to_restart_path(self, tmp_path):
        """Past runtime.max_agent_heals a per-row fault is treated as
        systemic: it must route through the supervised restart path (and
        its max_restarts budget) instead of heal->re-poison->heal forever.
        Budget 0 = healing disabled entirely."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.max_agent_heals = 0
        poisoned = []

        def chaos(chunk_idx, metrics):
            # Poison AFTER the chunk-1 checkpoint landed so the escalated
            # restore has a clean state to come back to.
            if chunk_idx == 2 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
                budget[2] = np.nan
                orch._ts = ts.replace(env_state=ts.env_state.replace(
                    budget=jnp.asarray(budget)))

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.agent_heals == 0          # never healed in place...
        assert orch.restarts >= 1             # ...restored from checkpoint

    def test_resume_completed_run_recompletes_immediately(self, tmp_path):
        """The FINAL checkpoint of a completed run stores the episode
        counter already incremented past the last episode; resuming it must
        clamp the index (send_training_data resume path) — unclamped it
        sets an unreachable (episode+1)*horizon completion threshold and
        the chunk loop spins forever with every agent frozen."""
        cfg = fast_cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        avg = orch.get_avg().value
        resumed = Orchestrator(cfg)
        resumed.send_training_data(PRICES, resume=True)
        assert resumed.episode == cfg.runtime.episodes - 1  # clamped
        resumed.start_training(background=True)
        assert resumed.wait(120), "resumed run failed to re-complete"
        assert resumed.is_everything_done().state is ReplyState.COMPLETED
        assert resumed.get_avg().value == pytest.approx(avg, rel=1e-6)

    def test_resume_completed_run_with_more_episodes_rearms(self, tmp_path):
        """Resuming a completed run with runtime.episodes RAISED must
        re-arm the next episode (fresh cursors, learned params kept) and
        actually train it — without the re-arm, every cursor sits frozen
        at the horizon and the chunk loop spins forever toward a
        completion threshold nothing advances (pre-existing bug found in
        round 5: reproduced on the round-4 tree)."""
        cfg = fast_cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        horizon = len(PRICES) - WINDOW
        assert int(orch.train_state.env_steps) == horizon
        updates_before = int(orch.train_state.updates)

        more = fast_cfg(tmp_path)
        more.runtime.episodes = 2
        resumed = Orchestrator(more)
        resumed.send_training_data(PRICES, resume=True)
        resumed.start_training(background=True)
        assert resumed.wait(180), "resumed run never completed episode 2"
        assert resumed.is_everything_done().state is ReplyState.COMPLETED
        # Episode 2 genuinely trained: cumulative steps doubled, learned
        # updates carried over and extended.
        assert int(resumed.train_state.env_steps) == 2 * horizon
        assert int(resumed.train_state.updates) > updates_before

    def test_recovery_disabled_completes_without_stranded_agent(self, tmp_path):
        """With partial_recovery=False a quarantined row can never respawn;
        the run must still COMPLETE (the stranded row counts as excluded)
        rather than spin forever waiting for a cursor that will never reach
        the horizon."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.partial_recovery = False
        poisoned = []

        def chaos(chunk_idx, metrics):
            if chunk_idx == 1 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
                budget[1] = np.nan
                orch._ts = ts.replace(env_state=ts.env_state.replace(
                    budget=jnp.asarray(budget)))

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.agent_heals == 0 and orch.restarts == 0
        snap = orch.snapshot()
        assert snap["unhealthy_workers"] == 1       # still quarantined...
        assert snap["trained_workers"] == cfg.parallel.num_workers - 1
        assert np.isfinite(orch.get_avg().value)    # ...and excluded

    def test_episode_model_row_heals_at_survivors_cursor(self, tmp_path):
        """Round-3 exemption removed: a poisoned row of a trunk-rollout
        (episode transformer) run heals IN PLACE — fresh wallet rejoining
        at the survivors' cursor with the representative's carry — instead
        of rolling the whole run back to the last checkpoint. Survivors'
        cursors never rewind and no restore happens."""
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path, algo="ppo")
        cfg.model.kind = "transformer"
        cfg.model.seq_mode = "episode"
        cfg.model.num_layers = 2
        cfg.model.num_heads = 2
        cfg.model.head_dim = 16
        cfg.learner.unroll_len = cfg.runtime.chunk_steps
        events_path = str(tmp_path / "events.jsonl")
        poisoned = []
        cursor_before_heal = []

        def chaos(chunk_idx, metrics):
            if chunk_idx == 1 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                cursor_before_heal.append(int(np.asarray(ts.env_state.t[0])))
                budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
                budget[2] = np.nan
                orch._ts = ts.replace(env_state=ts.env_state.replace(
                    budget=jnp.asarray(budget)))
            elif chunk_idx >= 3 and len(cursor_before_heal) == 1:
                # First chunk AFTER the heal (the detection chunk's hook
                # runs before _heal_agents): the healed row must sit at the
                # survivors' (advanced) cursor — lockstep preserved,
                # nobody rolled back.
                ts = orch._ts
                t = np.asarray(jax.device_get(ts.env_state.t))
                horizon = len(PRICES) - WINDOW
                assert (t == min(t[0], horizon)).all(), \
                    f"lockstep broken after heal: cursors {t}"
                assert t[0] > cursor_before_heal[0], "survivors rolled back"
                cursor_before_heal.append(int(t[2]))

        orch = Orchestrator(cfg, fault_hook=chaos,
                            event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.agent_heals == 1 and orch.restarts == 0
        import json
        events = [json.loads(l) for l in open(events_path)]
        kinds = [e["kind"] for e in events]
        assert "agents_healed" in kinds
        assert next(e for e in events
                    if e["kind"] == "agents_healed")["agents"] == [2]
        assert "restored" not in kinds and "reinitialized" not in kinds
        snap = orch.snapshot()
        assert snap["unhealthy_workers"] == 0
        assert snap["trained_workers"] == cfg.parallel.num_workers
        assert np.isfinite(orch.get_avg().value)

    def test_all_rows_poisoned_without_recovery_routes_to_restart(self, tmp_path):
        """With partial_recovery=False and EVERY row non-finite the run can
        make no progress (the unconditional quarantine freezes every
        cursor); it must raise into the supervision path — restore from
        checkpoint and complete — instead of spinning chunks forever."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.partial_recovery = False
        poisoned = []

        def chaos(chunk_idx, metrics):
            # Poison AFTER the chunk-1 checkpoint landed so the restore
            # has a clean state to come back to.
            if chunk_idx == 2 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
                budget[:] = np.nan
                orch._ts = ts.replace(env_state=ts.env_state.replace(
                    budget=jnp.asarray(budget)))

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=True)
        assert orch.wait(180), \
            "all-stranded run neither completed nor failed (infinite spin)"
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts >= 1 and orch.agent_heals == 0

    def test_poisoned_shared_params_fall_back_to_restore(self, tmp_path):
        """When poison breaches into the SHARED state (params), a row
        respawn can't help: the non-finite-loss detector must route through
        the full checkpoint-restore supervision path."""
        cfg = fast_cfg(tmp_path)
        poisoned = []

        def chaos(chunk_idx, metrics):
            # Poison AFTER the first checkpoint landed (chunk 1, updates 32)
            # so the restore has a clean checkpoint to come back to.
            if chunk_idx == 2 and not poisoned:
                poisoned.append(1)
                ts = orch._ts
                params = jax.device_get(ts.params)
                params = jax.tree.map(
                    lambda a: np.full_like(np.asarray(a), np.nan), params)
                orch._ts = ts.replace(params=params)

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts >= 1          # full restore, not a row heal
        assert orch.agent_heals == 0
        assert np.isfinite(orch.get_avg().value)


class TestFailedPhaseProtocol:
    def test_failed_run_serves_no_results(self, tmp_path):
        """A dead run must not serve its stale pre-failure snapshot as a
        RESULT: after the restart budget is exhausted, GetAvg/GetStd answer
        NotComputed like IsEverythingDone does (the reference protocol has no
        'result from a dead run' arm, TrainerRouterActor.scala:15-34)."""
        cfg = fast_cfg(tmp_path)
        calls = []

        def fake_step(ts):
            calls.append(1)
            return ts, {"env_steps": float(min(len(calls), 2)),
                        "updates": 0.0, "portfolio_mean": 10.0,
                        "portfolio_std": 0.0}

        def chaos(chunk_idx, metrics):
            from sharetrade_tpu.config import ConfigError
            if chunk_idx >= 2:   # let two chunks land a snapshot first
                raise ConfigError("poisoned")  # policy: stop -> FAILED

        orch = Orchestrator(cfg, step_override=fake_step, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.lifecycle.phase is Phase.FAILED
        assert orch.snapshot()["portfolio_mean"] == 10.0  # snapshot exists...
        assert orch.get_avg().state is ReplyState.NOT_COMPUTED  # ...not served
        assert orch.get_std().state is ReplyState.NOT_COMPUTED
        assert orch.is_everything_done().state is ReplyState.NOT_COMPUTED


class TestTrainedOnlyQueries:
    """The reference's GetAvg averages only workers that FINISHED training
    (it asks the trained list, TrainerRouterActor.scala:84-95,137-139);
    trained_only reproduces that observable next to the default progressive
    stats."""

    def test_not_computed_until_a_worker_finishes(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        horizon = len(PRICES) - WINDOW
        chunks = []

        def fake_step(ts):
            chunks.append(1)
            # Chunk 1: nobody finished; chunk 2: 2 of 4 agents finished.
            n = len(chunks)
            return ts, {"env_steps": float(min(n * 16, horizon)),
                        "updates": 0.0,
                        "portfolio_mean": 11.0, "portfolio_std": 1.0,
                        "portfolio_mean_trained": 10.0,
                        "portfolio_std_trained": 0.0,
                        "trained_workers": 0.0 if n < 2 else 2.0}

        orch = Orchestrator(cfg, step_override=fake_step)
        orch.send_training_data(PRICES)
        orch.lifecycle.to(Phase.TRAINING)
        ts, m = fake_step(None)
        orch._snapshot = m
        assert orch.get_avg(trained_only=True).state is ReplyState.NOT_COMPUTED
        assert orch.get_avg().ok  # progressive stats still answer
        ts, m = fake_step(None)
        orch._snapshot = m
        assert orch.get_avg(trained_only=True) == QueryReply(
            ReplyState.RESULT, 10.0)
        assert orch.get_std(trained_only=True) == QueryReply(
            ReplyState.RESULT, 0.0)
        assert orch.get_avg() == QueryReply(ReplyState.RESULT, 11.0)

    def test_real_run_emits_trained_stats(self, tmp_path):
        """At completion every agent's cursor sits at the horizon, so the
        trained-only view matches the all-agents view."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.query_trained_only = True   # config-level switch
        orch = run_end_to_end(cfg, PRICES)
        snap = orch.snapshot()
        assert snap["trained_workers"] == cfg.parallel.num_workers
        avg = orch.get_avg()    # trained-only via config
        assert avg.ok
        assert avg.value == pytest.approx(snap["portfolio_mean"], rel=1e-6)
        assert orch.get_avg(trained_only=False).value == pytest.approx(
            avg.value, rel=1e-6)


class TestStubbedStepSeam:
    def test_lifecycle_without_ml(self, tmp_path):
        """Full lifecycle with fake compute — the TestKit seam where
        train() is overridden to sleep-and-return-10.0
        (TrainerRouterActorSpec:144-153)."""
        cfg = fast_cfg(tmp_path)
        horizon = len(PRICES) - WINDOW
        calls = []

        def fake_step(ts):
            calls.append(1)
            steps = min(len(calls) * cfg.runtime.chunk_steps, horizon)
            return ts, {"env_steps": float(steps), "updates": float(steps),
                        "portfolio_mean": 10.0, "portfolio_std": 0.0}

        orch = Orchestrator(cfg, step_override=fake_step)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        # avg 10.0, std 0.0 — the spec's expected aggregation (:65-79).
        assert orch.get_avg() == QueryReply(ReplyState.RESULT, 10.0)
        assert orch.get_std() == QueryReply(ReplyState.RESULT, 0.0)


@pytest.mark.slow
class TestMultiEpisode:
    def test_episodes_replay_history(self, tmp_path):
        """episodes=3 replays the price history three times with parameters
        carried across episodes (the Initialise→Train cycle automated)."""
        cfg = fast_cfg(tmp_path)
        cfg.runtime.episodes = 3
        orch = run_end_to_end(cfg, PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.episode == 3
        horizon = len(PRICES) - WINDOW
        # qlearn updates once per env step: 3 episodes of updates accumulated.
        assert int(orch.train_state.updates) == 3 * horizon


@pytest.mark.slow
class TestEvaluateAndResume:
    def test_greedy_evaluation(self, tmp_path):
        orch = run_end_to_end(fast_cfg(tmp_path), PRICES)
        result = orch.evaluate()
        assert np.isfinite(result["eval_portfolio"])
        assert result["eval_portfolio"] > 0
        # Deterministic: same params, same greedy rollout.
        assert orch.evaluate() == result

    def test_resume_from_checkpoint(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        updates_before = int(orch.train_state.updates)
        params_before = jax.device_get(orch.train_state.params)
        # A new orchestrator resumes the final checkpoint.
        orch2 = Orchestrator(cfg)
        orch2.send_training_data(PRICES, resume=True)
        assert int(orch2.train_state.updates) == updates_before
        for a, b in zip(jax.tree.leaves(params_before),
                        jax.tree.leaves(jax.device_get(orch2.train_state.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
class TestJournalBackedReplay:
    """learner.journal_replay: every chunk's transitions are appended to a
    durable event log and the DQN buffer is rebuilt from it on resume — the
    reference's event-sourced persistence (SharePriceGetter.scala:37,55-62)
    generalized to experience data (SURVEY.md §7.4)."""

    def _cfg(self, tmp_path):
        cfg = fast_cfg(tmp_path, algo="dqn")
        cfg.learner.journal_replay = True
        cfg.learner.replay_capacity = 1024
        cfg.learner.replay_batch = 8
        cfg.data.journal_dir = str(tmp_path / "journal")
        return cfg

    def test_resume_rebuilds_buffer_from_journal(self, tmp_path):
        cfg = self._cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        horizon = len(PRICES) - WINDOW
        size_after = int(orch.train_state.extras.replay.size)
        assert size_after == horizon * cfg.parallel.num_workers
        orch.stop()
        # A fresh orchestrator resuming from checkpoint warm-starts the
        # buffer from the journal (train → crash → resume with warm buffer).
        orch2 = Orchestrator(cfg)
        orch2.send_training_data(PRICES, resume=True)
        assert int(orch2.train_state.extras.replay.size) == size_after
        orch2.stop()

    def test_fresh_retrain_truncates_and_rejournals(self, tmp_path):
        """A fresh (non-resume) send_training_data truncates the journal AND
        resets the journaling high-water mark — the new run's env_steps
        restart at zero and must journal from its first chunk."""
        cfg = self._cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        horizon = len(PRICES) - WINDOW
        orch.send_training_data(PRICES)     # fresh run on the same orch
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert _journaled_rows(cfg) == horizon * cfg.parallel.num_workers
        orch.stop()

    def test_heal_after_fault_with_journaled_buffer(self, tmp_path):
        cfg = self._cfg(tmp_path)
        fail_at = {1}

        def chaos(chunk_idx, metrics):
            if chunk_idx in fail_at:
                fail_at.discard(chunk_idx)
                raise RuntimeError("injected PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 1
        # Exactly-once: the heal (restore -> warm-start -> re-run) must not
        # double-count the chunks between checkpoint and crash, in the live
        # buffer or in the journal.
        horizon = len(PRICES) - WINDOW
        assert (int(orch.train_state.extras.replay.size)
                == horizon * cfg.parallel.num_workers)
        assert _journaled_rows(cfg) == horizon * cfg.parallel.num_workers
        orch.stop()


def _journaled_rows(cfg) -> int:
    """Total transition rows in the journal: packed binary records (the
    runtime's format, data/transitions.py) plus any legacy JSON events."""
    from sharetrade_tpu.data.journal import Journal
    from sharetrade_tpu.data.transitions import read_tail_transitions
    path = f"{cfg.data.journal_dir}/transitions.journal"
    tail = read_tail_transitions(path, 0)      # 0 = unbounded
    rows = 0 if tail is None else tail[0].shape[0]
    rows += sum(len(e["action"]) for e in Journal(path).replay()
                if e.get("type") == "transitions")
    return rows


@pytest.mark.slow
class TestPeriodicEval:
    def test_periodic_eval_retains_best_during_training(self, tmp_path):
        """runtime.eval_every_updates fires greedy evals between chunks
        unattended, so the event-log learning curve and the keep_best_eval
        retention work during long runs where nobody calls evaluate()."""
        import json
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path)
        cfg.runtime.eval_every_updates = 32
        # Per-chunk metrics: this test pins the FINE cadence semantics; the
        # sampled default quantizes cadences to metrics_every_chunks
        # (TestSampledMetrics covers that mode).
        cfg.runtime.metrics_every_chunks = 1
        events_path = str(tmp_path / "events.jsonl")
        orch = Orchestrator(cfg, event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        events = [json.loads(l) for l in open(events_path)]
        evals = [e for e in events if e["kind"] == "evaluation"]
        assert len(evals) >= 2, "cadence evals did not fire"
        assert "best_eval_retained" in {e["kind"] for e in events}
        best = orch.evaluate_best()   # retained with no explicit evaluate()
        assert np.isfinite(best["eval_portfolio"])
        assert best["eval_portfolio"] == pytest.approx(
            max(e["eval_portfolio"] for e in evals))


@pytest.mark.slow
class TestInitialise:
    def test_retrain_keeps_params(self, tmp_path):
        orch = run_end_to_end(fast_cfg(tmp_path), PRICES)
        params_after = jax.device_get(orch.train_state.params)
        orch.initialise()
        assert orch.lifecycle.phase is Phase.READY
        assert int(orch.train_state.env_state.t[0]) == 0  # cursor reset
        for a, b in zip(jax.tree.leaves(params_after),
                        jax.tree.leaves(jax.device_get(orch.train_state.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED


class TestCrashSafety:
    """The durability tentpole at the orchestrator level: a corrupt newest
    checkpoint never strands --resume, and SIGTERM-style preemption writes a
    resumable emergency checkpoint within the grace budget."""

    def _bitflip(self, path):
        from test_checkpoint import _bitflip   # the one corruption helper
        _bitflip(path)

    def test_resume_walks_back_past_corrupt_newest(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        orch = run_end_to_end(cfg, PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()
        ckpt_dir = cfg.runtime.checkpoint_dir
        names = sorted(n for n in os.listdir(ckpt_dir)
                       if n.startswith("ckpt_"))
        assert len(names) >= 2, "need an older step to walk back to"
        self._bitflip(os.path.join(ckpt_dir, names[-1], "state.msgpack"))

        orch2 = Orchestrator(cfg)
        orch2.send_training_data(PRICES, resume=True)   # must not raise
        # The damaged newest was quarantined (not deleted) and the restore
        # fell back — surfaced through the counters the obs exporter ships.
        assert any(n.startswith("corrupt_")
                   for n in os.listdir(ckpt_dir))
        counters = orch2.metrics.counters()
        assert counters["ckpt_restore_fallbacks_total"] == 1
        assert counters["ckpt_quarantined_total"] == 1
        # ... and training still completes from the walk-back point.
        orch2.start_training(background=False)
        assert orch2.is_everything_done().state is ReplyState.COMPLETED
        orch2.stop()

    def test_preempt_writes_emergency_checkpoint_and_resume_prefers_it(
            self, tmp_path):
        cfg = fast_cfg(tmp_path)
        cfg.runtime.episodes = 200          # long run: cannot complete
        cfg.runtime.preempt_grace_s = 20.0
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        orch.start_training(background=True)
        deadline = time.monotonic() + 30
        while not orch.snapshot() and time.monotonic() < deadline:
            time.sleep(0.02)                # let some chunks commit
        orch.request_preempt()
        assert orch.wait(timeout=30), "preemption drain did not finish"
        assert orch.preempted
        meta = orch.checkpoints.tagged_metadata("preempt")
        assert meta is not None
        assert {"updates", "env_steps", "episode"} <= set(meta)
        orch.stop()

        # --resume prefers the emergency checkpoint: the restored state's
        # counters equal the preempt metadata, not an older cadence save.
        orch2 = Orchestrator(cfg)
        orch2.send_training_data(PRICES, resume=True)
        assert int(jax.device_get(orch2.train_state.env_steps)) \
            == int(meta["env_steps"])
        assert int(jax.device_get(orch2.train_state.updates)) \
            == int(meta["updates"])
        orch2.stop()

    def test_preempt_before_start_drains_immediately(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        cfg.runtime.episodes = 200
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        orch.request_preempt()              # notice during bring-up
        orch.start_training(background=False)
        assert orch.preempted
        assert orch.checkpoints.tagged_metadata("preempt") is not None
        orch.stop()

    def test_stop_waits_for_pending_async_saves(self, tmp_path):
        """A stop right after a cadence save must not drop the queued
        save_async write (the writer is a daemon thread)."""
        cfg = fast_cfg(tmp_path)
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        ts = orch.train_state
        orch.checkpoints.save_async(777, ts, metadata={"episode": 0})
        orch.stop()                         # must drain, not drop
        assert 777 in orch.checkpoints.steps()
        assert orch.checkpoints.verify(777)["step"] == 777

    def test_resume_reprefers_preempt_when_newest_step_corrupt(
            self, tmp_path):
        """A corrupt newest STEP checkpoint numbered above the emergency
        checkpoint must not suppress the tag_preempt preference: after the
        walk-back quarantines it, the intact emergency checkpoint is the
        freshest state and wins."""
        cfg = fast_cfg(tmp_path)
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        ts = orch.train_state
        mgr = orch.checkpoints
        mgr.save(32, ts, metadata={"episode": 0, "env_steps": 32})
        mgr.save_tagged("preempt", ts, metadata={
            "updates": 47, "env_steps": 47, "episode": 0,
            "preempted": True})
        mgr.save(55, ts, metadata={"episode": 0, "env_steps": 55})
        self._bitflip(str(tmp_path / "ckpts" / "ckpt_0000000055"
                          / "state.msgpack"))
        template = orch.agent.init(jax.random.PRNGKey(cfg.seed))
        _, step, meta = orch._restore_for_resume(template)
        assert step == 47 and meta["preempted"] is True
        assert any(n.startswith("corrupt_0000000055")
                   for n in os.listdir(tmp_path / "ckpts"))
        orch.stop()

    def test_resume_serves_older_preempt_when_all_steps_corrupt(
            self, tmp_path):
        """Every step checkpoint corrupt but an intact OLDER tag_preempt
        exists: resume must serve the emergency checkpoint instead of
        stranding — 'resume always succeeds from some intact checkpoint'."""
        cfg = fast_cfg(tmp_path)
        orch = Orchestrator(cfg)
        orch.send_training_data(PRICES)
        ts = orch.train_state
        mgr = orch.checkpoints
        mgr.save_tagged("preempt", ts, metadata={
            "updates": 10, "env_steps": 10, "episode": 0,
            "preempted": True})
        for step in (20, 30):
            mgr.save(step, ts, metadata={"episode": 0, "env_steps": step})
            self._bitflip(str(tmp_path / "ckpts" / f"ckpt_{step:010d}"
                              / "state.msgpack"))
        template = orch.agent.init(jax.random.PRNGKey(cfg.seed))
        _, step, meta = orch._restore_for_resume(template)
        assert step == 10 and meta["preempted"] is True
        # Both damaged steps were quarantined along the way.
        corrupt = [n for n in os.listdir(tmp_path / "ckpts")
                   if n.startswith("corrupt_")]
        assert len(corrupt) == 2
        orch.stop()

    def test_baseline_checkpoint_written_despite_torn_store(self, tmp_path):
        """steps() lists damaged dirs (so walk-back can quarantine them),
        but the baseline-save guard must key on INTACTNESS: a store holding
        only a torn ckpt_ dir still gets its chunk-0 baseline, keeping the
        'lose at most checkpoint_every_updates' bound true."""
        cfg = fast_cfg(tmp_path)
        junk = tmp_path / "ckpts" / "ckpt_0000000099"
        junk.mkdir(parents=True)
        (junk / "state.msgpack").write_bytes(b"torn")
        orch = run_end_to_end(cfg, PRICES)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        orch.stop()
        assert 0 in orch.checkpoints.steps(), \
            "baseline save was skipped because a torn dir looked like a " \
            "checkpoint"
