"""Crash-consistent disk spill tier + warm-carry migration (ISSUE 20).

The load-bearing contracts:

- **Record discipline**: a SpillArena record is sealed atomically
  (tmp → fsync → rename), carries a CRC over meta+payload and the
  session's step stamp, and is consumed on take — a torn, corrupt,
  wrong-model, or digest-colliding record NEVER hands back bytes, it
  demotes to cold; a stale stamp (or, with no fleet clock, a foreign
  incarnation) likewise. Injected corruption can change latency, never
  bytes.
- **Adoption bitwise oracle**: engine A drains (stop → page_out_all →
  sealed arena), engine B adopts every session via the router-carried
  ``session_clock`` — B's responses are bit-identical to a single
  uninterrupted engine fed the same requests.
- **Drain ordering**: ``page_out_all()`` REFUSES while the worker
  threads are alive (drain → stop() → page_out_all() → exit 75) and,
  post-stop, seals every surviving carry — hot slots, RAM-warm, and
  in-flight inbox rows.
- **Router half of the contract**: the session clock ticks only on a
  200, survives engine death (affinity detached, clock kept), and the
  engine-side spill counters fold into same-named ``fleet_`` counters
  that the kill soak reconciles exactly (restart rebases at zero).
- **Tooling**: lint check 19 fixture semantics (arena I/O confinement,
  CRC'd publishes, no in-memory record index) and the ``cli obs``
  sessions.spill section.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.config import (
    ConfigError,
    FleetConfig,
    ModelConfig,
    ServeConfig,
)
from sharetrade_tpu.fleet import FleetRouter, StaticEndpoints
from sharetrade_tpu.fleet import wire
from sharetrade_tpu.fleet.router import _EngineView
from sharetrade_tpu.models.transformer_episode import (
    episode_transformer_policy,
)
from sharetrade_tpu.serve import ServeEngine
from sharetrade_tpu.serve.engine import WarmStore
from sharetrade_tpu.serve.spill import (
    SPILL_SUFFIX,
    SpillArena,
    record_name,
    sweep_debris,
)
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8
OBS_DIM = WINDOW + 2


@pytest.fixture(scope="module")
def episode_model():
    return episode_transformer_policy(obs_dim=OBS_DIM, num_layers=2,
                                      num_heads=2, head_dim=8)


@pytest.fixture(scope="module")
def episode_params(episode_model):
    return episode_model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def prices():
    rng = np.random.default_rng(7)
    return rng.uniform(10.0, 20.0, 256).astype(np.float32)


def obs_at(prices, start, t):
    lo = start + t
    return np.concatenate(
        [prices[lo:lo + WINDOW],
         np.asarray([2400.0, 0.0], np.float32)]).astype(np.float32)


def _carry_nbytes(model) -> int:
    return sum(int(np.asarray(leaf).size) * np.asarray(leaf).dtype.itemsize
               for leaf in jax.tree.leaves(model.init_carry()))


def _spill_engine(model, params, spill_dir, *, warm_carries=1, slots=2,
                  max_batch=2, registry=None):
    engine = ServeEngine(
        model,
        ServeConfig(max_batch=max_batch, slots=slots, batch_timeout_ms=2.0,
                    warm_bytes=warm_carries * _carry_nbytes(model),
                    warm_max_sessions=4096,
                    spill_dir=str(spill_dir), spill_bytes=1 << 26),
        params, registry=registry or MetricsRegistry())
    engine.warmup()
    return engine


def _sealed(spill_dir) -> list[str]:
    return sorted(f for f in os.listdir(spill_dir)
                  if f.endswith(SPILL_SUFFIX))


class SequentialReference:
    """One-at-a-time ``model.apply`` with carries threaded per session —
    the parity baseline (same as tests/test_session_paging.py)."""

    def __init__(self, model, params):
        self.model = model
        self.params = params
        self._apply = jax.jit(model.apply)
        self._carries: dict = {}

    def step(self, sid, obs):
        carry = self._carries.get(sid)
        if carry is None:
            carry = self.model.init_carry()
        out, carry = self._apply(self.params, obs, carry)
        self._carries[sid] = carry
        return np.asarray(out.logits)


# ---------------------------------------------------------------------------
# SpillArena unit semantics (record discipline, no engine)


def _leaves(nbytes: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    return [rng.random(nbytes // 8, dtype=np.float64).view(np.float64)]


def _arena(root, *, nbytes=64, incarnation="inc-a", max_bytes=1 << 20):
    return SpillArena(str(root), max_bytes=max_bytes,
                      record_nbytes=nbytes, incarnation=incarnation)


class TestSpillArena:
    def test_put_take_roundtrip_consumes(self, tmp_path):
        arena = _arena(tmp_path)
        leaves = _leaves(64)
        assert arena.put("s0", leaves, steps=7)
        assert arena.probe("s0")
        payload, steps, reason, foreign = arena.take("s0", expected_steps=7)
        assert reason == "hit" and not foreign and steps == 7
        assert payload == b"".join(
            np.ascontiguousarray(x).tobytes() for x in leaves)
        # Consume-on-take: adopted at most once.
        assert not arena.probe("s0")
        assert arena.take("s0", expected_steps=7)[2] == "miss"
        assert arena.takes == 1 and arena.sessions == 0

    def test_stale_stamp_consumed_and_demotes(self, tmp_path):
        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=7)
        payload, steps, reason, _ = arena.take("s0", expected_steps=6)
        assert payload is None and reason == "stale" and steps == 7
        # The safe direction: the record is gone, the session lands cold
        # and can never read this stamp again.
        assert not arena.probe("s0")
        assert arena.stale == 1

    def test_no_clock_accepts_own_incarnation_only(self, tmp_path):
        writer = _arena(tmp_path, incarnation="inc-a")
        writer.put("s0", _leaves(64), steps=3)
        # A clock-less take from a DIFFERENT incarnation is stale (the
        # supervised-restart contract: a rebuilt engine serves only cold
        # re-entries without the fleet clock vouching for the record).
        other = _arena(tmp_path, incarnation="inc-b")
        payload, _steps, reason, foreign = other.take("s0")
        assert payload is None and reason == "stale" and foreign
        # Same incarnation, no clock: the engine-local warm continuation.
        writer.put("s1", _leaves(64, seed=1), steps=5)
        payload, steps, reason, foreign = writer.take("s1")
        assert reason == "hit" and not foreign and steps == 5

    def test_foreign_record_with_matching_clock_adopts(self, tmp_path):
        _arena(tmp_path, incarnation="inc-a").put("s0", _leaves(64), steps=9)
        payload, steps, reason, foreign = _arena(
            tmp_path, incarnation="inc-b").take("s0", expected_steps=9)
        assert reason == "hit" and foreign and steps == 9
        assert payload is not None

    def test_corrupt_record_consumed(self, tmp_path):
        from soak_common import flip_byte

        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=1)
        flip_byte(str(tmp_path / record_name("s0")), offset_frac=0.9)
        payload, _steps, reason, _ = arena.take("s0", expected_steps=1)
        assert payload is None and reason == "corrupt"
        assert not arena.probe("s0")
        assert arena.corrupt == 1

    def test_torn_record_consumed(self, tmp_path):
        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=1)
        path = tmp_path / record_name("s0")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        assert arena.take("s0", expected_steps=1)[2] == "corrupt"
        # Zero-length (crashed writer raced the rename): same demotion.
        arena.put("s1", _leaves(64, seed=1), steps=1)
        with open(tmp_path / record_name("s1"), "r+b") as f:
            f.truncate(0)
        assert arena.take("s1", expected_steps=1)[2] == "corrupt"

    def test_wrong_model_footprint(self, tmp_path):
        # Writer refuses a payload that is not ITS record size...
        arena = _arena(tmp_path, nbytes=64)
        assert not arena.put("s0", _leaves(32), steps=1)
        assert arena.put_refusals == 1 and not arena.probe("s0")
        # ...and a reader with a different carry template fails the
        # length check — a different model/precision simply lands cold.
        arena.put("s0", _leaves(64), steps=1)
        reader = _arena(tmp_path, nbytes=128)
        assert reader.take("s0", expected_steps=1)[2] == "corrupt"

    def test_digest_rendezvous_never_crosses_sessions(self, tmp_path):
        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=1)
        # A record renamed onto another session's slot (the digest-
        # collision stand-in) must read corrupt, never as s1's state.
        os.replace(tmp_path / record_name("s0"),
                   tmp_path / record_name("s1"))
        assert arena.take("s1", expected_steps=1)[2] == "corrupt"

    def test_byte_budget_refuses(self, tmp_path):
        arena = _arena(tmp_path, max_bytes=200)   # header+meta+64 > 200/2
        assert arena.put("s0", _leaves(64), steps=1)
        assert not arena.put("s1", _leaves(64, seed=1), steps=1)
        assert arena.put_refusals == 1
        assert _sealed(tmp_path) == [record_name("s0")]

    def test_scan_usage_reanchors_counters(self, tmp_path):
        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=1)
        arena.put("s1", _leaves(64, seed=1), steps=2)
        total, count = arena.scan_usage()
        assert count == 2
        assert total == sum(
            os.path.getsize(tmp_path / f) for f in _sealed(tmp_path))
        # A peer's out-of-band delete drifts the incremental counters;
        # the next scan re-anchors them.
        os.unlink(tmp_path / record_name("s0"))
        assert arena.scan_usage()[1] == 1
        assert arena.sessions == 1

    def test_sweep_debris_only_tmp(self, tmp_path):
        arena = _arena(tmp_path)
        arena.put("s0", _leaves(64), steps=1)
        (tmp_path / "abc.spill.tmp-111").write_bytes(b"torn")
        (tmp_path / "def.spill.tmp-222").write_bytes(b"torn")
        # Pid-specific sweep (pool reaping one dead engine)...
        assert sweep_debris(str(tmp_path), pid=111) == 1
        # ...then the fleet-start full sweep; sealed records untouched.
        assert sweep_debris(str(tmp_path)) == 1
        assert _sealed(tmp_path) == [record_name("s0")]
        assert arena.probe("s0")

    def test_record_name_is_the_rendezvous(self, tmp_path):
        # Any engine computes the same name from the session id alone.
        assert record_name("s0") == record_name("s0")
        assert record_name("s0") != record_name("s1")
        assert record_name("s0").endswith(SPILL_SUFFIX)


# ---------------------------------------------------------------------------
# WarmStore: the spill tier's RAM half (drop-while-parked)


def test_warm_discard_while_parked_never_resurrects():
    store = WarmStore(max_bytes=1000, max_sessions=8)
    store.put("a", "A", 100, steps=3)
    store.discard("a")
    assert store.pop("a") is None and len(store) == 0 and store.bytes == 0
    # Idempotent on a miss.
    store.discard("a")
    assert store.bytes == 0


# ---------------------------------------------------------------------------
# engine-level: adoption bitwise oracle + corruption/stale demotion


def test_spill_adoption_is_bitwise_uninterrupted(episode_model,
                                                 episode_params, prices,
                                                 tmp_path):
    """Engine A thrashes 4 sessions through a one-carry warm budget (the
    overflow spills to disk), then drains: stop → page_out_all seals the
    whole population. Engine B — a different process stand-in with its
    own incarnation — adopts every session via the router-carried
    session clock, and its responses are bit-identical to ONE
    uninterrupted engine (the reference) fed the same requests."""
    model, params = episode_model, episode_params
    ref = SequentialReference(model, params)
    sids = [(f"s{i}", i * 3) for i in range(4)]
    clock: dict = {}

    def send(engine, sid, t0, t):
        obs = obs_at(prices, t0, t)
        result = engine.submit(
            sid, obs, session_clock=clock.get(sid) or None).wait(30)
        expect = ref.step(sid, obs)
        assert np.array_equal(np.asarray(result.logits), expect), (sid, t)
        clock[sid] = clock.get(sid, 0) + 1

    reg_a = MetricsRegistry()
    a = _spill_engine(model, params, tmp_path, registry=reg_a)
    for rnd in range(3):
        for sid, t0 in sids:
            send(a, sid, t0, rnd)
    a.stop(timeout_s=30.0)
    out = a.page_out_all()
    assert out["refused"] == 0
    # Warm handoff: one sealed record per session, none lost.
    assert len(_sealed(tmp_path)) == len(sids)

    reg_b = MetricsRegistry()
    b = _spill_engine(model, params, tmp_path, registry=reg_b)
    try:
        for rnd in range(3, 5):
            for sid, t0 in sids:
                send(b, sid, t0, rnd)
        counters = reg_b.counters()
        # Every session's first request on B was a clocked foreign-
        # incarnation disk hit — a warm ADOPTION, counted exactly once.
        assert counters.get("serve_adopt_warm_total", 0) == len(sids)
        assert counters.get("serve_adopt_cold_total", 0) == 0
        assert counters.get("serve_spill_hits_total", 0) >= len(sids)
    finally:
        b.stop(drain=False, timeout_s=30.0)


def test_corrupt_and_stale_records_land_cold_bitwise_fresh(
        episode_model, episode_params, prices, tmp_path):
    """Injected corruption (and a stale clock) can change LATENCY, never
    bytes: the adopting engine demotes the session to the cold-restart
    path and its response is bit-identical to a fresh session's first
    step — with the per-reason counters naming what happened."""
    from soak_common import flip_byte

    model, params = episode_model, episode_params
    ref = SequentialReference(model, params)
    a = _spill_engine(model, params, tmp_path)
    for sid, t0 in (("c0", 0), ("s0", 8)):
        for t in range(3):
            obs = obs_at(prices, t0, t)
            result = a.submit(sid, obs).wait(30)
            assert np.array_equal(np.asarray(result.logits),
                                  ref.step(sid, obs))
    a.stop(timeout_s=30.0)
    assert a.page_out_all()["written"] == 2
    flip_byte(str(tmp_path / record_name("c0")), offset_frac=0.99)

    reg_b = MetricsRegistry()
    b = _spill_engine(model, params, tmp_path, registry=reg_b)
    try:
        fresh = SequentialReference(model, params)
        # c0: record exists, clock matches, CRC does not → corrupt →
        # cold restart, bitwise a fresh session's first step.
        obs = obs_at(prices, 0, 3)
        result = b.submit("c0", obs, session_clock=3).wait(30)
        assert np.array_equal(np.asarray(result.logits),
                              fresh.step("c0", obs))
        # s0: record intact but the clock disagrees with the stamp (the
        # router saw fewer completions than the seal) → stale → cold.
        obs = obs_at(prices, 8, 3)
        result = b.submit("s0", obs, session_clock=2).wait(30)
        assert np.array_equal(np.asarray(result.logits),
                              fresh.step("s0", obs))
        counters = reg_b.counters()
        assert counters.get("serve_spill_corrupt_total", 0) == 1
        assert counters.get("serve_spill_stale_total", 0) == 1
        assert counters.get("serve_adopt_warm_total", 0) == 0
        # Both clocked re-entries that missed warm are cold adoptions.
        assert counters.get("serve_adopt_cold_total", 0) == 2
        # Consumed either way: nothing left to adopt.
        assert _sealed(tmp_path) == []
    finally:
        b.stop(drain=False, timeout_s=30.0)


def test_park_inbox_commit_races_eviction_bitwise(episode_model,
                                                  episode_params, prices):
    """Two sessions ping-pong through ONE slot: every request evicts the
    other session, whose page-out readback races the next admission.
    The park-inbox commit points (collect-top and pre-admission) must
    make every parked carry visible before its session re-enters — the
    whole exchange stays bitwise against the uninterrupted reference."""
    model, params = episode_model, episode_params
    reg = MetricsRegistry()
    engine = ServeEngine(
        model,
        ServeConfig(max_batch=1, slots=1, batch_timeout_ms=2.0,
                    warm_bytes=2 * _carry_nbytes(model),
                    warm_max_sessions=4096),
        params, registry=reg)
    engine.warmup()
    try:
        ref = SequentialReference(model, params)
        for t in range(6):
            for sid, t0 in (("a", 0), ("b", 16)):
                obs = obs_at(prices, t0, t)
                result = engine.submit(sid, obs).wait(30)
                assert np.array_equal(np.asarray(result.logits),
                                      ref.step(sid, obs)), (sid, t)
        counters = reg.counters()
        # The race was real: the loop parked and unparked repeatedly.
        assert counters.get("serve_warm_parks_total", 0) >= 10
        assert counters.get("serve_warm_hits_total", 0) >= 10
    finally:
        engine.stop(drain=False, timeout_s=30.0)


def test_page_out_all_refuses_until_stopped(episode_model, episode_params,
                                            prices, tmp_path):
    """The drain ORDERING contract (satellite of ISSUE 20): drain →
    stop() → page_out_all() → exit 75. A live dispatcher/consumer still
    owns the session stores, so the page-out refuses loudly; after
    stop() it seals the full surviving population — hot AND warm."""
    model, params = episode_model, episode_params
    engine = _spill_engine(model, params, tmp_path, warm_carries=2,
                           slots=2)
    # 3 sessions on 2 slots: two stay hot, one is parked RAM-warm.
    for sid, t0 in (("h0", 0), ("h1", 8), ("w0", 16)):
        engine.submit(sid, obs_at(prices, t0, 0)).wait(30)
    with pytest.raises(RuntimeError, match="page_out_all\\(\\) before "
                                           "stop\\(\\)"):
        engine.page_out_all()
    assert _sealed(tmp_path) == []      # refused means NOTHING written
    assert engine.stop(timeout_s=30.0)
    out = engine.page_out_all()
    assert out["written"] == 3 and out["refused"] == 0
    assert len(_sealed(tmp_path)) == 3
    for sid in ("h0", "h1", "w0"):
        assert record_name(sid) in _sealed(tmp_path)


def test_spill_config_validation(tmp_path):
    mlp = ModelConfig(kind="mlp", hidden_dim=8, num_layers=1)
    from sharetrade_tpu.models import build_model

    model = build_model(mlp, OBS_DIM)
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(ConfigError, match="spill_bytes"):
        ServeEngine(model, ServeConfig(spill_bytes=-1), params)
    with pytest.raises(ConfigError, match="spill_dir requires the warm"):
        ServeEngine(model, ServeConfig(spill_dir=str(tmp_path),
                                       warm_bytes=0), params)


# ---------------------------------------------------------------------------
# router: the session clock (the adoption stamp's fleet half)


def _router(reg=None):
    return FleetRouter(StaticEndpoints({}), FleetConfig(),
                       reg or MetricsRegistry(), workdir="")


class TestRouterSessionClock:
    def test_clock_ticks_on_200_only(self):
        router = _router()
        assert router.session_clock("s") == 0
        router.finish_relay("s", "e0", False, wire.STATUS_OK, b"{}")
        router.finish_relay("s", "e0", False, wire.STATUS_OK, b"{}")
        assert router.session_clock("s") == 2
        # A protocol refusal never touched the carry: clock holds.
        router.finish_relay("s", "e0", False, wire.STATUS_UNAVAILABLE,
                            b"{}")
        assert router.session_clock("s") == 2

    def test_clock_survives_engine_death(self):
        router = _router()
        router.finish_relay("s", "e0", False, wire.STATUS_OK, b"{}")
        router._drop_engine_affinity("e0")
        # Detached from the dead engine, clock kept — the key that
        # unlocks warm adoption on the next engine.
        assert router._affinity["s"] == (None, 1)
        assert router.session_clock("s") == 1

    def test_engine_id_spliced_into_reply(self):
        router = _router()
        status, reply = router.finish_relay(
            "s", "e7", False, wire.STATUS_OK, b'{"logits":[1]}')
        assert status == wire.STATUS_OK
        assert json.loads(reply)["engine"] == "e7"

    def test_counter_deltas_fold_and_restart_rebase(self):
        reg = MetricsRegistry()
        router = _router(reg)
        view = _EngineView("e0", ("h", 1))

        def metrics(total, warm, corrupt=0.0):
            return {"counters": {
                "sharetrade_serve_requests_total": total,
                "sharetrade_serve_adopt_warm_total": warm,
                "sharetrade_serve_spill_corrupt_total": corrupt}}

        # First scrape of a new engine folds everything since boot.
        router._counter_deltas(view, metrics(10.0, 3.0))
        assert reg.counters()["fleet_adopt_warm_total"] == 3
        # Steady state folds the window delta.
        router._counter_deltas(view, metrics(20.0, 5.0, corrupt=1.0))
        counters = reg.counters()
        assert counters["fleet_adopt_warm_total"] == 5
        assert counters["fleet_spill_corrupt_total"] == 1
        # A restart (total shrank) rebases at zero: the fresh counters
        # ARE the window — nothing double-counted, nothing lost.
        router._counter_deltas(view, metrics(2.0, 2.0))
        assert reg.counters()["fleet_adopt_warm_total"] == 7


# ---------------------------------------------------------------------------
# lint check 19 fixture semantics


def test_lint_spill_arena_semantics(tmp_path):
    """Fixture semantics: arena record I/O outside serve/spill.py is
    flagged unless marked ``spill-io-ok``; a SpillArena method that
    publishes via os.replace without a crc32 call is flagged; an
    in-memory container assigned in __init__ needs ``spill-index-ok``;
    a compliant module passes all three."""
    import lint_hot_loop

    root = tmp_path / "bad"
    (root / "serve").mkdir(parents=True)
    (root / "other.py").write_text(
        "import os\n"
        "def sneaky(root, sid):\n"
        "    return open(os.path.join(root, record_name(sid)))\n")
    (root / "serve" / "spill.py").write_text(
        "import os, zlib\n"
        "class SpillArena:\n"
        "    def __init__(self):\n"
        "        self._index = {}\n"
        "    def put(self, sid, data):\n"
        "        os.replace('a.tmp', 'a')\n")
    io_bad, crc_bad, index_bad, found = lint_hot_loop.lint_spill_arena(
        root=root)
    assert found == {"SpillArena"}
    assert [(path, ln) for path, ln, _ in io_bad] == [("other.py", 3)]
    assert len(crc_bad) == 1 and "without calling crc32" in crc_bad[0][2]
    assert [(ln, text) for _, ln, text in index_bad] == [
        (4, "self._index = {}")]

    good = tmp_path / "good"
    (good / "serve").mkdir(parents=True)
    (good / "pool.py").write_text(
        "# spill-io-ok: the supervisor's debris sweep\n"
        "def sweep(root, sid):\n"
        "    return record_name(sid)\n")
    (good / "serve" / "spill.py").write_text(
        "import os, zlib\n"
        "class SpillArena:\n"
        "    def __init__(self):\n"
        "        # counters only  # spill-index-ok\n"
        "        self.stats = dict(puts=0)\n"
        "    def put(self, sid, data):\n"
        "        crc = zlib.crc32(data)\n"
        "        os.replace('a.tmp', 'a')\n")
    io_bad, crc_bad, index_bad, _found = lint_hot_loop.lint_spill_arena(
        root=good)
    assert io_bad == [] and crc_bad == [] and index_bad == []

    # No sealed publish at all is ALSO a finding (the crash-consistency
    # claim rests on the rename), and a missing module even more so.
    sealed_less = tmp_path / "sealedless"
    (sealed_less / "serve").mkdir(parents=True)
    (sealed_less / "serve" / "spill.py").write_text(
        "class SpillArena:\n"
        "    def put(self, sid, data):\n"
        "        open('a', 'wb').write(data)\n")
    _io, crc_bad, _idx, _found = lint_hot_loop.lint_spill_arena(
        root=sealed_less)
    assert any("no os.replace publish" in text for _, _, text in crc_bad)
    _io, crc_bad, _idx, found = lint_hot_loop.lint_spill_arena(
        root=tmp_path / "void")
    assert found == set()
    assert any("missing" in text for _, _, text in crc_bad)


def test_lint_check19_clean_on_real_repo():
    import lint_hot_loop

    io_bad, crc_bad, index_bad, found = lint_hot_loop.lint_spill_arena()
    assert io_bad == [] and crc_bad == [] and index_bad == []
    assert "SpillArena" in found


# ---------------------------------------------------------------------------
# cli obs: the sessions.spill section


def test_obs_spill_section(tmp_path):
    from sharetrade_tpu.config import FrameworkConfig
    from sharetrade_tpu.obs import build_obs, summarize_run_dir

    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "run")
    registry = MetricsRegistry()
    bundle = build_obs(cfg, registry)
    registry.record_many({
        "serve_sessions_hot": 2.0, "serve_warm_sessions": 3.0,
        "serve_warm_bytes": 4096.0, "serve_warm_budget_bytes": 8192.0,
        "serve_spill_sessions": 5.0, "serve_spill_bytes": 20480.0,
        "serve_spill_budget_bytes": 1048576.0})
    registry.inc("serve_warm_hits_total", 6)
    registry.inc("serve_spill_puts_total", 9)
    registry.inc("serve_spill_hits_total", 4)
    registry.inc("serve_spill_corrupt_total", 1)
    registry.inc("serve_adopt_warm_total", 4)
    registry.inc("serve_adopt_cold_total", 2)
    bundle.flush()
    bundle.close()
    spill = summarize_run_dir(cfg.obs.dir)["sessions"]["spill"]
    assert spill["sessions"] == 5.0
    assert spill["bytes"] == 20480.0
    assert spill["budget_bytes"] == 1048576.0
    assert spill["puts_total"] == 9.0
    assert spill["hits_total"] == 4.0
    assert spill["corrupt_total"] == 1.0
    assert spill["adopt_warm_total"] == 4.0
    assert spill["adopt_cold_total"] == 2.0


def test_obs_no_spill_section_without_tier(tmp_path):
    from sharetrade_tpu.config import FrameworkConfig
    from sharetrade_tpu.obs import build_obs, summarize_run_dir

    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "run")
    registry = MetricsRegistry()
    bundle = build_obs(cfg, registry)
    registry.record_many({"serve_sessions_hot": 2.0,
                          "serve_warm_sessions": 3.0,
                          "serve_warm_bytes": 1.0,
                          "serve_warm_budget_bytes": 2.0})
    registry.inc("serve_warm_hits_total", 1)
    bundle.flush()
    bundle.close()
    assert "spill" not in summarize_run_dir(cfg.obs.dir)["sessions"]
