"""Fleet kill-test (tools/fleet_soak.py) — REAL router + engine worker
subprocesses + live learner, real SIGKILLs, driven in-process.

The quick profile (2 engines, 1 whole-engine SIGKILL under closed-loop
journaling load) is the tier-1 guard for the fleet contract: the router
never wedges (a post-kill probe answers immediately and ZERO client
requests fail — migration absorbs the corpse's in-flight work), the
pool's restart counter reconciles exactly with the injected kills, the
flywheel closes (journaled session transitions ingested by the live
learner, a fresh ``tag_best`` hot-swapped into EVERY engine — healthz
``params_step`` advances fleet-wide), the merged-histogram fleet SLO
gauges are live, router counters balance exactly, and SIGTERM drains
the whole tier with exit 75. The full soak — >=3 engines, >=3 kills —
is the ``slow``-marked variant (also ``make fleet-soak``).

The spill soak (ISSUE 20) kills an engine UNDER a populated spill
arena: survivors must adopt the victim's sessions warm from disk (the
majority — only the injected-corruption record and the in-memory tail
restart cold), the fleet adoption counters must reconcile exactly, and
the drain must seal the entire population for the next incarnation.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import fleet_soak  # noqa: E402


class TestQuickSoak:
    def test_one_kill_flywheel_and_reconciliation(self, tmp_path):
        summary = fleet_soak.run_soak(
            engines=2, kills=1, ramp_s=3.0, sessions=32, concurrency=8,
            workdir=str(tmp_path))
        assert summary["ok"] is True
        assert summary["kills_injected"] == 1
        # Migration absorbed the kill: the closed loop dropped nothing.
        assert summary["traffic"]["failed"] == 0
        assert summary["traffic"]["completed"] > 0
        # Flywheel: sessions' journals fed the learner and the republished
        # tag_best reached every live engine.
        fw = summary["flywheel"]
        assert fw["rows_ingested"] > 0
        assert all(s > fw["boot_params_step"]
                   for s in fw["post_swap_params_steps"])
        # Live merged-histogram SLO gauges.
        assert summary["fleet_slo"]["merged"]["count"] > 0
        assert summary["drain_rc"] == 75
        # Stitched kill forensics: one CLEAN trace spans the killed
        # engine (eagerly-flushed ingress marker), a survivor, the
        # client's root span, and the router's migrate-annotated relay
        # attempt (run_soak raises unless all of that held).
        tr = summary["tracing"]
        assert tr["migrated_traces"] >= 1
        assert len(tr["witness"]["engines"]) >= 2
        assert "client" in tr["witness"]["procs"]
        assert "fleet" in tr["witness"]["procs"]


class TestAutoscaleSoak:
    def test_diurnal_profile_tracks_load(self, tmp_path):
        """One ``cli fleet --autoscale`` tier through a surge/quiet
        cycle: membership grows to the ceiling under queueing load and
        retires back to the floor in silence, with ZERO restart storms
        (every change a deliberate spawn/retirement), no dropped
        requests, availability burn < 1 in the same history ring the
        autoscaler decided on, and a clean exit-75 drain."""
        summary = fleet_soak.run_autoscale_soak(
            ceiling=2, sessions=32, concurrency=16,
            workdir=str(tmp_path))
        assert summary["ok"] is True
        assert summary["autoscaler"]["decisions"] >= 2
        assert summary["autoscaler"]["last_decision"]["action"] == "down"
        assert summary["autoscaler"]["peak_burn"] < 1.0
        assert summary["traffic"]["failed"] == 0
        assert summary["traffic"]["completed"] > 0
        assert summary["drain_rc"] == 75


class TestSpillSoak:
    def test_kill_under_population_warm_majority(self, tmp_path):
        """SIGKILL the engine holding the most spilled carries while the
        arena holds a populated session census (one record injected with
        corruption): survivors adopt the MAJORITY warm, the fleet
        adoption/corruption counters reconcile EXACTLY against the
        census, and the final drain seals every session's carry for the
        next incarnation (the warm-handoff half of ISSUE 20)."""
        summary = fleet_soak.run_spill_soak(
            engines=2, sessions=24, rounds=2, workdir=str(tmp_path))
        assert summary["ok"] is True
        recon = summary["recon"]
        census = summary["census"]
        # Exact reconciliation: every spilled victim session adopted
        # warm except the one corrupted record; every in-memory victim
        # session (plus the corrupt one) restarted cold.
        assert recon["fleet_adopt_warm_total"] == \
            census["victim_spilled"] - 1
        assert recon["fleet_adopt_cold_total"] == \
            census["victim_memory"] + 1
        assert recon["fleet_spill_corrupt_total"] == 1
        assert recon["fleet_spill_stale_total"] == 0
        assert recon["fleet_adopt_warm_total"] > \
            recon["fleet_adopt_cold_total"]
        assert summary["drain_rc"] == 75
        # Drain-time page-out: one sealed record per session, none lost.
        assert summary["arena_records_after_drain"] == 24


@pytest.mark.slow
class TestFullSoak:
    def test_multi_engine_multi_kill(self, tmp_path):
        summary = fleet_soak.run_soak(
            engines=3, kills=3, ramp_s=6.0, sessions=64, concurrency=12,
            workdir=str(tmp_path))
        assert summary["ok"] is True
        assert summary["kills_injected"] >= 3
        assert summary["traffic"]["failed"] == 0
