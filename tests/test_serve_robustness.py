"""Overload-safe, self-healing serving (serve/ — ISSUE 10).

The load-bearing contracts, on top of tests/test_serve.py's PR-8 suite
(which pins that DEFAULT-config behavior is unchanged):

- **Admission control**: the ingress queue is bounded at
  ``serve.max_queue``; a flood is shed (``shed_policy="oldest"``) or
  rejected (``"reject"``) with explicit ``ServeRejected`` terminal
  outcomes — the caller's thread is never blocked silently and host
  memory never grows without bound.
- **Deadlines**: expired requests complete with ``ServeDeadlineExceeded``
  BEFORE batch collection (never occupying a padded device row), and the
  batch-coalescing wait is clamped to the earliest surviving deadline.
- **Supervision**: with ``serve.max_restarts > 0`` a dispatch fault fails
  its batch and then REBUILDS the engine (fresh programs + fresh arena —
  previously-warm sessions re-enter cold, bitwise-matching fresh
  sessions); a consecutive-fault storm trips a terminal failed state
  that fails queued work loudly and makes submits raise.
- **Swap breaker**: repeated verified-restore failures stop the watcher
  from polling a wedged tag for a cooldown, with gauge + counters.
- **Shutdown honesty**: ``stop()`` returns False when a thread survived
  its join timeout; ``drain()``'s timeout path returns False.
- **Tooling**: lint check 10 (no unbounded queues / stray sleeps in
  serve/) and the serve chaos soak's quick profile run in tier-1; the
  full >= 20-injection soak is ``slow``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.agents.base import TrainState
from sharetrade_tpu.checkpoint.manager import CheckpointManager
from sharetrade_tpu.config import ConfigError, ModelConfig, ServeConfig
from sharetrade_tpu.models import build_model
from sharetrade_tpu.models.transformer_episode import (
    episode_transformer_policy,
)
from sharetrade_tpu.serve import (
    ServeDeadlineExceeded,
    ServeEngine,
    ServeEngineFailed,
    ServeRejected,
    WeightSwapWatcher,
)
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8
OBS_DIM = WINDOW + 2


@pytest.fixture(scope="module")
def mlp_model():
    return build_model(ModelConfig(kind="mlp", hidden_dim=16), OBS_DIM,
                       head="ac")


@pytest.fixture(scope="module")
def mlp_params(mlp_model):
    return mlp_model.init(jax.random.PRNGKey(1))


@pytest.fixture(scope="module")
def episode_model():
    return episode_transformer_policy(obs_dim=OBS_DIM, num_layers=2,
                                      num_heads=2, head_dim=8)


@pytest.fixture(scope="module")
def episode_params(episode_model):
    return episode_model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def prices():
    rng = np.random.default_rng(7)
    return rng.uniform(10.0, 20.0, 256).astype(np.float32)


def obs_at(prices, start, t, *, budget=2400.0, shares=0.0):
    lo = start + t
    return np.concatenate(
        [prices[lo:lo + WINDOW],
         np.asarray([budget, shares], np.float32)]).astype(np.float32)


def _stalled_engine(model, params, *, max_queue, shed_policy,
                    registry=None, stall_s=0.4, prices=None, **cfg_kw):
    """Engine with a SHALLOW pipeline (done_depth=1) whose consumer is
    stalled by one sleeping-callback request: the deterministic way to
    make later submits pile into the bounded ingress queue. Returns
    (engine, stall_handle) with the stall already engaged."""
    engine = ServeEngine(
        model,
        ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0,
                    max_queue=max_queue, shed_policy=shed_policy,
                    **cfg_kw),
        params, registry=registry, done_depth=1)
    engine.warmup()
    engaged = threading.Event()

    def stall_cb(_result):
        engaged.set()
        time.sleep(stall_s)

    handle = engine.submit("stall", obs_at(prices, 0, 0),
                           callback=stall_cb)
    assert engaged.wait(20.0), "stall request never dispatched"
    return engine, handle


# ---------------------------------------------------------------------------
# config validation


def test_new_knob_validation(mlp_model, mlp_params):
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1, max_queue=0),
                    mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1,
                                shed_policy="brownout"), mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1,
                                default_deadline_ms=-1.0), mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1, max_restarts=-1),
                    mlp_params)
    with pytest.raises(ConfigError):
        ServeEngine(mlp_model,
                    ServeConfig(max_batch=1, slots=1,
                                restart_backoff_s=0.0), mlp_params)


# ---------------------------------------------------------------------------
# admission control / load shedding


def test_flood_rejects_with_explicit_outcome(mlp_model, mlp_params,
                                             prices):
    """shed_policy='reject': a flood past max_queue completes the excess
    with ServeRejected — immediately (wait() does not block out its
    timeout), counted exactly, queue depth bounded — and the engine
    serves normally afterward."""
    registry = MetricsRegistry()
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=4,
                                    shed_policy="reject",
                                    registry=registry, prices=prices)
    try:
        handles = [engine.submit(f"f{i}", obs_at(prices, i % 32, 0))
                   for i in range(64)]
        assert engine.queue_depth() <= 4
        rejected = []
        for handle in handles:
            t0 = time.perf_counter()
            result = handle.wait(30.0)
            if result is None:
                assert isinstance(handle.error, ServeRejected)
                assert handle.error.reason == "queue_full"
                rejected.append(handle)
                # A rejected handle completed at submit time: waiting on
                # it returns instantly, not after a timeout.
                assert time.perf_counter() - t0 < 1.0
        assert rejected, "a 64-request flood past max_queue=4 with a "\
            "stalled consumer rejected nothing"
        counters = registry.counters()
        assert counters["serve_queue_rejected_total"] == len(rejected)
        assert "serve_shed_total" not in counters
        # Recovery: the engine still answers.
        result = engine.submit("after", obs_at(prices, 40, 0)).wait(30.0)
        assert result is not None
        assert registry.latest("serve_overload") == 1.0
    finally:
        assert stall.wait(10.0) is not None
        engine.stop()


def test_flood_shed_oldest_admits_newest(mlp_model, mlp_params, prices):
    """shed_policy='oldest': the brownout sheds QUEUED work to admit new
    arrivals — the newest submit survives to completion, shed victims
    carry ServeRejected(reason='shed_oldest'), and the shed counter
    matches the victims exactly."""
    registry = MetricsRegistry()
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=4,
                                    shed_policy="oldest",
                                    registry=registry, prices=prices)
    try:
        handles = [engine.submit(f"o{i}", obs_at(prices, i % 32, 0))
                   for i in range(64)]
        assert engine.queue_depth() <= 4
        shed = [h for h in handles if h.wait(30.0) is None]
        for handle in shed:
            assert isinstance(handle.error, ServeRejected)
            assert handle.error.reason == "shed_oldest"
        assert shed, "the flood shed nothing"
        # Under 'oldest' the LAST submit is always admitted (it evicts
        # an older victim), so it must have been served.
        assert handles[-1].result is not None
        assert registry.counters()["serve_shed_total"] == len(shed)
    finally:
        assert stall.wait(10.0) is not None
        engine.stop()


def test_wait_on_shed_request_returns_none_with_error(mlp_model,
                                                      mlp_params, prices):
    """Satellite: wait(timeout) on a request whose batch was shed is a
    prompt None + error, indistinguishable from neither a timeout (error
    set) nor a served result (result None)."""
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=2,
                                    shed_policy="oldest", prices=prices)
    try:
        handles = [engine.submit(f"w{i}", obs_at(prices, i, 0))
                   for i in range(16)]
        shed = [h for h in handles if h.wait(20.0) is None]
        assert shed
        handle = shed[0]
        assert handle.wait(0.001) is None       # already terminal
        assert handle.result is None
        assert isinstance(handle.error, ServeRejected)
    finally:
        assert stall.wait(10.0) is not None
        engine.stop()


# ---------------------------------------------------------------------------
# per-request deadlines


def test_deadline_expires_before_batch_collection(mlp_model, mlp_params,
                                                  prices):
    """Requests queued behind a stalled consumer whose deadline passes
    must complete with ServeDeadlineExceeded, matching the counter
    exactly; later requests are unaffected."""
    registry = MetricsRegistry()
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=8,
                                    shed_policy="reject",
                                    registry=registry, prices=prices)
    try:
        handles = [engine.submit(f"d{i}", obs_at(prices, i, 0),
                                 deadline_ms=20.0) for i in range(8)]
        outcomes = [h.wait(30.0) for h in handles]
        expired = [h for h, r in zip(handles, outcomes) if r is None]
        for handle in expired:
            assert isinstance(handle.error, ServeDeadlineExceeded)
        assert expired, "no deadline expiries behind a stalled consumer"
        assert registry.counters()["serve_deadline_expired_total"] == len(
            expired)
        # The engine serves deadline-free traffic normally afterward.
        assert engine.submit("ok", obs_at(prices, 50, 0)).wait(30.0)
    finally:
        assert stall.wait(10.0) is not None
        engine.stop()


def test_default_deadline_from_config(mlp_model, mlp_params, prices):
    """serve.default_deadline_ms applies when submit() passes none."""
    registry = MetricsRegistry()
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=8,
                                    shed_policy="reject",
                                    registry=registry, prices=prices,
                                    default_deadline_ms=15.0)
    try:
        handles = [engine.submit(f"dd{i}", obs_at(prices, i, 0))
                   for i in range(8)]
        expired = [h for h in handles if h.wait(30.0) is None]
        assert expired
        assert all(isinstance(h.error, ServeDeadlineExceeded)
                   for h in expired)
        # Explicit deadline_ms=0 overrides the default to NO deadline.
        assert engine.submit("nodl", obs_at(prices, 60, 0),
                             deadline_ms=0).wait(30.0) is not None
    finally:
        assert stall.wait(10.0) is not None
        engine.stop()


def test_deadline_anchors_batch_coalescing(mlp_model, mlp_params, prices):
    """A lone tightly-deadlined request under a LONG batch_timeout_ms
    must dispatch at its deadline, not the coalescing timeout: the
    collection wait is clamped to the earliest surviving deadline."""
    engine = ServeEngine(
        mlp_model,
        ServeConfig(max_batch=8, slots=8, batch_timeout_ms=2000.0,
                    max_queue=8),
        mlp_params)
    engine.warmup()
    try:
        t0 = time.perf_counter()
        result = engine.submit("anchor", obs_at(prices, 0, 0),
                               deadline_ms=50.0).wait(10.0)
        elapsed = time.perf_counter() - t0
        assert result is not None, "anchored request expired instead of "\
            "dispatching at its deadline"
        assert elapsed < 1.5, (
            f"request waited {elapsed:.2f}s: the coalescing deadline "
            "ignored the request's own deadline")
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# dispatch supervision


def test_supervised_restart_rebuilds_arena(episode_model, episode_params,
                                           prices):
    """With max_restarts > 0 a dispatch fault rebuilds the engine: the
    formerly-warm session re-enters COLD and answers bit-identically to
    a fresh session (the rebuild discarded its slot carry), and the
    restart counter advances by exactly one."""
    registry = MetricsRegistry()
    engine = ServeEngine(
        episode_model,
        ServeConfig(max_batch=4, slots=8, batch_timeout_ms=2.0,
                    max_restarts=2, restart_backoff_s=0.01,
                    restart_backoff_max_s=0.05),
        episode_params, registry=registry)
    engine.warmup()
    apply_fn = jax.jit(episode_model.apply)
    try:
        for t in range(2):                       # warm session A
            assert engine.submit("A", obs_at(prices, 0, t)).wait(30.0)
        bad = engine.submit("bad", np.ones(3, np.float32))
        assert bad.wait(30.0) is None and bad.error is not None
        # Post-rebuild: A is cold; its next answer equals a FRESH session
        # (NOT the warm continuation the PR-8 default preserves).
        obs = obs_at(prices, 0, 2)
        result = engine.submit("A", obs).wait(60.0)
        assert result is not None, "engine did not heal after the fault"
        out, _ = apply_fn(episode_params, obs, episode_model.init_carry())
        assert np.array_equal(result.logits, np.asarray(out.logits)), (
            "post-restart response is not a fresh-session response: the "
            "rebuild kept a stale arena")
        assert registry.counters()["serve_restarts_total"] == 1.0
    finally:
        engine.stop()


def test_restart_storm_trips_terminal_failed(mlp_model, mlp_params,
                                             prices):
    """More than max_restarts CONSECUTIVE faults: the engine enters the
    terminal failed state — queued work fails loudly, submits raise
    ServeEngineFailed, stop() still shuts down cleanly."""
    registry = MetricsRegistry()
    engine = ServeEngine(
        mlp_model,
        ServeConfig(max_batch=2, slots=2, batch_timeout_ms=1.0,
                    max_restarts=1, restart_backoff_s=0.01,
                    restart_backoff_max_s=0.02),
        mlp_params, registry=registry)
    engine.warmup()
    try:
        first = engine.submit("s1", np.ones(3, np.float32))
        assert first.wait(30.0) is None          # fault 1 -> restart 1
        second = engine.submit("s2", np.ones(3, np.float32))
        assert second.wait(30.0) is None         # fault 2 -> terminal
        deadline = time.monotonic() + 10.0
        while engine.failed is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.failed is not None, "restart storm did not trip "\
            "the terminal failed state"
        with pytest.raises(ServeEngineFailed):
            engine.submit("late", obs_at(prices, 0, 0))
        assert registry.counters()["serve_restarts_total"] == 1.0
        assert registry.latest("serve_failed") == 1.0
    finally:
        assert engine.stop(drain=False) is True


# ---------------------------------------------------------------------------
# shutdown honesty (satellites)


def test_drain_timeout_returns_false(mlp_model, mlp_params, prices):
    """Satellite: drain(timeout_s) with work still in flight is an
    honest False; once the pipeline clears it flips to True."""
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=8,
                                    shed_policy="reject", prices=prices,
                                    stall_s=0.5)
    try:
        assert engine.drain(timeout_s=0.05) is False
        assert engine.drain(timeout_s=20.0) is True
    finally:
        assert stall.wait(10.0) is not None
        assert engine.stop() is True


def test_stop_reports_hung_thread(mlp_model, mlp_params, prices):
    """Satellite: a consumer wedged past the join timeout makes stop()
    return False (the cli exits nonzero on it) instead of lying."""
    engine, stall = _stalled_engine(mlp_model, mlp_params, max_queue=8,
                                    shed_policy="reject", prices=prices,
                                    stall_s=1.2)
    # The consumer thread is mid-sleep inside the stall callback: a stop
    # with a short join timeout must say so.
    assert engine.stop(drain=False, timeout_s=0.2) is False
    # After the stall clears, the threads exit and stop() is honest again.
    assert stall.wait(10.0) is not None
    assert engine.stop(drain=False, timeout_s=10.0) is True


# ---------------------------------------------------------------------------
# swap circuit breaker


def _train_state(params, updates: int) -> TrainState:
    return TrainState(params=params, opt_state=(), carry=(),
                      env_state=(), rng=jax.random.PRNGKey(0),
                      env_steps=jnp.int32(0), updates=jnp.int32(updates))


def _corrupt_tag(tmp_path) -> None:
    state_path = tmp_path / "ckpt" / "tag_best" / "state.msgpack"
    raw = bytearray(state_path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    state_path.write_bytes(bytes(raw))


def test_swap_breaker_opens_and_recovers(mlp_model, prices, tmp_path):
    """Consecutive refused candidates open the breaker (gauge 1, polls
    skipped without re-verifying); after the cooldown a genuine candidate
    probes through, swaps, and closes it (gauge 0)."""
    v1 = mlp_model.init(jax.random.PRNGKey(31))
    manager = CheckpointManager(str(tmp_path / "ckpt"), fsync=False)
    registry = MetricsRegistry()
    engine = ServeEngine(
        mlp_model, ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0),
        v1, params_step=1, registry=registry)
    engine.warmup()
    watcher = WeightSwapWatcher(engine, manager, _train_state(v1, 1),
                                tag="best", poll_s=60.0,
                                breaker_failures=2,
                                breaker_cooldown_s=0.2)
    try:
        for k in (2, 3):                     # two corrupt candidates
            manager.save_tagged("best",
                                _train_state(mlp_model.init(
                                    jax.random.PRNGKey(40 + k)), k),
                                metadata={"updates": k})
            _corrupt_tag(tmp_path)
            assert watcher.poll_once() is False
        assert watcher.rejected == 2
        assert watcher.breaker_opens == 1
        assert watcher.breaker_open is True
        assert registry.latest("serve_swap_breaker_open") == 1.0
        assert registry.counters()["serve_swap_breaker_opens_total"] == 1.0
        # While open: a fresh candidate is NOT verified (no new reject).
        manager.save_tagged("best",
                            _train_state(mlp_model.init(
                                jax.random.PRNGKey(44)), 4),
                            metadata={"updates": 4})
        _corrupt_tag(tmp_path)
        assert watcher.poll_once() is False
        assert watcher.rejected == 2, "breaker-open poll still verified "\
            "the wedged tag"
        # Cooldown over: a GENUINE candidate probes through and closes it.
        time.sleep(0.25)
        v5 = mlp_model.init(jax.random.PRNGKey(45))
        manager.save_tagged("best", _train_state(v5, 5),
                            metadata={"updates": 5})
        assert watcher.poll_once() is True
        assert engine.params_step == 5
        assert watcher.breaker_open is False
        assert registry.latest("serve_swap_breaker_open") == 0.0
        # Serving continued on the old weights the whole time.
        assert engine.submit("up", obs_at(prices, 0, 0)).wait(30.0)
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# chaos soak / lint / obs satellites


def test_serve_chaos_quick_profile(tmp_path):
    """The 2-injection quick profile of the chaos soak (also wired into
    `make check`): engine never wedges, queue stays bounded, counters
    reconcile. The full >= 20-injection soak across all five fault
    classes is the `slow` test below."""
    import serve_chaos

    summary = serve_chaos.run_chaos(injections=2, seed=0,
                                    workdir=str(tmp_path),
                                    verbose=False)
    assert summary["injections"] == 2
    assert summary["max_queue_depth_seen"] <= 16
    assert summary["requests_total"] > 0


@pytest.mark.slow
def test_serve_chaos_full_soak(tmp_path):
    """ISSUE 10 acceptance: >= 20 seeded injections covering all five
    fault classes, every invariant asserted after each."""
    import serve_chaos

    summary = serve_chaos.run_chaos(injections=20, seed=0,
                                    workdir=str(tmp_path),
                                    verbose=False)
    assert all(summary["by_class"][c] >= 1
               for c in serve_chaos.FAULT_CLASSES), summary["by_class"]
    assert summary["restarts_total"] == summary["by_class"][
        "dispatch_exception"]
    assert summary["shed_total"] + summary["queue_rejected_total"] > 0
    assert summary["deadline_expired_total"] > 0
    assert summary["swap_breaker_opens_total"] >= 1


def test_lint_serve_overload_safety_clean():
    """Check 10 on the shipped tree: serve/ has no unbounded queues and
    no unmarked sleeps outside the backoff helper."""
    import lint_hot_loop

    hits = lint_hot_loop.lint_serve_overload_safety()
    assert hits == [], f"serve overload-safety lint hits: {hits}"


def test_lint_serve_overload_safety_semantics(tmp_path):
    """Pattern semantics on a fixture: unbounded Queue() (including the
    literal maxsize=0) and EVERY time.sleep are flagged — there is no
    function allowlist, the real backoff helper waits on the stop event
    — while bounded queues and marked lines are not."""
    import lint_hot_loop

    (tmp_path / "engine.py").write_text(
        "import queue\nimport time\nfrom time import sleep\n\n"
        "def bad():\n"
        "    q = queue.Queue()\n"
        "    z = queue.Queue(maxsize=0)\n"   # maxsize=0 IS unbounded
        "    y = queue.Queue(0)\n"
        "    time.sleep(1.0)\n\n"
        "def also_bad():\n"
        "    sleep(2.0)\n\n"          # bare form must be caught too
        "def _backoff_sleep(d):\n"
        "    time.sleep(d)\n\n"       # NOT exempt: no allowlist
        "def fine():\n"
        "    q = queue.Queue(maxsize=8)\n"
        "    r = queue.Queue(4)\n"
        "    other.sleep(9)\n"        # non-time dotted receiver: legal
        "    time.sleep(0.1)  # serve-block-ok: fixture\n")
    hits = lint_hot_loop.lint_serve_overload_safety(root=tmp_path)
    assert {(rel, ln) for rel, ln, _text in hits} == {
        ("serve/engine.py", 6), ("serve/engine.py", 7),
        ("serve/engine.py", 8), ("serve/engine.py", 9),
        ("serve/engine.py", 12), ("serve/engine.py", 15)}


def test_obs_serve_section_includes_overload_block(tmp_path):
    """`cli obs`'s serve section surfaces the shed/deadline/restart/
    breaker counters and the overload gauge in the same block (the PR 9
    'replay' section style)."""
    from sharetrade_tpu.config import FrameworkConfig
    from sharetrade_tpu.obs import build_obs, summarize_run_dir

    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "run")
    registry = MetricsRegistry()
    bundle = build_obs(cfg, registry)
    registry.record_many({"serve_qps": 100.0, "serve_overload": 1.0,
                          "serve_swap_breaker_open": 0.0})
    registry.inc("serve_requests_total", 64)
    registry.inc("serve_shed_total", 5)
    registry.inc("serve_queue_rejected_total", 3)
    registry.inc("serve_deadline_expired_total", 2)
    registry.inc("serve_restarts_total", 1)
    registry.inc("serve_swap_breaker_opens_total", 1)
    bundle.flush()
    bundle.close()
    summary = summarize_run_dir(cfg.obs.dir)
    serve = summary["serve"]
    assert serve["shed_total"] == 5.0
    assert serve["queue_rejected_total"] == 3.0
    assert serve["deadline_expired_total"] == 2.0
    assert serve["restarts_total"] == 1.0
    assert serve["overload"] == 1.0
    assert serve["swap_breaker_open"] == 0.0
    assert serve["swap_breaker_opens_total"] == 1.0
    prom = (tmp_path / "run" / "metrics.prom").read_text()
    assert "sharetrade_serve_shed_total" in prom
    assert "sharetrade_serve_overload" in prom
