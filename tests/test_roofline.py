"""Roofline telemetry (obs/roofline.py) + perf gate (tools/perf_gate.py).

What this file pins, per the roofline PR's acceptance criteria:

- golden compiled-cost capture on the reference-shape MLP (CPU backend):
  XLA FLOPs, trip-count corrected, land within the analytic model's band;
- the live gauges (``mfu``/``achieved_tflops``/``hbm_gbps``/
  ``arithmetic_intensity``) reach the Prometheus textfile during an
  obs-enabled training run with ``obs.roofline=true``;
- ``obs.roofline=false`` (the default) produces ZERO roofline artifacts
  and no gauges — the knob is inert until asked for;
- the analytic-vs-XLA discrepancy warning fires (flight ring + log) on a
  deliberately wrong analytic count;
- ``tools/perf_gate.py`` passes on a self-baseline and fails on a
  synthetically regressed row — and passes on the repo's real BENCH
  trajectory (the ``make check`` wiring must not be red on day one);
- the compile-time-only lint (tools/lint_hot_loop.py check 6) stays
  green on the shipped tree.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.obs.roofline import (
    ARTIFACT,
    RooflineCapture,
    read_roofline,
    summarize_roofline,
)
from sharetrade_tpu.runtime import Orchestrator
from sharetrade_tpu.utils.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _cfg(tmp_path, *, roofline: bool = True, megachunk: int = 1,
         hidden: int = 200) -> FrameworkConfig:
    """Reference-shape-flavored qlearn config (10 workers, h=200 MLP by
    default — the shape whose matmuls dominate enough for the golden
    cross-check), shrunk to a seconds-long CPU episode."""
    cfg = FrameworkConfig()
    cfg.learner.algo = "qlearn"
    cfg.parallel.num_workers = 10
    cfg.model.hidden_dim = hidden
    cfg.env.window = 8
    cfg.runtime.chunk_steps = 16
    cfg.runtime.megachunk_factor = megachunk
    cfg.runtime.metrics_every_chunks = 2
    cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts")
    cfg.obs.enabled = True
    cfg.obs.roofline = roofline
    cfg.obs.dir = str(tmp_path / "obs")
    cfg.obs.export_interval_s = 0.1
    return cfg


def _train(cfg: FrameworkConfig, *, steps: int = 200) -> Orchestrator:
    orch = Orchestrator(cfg)
    orch.send_training_data(np.linspace(10.0, 20.0, steps,
                                        dtype=np.float32))
    orch.start_training(background=False)
    orch.stop()
    return orch


def test_golden_cost_capture_reference_mlp(tmp_path):
    """The tentpole's golden row: the captured chunk program's FLOPs are
    real numbers (trip-count corrected, not the loop-body-once HLO raw
    count) and agree with the analytic utils/flops.py model within the
    discrepancy band on the matmul-dominated reference MLP."""
    cfg = _cfg(tmp_path)
    _train(cfg)
    bundle = read_roofline(cfg.obs.dir)
    assert bundle is not None
    assert bundle["schema_version"] == 1
    assert bundle["ridge_flops_per_byte"] > 0
    chunk = bundle["programs"]["chunk"]
    assert chunk["flops"] > 0
    assert chunk["bytes_accessed"] > 0
    # Trip-count correction: the per-dispatch number must be the raw HLO
    # count scaled by the chunk's scan length (XLA counts loop bodies
    # once; obs/roofline.py probes and corrects).
    assert chunk["trip_count_corrected"]
    assert chunk["loop_iterations"] == cfg.runtime.chunk_steps
    assert chunk["flops"] == chunk["flops_hlo_once"] * cfg.runtime.chunk_steps
    # Golden cross-check: XLA within ±25% of the analytic model at h=200
    # (measured ~0.97 on the CPU backend; a drift past the band means one
    # of the countings broke).
    assert chunk["analytic_flops"] > 0
    assert not chunk["discrepancy"], (
        f"XLA vs analytic ratio {chunk['xla_vs_analytic']}")
    assert 0.75 <= chunk["xla_vs_analytic"] <= 1.25
    # Agreement keeps the measured XLA count as the gauge source.
    assert chunk["gauge_flops_source"] == "xla"
    assert chunk["gauge_flops"] == chunk["flops"]
    assert chunk["classification"] in ("compute-bound", "memory-bound")
    assert chunk["arithmetic_intensity"] == pytest.approx(
        chunk["flops"] / chunk["bytes_accessed"])


def test_megachunk_program_captured(tmp_path):
    cfg = _cfg(tmp_path, megachunk=2)
    _train(cfg)
    bundle = read_roofline(cfg.obs.dir)
    programs = bundle["programs"]
    assert set(programs) == {"chunk", "megachunk_k2"}
    mega = programs["megachunk_k2"]
    assert mega["megachunk_factor"] == 2
    assert mega["loop_iterations"] == 2 * cfg.runtime.chunk_steps
    # The fused program does K chunks' work: per-dispatch FLOPs ~2x the
    # single-chunk program (identical body, twice the iterations).
    ratio = mega["flops"] / programs["chunk"]["flops"]
    assert 1.8 <= ratio <= 2.2


def test_gauges_reach_prometheus_textfile(tmp_path):
    """Acceptance: mfu/achieved_tflops/hbm_gbps exported via the existing
    Prometheus textfile during a CPU training run with obs.roofline."""
    cfg = _cfg(tmp_path, megachunk=2)
    orch = _train(cfg)
    prom = open(os.path.join(cfg.obs.dir, "metrics.prom")).read()
    for gauge in ("sharetrade_mfu", "sharetrade_achieved_tflops",
                  "sharetrade_hbm_gbps", "sharetrade_arithmetic_intensity",
                  "sharetrade_roofline_compute_bound"):
        assert f"# TYPE {gauge} gauge" in prom, f"{gauge} missing"
    # And they are live numbers, not placeholders.
    assert orch.metrics.latest("mfu") > 0
    assert orch.metrics.latest("achieved_tflops") > 0
    assert orch.metrics.latest("hbm_gbps") > 0


def test_off_by_default_zero_artifacts(tmp_path):
    """obs.roofline=false (the default): no roofline.json, no gauges, no
    capture object — the rest of obs/ unaffected."""
    cfg = _cfg(tmp_path, roofline=False)
    assert FrameworkConfig().obs.roofline is False   # the default
    orch = _train(cfg)
    assert orch.obs.roofline is None
    assert not os.path.exists(os.path.join(cfg.obs.dir, ARTIFACT))
    assert orch.metrics.latest("mfu") is None
    prom = open(os.path.join(cfg.obs.dir, "metrics.prom")).read()
    assert "sharetrade_mfu" not in prom
    # The non-roofline obs surfaces still ran.
    assert os.path.isfile(os.path.join(cfg.obs.dir, "metrics.jsonl"))


def test_discrepancy_warning_fires_on_wrong_analytic(tmp_path):
    """A deliberately wrong analytic count must warn through the flight
    recorder and mark the program's artifact row."""
    from sharetrade_tpu.obs.flight import FlightRecorder

    flight = FlightRecorder(16)
    cap = RooflineCapture(MetricsRegistry(), str(tmp_path),
                          flight_record=flight.record)
    cap.steps_per_chunk = 4
    cap.analytic_flops_per_chunk = 1.0        # absurdly wrong on purpose

    def step(x):
        def body(c, _):
            return c @ c, None
        c, _ = jax.lax.scan(body, x, None, length=4)
        return c

    cost = cap.capture(jax.jit(step), (jnp.ones((16, 16)),))
    assert cost is not None and cost.discrepancy
    # The warning lands in the flight ring (the RingLogHandler mirrors
    # WARNING+ logs there in a real run; here the direct record is the
    # contract): a later forensic dump names the miscounted program.
    events = [e for e in flight.snapshot()
              if e["kind"] == "roofline_discrepancy"]
    assert events and events[0]["program"] == "chunk"
    assert events[0]["ratio"] == pytest.approx(cost.xla_vs_analytic)
    # On disagreement the live gauges switch to the analytic count (the
    # model-FLOPs MFU convention): a structurally mis-corrected XLA
    # number must not inflate the MFU gauge ~150x, as the flagship
    # episode-PPO program otherwise would (its trunk/replay FLOPs live
    # outside the chunk-steps scan).
    assert cost.gauge_flops_source == "analytic"
    assert cost.gauge_flops == cost.analytic_flops
    # And the artifact records the mismatch for post-hoc forensics.
    bundle = read_roofline(str(tmp_path))
    assert bundle["programs"]["chunk"]["discrepancy"] is True


def test_multichip_analytic_is_per_device():
    """cost_analysis() describes ONE device's partition of an SPMD
    program; the analytic (global) model must be divided by the mesh size
    before the cross-check, or every multichip run false-alarms."""
    cap = RooflineCapture(MetricsRegistry(), None,
                          peak_flops=1e12, peak_hbm_bw=1e9)
    cap._trip_blind = True
    cap.steps_per_chunk = 10
    cap.analytic_flops_per_chunk = 8000.0   # global work, 8 devices
    costs = {"flops": 100.0, "bytes_accessed": 100.0,
             "argument_bytes": None, "temp_bytes": None,
             "output_bytes": None}
    cost = cap._build_cost("chunk", 1, costs, devices=8)
    assert cost.devices == 8
    # corrected per-device XLA = 100*10 = 1000; analytic/8 = 1000.
    assert cost.analytic_flops == pytest.approx(1000.0)
    assert cost.xla_vs_analytic == pytest.approx(1.0)
    assert not cost.discrepancy


def test_mesh_cost_hook_passes_device_count():
    """The jit_parallel_step seam hands the mesh size to the capture (the
    forced-8-device CPU mesh, the shard-audit platform)."""
    import numpy as np
    from jax.sharding import Mesh

    from sharetrade_tpu.agents import build_agent
    from sharetrade_tpu.env import trading
    from sharetrade_tpu.parallel import jit_parallel_step

    cfg = FrameworkConfig()
    cfg.learner.algo = "qlearn"
    cfg.env.window = 8
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 8
    cfg.runtime.chunk_steps = 4
    env = trading.env_from_prices(jnp.linspace(10.0, 20.0, 64),
                                  window=cfg.env.window)
    agent = build_agent(cfg, env)
    devices = np.asarray(jax.devices("cpu")[:8])
    mesh = Mesh(devices, ("dp",))
    cap = RooflineCapture(MetricsRegistry(), None)
    cap.steps_per_chunk = cfg.runtime.chunk_steps
    ts = agent.init(jax.random.PRNGKey(0))
    jit_parallel_step(agent, mesh, ts, cost_hook=cap.capture)
    assert cap.programs["chunk"].devices == 8


def test_capture_failure_degrades_not_raises(tmp_path):
    cap = RooflineCapture(MetricsRegistry(), str(tmp_path))
    assert cap.capture(object(), ()) is None   # not a jitted fn: swallowed


def test_on_boundary_without_capture_is_noop():
    reg = MetricsRegistry()
    cap = RooflineCapture(reg, None)
    cap.on_boundary(k=1, chunk_seconds=0.1)    # nothing captured yet
    cap.on_boundary(k=1, chunk_seconds=None)   # first tick has no timing
    assert reg.snapshot() == {}


def test_cli_obs_summarizes_roofline_and_counters(tmp_path, capsys):
    from sharetrade_tpu import cli

    cfg = _cfg(tmp_path, megachunk=2)
    _train(cfg)
    assert cli.main(["obs", "--dir", cfg.obs.dir]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert "roofline" in summary
    roof = summary["roofline"]
    assert roof["programs"] == 2
    named = [p["program"]
             for p in roof["compute_bound"] + roof["memory_bound"]]
    assert set(named) == {"chunk", "megachunk_k2"}
    # Counter totals surfaced (the cli-obs satellite): totals dict plus
    # the explicit pipeline health number.
    assert "counters" in summary["metrics"]
    assert "pipeline_stalls_total" in summary["metrics"]


def test_summarize_roofline_orders_by_flops():
    bundle = {
        "schema_version": 1, "ridge_flops_per_byte": 240.0,
        "programs": {
            "a": {"flops": 10.0, "bytes_accessed": 1.0,
                  "arithmetic_intensity": 10.0,
                  "classification": "memory-bound"},
            "b": {"flops": 1000.0, "bytes_accessed": 1.0,
                  "arithmetic_intensity": 1000.0,
                  "classification": "compute-bound"},
        },
    }
    s = summarize_roofline(bundle)
    assert s["compute_bound"][0]["program"] == "b"
    assert s["memory_bound"][0]["program"] == "a"


# ---------------------------------------------------------------------------
# perf gate
# ---------------------------------------------------------------------------

def _snapshot(path, n, metric, value, mfu=None, backend=None):
    parsed = {"metric": metric, "value": value, "schema_version": 1,
              "backend": backend or "cpu"}
    if mfu is not None:
        parsed["mfu"] = mfu
    path.write_text(json.dumps({"n": n, "parsed": parsed}))


def test_perf_gate_passes_on_self_baseline(tmp_path):
    import perf_gate

    _snapshot(tmp_path / "BENCH_r01.json", 1, "m", 100.0, mfu=0.1)
    _snapshot(tmp_path / "BENCH_r02.json", 2, "m", 100.0, mfu=0.1)
    assert perf_gate.run_gate(tmp_path) == 0


def test_perf_gate_fails_on_degraded_row(tmp_path, capsys):
    import perf_gate

    _snapshot(tmp_path / "BENCH_r01.json", 1, "m", 100.0, mfu=0.1)
    _snapshot(tmp_path / "BENCH_r02.json", 2, "m", 50.0, mfu=0.1)
    assert perf_gate.run_gate(tmp_path) == 1
    assert "FAIL" in capsys.readouterr().out


def test_perf_gate_fails_on_mfu_regression_alone(tmp_path):
    import perf_gate

    _snapshot(tmp_path / "BENCH_r01.json", 1, "m", 100.0, mfu=0.2)
    _snapshot(tmp_path / "BENCH_r02.json", 2, "m", 101.0, mfu=0.05)
    assert perf_gate.run_gate(tmp_path) == 1


def test_perf_gate_separates_backends(tmp_path):
    """A CPU-fallback round must not gate against TPU-era numbers: the
    r04/r05 outage pattern — huge apparent 'regression', different
    backend — stays a note, not a failure."""
    import perf_gate

    _snapshot(tmp_path / "BENCH_r01.json", 1, "m", 100000.0, backend="tpu")
    _snapshot(tmp_path / "BENCH_r02.json", 2, "m", 100.0, backend="cpu")
    assert perf_gate.run_gate(tmp_path) == 0


def test_perf_gate_legacy_fallback_parser(tmp_path):
    """Pre-schema snapshots (no schema_version, cpu_fallback subtree, raw
    tail line) parse through the fallback path."""
    import perf_gate

    # Legacy TPU row (r01 shape).
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "parsed": {"metric": "m", "value": 200.0}}))
    # Parse-failed snapshot whose tail still holds the JSON line.
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "tail": "noise\n" + json.dumps(
            {"metric": "m", "value": 195.0}) + "\n"}))
    # Error round with a cpu_fallback subtree (r05 shape).
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "n": 3, "parsed": {"error": "tunnel down", "cpu_fallback": {
            "metric": "m", "value": 50.0, "backend": "cpu"}}}))
    snap1 = perf_gate.parse_bench_file(str(tmp_path / "BENCH_r01.json"))
    assert snap1["rows"] == [{"metric": "m", "value": 200.0,
                              "backend": "tpu"}]
    snap3 = perf_gate.parse_bench_file(str(tmp_path / "BENCH_r03.json"))
    assert snap3["rows"][0]["backend"] == "cpu"
    assert perf_gate.run_gate(tmp_path) == 0   # 200 -> 195 within band


def test_perf_gate_candidate_row(tmp_path):
    import perf_gate

    _snapshot(tmp_path / "BENCH_r01.json", 1, "m", 100.0)
    cand = tmp_path / "candidate.json"
    cand.write_text(json.dumps({"metric": "m", "value": 10.0,
                                "schema_version": 1, "backend": "cpu"}))
    assert perf_gate.run_gate(tmp_path, candidate=str(cand)) == 1


def test_perf_gate_passes_on_repo_trajectory():
    """The make-check wiring: the gate must be green on the checked-in
    BASELINE.json + BENCH_r01..r05 trajectory."""
    import perf_gate

    assert perf_gate.run_gate(REPO) == 0


def test_roofline_lint_green():
    """tools/lint_hot_loop.py check 6 on the shipped tree: no capture
    sites in the dispatcher or traced closures."""
    import lint_hot_loop

    assert lint_hot_loop.lint_roofline_capture() == []


def test_shard_audit_manifest_has_roofline_rows():
    """The manifest the audit gates against carries FLOPs/HBM rows for
    every config in the matrix (regenerated with --update)."""
    with open(os.path.join(REPO, "tools",
                           "shard_audit_manifest.json")) as f:
        manifest = json.load(f)
    for name, entry in manifest["configs"].items():
        cost = entry.get("cost")
        assert cost, f"{name} missing roofline cost row"
        assert cost.get("flops", 0) > 0, f"{name} flops not recorded"
        assert cost.get("hbm_peak_bytes", 0) > 0
