"""Golden end-to-end run over the committed price fixture.

Reproduces the reference's observable flow (SURVEY.md §3.1-3.4): load the
6,046-row price CSV, filter 1992-01-01..2015-01-01 (the driver's requested
range, ShareTradeHelper.scala:23), train 10 workers over the full episode,
and report the avg/std portfolio aggregation (ShareTradeHelper.scala:46) —
through the public CLI, no test harness shortcuts. The fixture is a frozen
generated series (tools/make_fixture.py), not the reference's data file.
"""

import json
import os

import numpy as np
import pytest

from sharetrade_tpu import cli
from sharetrade_tpu.data.service import PriceDataService
from sharetrade_tpu.config import FrameworkConfig

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "data", "fixtures", "msft-synth-prices.csv")
HIST_FIXTURE = os.path.join(os.path.dirname(FIXTURE), "msft-hist-shaped.csv")
START, END = "1992-01-01", "2015-01-01"
FIXTURE_ROWS = 6046        # full file (reference fixture's line count)
RANGE_ROWS = 5857          # rows inside the driver's requested date range
WINDOW = 201


def _train_args(tmp_path, tag):
    return [
        "train", "--symbol", "MSFT", "--start", START, "--end", END,
        "--set", f"data.csv_path={FIXTURE}",
        "--set", f"data.journal_dir={tmp_path}/journal-{tag}",
        "--set", f"runtime.checkpoint_dir={tmp_path}/ckpts-{tag}",
        "--set", "runtime.chunk_steps=512",
    ]


class TestDataLayerGolden:
    def test_fixture_loads_and_filters(self, tmp_path):
        cfg = FrameworkConfig()
        cfg.data.csv_path = FIXTURE
        cfg.data.journal_dir = str(tmp_path / "journal")
        service = PriceDataService(config=cfg.data)
        full = service.request("MSFT")
        assert len(full.series) == FIXTURE_ROWS
        ranged = service.request("MSFT", START, END)
        assert len(ranged.series) == RANGE_ROWS
        assert str(ranged.series.dates[0]) >= START
        assert str(ranged.series.dates[-1]) <= END
        service.close()

    def test_query_subcommand(self, tmp_path, capsys):
        rc = cli.main(["query", "--symbol", "MSFT", "--start", START,
                       "--end", END,
                       "--set", f"data.csv_path={FIXTURE}",
                       "--set", f"data.journal_dir={tmp_path}/journal"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out == {"symbol": "MSFT", "rows": RANGE_ROWS,
                       "first": "1992-07-22", "last": "2015-01-01"}


@pytest.mark.slow
class TestEndToEndGolden:
    def _run(self, tmp_path, capsys, tag):
        rc = cli.main(_train_args(tmp_path, tag))
        assert rc == 0
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    def test_reference_flow_and_determinism(self, tmp_path, capsys):
        result = self._run(tmp_path, capsys, "a")
        # The full episode ran: range rows minus the observation window.
        assert result["env_steps"] == RANGE_ROWS - WINDOW
        assert result["updates"] == RANGE_ROWS - WINDOW
        assert np.isfinite(result["avg_portfolio"])
        assert result["avg_portfolio"] > 0
        assert np.isfinite(result["std_portfolio"])
        assert result["restarts"] == 0
        # Determinism: an identical fresh run reproduces the aggregation
        # bit-for-bit (seeded RNG end to end; no host-side nondeterminism).
        again = self._run(tmp_path, capsys, "b")
        assert again["avg_portfolio"] == result["avg_portfolio"]
        assert again["std_portfolio"] == result["std_portfolio"]

    def test_historical_shaped_data_trains(self, tmp_path, capsys):
        """The reference replays 23 years of REAL market dynamics every run
        (MSFT-stock-prices-revised.txt); the synthetic-walk fixture can't
        represent that regime. msft-hist-shaped.csv is a committed
        reconstruction of the real trajectory's documented milestones
        (tools/make_historical_fixture.py — dot-com run-up/crash, flat
        decade, GFC drawdown, recovery, a trading calendar with gaps), and
        the golden CLI flow must train over it end to end."""
        prices = np.array([float(l.split(",")[0])
                           for l in open(HIST_FIXTURE)])
        dates = [l.split(",")[1].strip() for l in open(HIST_FIXTURE)]
        # The features the walk lacks, asserted so the fixture can't quietly
        # regress into another featureless series:
        assert prices.max() / prices.min() > 10.0     # order-of-magnitude drift
        peak_to_trough = 1.0 - prices[np.argmax(prices):].min() / prices.max()
        assert peak_to_trough > 0.5                   # a real crash
        gaps = np.diff([np.datetime64(d) for d in dates]).astype(int)
        assert (gaps > 1).any() and (gaps >= 3).any()  # holidays + weekends

        rc = cli.main([
            "train", "--symbol", "MSFT", "--start", START, "--end", END,
            "--set", f"data.csv_path={HIST_FIXTURE}",
            "--set", f"data.journal_dir={tmp_path}/journal-hist",
            "--set", f"runtime.checkpoint_dir={tmp_path}/ckpts-hist",
            "--set", "runtime.chunk_steps=512",
        ])
        assert rc == 0
        result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert result["env_steps"] == len(prices) - WINDOW
        assert np.isfinite(result["avg_portfolio"])
        assert result["avg_portfolio"] > 0
        assert result["restarts"] == 0

    def test_resume_completes_consistently(self, tmp_path, capsys):
        """Train to completion, then --resume from the final checkpoint:
        the resumed run restores params/opt/RNG/env cursor and reports the
        same aggregation (the reference's stubbed saveSnapshot made real,
        QDecisionPolicyActor.scala:74,91-93)."""
        result = self._run(tmp_path, capsys, "c")
        rc = cli.main(_train_args(tmp_path, "c") + ["--resume"])
        assert rc == 0
        resumed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        # The checkpoint holds the completed episode: nothing left to train,
        # and the portfolio aggregation is preserved across the restore.
        assert resumed["avg_portfolio"] == pytest.approx(
            result["avg_portfolio"], rel=1e-6)
