"""Device-resident megachunk tests (runtime.megachunk_factor).

The contract under test: K chunks fused into one compiled ``lax.scan``
(agents/base.py ``megachunk_step``) are BIT-IDENTICAL to K host-dispatched
chunks — TrainState and per-chunk metric stream both — while the
orchestrator's supervision semantics (fault attribution by true chunk
index, restart/backoff, exact episode completion) survive at megachunk
granularity, with the documented near-episode-end fallback to K=1.
"""

import importlib.util
import pathlib

import jax
import numpy as np
import pytest

from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.runtime import Orchestrator, ReplyState, run_end_to_end

WINDOW = 8
#: 256-step episode: long enough that a K=8 megachunk engages for the first
#: half (upper bound 8 x 16 = 128 < 256) and the loop then falls back to
#: K=1 singles for the exact completion approach.
PRICES = np.linspace(10.0, 20.0, 264, dtype=np.float32)
#: 512-step episode: cruise region wide enough for double-buffered dispatch
#: (the prefetch guard needs TWO megachunks of headroom below the threshold).
LONG_PRICES = np.linspace(10.0, 20.0, 520, dtype=np.float32)


def fast_cfg(tmp_path, *, megachunk=1, algo="qlearn"):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 8
    cfg.parallel.num_workers = 4
    cfg.runtime.chunk_steps = 16
    cfg.runtime.checkpoint_every_updates = 64
    cfg.runtime.checkpoint_dir = str(tmp_path / f"ckpts_k{megachunk}")
    cfg.runtime.backoff_initial_s = 0.01
    cfg.runtime.backoff_max_s = 0.05
    cfg.runtime.max_restarts = 3
    cfg.runtime.metrics_every_chunks = 1   # per-chunk stream for parity
    cfg.runtime.megachunk_factor = megachunk
    return cfg


def _assert_states_identical(a, b):
    for la, lb in zip(jax.tree.leaves(jax.device_get(a)),
                      jax.tree.leaves(jax.device_get(b))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestMegachunkStep:
    """agents/base.py megachunk_step in isolation."""

    def test_fused_matches_sequential_bitwise(self, tmp_path):
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.agents.base import megachunk_step
        from sharetrade_tpu.env import trading
        cfg = fast_cfg(tmp_path)
        env = trading.make_trading_env(
            PRICES, window=WINDOW, initial_budget=cfg.env.initial_budget,
            initial_shares=0)
        agent = build_agent(cfg, env)
        k = 4

        step = jax.jit(agent.step)
        ts_seq = agent.init(jax.random.PRNGKey(7))
        last_metrics = None
        for _ in range(k):
            ts_seq, last_metrics = step(ts_seq)

        fused = jax.jit(megachunk_step(agent.step, k))
        ts_fused, stacked = fused(agent.init(jax.random.PRNGKey(7)))

        _assert_states_identical(ts_seq, ts_fused)
        # Metrics stack along a leading (K,) axis; the last row is the
        # boundary row the orchestrator snapshots.
        for key, v in stacked.items():
            assert np.asarray(v).shape[0] == k, key
            np.testing.assert_array_equal(
                np.asarray(v)[-1], np.asarray(last_metrics[key]))

    def test_factor_below_one_rejected(self):
        from sharetrade_tpu.agents.base import megachunk_step
        with pytest.raises(ValueError, match="factor"):
            megachunk_step(lambda ts: (ts, {}), 0)


class TestOrchestratorParity:
    def test_k8_bit_identical_to_k1(self, tmp_path):
        """The acceptance row: megachunk_factor=8 produces the SAME
        TrainState and the SAME per-chunk metric stream as K=1 on a fixed
        seed — one fused scan per 8 chunks is a pure dispatch-count
        optimization, not a numerics change."""
        runs = {}
        for k in (1, 8):
            orch = run_end_to_end(fast_cfg(tmp_path, megachunk=k), PRICES)
            assert orch.is_everything_done().state is ReplyState.COMPLETED
            assert orch.restarts == 0
            runs[k] = orch
        _assert_states_identical(runs[1].train_state, runs[8].train_state)
        for key in ("loss", "env_steps", "updates", "reward_sum",
                    "portfolio_mean", "portfolio_std"):
            s1 = [v for _, v in runs[1].metrics.series(key)]
            s8 = [v for _, v in runs[8].metrics.series(key)]
            assert s1 == s8, f"metric stream diverged for {key!r}"
        assert runs[1].get_avg().value == runs[8].get_avg().value

    def test_double_buffer_bit_identical(self, tmp_path):
        """double_buffer_dispatch only reorders HOST work (readback overlaps
        the in-flight megachunk); device results must stay bit-identical."""
        plain = run_end_to_end(fast_cfg(tmp_path, megachunk=8), LONG_PRICES)
        cfg = fast_cfg(tmp_path, megachunk=8)
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts_db")
        cfg.runtime.double_buffer_dispatch = True
        buffered = run_end_to_end(cfg, LONG_PRICES)
        for orch in (plain, buffered):
            assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert int(buffered.train_state.env_steps) == len(LONG_PRICES) - WINDOW
        _assert_states_identical(plain.train_state, buffered.train_state)
        s1 = [v for _, v in plain.metrics.series("loss")]
        s2 = [v for _, v in buffered.metrics.series("loss")]
        assert s1 == s2

    def test_mesh_megachunk_matches_singles(self, tmp_path, cpu_mesh):
        """The pjit composition (parallel/sharding.py): a K-chunk scan
        compiled INSIDE the sharded program equals K single sharded steps."""
        from sharetrade_tpu.agents import build_agent
        from sharetrade_tpu.env import trading
        from sharetrade_tpu.parallel import make_parallel_step
        cfg = fast_cfg(tmp_path)
        cfg.parallel.num_workers = 8           # divisible by the dp mesh
        env = trading.make_trading_env(
            PRICES, window=WINDOW, initial_budget=cfg.env.initial_budget,
            initial_shares=0)
        agent = build_agent(cfg, env)
        k = 4

        place, step = make_parallel_step(agent, cpu_mesh)
        ts_seq = place(agent.init(jax.random.PRNGKey(3)))
        for _ in range(k):
            ts_seq, metrics = step(ts_seq)

        place_k, mega = make_parallel_step(agent, cpu_mesh,
                                           megachunk_factor=k)
        ts_fused, stacked = mega(place_k(agent.init(jax.random.PRNGKey(3))))

        _assert_states_identical(ts_seq, ts_fused)
        np.testing.assert_array_equal(
            np.asarray(stacked["env_steps"])[-1],
            np.asarray(metrics["env_steps"]))


class TestSupervisionAtMegachunkGranularity:
    def test_fault_mid_megachunk_fires_with_true_chunk_index(self, tmp_path):
        """A fault landing on an inner chunk surfaces at the megachunk
        boundary but is attributed to the chunk that raised it, and the
        restarted loop retries from that same chunk index — the reference's
        PoisonPill chaos seam preserved at megachunk granularity."""
        cfg = fast_cfg(tmp_path, megachunk=4)
        seen, fired = [], []

        def chaos(chunk_idx, metrics):
            seen.append(chunk_idx)
            if chunk_idx == 2 and not fired:
                fired.append(1)
                raise RuntimeError("injected mid-megachunk PoisonPill")

        orch = Orchestrator(cfg, fault_hook=chaos)
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 1
        # Inner chunks 0 and 1 were processed from the stacked rows, the
        # fault fired at TRUE index 2, and the post-restore loop retried
        # chunk 2 (same index), not 4 (the already-dispatched boundary).
        assert seen[:4] == [0, 1, 2, 2]

    def test_heal_under_double_buffer_not_double_counted(self, tmp_path):
        """double_buffer_dispatch keeps one megachunk in flight past the
        boundary that heals a poisoned row; the in-flight rows were computed
        PRE-heal and still report the quarantined row. That stale report
        must not re-trigger healing (no bad rows would be found, spuriously
        escalating to a full checkpoint restore): one heal, zero restarts."""
        cfg = fast_cfg(tmp_path, megachunk=8)
        cfg.runtime.double_buffer_dispatch = True
        orch = Orchestrator(cfg)
        orch.send_training_data(LONG_PRICES)
        # Poison one wallet BEFORE the loop starts: the quarantine masks the
        # row on-device from chunk 0, and with double buffering the second
        # megachunk is dispatched before the first boundary's heal runs.
        ts = orch._ts
        budget = np.asarray(jax.device_get(ts.env_state.budget)).copy()
        budget[2] = np.nan
        orch._ts = ts.replace(env_state=ts.env_state.replace(
            budget=jax.numpy.asarray(budget)))
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.agent_heals == 1
        assert orch.restarts == 0
        assert orch.snapshot()["unhealthy_workers"] == 0

    def test_completion_gate_never_overshoots(self, tmp_path):
        """Two episodes under K=8 with sampling coarser than the run: the
        upper-bound guard must fall back to single chunks near each episode
        threshold, completing at EXACTLY episodes x horizon env steps with
        exactly the K=1 chunk count (no fused overshoot past a re-arm)."""
        import json
        from sharetrade_tpu.utils.logging import EventLog
        cfg = fast_cfg(tmp_path, megachunk=8)
        cfg.runtime.metrics_every_chunks = 1000
        cfg.runtime.episodes = 2
        events_path = str(tmp_path / "events.jsonl")
        orch = Orchestrator(cfg, event_log=EventLog(events_path))
        orch.send_training_data(PRICES)
        orch.start_training(background=False)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0
        horizon = len(PRICES) - WINDOW
        done = [json.loads(l) for l in open(events_path)
                if json.loads(l)["kind"] == "training_completed"][0]
        assert done["env_steps"] == 2 * horizon       # exact, no overshoot
        chunks_per_episode = -(-horizon // cfg.runtime.chunk_steps)
        assert done["chunks_timed"] == 2 * chunks_per_episode

    def test_factor_shorter_than_episode_always_falls_back(self, tmp_path):
        """A megachunk that cannot fit below the first threshold (K x
        chunk_steps >= horizon) must transparently run the K=1 path for the
        whole episode — same completion, same results as factor 1."""
        short = np.linspace(10.0, 20.0, 72, dtype=np.float32)  # horizon 64
        base = run_end_to_end(fast_cfg(tmp_path, megachunk=1), short)
        cfg = fast_cfg(tmp_path, megachunk=8)
        cfg.runtime.checkpoint_dir = str(tmp_path / "ckpts_fb")
        fb = run_end_to_end(cfg, short)
        assert fb.is_everything_done().state is ReplyState.COMPLETED
        _assert_states_identical(base.train_state, fb.train_state)

    def test_invalid_factor_rejected_at_construction(self, tmp_path):
        cfg = fast_cfg(tmp_path)
        cfg.runtime.megachunk_factor = 0
        with pytest.raises(ConfigError, match="megachunk_factor"):
            Orchestrator(cfg)


class TestJournaledTransitionsAcrossMegachunks:
    def test_dqn_journal_rows_exactly_once(self, tmp_path):
        """DQN journaling under K=4: the stacked (K, T, B, ...) transition
        batch is journaled per inner chunk from the single batched readback,
        keeping the exactly-once row count of the K=1 path."""
        cfg = fast_cfg(tmp_path, megachunk=4, algo="dqn")
        cfg.runtime.chunk_steps = 8
        cfg.learner.journal_replay = True
        cfg.learner.replay_capacity = 4096
        cfg.learner.replay_batch = 8
        cfg.data.journal_dir = str(tmp_path / "journal")
        prices = np.linspace(10.0, 20.0, 72, dtype=np.float32)  # horizon 64
        orch = run_end_to_end(cfg, prices)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        horizon = len(prices) - WINDOW
        assert (int(orch.train_state.extras.replay.size)
                == horizon * cfg.parallel.num_workers)
        from sharetrade_tpu.data.transitions import read_tail_transitions
        tail = read_tail_transitions(
            f"{cfg.data.journal_dir}/transitions.journal", 0)  # unbounded
        assert tail is not None
        assert tail[0].shape[0] == horizon * cfg.parallel.num_workers
        orch.stop()


def test_hot_loop_sync_lint_passes():
    """tools/lint_hot_loop.py is the guard that keeps bare scalar device
    syncs out of _run_supervised; run it as part of tier-1 so a regression
    fails CI, not just `make check`."""
    tool = (pathlib.Path(__file__).resolve().parent.parent
            / "tools" / "lint_hot_loop.py")
    spec = importlib.util.spec_from_file_location("lint_hot_loop", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


@pytest.mark.slow
class TestMegachunkSoak:
    def test_k64_soak_completes_exactly(self, tmp_path):
        """K=64 with tiny chunks: 256 chunks collapse to a handful of host
        dispatches; the run must still complete at the exact horizon."""
        cfg = fast_cfg(tmp_path, megachunk=64)
        cfg.runtime.chunk_steps = 4
        cfg.runtime.metrics_every_chunks = 64
        prices = np.linspace(10.0, 20.0, 1032, dtype=np.float32)
        orch = run_end_to_end(cfg, prices)
        assert orch.is_everything_done().state is ReplyState.COMPLETED
        assert orch.restarts == 0
        assert int(orch.train_state.env_steps) == len(prices) - WINDOW
