"""Per-request serve observability (ISSUE 11): stage-stamped request
traces, histogram-sourced SLO gauges, exemplars, and burn-rate
monitoring.

Acceptance contracts pinned here:

- with tracing on, EVERY terminal request — completed, shed, expired,
  failed — emits a ``serve_request`` trace span carrying its outcome,
  and a completed request's stage spans sum to its end-to-end latency
  (the telescoping-stamp invariant, also self-checked by the engine's
  ``serve_trace_decomposition_error_total`` counter, which the soaks
  assert stays 0);
- ``serve_p50_ms``/``serve_p99_ms`` gauges now come from the mergeable
  end-to-end histogram (per-window bucket deltas);
- exemplar ring bounded at ``obs.exemplar_k`` per window, stage
  breakdown included, exported to ``serve_exemplars.json``;
- SLO burn gauges + a flight-ring event on threshold crossing;
- obs off ⇒ zero artifacts; ``obs.request_trace=false`` ⇒ histograms
  and exemplars but no per-request spans;
- lint check 11 (bounded trace buffers) clean on the tree.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from sharetrade_tpu.config import (
    ConfigError,
    FrameworkConfig,
    ModelConfig,
    ServeConfig,
)
from sharetrade_tpu.models import build_model
from sharetrade_tpu.obs import build_obs, read_trace, summarize_run_dir
from sharetrade_tpu.serve.engine import (
    ServeDeadlineExceeded,
    ServeEngine,
    ServeRejected,
)
from sharetrade_tpu.utils.metrics import MetricsRegistry

OBS_DIM = 10


@pytest.fixture(scope="module")
def mlp_bundle():
    model = build_model(ModelConfig(kind="mlp", hidden_dim=8), OBS_DIM,
                        head="ac")
    return model, model.init(jax.random.PRNGKey(0))


def obs_for(i: float = 10.0) -> np.ndarray:
    return np.full((OBS_DIM,), i, np.float32)


def make_cfg(tmp_path, **obs_overrides):
    cfg = FrameworkConfig()
    cfg.obs.enabled = True
    cfg.obs.dir = str(tmp_path / "obs")
    cfg.obs.export_interval_s = 0.1
    for key, value in obs_overrides.items():
        setattr(cfg.obs, key, value)
    cfg.serve = ServeConfig(max_batch=4, slots=8, batch_timeout_ms=1.0,
                            swap_poll_s=0.0, stats_interval_s=0.1)
    return cfg


def build_engine(tmp_path, mlp_bundle, *, serve_cfg=None, **obs_overrides):
    model, params = mlp_bundle
    cfg = make_cfg(tmp_path, **obs_overrides)
    if serve_cfg is not None:
        cfg.serve = serve_cfg
    registry = MetricsRegistry()
    obs = build_obs(cfg, registry)
    engine = ServeEngine(model, cfg.serve, params, registry=registry,
                         obs=obs, obs_cfg=cfg.obs)
    engine.warmup()
    return engine, registry, obs, cfg


class TestRequestTraces:
    def test_every_terminal_outcome_traced(self, tmp_path, mlp_bundle):
        """Completed, expired (pre-dispatch deadline), and rejected
        (queue_full behind a stalled consumer) requests ALL leave a
        serve_request span naming their outcome."""
        model, params = mlp_bundle
        serve_cfg = ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0,
                                swap_poll_s=0.0, stats_interval_s=0.1,
                                max_queue=2, shed_policy="reject")
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, serve_cfg=serve_cfg)
        # Sequential submit-wait: max_queue is 2 here, so a burst of
        # healthy submits would itself shed — this phase wants completions.
        handles = []
        for i in range(6):
            h = engine.submit(f"ok{i}", obs_for())
            handles.append(h)
            assert h.wait(30.0) is not None
        # Negative deadline = already expired at submit; expires at
        # collection, never dispatched.
        expired = engine.submit("late", obs_for(), deadline_ms=-1.0)
        assert expired.wait(30.0) is None
        assert isinstance(expired.error, ServeDeadlineExceeded)
        # Stall the consumer, then flood past max_queue: rejections.
        engaged = threading.Event()

        def stall(_r):
            engaged.set()
            time.sleep(0.25)

        stalled = engine.submit("stall", obs_for(), callback=stall)
        assert engaged.wait(20.0)
        flood = [engine.submit(f"f{i}", obs_for()) for i in range(40)]
        rejected = 0
        for h in flood:
            h.wait(30.0)
            if isinstance(h.error, ServeRejected):
                rejected += 1
        assert rejected > 0
        assert stalled.wait(20.0) is not None
        engine.stop()
        obs.flush()
        obs.close()

        events = read_trace(os.path.join(cfg.obs.dir, "trace.jsonl"))
        spans = [e for e in events if e.get("name") == "serve_request"]
        total = len(handles) + 1 + 1 + len(flood)
        assert len(spans) == total
        outcomes = [e["args"]["outcome"] for e in spans]
        assert outcomes.count("expired") == 1
        assert outcomes.count("queue_full") == rejected
        assert outcomes.count("completed") == total - 1 - rejected
        # Completed spans carry batch/session keys; their stage child
        # spans sum to the envelope duration (readback rides after the
        # latency-defining device edge, inside the envelope).
        by_req: dict = {}
        for e in events:
            if e.get("ph") == "X" and "args" in e \
                    and "request" in e.get("args", {}):
                by_req.setdefault(e["args"]["request"], {})[e["name"]] = e
        completed_reqs = [e["args"]["request"] for e in spans
                          if e["args"]["outcome"] == "completed"]
        for rid in completed_reqs:
            group = by_req[rid]
            assert {"serve_request", "queue_wait", "batch_wait",
                    "device", "readback"} <= set(group)
            stage_sum = sum(group[n]["dur"] for n in
                            ("queue_wait", "batch_wait", "device",
                             "readback"))
            assert stage_sum == pytest.approx(
                group["serve_request"]["dur"], abs=1.0)   # µs units
            assert group["serve_request"]["args"]["batch"] >= 1
            assert "session" in group["serve_request"]["args"]

    def test_stage_decomposition_exact_and_counter_zero(
            self, tmp_path, mlp_bundle):
        engine, registry, obs, cfg = build_engine(tmp_path, mlp_bundle)
        for i in range(30):
            r = engine.submit(f"s{i % 5}", obs_for()).wait(30.0)
            assert r is not None
            assert set(r.stages) == {"queue_wait_ms", "batch_wait_ms",
                                     "device_ms"}
            assert sum(r.stages.values()) == pytest.approx(
                r.latency_ms, abs=1e-6)
        engine.stop()
        obs.close()
        assert registry.counters().get(
            "serve_trace_decomposition_error_total", 0) == 0
        # Histograms saw every completed request, and the gauges came
        # from them.
        assert registry.histogram("serve_request_ms").count == 30
        for stage in ("queue_wait", "batch_wait", "device", "readback"):
            assert registry.histogram(f"serve_{stage}_ms").count == 30
        assert registry.latest("serve_p50_ms") > 0
        assert registry.latest("serve_p99_ms") >= registry.latest(
            "serve_p50_ms")

    def test_request_trace_knob_off_keeps_histograms(
            self, tmp_path, mlp_bundle):
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, request_trace=False)
        assert engine.submit("a", obs_for()).wait(30.0) is not None
        engine.stop()
        obs.flush()
        obs.close()
        events = read_trace(os.path.join(cfg.obs.dir, "trace.jsonl"))
        assert not any(e.get("name") == "serve_request" for e in events)
        assert registry.histogram("serve_request_ms").count == 1
        assert os.path.isfile(
            os.path.join(cfg.obs.dir, "serve_exemplars.json"))

    def test_obs_off_zero_artifacts(self, tmp_path, mlp_bundle):
        model, params = mlp_bundle
        cfg = ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0,
                          swap_poll_s=0.0, stats_interval_s=0.1)
        engine = ServeEngine(model, cfg, params)
        engine.warmup()
        r = engine.submit("a", obs_for()).wait(30.0)
        assert r is not None and r.stages is not None    # stamps always on
        engine.stop()
        assert engine._req_tracer is None
        assert list(tmp_path.iterdir()) == []            # nothing written


class TestExemplars:
    def test_ring_bounded_sorted_with_stages(self, tmp_path, mlp_bundle):
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, exemplar_k=2)
        for i in range(40):
            assert engine.submit(f"e{i % 6}", obs_for()).wait(30.0)
        engine.stop()
        obs.close()
        ex = engine.exemplars()
        # Ring bound: 4 windows x K plus the in-progress window's K.
        assert 0 < len(ex) <= 4 * 2 + 2
        lats = [e["latency_ms"] for e in ex]
        assert lats == sorted(lats, reverse=True)
        assert all({"queue_wait_ms", "batch_wait_ms", "device_ms"}
                   <= set(e["stages"]) for e in ex)
        artifact = json.load(open(
            os.path.join(cfg.obs.dir, "serve_exemplars.json")))
        assert artifact["exemplars"][0]["latency_ms"] == lats[0]

    def test_exemplar_k_zero_disables(self, tmp_path, mlp_bundle):
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, exemplar_k=0)
        assert engine.submit("a", obs_for()).wait(30.0)
        engine.stop()
        obs.close()
        assert engine.exemplars() == []


class TestSlo:
    def test_burn_gauges_and_flight_event(self, tmp_path, mlp_bundle):
        """Half the traffic expires against a 0.9 availability
        objective: availability burn >> 1, one alert (hysteresis), the
        flight ring carries the slo_burn event with exemplars."""
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, slo_availability=0.9,
            slo_target_p99_ms=10_000.0, slo_window_s=60.0,
            slo_burn_threshold=2.0)
        for i in range(10):
            assert engine.submit(f"g{i}", obs_for()).wait(30.0)
        bad = [engine.submit(f"b{i}", obs_for(), deadline_ms=-1.0)
               for i in range(10)]
        for h in bad:
            h.wait(30.0)
            assert isinstance(h.error, ServeDeadlineExceeded)
        time.sleep(0.3)                     # let a stats window publish
        engine.stop()
        obs.close()
        burn = registry.latest("serve_slo_availability_burn")
        # 10 bad / 20 total against a 10% budget = burn 5.0.
        assert burn is not None and burn > 2.0
        assert registry.latest("serve_slo_latency_burn") == 0.0
        assert registry.counters()["serve_slo_burn_alerts_total"] == 1
        kinds = [e for e in obs.flight.snapshot()
                 if e["kind"] == "slo_burn"]
        assert len(kinds) == 1
        assert kinds[0]["burns"]["availability"] > 2.0
        assert "exemplars" in kinds[0]

    def test_burn_updates_during_total_outage(self, tmp_path, mlp_bundle):
        """The availability-SLO scenario that matters most is a TOTAL
        outage — and there no batch ever completes, so the consumer-thread
        publish never runs. Terminal failures must drive the stats cadence
        themselves: wedge the consumer with a sleeping callback, flood the
        bounded queue, and the burn gauge + alert must fire MID-incident
        (zero completions), not after recovery."""
        model, params = mlp_bundle
        serve_cfg = ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0,
                                swap_poll_s=0.0, stats_interval_s=0.05,
                                max_queue=2, shed_policy="reject")
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, serve_cfg=serve_cfg,
            slo_availability=0.99, slo_window_s=60.0,
            slo_burn_threshold=2.0)
        for i in range(4):                       # healthy warm phase
            assert engine.submit(f"g{i}", obs_for()).wait(30.0)
        unwedge = threading.Event()
        engine.submit("staller", obs_for(),
                      callback=lambda r: unwedge.wait(20))
        time.sleep(0.3)                          # let the stall engage
        completed_before = engine._term_completed
        deadline = time.perf_counter() + 10.0
        while (registry.counters().get("serve_slo_burn_alerts_total", 0)
               < 1 and time.perf_counter() < deadline):
            engine.submit("flood", obs_for())
            time.sleep(0.002)
        # Nothing completed during the stall, yet the gauge moved and the
        # alert fired — published from the terminal-failure path.
        assert engine._term_completed == completed_before
        assert registry.counters()["serve_slo_burn_alerts_total"] >= 1
        burn = registry.latest("serve_slo_availability_burn")
        assert burn is not None and burn > 2.0
        unwedge.set()
        assert engine.stop()
        obs.close()

    def test_window_base_survives_sparse_publishes(
            self, tmp_path, mlp_bundle):
        """Publishes sparser than slo_window_s must degrade the window to
        one publish interval, never collapse the delta to zero: the prune
        keeps the NEWEST snapshot at-or-before the window edge as the
        base (a prune-past-the-edge bug made every delta self-subtract
        whenever interval >= window_s)."""
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, slo_availability=0.9, slo_window_s=60.0)
        try:
            t0 = time.perf_counter()
            # Synthetic sparse publishes: snapshots 90 s apart (> window),
            # cumulative terms climbing all-bad.
            out1 = engine._slo_burn(t0, (0, 0, 0, 0))
            out2 = engine._slo_burn(t0 + 90.0, (10, 10, 0, 0))
            assert out2.get("serve_slo_availability_burn", 0.0) == (
                pytest.approx(10.0))             # 100% bad / 10% budget
            out3 = engine._slo_burn(t0 + 180.0, (30, 30, 0, 0))
            assert out3.get("serve_slo_availability_burn", 0.0) == (
                pytest.approx(10.0))
        finally:
            engine.stop()
            obs.close()

    def test_bad_slo_config_raises(self, tmp_path, mlp_bundle):
        model, params = mlp_bundle
        cfg = make_cfg(tmp_path, slo_availability=1.5)
        with pytest.raises(ConfigError, match="slo_availability"):
            ServeEngine(model, cfg.serve, params, obs_cfg=cfg.obs)
        cfg = make_cfg(tmp_path, slo_window_s=0.0)
        with pytest.raises(ConfigError, match="slo_window_s"):
            ServeEngine(model, cfg.serve, params, obs_cfg=cfg.obs)


class TestFailureForensics:
    def test_terminal_failure_dumps_flight_bundle(
            self, tmp_path, mlp_bundle):
        """A restart storm past max_restarts ends in the terminal failed
        state AND a serve_failed flight bundle carrying the restart
        trail."""
        model, params = mlp_bundle
        serve_cfg = ServeConfig(max_batch=2, slots=4, batch_timeout_ms=1.0,
                                swap_poll_s=0.0, stats_interval_s=0.1,
                                max_restarts=1, restart_backoff_s=0.01,
                                restart_backoff_max_s=0.02)
        engine, registry, obs, cfg = build_engine(
            tmp_path, mlp_bundle, serve_cfg=serve_cfg)
        assert engine.submit("warm", obs_for()).wait(30.0) is not None
        for i in range(2):                  # two malformed-obs faults
            bad = engine.submit(f"bad{i}", np.ones(3, np.float32))
            bad.wait(30.0)
            assert bad.error is not None
        deadline = time.monotonic() + 30
        while engine.failed is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.failed is not None
        engine.stop(drain=False)
        obs.close()
        bundle = json.load(open(
            os.path.join(cfg.obs.dir, "flight_recorder.json")))
        assert bundle["reason"] == "serve_failed"
        restarts = [e for e in bundle["events"]
                    if e["kind"] == "serve_restart"]
        assert len(restarts) == 1 and restarts[0]["streak"] == 1

    def test_summarize_run_dir_serve_block(self, tmp_path, mlp_bundle):
        engine, registry, obs, cfg = build_engine(tmp_path, mlp_bundle)
        for i in range(20):
            assert engine.submit(f"s{i % 4}", obs_for()).wait(30.0)
        time.sleep(0.3)
        engine.stop()
        obs.flush()
        obs.close()
        summary = summarize_run_dir(cfg.obs.dir)
        serve = summary["serve"]
        assert serve["trace_decomposition_errors_total"] == 0
        assert serve["stages"]["device"]["count"] == 20
        assert serve["stages"]["queue_wait"]["p99_ms"] >= \
            serve["stages"]["queue_wait"]["p50_ms"]
        assert serve["slowest_exemplars"][0]["latency_ms"] > 0
        assert summary["histograms"]["serve_request_ms"]["count"] == 20


class TestLintCheck11:
    def _load(self):
        import importlib.util
        import pathlib
        tool = (pathlib.Path(__file__).resolve().parent.parent
                / "tools" / "lint_hot_loop.py")
        spec = importlib.util.spec_from_file_location("lint_hot_loop11",
                                                      tool)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_tree_is_clean(self):
        assert self._load().lint_bounded_trace_buffers() == []

    def test_pattern_semantics(self, tmp_path):
        mod = self._load()
        fixture = tmp_path / "pkg"
        fixture.mkdir()
        (fixture / "sample.py").write_text(
            "from collections import deque\n"
            "a = deque()\n"                              # unbounded: flag
            "b = deque(maxlen=16)\n"                     # bounded: ok
            "c = deque(maxlen=None)\n"                   # literal None: flag
            "d = deque([], 0)\n"                         # literal 0: flag
            "e = deque([], cap)\n"                       # expression: ok
            "# trace-buffer-ok: drained every tick\n"
            "f = deque()\n"                              # marked above: ok
            "g = deque()  # trace-buffer-ok: bounded by max_queue\n")
        hits = mod.lint_bounded_trace_buffers(roots=[fixture])
        assert [ln for _, ln, _ in hits] == [2, 4, 5]
