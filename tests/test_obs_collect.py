"""Distributed-trace collection (obs/trace.py SpanJournal/SpanSink,
obs/collect.py, obs/tsdb.py — ISSUE 17): the correctness core of the
stitched cross-process trace.

The contracts pinned here:

- **Clock alignment** — each journal RECORD leads with its process's own
  ``(epoch, mono)`` anchor; the collector maps every span onto one
  epoch-microsecond timeline, so spans from processes whose
  ``perf_counter`` origins differ by SECONDS still nest correctly (and
  without the offset they provably would not).
- **Stitch verification** — unresolved parent ids and child intervals
  escaping their parent beyond :data:`NEST_SLACK_US` are REPORTED as
  errors (never silently dropped); spans parented under instants are
  exempt from the nesting check (a SIGKILLed engine leaves instants).
- **Bounded journals** — rotation at ``max_records``, oldest-segment
  pruning at ``max_segments``, and a torn tail loses only the torn
  record, never alignment (each record is self-describing).
- **Telemetry history ring** — TsdbRing keeps at most ``max_rows`` rows
  across an atomic compaction; readers tolerate a torn tail row.
- **Off by default** — obs.enabled=false with no span_dir builds an Obs
  with ``spans is None`` and writes NOTHING; span_dir alone (the engine-
  worker spelling) journals spans with the rest of obs still off.
"""

from __future__ import annotations

import json
import os

from sharetrade_tpu.obs import build_obs, collect, read_trace
from sharetrade_tpu.obs.trace import SpanJournal, SpanSink
from sharetrade_tpu.obs.tsdb import (
    TsdbRing,
    read_history,
    summarize_history,
)
from sharetrade_tpu.utils.metrics import MetricsRegistry


def make_sink(spans_dir, proc: str, *, epoch: float, mono: float,
              **journal_kw) -> SpanSink:
    """A SpanSink whose journal carries a CONTROLLED clock anchor, so
    tests can model processes with wildly different perf_counter
    origins (the real anchor is sampled; determinism needs an override)."""
    journal = SpanJournal(str(spans_dir), proc, **journal_kw)
    journal.epoch, journal.mono = epoch, mono
    journal._clock_line = json.dumps(
        {"clock": 1, "proc": proc, "pid": journal.pid,
         "epoch": epoch, "mono": mono}, separators=(",", ":")).encode()
    return SpanSink(journal)


class TestClockAlignment:
    def test_spans_align_across_disjoint_monotonic_clocks(self, tmp_path):
        # Proc a: mono origin 0; proc b: its perf_counter reads 5 s LESS
        # for the same wall instant (anchor mono=-5 at epoch 1000). Raw
        # t0s differ by ~5 s, yet the stitched intervals must nest: the
        # collector maps t0 -> epoch + (t0 - mono).
        a = make_sink(tmp_path, "fleet", epoch=1000.0, mono=0.0)
        b = make_sink(tmp_path, "engine-e0", epoch=1000.0, mono=-5.0)
        a.span("t1", "a.1", "", "relay", 100.0, 100.5)
        b.span("t1", "b.1", "a.1", "engine_request", 95.1, 95.3)
        a.close()
        b.close()
        spans = collect.read_span_dir(str(tmp_path))
        by_id = {s["span"]: s for s in spans}
        assert by_id["a.1"]["ts_us"] == (1000.0 + 100.0) * 1e6
        assert by_id["b.1"]["ts_us"] == (1000.0 + 95.1 - (-5.0)) * 1e6
        stitched = collect.stitch(spans, "t1")
        assert stitched["errors"] == []
        assert stitched["procs"] == ["engine-e0", "fleet"]
        # The offset is load-bearing: ignoring it, b.1 would sit ~5 s
        # outside its parent's 500 ms window.
        assert abs((95.1 - 100.0) * 1e6) > collect.NEST_SLACK_US

    def test_anchor_rides_every_record_not_just_the_first(self, tmp_path):
        # Two separate flushes = two framed records; both must carry the
        # anchor, so pruning record 1 can never misalign record 2.
        sink = make_sink(tmp_path, "fleet", epoch=50.0, mono=10.0)
        sink.span("t1", "a.1", "", "first", 11.0, 11.1)
        sink.flush()
        sink.span("t1", "a.2", "a.1", "second", 11.02, 11.05)
        sink.close()
        path = sink._journal.path
        from sharetrade_tpu.data.journal import iter_framed_records
        records = [payload for _off, payload in
                   iter_framed_records(path, warn=False)]
        assert len(records) == 2
        for payload in records:
            clock = json.loads(payload.split(b"\n")[0])
            assert (clock["epoch"], clock["mono"]) == (50.0, 10.0)


class TestStitchVerification:
    def _spans(self, tmp_path, triples) -> list:
        sink = make_sink(tmp_path, "fleet", epoch=0.0, mono=0.0)
        for span_id, parent, name, t0, t1 in triples:
            sink.span("t1", span_id, parent, name, t0, t1)
        sink.close()
        return collect.read_span_dir(str(tmp_path))

    def test_unresolved_parent_is_reported(self, tmp_path):
        spans = self._spans(tmp_path, [
            ("a.1", "", "relay", 1.0, 2.0),
            ("a.2", "ghost", "engine_recv", 1.1, None)])
        errors = collect.stitch(spans, "t1")["errors"]
        assert len(errors) == 1
        assert "parent ghost unresolved" in errors[0]

    def test_child_escaping_parent_is_reported(self, tmp_path):
        spans = self._spans(tmp_path, [
            ("a.1", "", "relay", 1.0, 1.1),
            ("a.2", "a.1", "late", 2.0, 2.1)])      # ~1 s outside
        errors = collect.stitch(spans, "t1")["errors"]
        assert len(errors) == 1 and "escapes parent" in errors[0]

    def test_nesting_within_slack_is_clean(self, tmp_path):
        slack_s = collect.NEST_SLACK_US / 1e6
        spans = self._spans(tmp_path, [
            ("a.1", "", "relay", 1.0, 1.1),
            ("a.2", "a.1", "edge", 1.0 - slack_s / 2,
             1.1 + slack_s / 2)])
        assert collect.stitch(spans, "t1")["errors"] == []

    def test_instant_parents_are_never_nest_checked(self, tmp_path):
        # engine_recv is an instant (no dur); a SIGKILLed engine leaves
        # exactly these — children under them must not be flagged.
        spans = self._spans(tmp_path, [
            ("a.1", "", "engine_recv", 1.0, None),
            ("a.2", "a.1", "engine_request", 5.0, 6.0)])
        assert collect.stitch(spans, "t1")["errors"] == []

    def test_trace_ids_ordered_by_first_timestamp(self, tmp_path):
        spans = self._spans(tmp_path, [
            ("a.1", "", "relay", 10.0, 11.0),
            ("a.2", "", "relay", 2.0, 3.0),
            ("a.3", "", "relay", 2.5, 3.5)])
        spans[0]["trace"] = "late"
        spans[1]["trace"] = "early"
        spans[2]["trace"] = "early"
        assert collect.trace_ids(spans) == {"early": 2, "late": 1}

    def test_migrated_traces_key_on_the_migrate_annotation(self, tmp_path):
        sink = make_sink(tmp_path, "fleet", epoch=0.0, mono=0.0)
        e0 = make_sink(tmp_path, "engine-e0", epoch=0.0, mono=0.0)
        e1 = make_sink(tmp_path, "engine-e1", epoch=0.0, mono=0.0)
        # Trace "mig": first attempt dies on e0, migrates to e1.
        sink.span("mig", "f.1", "", "relay", 1.0, 2.0, note="migrated")
        sink.span("mig", "f.2", "f.1", "relay_attempt", 1.0, 1.4,
                  note="first conn reset")
        sink.span("mig", "f.3", "f.1", "relay_attempt", 1.4, 1.9,
                  note="migrate:conn reset status 200")
        e0.span("mig", "e0.1", "f.2", "engine_recv", 1.1, None)
        e1.span("mig", "e1.1", "f.3", "engine_recv", 1.5, None)
        # Trace "ok": plain single-attempt success — not migrated.
        sink.span("ok", "f.4", "", "relay", 3.0, 3.2)
        sink.span("ok", "f.5", "f.4", "relay_attempt", 3.0, 3.2,
                  note="first status 200")
        for s in (sink, e0, e1):
            s.close()
        spans = collect.read_span_dir(str(tmp_path))
        migrated = collect.migrated_traces(spans)
        assert [t["trace_id"] for t in migrated] == ["mig"]
        assert migrated[0]["engines"] == ["engine-e0", "engine-e1"]
        assert migrated[0]["errors"] == []

    def test_write_perfetto_rendering(self, tmp_path):
        spans = self._spans(tmp_path, [
            ("a.1", "", "relay", 1.0, 2.0),
            ("a.2", "a.1", "engine_recv", 1.5, None)])
        out = str(tmp_path / "trace.json")
        stitched = collect.collect_trace(str(tmp_path), "t1", out=out)
        assert stitched["perfetto"] == out
        events = read_trace(out)    # same array format as obs traces
        meta = [e for e in events if e.get("ph") == "M"]
        assert [m["args"]["name"] for m in meta] == ["fleet"]
        complete = [e for e in events if e.get("ph") == "X"]
        instants = [e for e in events if e.get("ph") == "i"]
        assert [e["name"] for e in complete] == ["relay"]
        assert complete[0]["dur"] == 1e6
        assert [e["name"] for e in instants] == ["engine_recv"]

    def test_missing_trace_stitches_empty(self, tmp_path):
        stitched = collect.collect_trace(str(tmp_path), "nope")
        assert stitched["spans"] == [] and stitched["errors"] == []


class TestJournalBounds:
    def test_rotation_and_oldest_first_pruning(self, tmp_path):
        sink = make_sink(tmp_path, "fleet", epoch=0.0, mono=0.0,
                         max_records=2, max_segments=2)
        for i in range(12):     # one record per flush
            sink.span("t1", f"a.{i}", "", "step", float(i), i + 0.5)
            sink.flush()
        sink.close()
        names = sorted(os.listdir(tmp_path))
        segs = [n for n in names if ".seg" in n]
        assert len(segs) == 2   # pruned down from 6 rotations
        spans = collect.read_span_dir(str(tmp_path))
        # Newest survive (2 segments x 2 records); the prune took
        # whole oldest segments.
        kept = sorted(int(s["span"].split(".")[1]) for s in spans)
        assert kept == list(range(8, 12))

    def test_torn_tail_loses_only_the_torn_record(self, tmp_path):
        sink = make_sink(tmp_path, "fleet", epoch=0.0, mono=0.0)
        sink.span("t1", "a.1", "", "whole", 1.0, 2.0)
        sink.flush()
        sink.span("t1", "a.2", "", "torn", 3.0, 4.0)
        sink.close()
        path = sink._journal.path
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[:-7])   # tear mid-record
        spans = collect.read_span_dir(str(tmp_path))
        assert [s["span"] for s in spans] == ["a.1"]

    def test_sink_ring_is_bounded_and_counts_drops(self, tmp_path):
        sink = make_sink(tmp_path, "fleet", epoch=0.0, mono=0.0)
        sink._buf = type(sink._buf)(maxlen=4)
        sink._flush_every = 100     # never auto-flush: force overflow
        for i in range(10):
            sink.span("t1", f"a.{i}", "", "s", float(i), i + 0.1)
        assert sink.dropped == 6
        sink.close()
        spans = collect.read_span_dir(str(tmp_path))
        assert len(spans) == 4      # the newest ring-ful


class TestTsdbRing:
    def test_bounded_by_atomic_compaction(self, tmp_path):
        path = str(tmp_path / "hist.jsonl")
        ring = TsdbRing(path, max_rows=5)
        for i in range(23):
            ring.append({"ts": float(i), "fleet_p99_ms": i * 2.0})
        ring.close()
        rows = read_history(path)
        assert len(rows) <= 10      # never past 2x the bound
        assert rows[-1]["ts"] == 22.0
        assert all(r["ts"] > 12 for r in rows)  # oldest were compacted

    def test_reopen_counts_existing_rows(self, tmp_path):
        path = str(tmp_path / "hist.jsonl")
        ring = TsdbRing(path, max_rows=4)
        for i in range(3):
            ring.append({"ts": float(i)})
        ring.close()
        ring2 = TsdbRing(path, max_rows=4)      # a restarted router
        for i in range(3, 10):
            ring2.append({"ts": float(i)})
        ring2.close()
        assert len(read_history(path)) <= 8

    def test_torn_tail_row_is_tolerated(self, tmp_path):
        path = str(tmp_path / "hist.jsonl")
        with open(path, "w") as f:
            f.write('{"ts": 1.0, "fleet_p99_ms": 9.0}\n{"ts": 2.0, "fl')
        assert read_history(path) == [{"ts": 1.0, "fleet_p99_ms": 9.0}]
        assert read_history(str(tmp_path / "missing.jsonl")) == []

    def test_summarize_history(self):
        rows = [{"ts": 10.0, "fleet_p99_ms": 5.0},
                {"ts": 11.0, "fleet_p99_ms": 9.0},
                {"ts": 14.0, "fleet_p99_ms": 7.0, "fleet_engines_live": 2}]
        s = summarize_history(rows)
        assert s["rows"] == 3 and s["window_s"] == 4.0
        assert s["fleet_p99_ms"] == {"min": 5.0, "max": 9.0, "last": 7.0}
        assert s["fleet_engines_live"]["last"] == 2
        assert summarize_history([]) == {"rows": 0}

    def test_read_history_last_n(self, tmp_path):
        path = str(tmp_path / "hist.jsonl")
        ring = TsdbRing(path, max_rows=16)
        for i in range(6):
            ring.append({"ts": float(i)})
        ring.close()
        assert [r["ts"] for r in read_history(path, last_n=2)] \
            == [4.0, 5.0]


class TestObsGating:
    def test_disabled_default_builds_nothing(self, tmp_path, monkeypatch):
        from sharetrade_tpu.config import FrameworkConfig
        monkeypatch.chdir(tmp_path)
        cfg = FrameworkConfig()
        assert cfg.obs.enabled is False and cfg.obs.span_dir == ""
        obs = build_obs(cfg, MetricsRegistry())
        assert obs.spans is None and obs.enabled is False
        obs.close()
        assert list(tmp_path.iterdir()) == []   # ZERO files

    def test_span_dir_alone_journals_with_obs_off(self, tmp_path):
        # The fleet engine-worker spelling: obs.enabled stays False
        # (telemetry lives with the fleet process) but span_dir is
        # injected so the worker journals its half of every trace.
        from sharetrade_tpu.config import FrameworkConfig
        cfg = FrameworkConfig()
        cfg.obs.span_dir = str(tmp_path / "spans")
        cfg.obs.span_proc = "engine-e7"
        obs = build_obs(cfg, MetricsRegistry())
        assert obs.enabled is False and obs.spans is not None
        assert obs.spans.proc == "engine-e7"
        obs.spans.span("t1", obs.spans.new_span_id(), "", "engine_recv",
                       1.0, 1.5)
        obs.close()
        spans = collect.read_span_dir(str(tmp_path / "spans"))
        assert [s["proc"] for s in spans] == ["engine-e7"]
