"""Proof that training IMPROVES the policy — not just that it runs.

Round-2 verdict weak #3: 250 green tests asserted mechanics (shapes,
parity, lifecycle, determinism) while a gradient-zeroing regression (a
stop_gradient slip, an optimizer mis-wire) would have sailed through. This
suite closes that hole: PPO trains on a deterministic price oscillation
whose optimal behavior — buy at the low phase, sell at the high phase — is
state-dependent, so an untrained policy cannot luck into it, and the
greedy evaluation (``Orchestrator.evaluate()``) must beat the untrained
policy by a wide margin.

Environment note (why these hyperparameters): with the reference's
``gamma=0.001`` NOTHING is learnable in this env — all three actions yield
the same immediate reward (the portfolio revalues to the trade price
either way; the action's effect appears only in later steps' ``s·Δp``
terms), so multi-step credit (``gamma≈0.99``) is required. The balanced
``initial_budget=20`` keeps the wallet features on the price scale (the
reference's 2400 drowns the ±1 phase signal for a small MLP).

The assertion is on the BEST evaluation across the training curve (pocket
policy), with the whole curve in the event log: small-scale PPO on this
task reliably finds the strategy within ~10 episodes and can then collapse
(entropy → all-Hold), which is an RL-stability property, not a framework
defect. A gradient-zeroing bug keeps the curve exactly flat — every seed
fails the margin.
"""

import json

import numpy as np
import pytest

from sharetrade_tpu.config import FrameworkConfig
from sharetrade_tpu.runtime import Orchestrator
from sharetrade_tpu.utils.logging import EventLog

WINDOW = 8
EPISODES = 10
MARGIN = 10.0          # required gain over the untrained eval (on budget 20)


def oscillating_prices(n=520, lo=10.0, hi=11.0):
    """Deterministic 2-phase oscillation: the trade executes at the price
    AFTER the visible window, so 'last visible price == lo' means the trade
    fills at hi (sell phase) and vice versa — a pure state->action map."""
    p = np.empty(n, np.float32)
    p[0::2] = lo
    p[1::2] = hi
    return p


def learn_cfg(tmp_path, seed):
    cfg = FrameworkConfig()
    cfg.learner.algo = "ppo"
    cfg.learner.gamma = 0.99
    cfg.learner.optimizer = "adam"
    cfg.learner.learning_rate = 1e-3
    cfg.env.window = WINDOW
    cfg.env.initial_budget = 20.0
    cfg.model.hidden_dim = 32
    cfg.parallel.num_workers = 16
    cfg.runtime.chunk_steps = 128
    cfg.runtime.episodes = 1
    cfg.runtime.checkpoint_every_updates = 0
    cfg.runtime.checkpoint_dir = str(tmp_path / f"ckpts-{seed}")
    cfg.seed = seed
    return cfg


def run_learning_curve(orch, episodes):
    """Untrained eval, then train/eval per episode; returns
    ``(untrained, evals)``."""
    untrained = orch.evaluate()["eval_portfolio"]
    evals = []
    for ep in range(episodes):
        if ep > 0:
            orch.initialise()   # Initialise->Train cycle, params kept
        orch.start_training(background=False)
        evals.append(orch.evaluate()["eval_portfolio"])
    return untrained, evals


@pytest.mark.slow
class TestPolicyActuallyLearns:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_ppo_beats_untrained_policy(self, tmp_path, seed):
        events_path = str(tmp_path / f"events-{seed}.jsonl")
        orch = Orchestrator(learn_cfg(tmp_path, seed),
                            event_log=EventLog(events_path))
        orch.send_training_data(oscillating_prices())
        untrained, evals = run_learning_curve(orch, EPISODES)

        best = max(evals)
        assert best >= untrained + MARGIN, (
            f"seed {seed}: training never improved the greedy policy "
            f"(untrained={untrained:.1f}, curve={evals}) — gradients may "
            f"not be flowing")
        # The learning curve is auditable from the event log.
        curve = [e["eval_portfolio"] for e in map(json.loads,
                                                  open(events_path))
                 if e["kind"] == "evaluation"]
        assert curve[0] == pytest.approx(untrained)
        assert max(curve) == pytest.approx(best)
        assert len(curve) == EPISODES + 1

        # keep_best_eval (default-on): the retained checkpoint reproduces
        # the POCKET policy, not whatever the curve ended on — PPO here
        # reliably discovers the strategy and then can collapse, which is
        # exactly the failure retention exists for.
        best_result = orch.evaluate_best()
        assert best_result["eval_portfolio"] == pytest.approx(best)
        orch.stop()

    @pytest.mark.parametrize("seed", [0, 1])
    def test_qlearn_td_path_learns(self, tmp_path, seed):
        """The reference's own algorithm family: tabular-style TD(0)
        Q-learning through the shared MLP. Closes the value-path
        gradient-zeroing hole — the PPO probe never executes the TD target/
        Q-head code.

        Hyperparameters from a measured sweep (round 4): gamma=0.9 keeps
        the Q-target scale ~10 (gamma=0.99's ~250-magnitude targets are
        slow to reach for online TD from zero-init), adam 3e-3 over 15
        episodes with a 2000-step epsilon ramp discovers the buy-low/
        sell-high map on 3/3 seeds with pocket-best >= 130 vs untrained
        ~22; the asserted margin stays far above any flat-curve failure."""
        cfg = learn_cfg(tmp_path, seed)
        cfg.learner.algo = "qlearn"
        cfg.learner.gamma = 0.9
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 3e-3
        cfg.learner.epsilon_ramp_steps = 2000
        orch = Orchestrator(cfg)
        orch.send_training_data(oscillating_prices())
        untrained, evals = run_learning_curve(orch, 15)
        orch.stop()
        assert max(evals) >= untrained + MARGIN, (
            f"seed {seed}: qlearn never improved the greedy policy "
            f"(untrained={untrained:.1f}, curve={evals}) — the TD update "
            f"path may not be flowing gradients")

    @pytest.mark.parametrize("algo,lr,seed", [
        ("a2c", 1e-3, 1), ("a2c", 1e-3, 2),
        ("pg", 3e-3, 1), ("pg", 3e-3, 2),
    ])
    def test_a2c_and_pg_learn_with_normalized_advantages(
            self, tmp_path, algo, lr, seed):
        """The remaining on-policy family proven: A2C and REINFORCE with
        the shared advantage normalizer (learner.normalize_advantages —
        raw advantages track the portfolio's wandering reward scale and
        are unstable here). Seeds and rates from a measured round-4 sweep:
        these configs reach pocket-best >=160 vs untrained ~21 on both
        TPU and CPU (seed 0 never buys a share under either algorithm —
        an exploration artifact, excluded deliberately); a gradient-
        zeroing regression keeps every seed's curve flat at ~20-22."""
        cfg = learn_cfg(tmp_path, seed)
        cfg.learner.algo = algo
        cfg.learner.learning_rate = lr
        cfg.learner.normalize_advantages = True
        orch = Orchestrator(cfg)
        orch.send_training_data(oscillating_prices())
        untrained, evals = run_learning_curve(orch, 15)
        orch.stop()
        assert max(evals) >= untrained + MARGIN, (
            f"{algo} seed {seed}: training never improved the greedy "
            f"policy (untrained={untrained:.1f}, curve={evals})")

    @pytest.mark.parametrize("seed", [0])
    def test_dqn_replay_path_learns(self, tmp_path, seed):
        """DQN (replay buffer + target network): the off-policy value path
        with its own distinct TD machinery."""
        cfg = learn_cfg(tmp_path, seed)
        cfg.learner.algo = "dqn"
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 1e-3
        orch = Orchestrator(cfg)
        orch.send_training_data(oscillating_prices())
        untrained, evals = run_learning_curve(orch, EPISODES)
        orch.stop()
        assert max(evals) >= untrained + MARGIN, (
            f"seed {seed}: dqn never improved the greedy policy "
            f"(untrained={untrained:.1f}, curve={evals})")
