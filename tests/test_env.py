"""Environment semantics tests.

Mirrors the behavioral contract of the reference fold
(TrainerChildActor.scala:82-146) with the running-state fix, plus the
vmap/scan properties the TPU design depends on (SURVEY.md §7.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sharetrade_tpu.env import (
    BUY,
    HOLD,
    SELL,
    env_from_prices,
    num_steps,
    observe,
    portfolio_value,
    reset,
    step,
)

WINDOW = 4  # tiny window for hand-checkable episodes


def make_params(n=10, budget=100.0, shares=0):
    prices = jnp.arange(1.0, n + 1.0)  # 1, 2, ..., n
    return env_from_prices(prices, window=WINDOW, initial_budget=budget,
                           initial_shares=shares)


class TestConstruction:
    def test_rejects_short_series(self):
        # Reference guard: price count must exceed input nodes
        # (TrainerChildActor.scala:69-70).
        with pytest.raises(ValueError, match="must exceed"):
            env_from_prices(jnp.ones(WINDOW), window=WINDOW)

    def test_accepts_one_step_episode(self):
        # Exactly window + 1 prices is a valid 1-step episode — the reference
        # bound (sharePrices.size > h1Dim + 1) accepts it.
        p = env_from_prices(jnp.arange(1.0, WINDOW + 2.0), window=WINDOW)
        assert num_steps(p) == 1

    def test_num_steps(self):
        assert num_steps(make_params(n=10)) == 6  # len - window

    def test_episode_length_matches_reference_fixture_shape(self):
        # 6,046 prices with the real 201 window -> 5,845 fold steps
        # (SharePriceGetter fixture, TrainerChildActor.scala:67).
        p = env_from_prices(jnp.ones(6046) * 50.0, window=201)
        assert num_steps(p) == 5845


class TestObservation:
    def test_shape_and_contents(self):
        params = make_params()
        s = reset(params)
        obs = observe(params, s)
        assert obs.shape == (WINDOW + 2,)
        np.testing.assert_allclose(obs[:WINDOW], [1, 2, 3, 4])
        np.testing.assert_allclose(obs[WINDOW:], [100.0, 0.0])

    def test_window_advances_with_cursor(self):
        params = make_params()
        s = reset(params)
        s, _ = step(params, s, jnp.int32(HOLD))
        obs = observe(params, s)
        np.testing.assert_allclose(obs[:WINDOW], [2, 3, 4, 5])


class TestStepSemantics:
    def test_buy_at_post_window_price(self):
        params = make_params()
        s = reset(params)
        # Trade price at t=0 is prices[window] = 5.
        s, reward = step(params, s, jnp.int32(BUY))
        assert float(s.budget) == 95.0
        assert float(s.shares) == 1.0
        assert float(s.share_value) == 5.0
        # First portfolio = budget (share_value seeded 0); new = 95 + 1*5.
        assert float(reward) == 0.0

    def test_sell_requires_shares(self):
        params = make_params()
        s = reset(params)
        s2, _ = step(params, s, jnp.int32(SELL))
        # Infeasible sell degrades to Hold (TrainerChildActor.scala:122).
        assert float(s2.budget) == 100.0
        assert float(s2.shares) == 0.0

    def test_buy_requires_budget(self):
        params = make_params(budget=3.0)
        s = reset(params)
        s2, _ = step(params, s, jnp.int32(BUY))  # price 5 > budget 3
        assert float(s2.budget) == 3.0
        assert float(s2.shares) == 0.0

    def test_buy_then_sell_round_trip(self):
        params = make_params()
        s = reset(params)
        s, _ = step(params, s, jnp.int32(BUY))    # buy at 5
        s, r = step(params, s, jnp.int32(SELL))   # sell at 6
        assert float(s.budget) == 101.0
        assert float(s.shares) == 0.0
        # reward = (101 + 0*6) - (95 + 1*5) = 1
        assert float(r) == 1.0

    def test_hold_reward_marks_to_market(self):
        params = make_params()
        s = reset(params)
        s, _ = step(params, s, jnp.int32(BUY))   # 1 share at 5
        s, r = step(params, s, jnp.int32(HOLD))  # price moves to 6
        # reward = (95 + 1*6) - (95 + 1*5) = 1: the held share appreciates.
        assert float(r) == 1.0

    def test_running_state_is_threaded(self):
        # The fix for the reference's stale-constructor-state quirk
        # (SURVEY.md §2.1): repeated Buys must drain the *running* budget.
        params = make_params(budget=12.0)
        s = reset(params)
        s, _ = step(params, s, jnp.int32(BUY))  # price 5 -> budget 7
        s, _ = step(params, s, jnp.int32(BUY))  # price 6 -> budget 1
        s, _ = step(params, s, jnp.int32(BUY))  # price 7 > 1: degrades to Hold
        assert float(s.budget) == 1.0
        assert float(s.shares) == 2.0

    def test_final_portfolio_identity(self):
        params = make_params()
        s = reset(params)
        for a in [BUY, BUY, HOLD, SELL]:
            s, _ = step(params, s, jnp.int32(a))
        assert float(portfolio_value(s)) == float(s.budget) + float(s.shares) * float(
            s.share_value
        )


class TestTransformFriendliness:
    def test_full_episode_under_scan_and_jit(self):
        params = make_params(n=20)
        n = num_steps(params)

        def body(state, action):
            new_state, reward = step(params, state, action)
            return new_state, reward

        actions = jnp.zeros(n, dtype=jnp.int32)  # all Buy

        @jax.jit
        def run(actions):
            return jax.lax.scan(body, reset(params), actions)

        final, rewards = run(actions)
        assert rewards.shape == (n,)
        assert int(final.t) == n

    def test_vmapped_agent_batch_diverges(self):
        params = make_params()
        batch = 8

        def rollout(actions):
            def body(state, a):
                ns, r = step(params, state, a)
                return ns, r
            final, _ = jax.lax.scan(body, reset(params), actions)
            return portfolio_value(final)

        key = jax.random.PRNGKey(0)
        actions = jax.random.randint(key, (batch, num_steps(params)), 0, 3)
        portfolios = jax.jit(jax.vmap(rollout))(actions)
        assert portfolios.shape == (batch,)
        # Stochastic policies must actually diverge across the batch.
        assert len(set(np.asarray(portfolios).tolist())) > 1

    def test_reward_sum_telescopes_to_final_portfolio(self):
        # Sum of portfolio-delta rewards telescopes: final portfolio =
        # initial budget + sum(rewards). A strong whole-episode invariant.
        params = make_params(n=30, budget=50.0)
        key = jax.random.PRNGKey(7)
        actions = jax.random.randint(key, (num_steps(params),), 0, 3)

        def body(state, a):
            ns, r = step(params, state, a)
            return ns, r

        final, rewards = jax.lax.scan(body, reset(params), actions)
        np.testing.assert_allclose(
            float(portfolio_value(final)),
            50.0 + float(jnp.sum(rewards)),
            rtol=1e-5,
        )
