import os

import numpy as np
import pytest

from sharetrade_tpu.data.ingest import PriceSeries, from_rows, parse_price_lines
from sharetrade_tpu.data.journal import Journal
from sharetrade_tpu.data.service import PriceDataService, synthetic_provider
from sharetrade_tpu.data.synthetic import synthetic_price_series


# ---- ingest ----

def test_parse_price_lines_sorted_and_lenient():
    # "price, date" rows, bad rows dropped — SharePriceGetter.scala:89-101 behavior.
    series = parse_price_lines("MSFT", [
        "56.08, 1992-07-23",
        "not-a-price, 1992-07-24",
        "55.00, 1992-07-22",
        "garbage line",
        "57.5, 1992-07-27",
        "1.0, 1992-13-45",  # invalid date
    ])
    assert len(series) == 3
    assert [str(d) for d in series.dates] == ["1992-07-22", "1992-07-23", "1992-07-27"]
    assert series.prices[0] == pytest.approx(55.0)


def test_range_query_inclusive():
    # Date-range filtering — the intended behavior SharePriceGetterSpec documents.
    series = from_rows("X", [(f"2020-01-{d:02d}", float(d)) for d in range(1, 11)])
    sub = series.range("2020-01-03", "2020-01-07")
    assert len(sub) == 5
    assert sub.prices[0] == 3.0 and sub.prices[-1] == 7.0
    assert len(series.range(None, "2020-01-02")) == 2
    assert len(series.range("2020-01-09", None)) == 2
    assert len(series.range()) == 10


def test_merge_keep_old_on_collision():
    # updateStockMapIfTheresChange: existing values win (SharePriceGetter.scala:64-73).
    old = from_rows("X", [("2020-01-01", 1.0), ("2020-01-02", 2.0)])
    new = from_rows("X", [("2020-01-02", 99.0), ("2020-01-03", 3.0)])
    merged = old.merge_keep_old(new)
    assert len(merged) == 3
    assert merged.range("2020-01-02", "2020-01-02").prices[0] == 2.0
    assert merged.range("2020-01-03", "2020-01-03").prices[0] == 3.0


def test_merge_symbol_mismatch():
    a = from_rows("A", [("2020-01-01", 1.0)])
    b = from_rows("B", [("2020-01-01", 1.0)])
    with pytest.raises(ValueError):
        a.merge_keep_old(b)


def test_series_dict_roundtrip():
    s = synthetic_price_series(length=10)
    s2 = PriceSeries.from_dict(s.to_dict())
    assert np.array_equal(s.dates, s2.dates)
    assert np.allclose(s.prices, s2.prices)


def test_synthetic_deterministic_and_shaped():
    a = synthetic_price_series(length=6046, seed=7)
    b = synthetic_price_series(length=6046, seed=7)
    c = synthetic_price_series(length=6046, seed=8)
    assert len(a) == 6046
    assert np.array_equal(a.prices, b.prices)
    assert not np.array_equal(a.prices, c.prices)
    assert (a.prices > 0).all()


# ---- journal ----

def test_journal_append_replay(tmp_journal_path):
    with Journal(tmp_journal_path) as j:
        j.append({"type": "a", "n": 1})
        j.append({"type": "b", "n": 2})
    with Journal(tmp_journal_path) as j:
        events = list(j.replay())
    assert events == [{"type": "a", "n": 1}, {"type": "b", "n": 2}]


def test_journal_survives_torn_tail(tmp_journal_path):
    with Journal(tmp_journal_path) as j:
        j.append({"n": 1})
        j.append({"n": 2})
    # Corrupt the tail: truncate mid-record.
    import os
    size = os.path.getsize(tmp_journal_path)
    with open(tmp_journal_path, "r+b") as f:
        f.truncate(size - 3)
    # Reopen: replay yields the intact prefix; new appends still work.
    with Journal(tmp_journal_path) as j:
        assert [e["n"] for e in j.replay()] == [1]
        j.append({"n": 3})
        assert [e["n"] for e in j.replay()] == [1, 3]


class TestWriterLock:
    """Concurrent-writer guard (the disaggregation PR): a flock-held,
    pid-stamped lockfile makes the torn-record scenario — two live
    processes interleaving framed appends on one journal — impossible by
    construction, while a SIGKILLed writer's lock releases with its
    process (kernel flock, no sweep protocol to race)."""

    def _foreign_holder(self, path):
        """A real second PROCESS holding the writer lock on ``path``."""
        import subprocess
        import sys
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, sys.argv[2]); "
             "from sharetrade_tpu.data.journal import acquire_writer_lock;"
             "acquire_writer_lock(sys.argv[1]); print('locked', flush=True);"
             "import time; time.sleep(60)",
             path, os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))],
            stdout=subprocess.PIPE, text=True)
        assert proc.stdout.readline().strip() == "locked"
        return proc

    def test_second_live_writer_raises_loudly(self, tmp_journal_path):
        from sharetrade_tpu.data.journal import JournalLockError
        holder = self._foreign_holder(tmp_journal_path)
        try:
            with pytest.raises(JournalLockError):
                Journal(tmp_journal_path)
        finally:
            holder.kill()
            holder.wait(timeout=30)

    def test_append_feed_rows_respects_live_lock(self, tmp_path):
        from sharetrade_tpu.data.journal import JournalLockError
        from sharetrade_tpu.data.service import append_feed_rows
        feed = str(tmp_path / "prices.feed")
        series = synthetic_price_series(symbol="T", length=4, seed=0)
        holder = self._foreign_holder(feed)
        try:
            with pytest.raises(JournalLockError):
                append_feed_rows(feed, series)
        finally:
            holder.kill()
            holder.wait(timeout=30)
        # Holder SIGKILLed: the kernel released its flock with it, so the
        # append acquires and the stamp clears again on release.
        append_feed_rows(feed, series)
        with open(feed + ".lock") as f:
            assert f.read() == ""

    def test_sigkilled_writer_lock_releases_with_it(self,
                                                    tmp_journal_path):
        # The "stale lock" scenario: no sweep step exists to race — the
        # dead writer's flock is simply gone, and a lingering pid stamp
        # does not block the next writer.
        holder = self._foreign_holder(tmp_journal_path)
        holder.kill()
        holder.wait(timeout=30)
        with open(tmp_journal_path + ".lock") as f:
            assert int(f.read()) == holder.pid     # stamp lingers...
        with Journal(tmp_journal_path) as j:       # ...but does not hold
            j.append({"n": 1})
            with open(tmp_journal_path + ".lock") as f:
                assert int(f.read()) == os.getpid()

    def test_same_process_reopen_stays_legal(self, tmp_journal_path):
        with Journal(tmp_journal_path) as j:
            j.append({"n": 1})
        with Journal(tmp_journal_path) as j:
            assert [e["n"] for e in j.replay()] == [1]

    def test_in_process_holds_are_refcounted(self, tmp_journal_path):
        from sharetrade_tpu.data.journal import (
            acquire_writer_lock, release_writer_lock)
        with Journal(tmp_journal_path) as j:
            # A second in-process hold (reader-side open) is legal, and
            # ITS release must not drop the writer's lock mid-append.
            acquire_writer_lock(tmp_journal_path)
            release_writer_lock(tmp_journal_path)
            j.append({"n": 1})
            with open(tmp_journal_path + ".lock") as f:
                assert int(f.read()) == os.getpid()   # still held
        with open(tmp_journal_path + ".lock") as f:
            assert f.read() == ""                      # now released

    def test_release_of_unheld_path_is_a_noop(self, tmp_journal_path):
        # Releasing a path THIS process never locked must not disturb
        # another process's live lock.
        from sharetrade_tpu.data.journal import (
            JournalLockError, release_writer_lock)
        holder = self._foreign_holder(tmp_journal_path)
        try:
            release_writer_lock(tmp_journal_path)
            with pytest.raises(JournalLockError):
                Journal(tmp_journal_path)              # still held
        finally:
            holder.kill()
            holder.wait(timeout=30)


class TestGroupCommit:
    """Journal group commit (journal_fsync_every_records /
    fsync_interval_s): appends batch in memory and land as ONE write+fsync
    at the watermark; the torn-tail recovery contract survives a crash at
    ANY byte position, including between watermark commits."""

    def test_count_watermark_batches_writes(self, tmp_journal_path):
        import os
        j = Journal(tmp_journal_path, fsync_every_records=4)
        for n in range(3):
            j.append({"n": n})
        # Below the watermark: nothing on disk yet (the durability window).
        assert os.path.getsize(tmp_journal_path) == 0
        j.append({"n": 3})
        # Watermark hit: the whole batch committed in one write.
        assert os.path.getsize(tmp_journal_path) > 0
        assert [e["n"] for e in j.replay()] == [0, 1, 2, 3]
        j.close()

    def test_interval_watermark_commits_on_time(self, tmp_journal_path):
        import os
        import time
        j = Journal(tmp_journal_path, fsync_every_records=1000,
                    fsync_interval_s=0.05)
        j.append({"n": 0})
        time.sleep(0.08)
        j.append({"n": 1})     # interval elapsed: this append commits both
        assert os.path.getsize(tmp_journal_path) > 0
        assert [e["n"] for e in j.replay()] == [0, 1]
        j.close()

    def test_readers_see_buffered_appends(self, tmp_journal_path):
        """replay()/flush() quiesce the batch — an acked append is never
        invisible to the process that wrote it."""
        j = Journal(tmp_journal_path, fsync_every_records=1000)
        j.append({"n": 0})
        assert [e["n"] for e in j.replay()] == [0]
        j.close()

    def test_close_commits_pending_batch(self, tmp_journal_path):
        with Journal(tmp_journal_path, fsync_every_records=1000) as j:
            j.append({"n": 7})
        with Journal(tmp_journal_path) as j:
            assert [e["n"] for e in j.replay()] == [7]

    def test_append_after_close_raises_not_swallows(self, tmp_journal_path):
        """Group mode must not ACK records into a buffer that can never
        reach the disk — same contract as the legacy path's closed-handle
        write error."""
        j = Journal(tmp_journal_path, fsync_every_records=1000)
        j.close()
        with pytest.raises(ValueError, match="closed"):
            j.append({"n": 1})

    def test_torn_tail_property_under_group_commit(self, tmp_journal_path):
        """Property: crash the journal at EVERY byte offset of a
        group-committed log — including offsets that fall between watermark
        commits — and recovery must always yield an exact event prefix,
        never garbage, never a lost committed prefix, and appends must
        continue cleanly after the truncation."""
        import os
        events = [{"n": n, "pad": "x" * (n * 7 % 23)} for n in range(12)]
        with Journal(tmp_journal_path, fsync_every_records=5) as j:
            for e in events:
                j.append(e)
        blob = open(tmp_journal_path, "rb").read()
        # A committed log: every event present after close().
        with Journal(tmp_journal_path) as j:
            assert list(j.replay()) == events
        for cut in range(len(blob) + 1):
            with open(tmp_journal_path, "wb") as f:
                f.write(blob[:cut])
            with Journal(tmp_journal_path,
                         fsync_every_records=5) as j:
                recovered = list(j.replay())
                # Exact prefix property — order preserved, nothing invented.
                assert recovered == events[:len(recovered)]
                # The journal stays appendable from the clean boundary.
                j.append({"n": "post-crash"})
                j.flush()
                assert list(j.replay())[-1] == {"n": "post-crash"}


# ---- service ----

def test_service_caches_and_persists(tmp_journal_path):
    calls = []
    base = synthetic_provider(length=50, seed=1)

    def counting_provider(symbol, start, end):
        calls.append(symbol)
        return base(symbol, start, end)

    svc = PriceDataService(journal=Journal(tmp_journal_path), provider=counting_provider)
    r1 = svc.request("MSFT", "1992-07-22", "1993-01-01")
    r2 = svc.request("MSFT")  # cache hit — no second fetch
    assert calls == ["MSFT"]
    assert len(r2.series) == 50
    assert len(r1.series) <= 50  # range-filtered
    svc.close()

    # Event-sourced recovery: a fresh service over the same journal needs no fetch.
    svc2 = PriceDataService(journal=Journal(tmp_journal_path), provider=counting_provider)
    r3 = svc2.request("MSFT")
    assert calls == ["MSFT"]
    assert np.allclose(r3.series.prices, r2.series.prices)
    svc2.close()


def test_service_range_filtering(tmp_journal_path):
    svc = PriceDataService(journal=Journal(tmp_journal_path),
                           provider=synthetic_provider(length=100, seed=2))
    full = svc.request("X")
    d0, d9 = str(full.series.dates[10]), str(full.series.dates[19])
    sub = svc.request("X", d0, d9)
    assert len(sub.series) == 10
    svc.close()


def test_service_auto_compacts_on_event_threshold(tmp_path):
    """A long-lived service's price journal must stay bounded WITHOUT anyone
    calling compact() — the reference's config-driven compaction intervals
    (application.conf:7-14). Refresh the same symbols far past the
    threshold and assert the journal never exceeds threshold+symbols."""
    from sharetrade_tpu.config import DataConfig

    cfg = DataConfig(price_compact_every_events=5,
                     journal_dir=str(tmp_path))
    journal = Journal(str(tmp_path / "events.journal"))
    svc = PriceDataService(journal=journal,
                           provider=synthetic_provider(length=50),
                           config=cfg)
    svc.request("AAA")
    svc.request("BBB")
    for _ in range(20):                       # 40 more fetch events
        svc.refresh("AAA")
        svc.refresh("BBB")
        assert len(journal) <= 5 + 2, "journal grew without bound"
    svc.close()
    # Recovery from the auto-compacted journal reproduces the cache.
    j2 = Journal(str(tmp_path / "events.journal"))
    svc2 = PriceDataService(journal=j2, provider=synthetic_provider(length=50))
    assert svc2.cached_symbols() == ["AAA", "BBB"]
    svc2.close()


def test_service_auto_compaction_disabled_by_zero(tmp_path):
    from sharetrade_tpu.config import DataConfig

    cfg = DataConfig(price_compact_every_events=0,
                     journal_dir=str(tmp_path))
    journal = Journal(str(tmp_path / "events.journal"))
    svc = PriceDataService(journal=journal,
                           provider=synthetic_provider(length=50),
                           config=cfg)
    svc.request("AAA")
    for _ in range(10):
        svc.refresh("AAA")
    assert len(journal) == 11                 # untouched: opt-out honored
    svc.close()


def test_service_compaction_not_thrashing_with_many_symbols(tmp_path):
    """With more cached symbols than the threshold, the trigger measures
    REDUNDANCY (events beyond one snapshot per symbol), not raw journal
    size — a size trigger would sit above threshold permanently and
    rewrite the whole journal on every subsequent fetch."""
    from sharetrade_tpu.config import DataConfig

    cfg = DataConfig(price_compact_every_events=2, journal_dir=str(tmp_path))
    journal = Journal(str(tmp_path / "events.journal"))
    svc = PriceDataService(journal=journal,
                           provider=synthetic_provider(length=50), config=cfg)
    for s in ["AA", "BB", "CC", "DD"]:        # 4 symbols > threshold 2
        svc.request(s)
    assert len(journal) == 4       # one event per symbol: nothing to shrink
    svc.refresh("AA")
    assert len(journal) == 5       # accumulates — no per-fetch rewrite
    svc.refresh("AA")
    assert len(journal) == 6
    svc.refresh("AA")              # redundancy 3 > 2: compacts
    assert len(journal) == 4       # back to one snapshot per symbol
    svc.close()


def test_service_bloated_journal_compacts_after_restart(tmp_path):
    """Events replayed at recovery count toward the threshold, so a journal
    bloated by a previous (auto-compaction-off) run shrinks on the first
    fetch after a restart with compaction on."""
    from sharetrade_tpu.config import DataConfig

    path = str(tmp_path / "events.journal")
    off = DataConfig(price_compact_every_events=0, journal_dir=str(tmp_path))
    svc = PriceDataService(journal=Journal(path),
                           provider=synthetic_provider(length=50), config=off)
    for _ in range(9):
        svc.refresh("AAA")
    svc.close()

    on = DataConfig(price_compact_every_events=4, journal_dir=str(tmp_path))
    j2 = Journal(path)
    svc2 = PriceDataService(journal=j2,
                            provider=synthetic_provider(length=50), config=on)
    svc2.refresh("AAA")                       # 10 > 4: compacts
    assert len(j2) == 1                       # one snapshot per symbol
    svc2.close()


# ---- compaction (reference: LevelDB compaction intervals, application.conf:7-14) ----

def test_journal_compact_collapses_and_survives(tmp_journal_path):
    with Journal(tmp_journal_path) as j:
        for i in range(20):
            j.append({"n": i})
        j.compact([{"snapshot": True, "upto": 19}])
        assert list(j.replay()) == [{"snapshot": True, "upto": 19}]
        j.append({"n": 20})  # appends continue on the compacted log
        assert len(j) == 2
    with Journal(tmp_journal_path) as j2:  # recovery sees the compacted log
        assert [e.get("n", -1) for e in j2.replay()] == [-1, 20]


def test_service_compact_preserves_cache(tmp_path):
    journal = Journal(str(tmp_path / "events.journal"))
    svc = PriceDataService(journal=journal,
                           provider=synthetic_provider(length=50))
    svc.request("AAA")
    svc.request("BBB")
    svc.refresh("AAA")  # 3 fetch events total
    assert len(journal) == 3
    svc.compact()
    assert len(journal) == 2  # one snapshot event per symbol
    svc.close()
    # Recovery from the compacted journal reproduces the cache exactly.
    j2 = Journal(str(tmp_path / "events.journal"))
    svc2 = PriceDataService(journal=j2, provider=synthetic_provider(length=50))
    assert svc2.cached_symbols() == ["AAA", "BBB"]
    np.testing.assert_array_equal(
        svc2.request("AAA").series.prices, svc.request("AAA").series.prices)
    svc2.close()


def test_native_journal_compact(tmp_journal_path):
    from sharetrade_tpu.data.native import native_available
    if not native_available():
        pytest.skip("native journal not built")
    from sharetrade_tpu.data.native import NativeJournal
    with NativeJournal(tmp_journal_path) as nj:
        for i in range(10):
            nj.append({"n": i})
        nj.compact([{"snap": True}])
        assert list(nj.replay()) == [{"snap": True}]
        nj.append({"n": 99})
        assert [e.get("n", 0) for e in nj.replay()] == [0, 99]
    # Python backend reads the compacted file (byte compatibility holds).
    with Journal(tmp_journal_path) as j:
        assert len(j) == 2


class TestHttpProvider:
    """The market-data HTTP fetch the reference fakes
    (SharePriceGetter.scala:83 "faking a http query"), made real and
    exercised against a live localhost server."""

    @pytest.fixture
    def price_server(self):
        import http.server
        import threading

        body = b"56.08, 1992-07-22\n55.65, 1992-07-23\nbad row\n57.01, 1992-07-24\n"
        requested = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                requested.append(self.path)
                if self.path.startswith("/prices/"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/csv")
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            yield f"http://127.0.0.1:{server.server_port}", requested
        finally:
            server.shutdown()
            thread.join()

    def test_fetch_parses_like_csv(self, price_server):
        from sharetrade_tpu.data.service import http_provider
        base, requested = price_server
        fetch = http_provider(base + "/prices/{symbol}.csv")
        series = fetch("MSFT")
        assert requested == ["/prices/MSFT.csv"]
        assert series.symbol == "MSFT"
        assert list(series.prices) == [56.08, 55.65, 57.01]  # bad row dropped
        assert str(series.dates[0]) == "1992-07-22"

    def test_service_over_http_caches_and_journals(self, price_server,
                                                   tmp_journal_path):
        from sharetrade_tpu.config import DataConfig
        from sharetrade_tpu.data.journal import Journal
        from sharetrade_tpu.data.service import PriceDataService
        base, requested = price_server
        cfg = DataConfig(http_url=base + "/prices/{symbol}.csv")
        service = PriceDataService(journal=Journal(tmp_journal_path),
                                   config=cfg)
        first = service.request("MSFT")
        again = service.request("MSFT")     # served from cache, no refetch
        assert len(requested) == 1
        assert list(first.series.prices) == list(again.series.prices)
        service.close()
        # Journal replay rebuilds the cache without touching the network.
        revived = PriceDataService(journal=Journal(tmp_journal_path),
                                   config=cfg)
        assert len(requested) == 1
        assert list(revived.request("MSFT").series.prices) == [
            56.08, 55.65, 57.01]
        revived.close()

    def test_fetch_failure_raises(self):
        from urllib.error import URLError
        from sharetrade_tpu.data.service import http_provider
        fetch = http_provider("http://127.0.0.1:9/prices/{symbol}.csv",
                              timeout=0.5)
        with pytest.raises((URLError, OSError)):
            fetch("MSFT")

    def test_empty_body_fails_loudly(self):
        import http.server
        import threading
        from sharetrade_tpu.data.service import http_provider

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"<html>maintenance</html>")

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            fetch = http_provider(
                f"http://127.0.0.1:{server.server_port}/p/{{symbol}}")
            with pytest.raises(ValueError, match="no parsable"):
                fetch("MSFT")
        finally:
            server.shutdown()

    def test_symbol_is_url_quoted(self, price_server):
        from sharetrade_tpu.data.service import http_provider
        base, requested = price_server
        fetch = http_provider(base + "/prices/{symbol}.csv")
        fetch("BRK B")
        assert requested[-1] == "/prices/BRK%20B.csv"

    def test_rejects_non_http_schemes(self, tmp_path):
        """urlopen would happily serve file:// — a config-injection path
        reading local files into the price cache/journal."""
        from sharetrade_tpu.data.service import http_provider
        secret = tmp_path / "secret.csv"
        secret.write_text("56.08, 1992-07-22\n")
        with pytest.raises(ValueError, match="http"):
            http_provider(f"file://{secret}")
        with pytest.raises(ValueError, match="http"):
            http_provider("ftp://quotes.example/{symbol}.csv")

    def test_oversized_response_rejected(self, monkeypatch):
        """A hostile/misconfigured endpoint can't balloon host memory: the
        body is read through a hard byte cap and over-cap responses raise."""
        import sharetrade_tpu.data.service as service_mod
        from sharetrade_tpu.data.service import http_provider

        class FakeResp:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

            def read(self, n=-1):
                # Pretend the body never ends: always fills the request.
                return b"x" * (n if n > 0 else 1)

        # Patch before construction: http_provider binds urlopen at build
        # time (`from urllib.request import urlopen` in its body).
        monkeypatch.setattr("urllib.request.urlopen",
                            lambda url, timeout: FakeResp())
        fetch = http_provider("http://quotes.example/{symbol}.csv")
        with pytest.raises(ValueError, match="response cap"):
            fetch("MSFT")
