"""Learner tests: schedule parity, TD math, replay semantics, every algorithm
end-to-end under jit on a tiny environment."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.agents.base import epsilon_greedy, exploit_probability
from sharetrade_tpu.agents.dqn import ReplayBuffer, fill_replay_from_journal
from sharetrade_tpu.agents.qlearn import make_qlearn_agent
from sharetrade_tpu.config import FrameworkConfig, LearnerConfig
from sharetrade_tpu.data.journal import Journal
from sharetrade_tpu.env import trading
from sharetrade_tpu.models.mlp import q_mlp

WINDOW = 8


def tiny_env(n=64, budget=500.0):
    prices = jnp.linspace(10.0, 20.0, n)
    return trading.make_trading_env(prices, window=WINDOW, initial_budget=budget)


def tiny_config(algo, **learner_kw):
    cfg = FrameworkConfig()
    cfg.learner.algo = algo
    for k, v in learner_kw.items():
        setattr(cfg.learner, k, v)
    cfg.env.window = WINDOW
    cfg.model.hidden_dim = 16
    cfg.model.num_layers = 1
    cfg.model.num_heads = 2
    cfg.model.head_dim = 8
    cfg.parallel.num_workers = 4
    cfg.runtime.chunk_steps = 8
    cfg.learner.unroll_len = 8
    cfg.learner.replay_capacity = 256
    cfg.learner.replay_batch = 16
    return cfg


class TestEpsilonSchedule:
    """QDecisionPolicyActor.scala:58: exploit iff rand < min(0.9, step/1000)."""

    def test_ramp_values(self):
        cfg = LearnerConfig()
        for step, want in [(0, 0.0), (500, 0.5), (900, 0.9), (5000, 0.9)]:
            got = float(exploit_probability(jnp.int32(step), cfg))
            assert got == pytest.approx(want), step

    def test_step_zero_is_uniform_random(self):
        # At step 0 exploit prob is 0: action never comes from argmax.
        cfg = LearnerConfig()
        q = jnp.array([100.0, -100.0, -100.0])  # argmax = 0, overwhelmingly
        keys = jax.random.split(jax.random.PRNGKey(0), 300)
        acts = jax.vmap(lambda k: epsilon_greedy(k, q, jnp.int32(0), cfg))(keys)
        counts = np.bincount(np.asarray(acts), minlength=3)
        assert (counts > 50).all()  # all three actions occur ~uniformly

    def test_late_steps_mostly_greedy(self):
        cfg = LearnerConfig()
        q = jnp.array([-5.0, 10.0, -5.0])
        keys = jax.random.split(jax.random.PRNGKey(1), 300)
        acts = jax.vmap(lambda k: epsilon_greedy(k, q, jnp.int32(10_000), cfg))(keys)
        frac_greedy = float(np.mean(np.asarray(acts) == 1))
        assert 0.85 < frac_greedy < 0.99  # ~ 0.9 + 0.1/3


class TestQLearnTD:
    def _run_one_step(self, update_taken_action):
        env = tiny_env()
        cfg = LearnerConfig(update_taken_action=update_taken_action)
        model = q_mlp(obs_dim=WINDOW + 2, hidden_dim=4, parity=True)
        agent = make_qlearn_agent(model, env, cfg,
                                  num_agents=1, steps_per_chunk=1)
        ts = agent.init(jax.random.PRNGKey(42))
        ts2, metrics = jax.jit(agent.step)(ts)
        return ts, ts2, metrics, model, env, cfg

    def test_one_step_matches_independent_computation(self):
        ts, ts2, metrics, model, env, cfg = self._run_one_step(True)

        # Replicate the step with straight-line code (no scan, no masking).
        rng, k_act = jax.random.split(ts.rng)
        act_key = jax.random.split(k_act, 1)[0]
        obs = env.observe(jax.tree.map(lambda x: x[0], ts.env_state))
        q_s, _ = model.apply(ts.params, obs, ())
        action = epsilon_greedy(act_key, q_s.logits, ts.env_steps, cfg)
        env1, reward = env.step(
            jax.tree.map(lambda x: x[0], ts.env_state), action)
        next_obs = env.observe(env1)

        def loss(params):
            q, _ = model.apply(params, obs, ())
            qn, _ = model.apply(params, next_obs, ())
            target = reward + cfg.gamma * jnp.max(jax.lax.stop_gradient(qn.logits))
            return jnp.square(q.logits[action] - target)

        grads = jax.grad(loss)(ts.params)
        opt = optax.adagrad(cfg.learning_rate)
        updates, _ = opt.update(grads, opt.init(ts.params), ts.params)
        want = optax.apply_updates(ts.params, updates)

        for got_leaf, want_leaf in zip(jax.tree.leaves(ts2.params),
                                       jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(got_leaf),
                                       np.asarray(want_leaf), rtol=1e-5, atol=1e-6)
        assert int(ts2.updates) == 1 and int(ts2.env_steps) == 1

    def test_bug_parity_mode_differs(self):
        # The reference updates the NEXT state's argmax index
        # (QDecisionPolicyActor.scala:69-71); textbook updates the taken
        # action. With enough steps the two must produce different params.
        def run(taken):
            env = tiny_env()
            cfg = LearnerConfig(update_taken_action=taken)
            # parity=False: the parity head's output ReLU can kill every
            # gradient at tiny widths, making the two modes trivially equal.
            model = q_mlp(obs_dim=WINDOW + 2, hidden_dim=4, parity=False)
            agent = make_qlearn_agent(model, env, cfg,
                                      num_agents=2, steps_per_chunk=20)
            ts0 = agent.init(jax.random.PRNGKey(7))
            ts, _ = jax.jit(agent.step)(ts0)
            return ts0.params, ts.params

        p0, p_fixed = run(True)
        _, p_bug = run(False)
        trained = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
                   for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p_fixed))]
        assert max(trained) > 0, "training was a no-op; test is vacuous"
        diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
                 for a, b in zip(jax.tree.leaves(p_fixed), jax.tree.leaves(p_bug))]
        assert max(diffs) > 0

    def test_horizon_freeze(self):
        # Chunks past episode end must not step envs or update params.
        env = tiny_env(n=WINDOW + 3)  # 3-step episode
        cfg = LearnerConfig()
        model = q_mlp(obs_dim=WINDOW + 2, hidden_dim=4)
        agent = make_qlearn_agent(model, env, cfg,
                                  num_agents=2, steps_per_chunk=10)
        ts = agent.init(jax.random.PRNGKey(0))
        ts, _ = jax.jit(agent.step)(ts)
        assert int(ts.env_steps) == 3
        assert int(ts.updates) == 3
        assert np.asarray(ts.env_state.t).tolist() == [3, 3]
        ts2, _ = jax.jit(agent.step)(ts)
        assert int(ts2.env_steps) == 3  # fully frozen
        for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ts2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestGAE:
    def test_matches_hand_rolled_recursion_with_mid_rollout_termination(self):
        """Episode ends at step 3 of a 5-step unroll: padded steps carry
        frozen values, and the terminal value must not bootstrap into the
        last real step's advantage (next-step liveness gating)."""
        from sharetrade_tpu.agents.rollout import gae_advantages

        gamma, lam = 0.9, 0.8
        # (T=5, B=1); steps 0..2 real, 3..4 padding (env frozen at terminal).
        rewards = jnp.array([1.0, -0.5, 2.0, 0.0, 0.0])[:, None]
        values = jnp.array([0.3, 0.1, 0.4, 0.7, 0.7])[:, None]
        active = jnp.array([1.0, 1.0, 1.0, 0.0, 0.0])[:, None]
        bootstrap = jnp.zeros((1,))  # collect_rollout zero-masks it at the end

        got = np.asarray(gae_advantages(
            rewards, values, active, bootstrap, gamma, lam)).ravel()

        # Hand recursion with next-step liveness: live_next = active[t+1]
        # (1.0 for the final slice — its successor value is the bootstrap).
        live_next = [1.0, 1.0, 0.0, 0.0, 1.0]
        next_values = [0.1, 0.4, 0.7, 0.7, 0.0]
        adv = [0.0] * 5
        adv_next = 0.0
        for t in reversed(range(5)):
            delta = (float(rewards[t, 0])
                     + gamma * next_values[t] * live_next[t]
                     - float(values[t, 0]))
            adv[t] = delta + gamma * lam * adv_next * live_next[t]
            adv_next = adv[t]
        np.testing.assert_allclose(got, adv, rtol=1e-6)
        # The last REAL step's advantage is exactly r - V(s): no V_terminal.
        np.testing.assert_allclose(got[2], 2.0 - 0.4, rtol=1e-6)


class TestReplayForwardFold:
    """The stateless fold path (one big batched forward) must match the
    per-step scan path exactly — a reshape-order bug here would silently
    permute time/batch rows in every PPO/A2C/PG loss."""

    def _traj_and_model(self, hidden=16, t=6, b=4, obs_dim=10):
        from sharetrade_tpu.agents.rollout import StepData
        from sharetrade_tpu.models.mlp import ac_mlp
        model = ac_mlp(obs_dim, hidden)
        params = model.init(jax.random.PRNGKey(0))
        obs = jax.random.uniform(jax.random.PRNGKey(1), (t, b, obs_dim))
        z = jnp.zeros((t, b))
        traj = StepData(obs=obs, action=z.astype(jnp.int32), logp=z,
                        value=z, reward=z, active=z + 1.0)
        return model, params, traj

    def _scan_reference(self, model, params, traj):
        from sharetrade_tpu.models.core import apply_batched

        def one_step(carry, obs_t):
            outs, _ = apply_batched(model, params, obs_t, ())
            return carry, (outs.logits, outs.value)

        _, (logits, values) = jax.lax.scan(one_step, None, traj.obs)
        return logits, values

    @pytest.mark.parametrize("max_rows", [10_000, 8, 1])
    def test_fold_matches_scan(self, max_rows, monkeypatch):
        """max_rows sweeps single-fold, grouped (fold=2), and per-step."""
        from sharetrade_tpu.agents import rollout
        monkeypatch.setattr(rollout, "_MAX_FOLD_ROWS", max_rows)
        model, params, traj = self._traj_and_model()
        want_l, want_v = self._scan_reference(model, params, traj)
        for remat in (False, True):
            got_l, got_v, _aux = rollout.replay_forward(
                model, params, traj, (), remat=remat)
            np.testing.assert_allclose(np.asarray(got_l), np.asarray(want_l),
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(got_v), np.asarray(want_v),
                                       rtol=1e-5, atol=1e-6)

    def test_fold_gradients_match_scan(self, monkeypatch):
        from sharetrade_tpu.agents import rollout
        monkeypatch.setattr(rollout, "_MAX_FOLD_ROWS", 8)  # 2 groups
        model, params, traj = self._traj_and_model()

        def loss_fold(p):
            lg, v, _ = rollout.replay_forward(model, p, traj, (), remat=True)
            return jnp.sum(lg ** 2) + jnp.sum(v ** 2)

        def loss_scan(p):
            lg, v = self._scan_reference(model, p, traj)
            return jnp.sum(lg ** 2) + jnp.sum(v ** 2)

        g_fold = jax.grad(loss_fold)(params)
        g_scan = jax.grad(loss_scan)(params)
        for a, b in zip(jax.tree.leaves(g_fold), jax.tree.leaves(g_scan)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_lstm_keeps_carry_scan(self):
        """Recurrent models must stay on the carry-threading path."""
        from sharetrade_tpu.agents import rollout
        from sharetrade_tpu.agents.rollout import StepData
        from sharetrade_tpu.models.lstm import lstm_policy
        t, b, obs_dim = 3, 2, 10
        model = lstm_policy(obs_dim, 8)
        params = model.init(jax.random.PRNGKey(0))
        carry = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (b,) + x.shape), model.init_carry())
        one = jax.random.uniform(jax.random.PRNGKey(1), (b, obs_dim))
        obs = jnp.broadcast_to(one, (t, b, obs_dim))   # identical every step
        z = jnp.zeros((t, b))
        traj = StepData(obs=obs, action=z.astype(jnp.int32), logp=z,
                        value=z, reward=z, active=z + 1.0)
        logits, values, _ = rollout.replay_forward(model, params, traj, carry)
        # Same obs at every step must give DIFFERENT outputs (carry evolves).
        assert not np.allclose(np.asarray(logits[0]), np.asarray(logits[1]))


class TestReplayBuffer:
    def test_push_wraps_and_masks(self):
        rb = ReplayBuffer.create(8, 3)
        obs = jnp.arange(12.0).reshape(4, 3)
        rb = rb.push(obs, jnp.zeros(4, jnp.int32), jnp.ones(4),
                     obs + 100, jnp.array([True, True, False, True]))
        assert int(rb.size) == 3 and int(rb.pos) == 3
        # Valid rows compacted: rows 0, 1, 3 stored.
        np.testing.assert_allclose(np.asarray(rb.obs[:3, 0]), [0.0, 3.0, 9.0])
        for _ in range(3):
            rb = rb.push(obs, jnp.zeros(4, jnp.int32), jnp.ones(4),
                         obs + 100, jnp.ones(4, bool))
        assert int(rb.size) == 8  # capacity-clamped
        assert int(rb.pos) == (3 + 12) % 8

    def test_sample_in_range(self):
        rb = ReplayBuffer.create(16, 2)
        rb = rb.push(jnp.ones((4, 2)), jnp.ones(4, jnp.int32) * 2,
                     jnp.ones(4), jnp.zeros((4, 2)), jnp.ones(4, bool))
        o, a, r, n = rb.sample(jax.random.PRNGKey(0), 32)
        assert o.shape == (32, 2) and (np.asarray(a) == 2).all()

    def test_journal_fill(self, tmp_journal_path):
        with Journal(tmp_journal_path) as j:
            j.append({"type": "transitions",
                      "obs": [[1.0, 2.0]], "action": [1],
                      "reward": [0.5], "next_obs": [[3.0, 4.0]]})
            rb = fill_replay_from_journal(ReplayBuffer.create(4, 2), j)
        assert int(rb.size) == 1
        np.testing.assert_allclose(np.asarray(rb.obs[0]), [1.0, 2.0])


@pytest.mark.slow
@pytest.mark.parametrize("algo", ["qlearn", "pg", "dqn", "a2c", "ppo"])
def test_every_algorithm_trains_a_chunk(algo):
    cfg = tiny_config(algo)
    agent = build_agent(cfg, tiny_env())
    ts = agent.init(jax.random.PRNGKey(0))
    step = jax.jit(agent.step)
    ts2, metrics = step(ts)
    # Params changed, counters advanced, metrics finite.
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ts2.params)))
    assert changed, f"{algo}: params did not change"
    assert int(ts2.env_steps) > 0
    assert int(ts2.updates) > 0
    for k, v in metrics.items():
        assert np.isfinite(np.asarray(v)).all(), f"{algo}: {k} not finite"
    # Second chunk composes (scan carry shapes stable).
    ts3, _ = step(ts2)
    assert int(ts3.env_steps) >= int(ts2.env_steps)


@pytest.mark.parametrize("algo", ["pg", "a2c"])
def test_normalized_advantages_reachable_and_change_training(algo):
    """learner.normalize_advantages must actually alter the PG/A2C update
    (zero-mean unit-variance advantages over active steps) — not silently
    no-op — while the default-off path preserves the textbook estimator."""
    outs = {}
    for norm in (False, True):
        cfg = tiny_config(algo, normalize_advantages=norm, gamma=0.9)
        agent = build_agent(cfg, tiny_env())
        ts = agent.init(jax.random.PRNGKey(0))
        ts2, metrics = jax.jit(agent.step)(ts)
        assert np.isfinite(float(metrics["loss"])), (algo, norm)
        outs[norm] = jax.device_get(ts2.params)
    diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
             for a, b in zip(jax.tree.leaves(outs[False]),
                             jax.tree.leaves(outs[True]))]
    assert max(diffs) > 0, f"{algo}: normalization changed nothing"


def test_value_based_algos_reject_recurrent_models():
    cfg = tiny_config("dqn")
    cfg.model.kind = "lstm"
    with pytest.raises(ValueError, match="requires model.kind='mlp'"):
        build_agent(cfg, tiny_env())


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["lstm", "transformer"])
def test_recurrent_and_attention_policies_with_ppo(kind):
    cfg = tiny_config("ppo")
    cfg.model.kind = kind
    agent = build_agent(cfg, tiny_env())
    ts = agent.init(jax.random.PRNGKey(0))
    ts2, metrics = jax.jit(agent.step)(ts)
    assert np.isfinite(float(metrics["loss"]))
    if kind == "lstm":
        # Carry must have evolved over the unroll.
        h0 = np.asarray(ts.carry[0])
        h1 = np.asarray(ts2.carry[0])
        assert not np.allclose(h0, h1)
