"""Fleet serving tier (fleet/ — ISSUE 15): network front-end, telemetry-
routed engine fleet, flywheel journaling.

The load-bearing contracts:

- **Wire fidelity**: serving over HTTP is the SAME serving — bitwise
  logits through JSON, every engine-side outcome reconstructed as its
  exact exception class from a distinct wire status.
- **Deadline propagation**: the client's ``X-Deadline-Ms`` header flows
  into ``submit(deadline_ms=)`` and expiry happens at the ENGINE's
  batch-collection gate (the engine-side counter moves), never on a
  router/front-end timer.
- **Exact merge**: fleet p50/p99 come from bucket-wise merged
  ``_bucket`` expositions — merged-shard quantiles equal concatenated-
  sample quantiles within one bucket width, through a full
  render→scrape→rebuild round trip.
- **Migration**: kill a session's affine engine mid-conversation and its
  next request lands on a survivor COLD — bitwise a fresh session's
  first step (the PR-8 prefill contract stretched across processes).
- **Degrade**: all engines gone ⇒ the router answers ServeEngineFailed
  (503) loudly, never a wedge; the EnginePool's ladder (shared with
  distrib/) classifies crashes, backs off seeded, and fails terminally
  past the budget.
- **Flywheel**: journaling sessions write learner-ingestible transition
  journals with monotone stamps that survive writer restarts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from sharetrade_tpu.config import FleetConfig, ModelConfig, ServeConfig
from sharetrade_tpu.fleet import (
    EngineBackend,
    EnginePool,
    FleetClient,
    FleetRouter,
    ServeFrontend,
    StaticEndpoints,
    WireEngine,
)
from sharetrade_tpu.fleet import wire
from sharetrade_tpu.models import build_model
from sharetrade_tpu.obs.exporter import parse_prom_text, render_prom_text
from sharetrade_tpu.obs.hist import Histogram, from_prom_buckets, merge
from sharetrade_tpu.serve import ServeEngine
from sharetrade_tpu.serve.engine import (
    ServeDeadlineExceeded,
    ServeEngineFailed,
    ServeRejected,
    latency_percentiles,
)
from sharetrade_tpu.utils.metrics import MetricsRegistry

WINDOW = 8
OBS_DIM = WINDOW + 2


def _obs(seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.uniform(1.0, 2.0, OBS_DIM).astype(np.float32)


@pytest.fixture(scope="module")
def mlp_model():
    model = build_model(ModelConfig(kind="mlp", hidden_dim=16), OBS_DIM,
                        head="ac")
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def lstm_model():
    model = build_model(ModelConfig(kind="lstm", hidden_dim=8), OBS_DIM,
                        head="ac")
    return model, model.init(jax.random.PRNGKey(1))


def _boot_engine(model, params, *, step=0, registry=None, **serve_kw):
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("slots", 8)
    serve_kw.setdefault("batch_timeout_ms", 1.0)
    serve_kw.setdefault("stats_interval_s", 0.2)
    registry = registry or MetricsRegistry()
    engine = ServeEngine(model, ServeConfig(**serve_kw), params,
                         params_step=step, registry=registry)
    engine.warmup()
    frontend = ServeFrontend(EngineBackend(engine), registry).start()
    return engine, frontend, registry


# ---------------------------------------------------------------------------
# wire protocol


class TestWireProtocol:
    def test_status_mapping_roundtrip(self):
        for exc, status in [
                (ServeRejected("q full", reason="queue_full"),
                 wire.STATUS_REJECTED),
                (ServeDeadlineExceeded("late"), wire.STATUS_DEADLINE),
                (ServeEngineFailed("dead"), wire.STATUS_UNAVAILABLE),
                (ValueError("bad obs"), wire.STATUS_BAD_REQUEST)]:
            code, body = wire.error_to_status(exc)
            assert code == status
            back = wire.status_to_error(code, body)
            assert type(back) is type(exc)
        rej = wire.status_to_error(
            *wire.error_to_status(
                ServeRejected("shed", reason="shed_oldest")))
        assert rej.reason == "shed_oldest"

    def test_submit_over_wire_bitwise(self, mlp_model):
        model, params = mlp_model
        engine, frontend, _ = _boot_engine(model, params, step=11)
        try:
            client = FleetClient(frontend.host, frontend.port)
            obs = _obs(3)
            out = client.submit("w1", obs)
            direct, _ = model.apply(params, obs, model.init_carry())
            # float64 JSON round-trips float32 exactly: the serving
            # tier's bitwise parity contract survives the wire.
            assert np.asarray(out["logits"], np.float32).tobytes() \
                == np.asarray(direct.logits, np.float32).tobytes()
            assert out["params_step"] == 11
            assert out["action"] == int(np.argmax(
                np.asarray(direct.logits)))
            stages = out["stages"]
            assert abs(sum(stages.values()) - out["latency_ms"]) < 1e-6
            client.close()
        finally:
            frontend.stop()
            engine.stop(drain=False)

    def test_malformed_and_missing(self, mlp_model):
        model, params = mlp_model
        engine, frontend, _ = _boot_engine(model, params)
        try:
            client = FleetClient(frontend.host, frontend.port)
            with pytest.raises(ValueError):
                client.submit("w2", [float("nan")] * OBS_DIM)
            status, _ = client._request("POST", "/nope", body=b"{}")
            assert status == 404
            status, _ = client._request("POST", wire.SUBMIT_PATH,
                                        body=b"not json")
            assert status == wire.STATUS_BAD_REQUEST
            client.close()
        finally:
            frontend.stop()
            engine.stop(drain=False)

    def test_metrics_exposition_valid(self, mlp_model):
        model, params = mlp_model
        engine, frontend, _ = _boot_engine(model, params)
        try:
            client = FleetClient(frontend.host, frontend.port)
            client.submit("w3", _obs())
            parsed = parse_prom_text(client.metrics())   # strict parser
            assert "sharetrade_serve_request_ms" in parsed["histograms"]
            assert parsed["counters"][
                "sharetrade_serve_requests_total"] >= 1
            client.close()
        finally:
            frontend.stop()
            engine.stop(drain=False)


# ---------------------------------------------------------------------------
# exact histogram merge at the router


class TestFleetHistogramMerge:
    def test_merged_shards_equal_concatenation(self):
        """Fleet p50/p99 from bucket-wise-merged scraped shards == the
        quantile of the concatenated raw samples, within one bucket
        width — through the FULL wire round trip (render → strict parse
        → rebuild → merge)."""
        rng = np.random.default_rng(7)
        shards, all_samples = [], []
        for e in range(4):
            h = Histogram()
            samples = rng.lognormal(mean=1.0 + 0.3 * e, sigma=1.0,
                                    size=500)
            for s in samples:
                h.observe(float(s))
            all_samples.extend(float(s) for s in samples)
            text = render_prom_text({}, {},
                                    {"serve_request_ms": h.snapshot()})
            parsed = parse_prom_text(text)[
                "histograms"]["sharetrade_serve_request_ms"]
            rebuilt = from_prom_buckets(parsed["buckets"], parsed["sum"],
                                        int(parsed["count"]))
            # The scrape is lossless: exact integer counts, exact bounds.
            assert rebuilt.snapshot()["counts"] == h.snapshot()["counts"]
            assert rebuilt.bounds == h.bounds
            shards.append(rebuilt)
        fleet = merge(shards)
        assert fleet.count == len(all_samples)
        exact = latency_percentiles(all_samples)
        for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
            est = fleet.quantile(q)
            idx = np.searchsorted(fleet.bounds, exact[key])
            lo = fleet.bounds[idx - 1] if idx > 0 else 0.0
            hi = (fleet.bounds[idx] if idx < len(fleet.bounds)
                  else fleet.bounds[-1])
            assert abs(est - exact[key]) <= (hi - lo) + 1e-9, \
                f"{key}: est {est} vs exact {exact[key]}"

    def test_from_prom_refuses_garbage(self):
        with pytest.raises(ValueError):
            from_prom_buckets([("1", 5), ("2", 3), ("+Inf", 3)], 0.0, 3)
        with pytest.raises(ValueError):
            from_prom_buckets([("1", 5)], 0.0, 5)      # no +Inf terminal
        with pytest.raises(ValueError):
            from_prom_buckets([("1", 2), ("+Inf", 5)], 0.0, 9)  # != count


# ---------------------------------------------------------------------------
# deadline propagation over the wire


class TestWireDeadline:
    def test_deadline_expires_engine_side(self, mlp_model):
        """A 50 ms-deadline request expires at the ENGINE's batch-
        collection gate (its counter moves), not on a router/front-end
        timer — routed through the full router→engine wire path."""
        model, params = mlp_model
        engine, frontend, ereg = _boot_engine(
            model, params, batch_timeout_ms=250.0, max_batch=4)
        rreg = MetricsRegistry()
        router = FleetRouter(
            StaticEndpoints({"e0": (frontend.host, frontend.port)}),
            FleetConfig(), rreg, workdir="")
        rfe = ServeFrontend(router, rreg).start()
        try:
            client = FleetClient(rfe.host, rfe.port)
            w1 = WireEngine(rfe.host, rfe.port, workers=3)
            # Tick 1 collects s-dl's FIRST request and coalesces for the
            # full 250 ms window (no deadline on it); the same-session
            # follower with a 50 ms deadline sits DEFERRED past its
            # expiry and dies at the next collection pop — engine-side.
            h1 = w1.submit("s-dl", _obs(1))
            time.sleep(0.01)
            before = ereg.counters().get(
                "serve_deadline_expired_total", 0)
            with pytest.raises(ServeDeadlineExceeded):
                client.submit("s-dl", _obs(2), deadline_ms=50.0)
            after = ereg.counters().get("serve_deadline_expired_total", 0)
            assert after == before + 1, \
                "expiry must be the engine's, not a proxy timeout"
            assert h1.wait(5.0) is not None
            w1.stop()
            client.close()
        finally:
            rfe.stop()
            router.stop()
            frontend.stop()
            engine.stop(drain=False)


# ---------------------------------------------------------------------------
# router: affinity, migration, degrade


class TestRouterMigration:
    def test_affinity_sticks_and_migrates_bitwise(self, lstm_model):
        """A session sticks to its engine's slot-pool carry; killing the
        engine mid-conversation re-routes the next request to a survivor
        where the session re-enters COLD through the prefill — bitwise a
        fresh session's first step (an LSTM makes warm≠cold observable:
        a surviving warm carry would change the logits)."""
        model, params = lstm_model
        e1, f1, _ = _boot_engine(model, params, step=1)
        e2, f2, _ = _boot_engine(model, params, step=1)
        reg = MetricsRegistry()
        endpoints = StaticEndpoints({"e0": (f1.host, f1.port),
                                     "e1": (f2.host, f2.port)})
        router = FleetRouter(endpoints, FleetConfig(), reg,
                             workdir="")
        rfe = ServeFrontend(router, reg).start()
        try:
            client = FleetClient(rfe.host, rfe.port)
            obs_a, obs_b = _obs(10), _obs(11)
            first = client.submit("mig", obs_a)
            home = first["engine"]
            warm = client.submit("mig", obs_b)
            assert warm["engine"] == home
            # Warm logits differ from a cold first step on obs_b — the
            # carry is real, so the migration claim below is non-trivial.
            cold_out, _ = model.apply(params, obs_b, model.init_carry())
            cold_logits = np.asarray(cold_out.logits, np.float32)
            assert np.asarray(warm["logits"], np.float32).tobytes() \
                != cold_logits.tobytes()
            # Kill the home engine (process-death stand-in).
            victim_fe, victim_eng = (f1, e1) if home == "e0" else (f2, e2)
            victim_fe.stop()
            victim_eng.stop(drain=False)
            migrated = client.submit("mig", obs_b)
            assert migrated["engine"] != home
            assert np.asarray(migrated["logits"], np.float32).tobytes() \
                == cold_logits.tobytes(), \
                "migrated session must equal a fresh session bitwise"
            assert reg.counters().get("fleet_migrations_total", 0) == 1
            client.close()
        finally:
            rfe.stop()
            router.stop()
            for fe, eng in ((f1, e1), (f2, e2)):
                fe.stop()
                eng.stop(drain=False)

    def test_degrade_when_all_engines_gone(self, mlp_model):
        model, params = mlp_model
        engine, frontend, _ = _boot_engine(model, params)
        reg = MetricsRegistry()
        router = FleetRouter(
            StaticEndpoints({"e0": (frontend.host, frontend.port)}),
            FleetConfig(), reg, workdir="")
        try:
            assert router.serve_request("d1", _obs(), None)["engine"] \
                == "e0"
            frontend.stop()
            engine.stop(drain=False)
            with pytest.raises(ServeEngineFailed):
                router.serve_request("d1", _obs(), None)
            assert reg.counters().get("fleet_unrouted_total", 0) >= 1
            # Still degraded, still loud — never a wedge.
            with pytest.raises(ServeEngineFailed):
                router.serve_request("d2", _obs(), None)
        finally:
            router.stop()
            frontend.stop()
            engine.stop(drain=False)


# ---------------------------------------------------------------------------
# engine pool supervision (stub children — no jax bring-up)


_HEALTHY_STUB = r"""
import json, sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a): pass
    def do_GET(self):
        body = json.dumps({"ok": True, "queue_depth": 1, "overload": 0,
                           "params_step": 3, "swaps_total": 0}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
print(json.dumps({"event": "engine_listening", "host": "127.0.0.1",
                  "port": srv.server_address[1]}), flush=True)
srv.serve_forever()
"""


def _stub_spawn(script: str):
    def spawn(engine_id: str, log_path: str):
        with open(log_path, "ab") as log_f:
            return subprocess.Popen([sys.executable, "-c", script],
                                    stdout=log_f,
                                    stderr=subprocess.STDOUT)
    return spawn


def _fleet_cfg(tmp_path, **kw):
    from sharetrade_tpu.config import FrameworkConfig
    cfg = FrameworkConfig()
    cfg.fleet.dir = str(tmp_path / "fleet")
    cfg.fleet.num_engines = kw.pop("num_engines", 2)
    cfg.fleet.engine_backoff_initial_s = 0.05
    cfg.fleet.engine_backoff_max_s = 0.2
    cfg.fleet.startup_timeout_s = kw.pop("startup_timeout_s", 30.0)
    cfg.fleet.health_timeout_s = kw.pop("health_timeout_s", 0.0)
    cfg.fleet.max_engine_restarts = kw.pop("max_engine_restarts", 2)
    for k, v in kw.items():
        setattr(cfg.fleet, k, v)
    return cfg


def _pump(pool, predicate, timeout_s=15.0, desc="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pool.poll_once()
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {desc}")


class TestEnginePool:
    def test_ready_health_crash_respawn_terminal(self, tmp_path):
        cfg = _fleet_cfg(tmp_path, max_engine_restarts=1)
        pool = EnginePool(cfg, spawn_fn=_stub_spawn(_HEALTHY_STUB))
        # No supervise thread: the test steps the pool deterministically.
        pool.target = 2
        with pool._lock:
            pool._spawn_new_locked()
            pool._spawn_new_locked()
        try:
            _pump(pool, lambda: pool.counts()["alive"] == 2
                  and len(pool.endpoints()) == 2
                  and all(h.state == "alive"
                          for h in pool._engines.values()),
                  desc="both stubs alive via healthz")
            status = pool.status()
            assert status["engines"]["e0"]["params_step"] == 3
            assert status["engines"]["e0"]["queue_depth"] == 1
            # SIGKILL e0: crash → seeded backoff → respawn → healthy
            # again, streak reset.
            h0 = pool._engines["e0"]
            pid0 = h0.pid
            h0.proc.kill()
            _pump(pool, lambda: pool.restarts_total == 1
                  and pool._engines["e0"].state == "alive"
                  and pool._engines["e0"].pid != pid0,
                  desc="e0 respawned and healthy")
            assert pool._engines["e0"].streak == 0
            # Now make e0 die repeatedly: replace its spawn with a
            # fail-fast stub → streak past max_engine_restarts=1 →
            # terminal FAILED, e1 untouched (degrade onto survivors).
            pool._spawn_fn = _stub_spawn("raise SystemExit(9)")
            pool._engines["e0"].proc.kill()
            _pump(pool, lambda: pool._engines["e0"].state == "failed",
                  desc="e0 terminally failed")
            assert pool._engines["e1"].state == "alive"
            assert pool.counts()["failed"] == 1
            assert "e0" not in pool.endpoints()
            assert "e1" in pool.endpoints()
        finally:
            pool.kill_all()
            pool.stop(grace_s=2.0)

    def test_startup_timeout_kills_wedged_bringup(self, tmp_path):
        cfg = _fleet_cfg(tmp_path, num_engines=1, startup_timeout_s=0.3,
                         max_engine_restarts=0)
        # Child that never prints a listening line = wedged bring-up.
        pool = EnginePool(
            cfg, spawn_fn=_stub_spawn("import time; time.sleep(60)"))
        pool.target = 1
        with pool._lock:
            pool._spawn_new_locked()
        try:
            _pump(pool, lambda: pool._engines["e0"].state == "failed",
                  desc="wedged bring-up killed and failed terminally")
            assert pool.restarts_total == 1
        finally:
            pool.kill_all()
            pool.stop(grace_s=2.0)

    def test_quiesced_exits_retire(self, tmp_path):
        cfg = _fleet_cfg(tmp_path, num_engines=1)
        pool = EnginePool(cfg, spawn_fn=_stub_spawn(_HEALTHY_STUB))
        pool.target = 1
        with pool._lock:
            pool._spawn_new_locked()
        try:
            _pump(pool, lambda: pool.counts()["alive"] == 1,
                  desc="stub alive")
            pool.quiesce()
            pool._engines["e0"].proc.kill()
            _pump(pool, lambda: pool._engines["e0"].state == "retired",
                  desc="quiesced exit retires, not crashes")
            assert pool.restarts_total == 0
        finally:
            pool.kill_all()
            pool.stop(grace_s=2.0)


# ---------------------------------------------------------------------------
# flywheel journaling


class TestFlywheelJournal:
    def test_sessions_journal_ingestible_rows(self, tmp_path):
        from sharetrade_tpu.data.transitions import read_new_transitions
        from sharetrade_tpu.fleet.flywheel import (
            SessionTransitionJournal, make_journaling_sessions)
        root = str(tmp_path / "actors")
        journal = SessionTransitionJournal(root, "fleet-w0",
                                           obs_dim=OBS_DIM,
                                           flush_rows=8)
        prices = np.linspace(10, 20, 64).astype(np.float32)
        sessions = make_journaling_sessions(prices, WINDOW, 3,
                                            journal=journal, seed=0)
        for step in range(10):
            for s in sessions:
                s.advance(action=step % 3)
        journal.flush()
        out = read_new_transitions(journal.path, 0, 10_000)
        assert out is not None
        obs, action, reward, next_obs, high_water = out
        assert obs.shape[1] == OBS_DIM          # the learner's obs_dim
        assert next_obs.shape == obs.shape
        assert np.isfinite(reward).all()
        rows0 = obs.shape[0]
        assert rows0 == journal.rows_journaled
        assert high_water == rows0              # monotone row stamps
        journal.close()
        # A writer restart continues past the recovered high-water:
        # stamps never reuse, so a learner cursor never re-reads rows.
        journal2 = SessionTransitionJournal(root, "fleet-w0",
                                            obs_dim=OBS_DIM,
                                            flush_rows=4)
        sessions2 = make_journaling_sessions(prices, WINDOW, 1,
                                             journal=journal2, seed=1)
        for _ in range(4):
            sessions2[0].advance(action=0)
        journal2.close()
        out2 = read_new_transitions(journal.path, rows0, 10_000)
        assert out2 is not None and out2[0].shape[0] == 4
        assert out2[4] == rows0 + 4

    def test_wrap_boundary_rows_skipped(self, tmp_path):
        from sharetrade_tpu.fleet.flywheel import (
            SessionTransitionJournal, JournalingSession)
        journal = SessionTransitionJournal(str(tmp_path / "a"), "w",
                                           obs_dim=OBS_DIM,
                                           flush_rows=1)
        prices = np.linspace(10, 20, WINDOW + 2).astype(np.float32)
        sess = JournalingSession("s", prices, WINDOW, 0, journal=journal)
        sess.advance(0)     # t 0→1: records one row
        gen = sess.generation
        sess.advance(0)     # wraps: boundary row must be skipped
        assert sess.generation == gen + 1
        journal.close()
        from sharetrade_tpu.data.transitions import read_new_transitions
        out = read_new_transitions(journal.path, 0, 100)
        assert out is not None and out[0].shape[0] == 1


# ---------------------------------------------------------------------------
# lint check 14 + cli obs fleet section


class TestFleetLintAndObs:
    def test_lint_fleet_net_semantics(self, tmp_path):
        import lint_hot_loop
        pkg = tmp_path / "pkg"
        (pkg / "fleet").mkdir(parents=True)
        (pkg / "serve").mkdir()
        (pkg / "fleet" / "fe.py").write_text(
            "import socketserver\nsrv = socketserver.TCPServer(a, h)\n")
        (pkg / "serve" / "bad.py").write_text(
            "import socket\ns = socket.socket()\n")
        (pkg / "serve" / "ok.py").write_text(
            "import socket\n"
            "s = socket.socket()  # fleet-net-ok: test probe\n")
        listener_bad, _ = lint_hot_loop.lint_fleet_net(root=pkg)
        assert [(r, ln) for r, ln, _ in listener_bad] \
            == [("serve/bad.py", 2)]
        # The real tree is clean (the repo-level invariant).
        real_listeners, real_dispatch = lint_hot_loop.lint_fleet_net()
        assert real_listeners == [] and real_dispatch == []

    def test_cli_obs_fleet_section(self, tmp_path):
        from sharetrade_tpu.obs import summarize_run_dir
        status = {
            "ts": 1.0,
            "router": {"ok": True, "engines_live": 2,
                       "affinity_sessions": 17, "params_steps": [4, 6]},
            "pool": {"alive": 2, "failed": 1, "restarts_total": 3,
                     "engines": {
                         "e0": {"state": "alive", "pid": 10, "port": 1,
                                "restarts": 0, "params_step": 6,
                                "queue_depth": 2},
                         "e1": {"state": "failed", "pid": None,
                                "port": None, "restarts": 3,
                                "params_step": None,
                                "queue_depth": None}}},
            "telemetry": {"e0": {"healthy": True,
                                 "window_p99_ms": 12.5}},
            "gauges": {"fleet_p50_ms": 2.5, "fleet_p99_ms": 12.5,
                       "fleet_swap_lag_steps": 2.0,
                       "fleet_proto_backend_native": 1.0,
                       "fleet_evloop_open_conns": 5.0},
            "counters": {"fleet_requests_total": 100,
                         "fleet_evloop_backpressure_pauses_total": 2,
                         "fleet_evloop_deadline_expiries_total": 1},
            "fleet_request_ms": {"count": 100, "p50_ms": 2.5,
                                 "p99_ms": 12.5},
        }
        with open(tmp_path / "fleet_status.json", "w") as f:
            json.dump(status, f)
        out = summarize_run_dir(str(tmp_path))
        fleet = out["fleet"]
        assert fleet["alive"] == 2 and fleet["failed"] == 1
        assert fleet["restarts_total"] == 3
        assert fleet["merged_p99_ms"] == 12.5
        assert fleet["affinity_sessions"] == 17
        assert fleet["swap_lag_steps"] == 2.0
        assert fleet["engines"]["e0"]["window_p99_ms"] == 12.5
        assert fleet["engines"]["e1"]["state"] == "failed"
        assert fleet["counters"]["fleet_requests_total"] == 100
        assert fleet["evloop"] == {
            "proto_backend": "native",
            "open_conns": 5.0,
            "backpressure_pauses_total": 2,
            "deadline_expiries_total": 1,
        }


# ---------------------------------------------------------------------------
# wire load harness adapter


class TestWireEngine:
    def test_closed_loop_over_wire(self, mlp_model):
        from sharetrade_tpu.serve.driver import (
            make_sessions, run_closed_loop)
        model, params = mlp_model
        engine, frontend, _ = _boot_engine(model, params)
        try:
            w = WireEngine(frontend.host, frontend.port, workers=4)
            prices = np.linspace(10, 20, 128).astype(np.float32)
            sessions = make_sessions(prices, WINDOW, 8, prefix="wl-")
            stats = run_closed_loop(w, sessions, concurrency=4,
                                    duration_s=1.0)
            assert stats["completed"] > 0
            assert stats["failed"] == 0
            assert stats["p99_ms"] > 0
            assert w.stop()
        finally:
            frontend.stop()
            engine.stop(drain=False)
