// Native append-only event journal for sharetrade_tpu.
//
// The reference's persistence layer is native too: LevelDB (C++) behind
// leveldbjni backing the Akka Persistence journal (reference build.sbt:18-19,
// application.conf:7-17). This is the TPU-framework equivalent: a minimal
// crash-safe framed log shared byte-for-byte with the pure-Python backend
// (sharetrade_tpu/data/journal.py):
//
//   record := [u32 length LE][u32 crc32 LE][payload bytes]
//
// Exposed as a C ABI consumed via ctypes (sharetrade_tpu/data/native.py) —
// the environment has no pybind11, and ctypes keeps the binding dependency-free.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#if !defined(_WIN32)
#include <unistd.h>
#endif

namespace {

// CRC32 (IEEE 802.3, zlib-compatible) — table-driven, built on first use.
uint32_t crc_table[256];
bool crc_ready = false;

void crc_init() {
  if (crc_ready) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_ready = true;
}

uint32_t crc32_of(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Journal {
  FILE* fh;
  bool fsync_each;
};

void put_u32(uint8_t* dst, uint32_t v) {
  dst[0] = v & 0xFF; dst[1] = (v >> 8) & 0xFF;
  dst[2] = (v >> 16) & 0xFF; dst[3] = (v >> 24) & 0xFF;
}

uint32_t get_u32(const uint8_t* src) {
  return (uint32_t)src[0] | ((uint32_t)src[1] << 8) |
         ((uint32_t)src[2] << 16) | ((uint32_t)src[3] << 24);
}

// Scan a journal file; return the byte offset of the end of the last intact
// record, collecting payloads if out != nullptr (newline-delimited).
long scan_file(const char* path, std::string* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 0;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return 0; }
  long file_size = ftell(f);
  if (file_size < 0 || fseek(f, 0, SEEK_SET) != 0) { fclose(f); return 0; }
  long offset = 0;
  uint8_t header[8];
  std::vector<uint8_t> payload;
  for (;;) {
    if (fread(header, 1, 8, f) != 8) break;
    uint32_t length = get_u32(header);
    uint32_t crc = get_u32(header + 4);
    // A length that overruns the file is a torn/corrupt header, not a real
    // record — stop before resize() so garbage bytes can't trigger a
    // std::bad_alloc that would escape the C ABI and abort the process.
    if ((long)length > file_size - offset - 8) break;
    payload.resize(length);
    if (length > 0 && fread(payload.data(), 1, length, f) != length) break;
    if (crc32_of(payload.data(), length) != crc) break;
    if (out) {
      out->append(reinterpret_cast<const char*>(payload.data()), length);
      out->push_back('\n');
    }
    offset += 8 + (long)length;
  }
  fclose(f);
  return offset;
}

}  // namespace

extern "C" {

// Open (create if absent) a journal for appending. Truncates any torn tail so
// appends continue from a clean record boundary — the same recovery contract
// as the Python backend. Returns an opaque handle, or nullptr on failure.
void* stj_open(const char* path, int fsync_each) {
  long valid = 0;
  FILE* probe = fopen(path, "rb");
  if (probe) {
    fclose(probe);
    valid = scan_file(path, nullptr);
    // truncate torn tail (ignore failure; appends would still be readable
    // up to the corruption point)
    FILE* rw = fopen(path, "rb+");
    if (rw) {
#if defined(_WIN32)
      fclose(rw);
#else
      if (ftruncate(fileno(rw), valid) != 0) { /* keep going */ }
      fclose(rw);
#endif
    }
  }
  FILE* fh = fopen(path, "ab");
  if (!fh) return nullptr;
  Journal* j = new Journal{fh, fsync_each != 0};
  return j;
}

// Append one payload. Returns 0 on success.
int stj_append(void* handle, const char* payload, uint32_t length) {
  Journal* j = static_cast<Journal*>(handle);
  if (!j || !j->fh) return 1;
  uint8_t header[8];
  put_u32(header, length);
  put_u32(header + 4, crc32_of(reinterpret_cast<const uint8_t*>(payload), length));
  if (fwrite(header, 1, 8, j->fh) != 8) return 2;
  if (length > 0 && fwrite(payload, 1, length, j->fh) != length) return 3;
  if (fflush(j->fh) != 0) return 4;
#if !defined(_WIN32)
  if (j->fsync_each && fsync(fileno(j->fh)) != 0) return 5;
#endif
  return 0;
}

void stj_close(void* handle) {
  Journal* j = static_cast<Journal*>(handle);
  if (!j) return;
  if (j->fh) fclose(j->fh);
  delete j;
}

// Read every intact record's payload, newline-delimited, into a malloc'd
// buffer (caller frees with stj_free). *out_len receives the byte count.
// Returns nullptr when the file is missing/empty.
void* stj_read_all(const char* path, uint64_t* out_len) {
  std::string out;
  scan_file(path, &out);
  if (out.empty()) { if (out_len) *out_len = 0; return nullptr; }
  void* buf = malloc(out.size());
  if (!buf) { if (out_len) *out_len = 0; return nullptr; }
  memcpy(buf, out.data(), out.size());
  if (out_len) *out_len = out.size();
  return buf;
}

void stj_free(void* buf) { free(buf); }

}  // extern "C"
