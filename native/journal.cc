// Native append-only event journal for sharetrade_tpu.
//
// The reference's persistence layer is native too: LevelDB (C++) behind
// leveldbjni backing the Akka Persistence journal (reference build.sbt:18-19,
// application.conf:7-17). This is the TPU-framework equivalent: a minimal
// crash-safe framed log shared byte-for-byte with the pure-Python backend
// (sharetrade_tpu/data/journal.py):
//
//   record := [u32 length LE][u32 crc32 LE][payload bytes]
//
// Exposed as a C ABI consumed via ctypes (sharetrade_tpu/data/native.py) —
// the environment has no pybind11, and ctypes keeps the binding dependency-free.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#if !defined(_WIN32)
#include <unistd.h>
#endif

namespace {

// CRC32 (IEEE 802.3, zlib-compatible) — table-driven, built on first use.
uint32_t crc_table[256];
bool crc_ready = false;

void crc_init() {
  if (crc_ready) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_ready = true;
}

uint32_t crc32_of(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Journal {
  FILE* fh;
  bool fsync_each;
};

void put_u32(uint8_t* dst, uint32_t v) {
  dst[0] = v & 0xFF; dst[1] = (v >> 8) & 0xFF;
  dst[2] = (v >> 16) & 0xFF; dst[3] = (v >> 24) & 0xFF;
}

uint32_t get_u32(const uint8_t* src) {
  return (uint32_t)src[0] | ((uint32_t)src[1] << 8) |
         ((uint32_t)src[2] << 16) | ((uint32_t)src[3] << 24);
}

// Scan a journal file; return the byte offset of the end of the last intact
// record, collecting payloads if out != nullptr (newline-delimited).
long scan_file(const char* path, std::string* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 0;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return 0; }
  long file_size = ftell(f);
  if (file_size < 0 || fseek(f, 0, SEEK_SET) != 0) { fclose(f); return 0; }
  long offset = 0;
  uint8_t header[8];
  std::vector<uint8_t> payload;
  for (;;) {
    if (fread(header, 1, 8, f) != 8) break;
    uint32_t length = get_u32(header);
    uint32_t crc = get_u32(header + 4);
    // A length that overruns the file is a torn/corrupt header, not a real
    // record — stop before resize() so garbage bytes can't trigger a
    // std::bad_alloc that would escape the C ABI and abort the process.
    if ((long)length > file_size - offset - 8) break;
    payload.resize(length);
    if (length > 0 && fread(payload.data(), 1, length, f) != length) break;
    if (crc32_of(payload.data(), length) != crc) break;
    if (out) {
      out->append(reinterpret_cast<const char*>(payload.data()), length);
      out->push_back('\n');
    }
    offset += 8 + (long)length;
  }
  fclose(f);
  return offset;
}

// Packed transition record payload (codec shared with the Python side,
// sharetrade_tpu/data/transitions.py):
//
//   "STR1" | u32 batch | u32 obs_dim | u64 env_steps |
//   f32 obs[batch*obs_dim] | i32 action[batch] | f32 reward[batch] |
//   f32 next_obs[batch*obs_dim]        (all little-endian)
//
// stj_read_tail_transitions scans the framed log once, keeps only the most
// recent records whose rows fit a replay buffer of `max_rows`, and packs
// them into one contiguous buffer — the host-side decode bandwidth the DQN
// replay warm-start needs (no per-record Python/JSON overhead).

constexpr char kTransMagic[4] = {'S', 'T', 'R', '1'};
constexpr size_t kTransHeader = 4 + 4 + 4 + 8;

struct TransRec {
  uint32_t batch;
  uint32_t obs_dim;
  uint64_t env_steps;
  std::vector<uint8_t> body;  // arrays only (payload minus header)
};

uint64_t get_u64(const uint8_t* src) {
  uint64_t lo = get_u32(src), hi = get_u32(src + 4);
  return lo | (hi << 32);
}

void put_u64(uint8_t* dst, uint64_t v) {
  put_u32(dst, (uint32_t)(v & 0xFFFFFFFFu));
  put_u32(dst + 4, (uint32_t)(v >> 32));
}

// Parse a framed log collecting intact "STR1" records (others skipped).
void scan_transitions(const char* path, std::vector<TransRec>* recs) {
  FILE* f = fopen(path, "rb");
  if (!f) return;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return; }
  long file_size = ftell(f);
  if (file_size < 0 || fseek(f, 0, SEEK_SET) != 0) { fclose(f); return; }
  long offset = 0;
  uint8_t header[8];
  std::vector<uint8_t> payload;
  for (;;) {
    if (fread(header, 1, 8, f) != 8) break;
    uint32_t length = get_u32(header);
    uint32_t crc = get_u32(header + 4);
    if ((long)length > file_size - offset - 8) break;
    payload.resize(length);
    if (length > 0 && fread(payload.data(), 1, length, f) != length) break;
    if (crc32_of(payload.data(), length) != crc) break;
    offset += 8 + (long)length;
    if (length < kTransHeader ||
        memcmp(payload.data(), kTransMagic, 4) != 0)
      continue;  // not a transition record (e.g. a JSON event): skip
    TransRec rec;
    rec.batch = get_u32(payload.data() + 4);
    rec.obs_dim = get_u32(payload.data() + 8);
    rec.env_steps = get_u64(payload.data() + 12);
    size_t row_bytes = (size_t)rec.obs_dim * 4 * 2 + 8;  // obs+next+act+rew
    if ((size_t)length != kTransHeader + row_bytes * rec.batch)
      continue;  // malformed body: skip defensively
    rec.body.assign(payload.begin() + kTransHeader, payload.end());
    recs->push_back(std::move(rec));
  }
  fclose(f);
}

}  // namespace

extern "C" {

// Read the transitions journal's TAIL: the most recent records covering at
// most `max_rows` rows, skipping records newer than `cutoff_env_steps`
// (0 = no cutoff; records with env_steps == 0 always pass). Returns a
// malloc'd packed buffer (caller frees with stj_free):
//
//   u32 rows | u32 obs_dim | u64 high_water |
//   f32 obs[rows*obs_dim] | i32 action[rows] | f32 reward[rows] |
//   f32 next_obs[rows*obs_dim]
//
// high_water is the max env_steps over ALL intact transition records —
// including ones excluded by the cutoff or the row budget — which is the
// resume-time double-journaling guard. Returns nullptr when the file has no
// intact transition records.
void* stj_read_tail_transitions(const char* path, uint64_t max_rows,
                                uint64_t cutoff_env_steps,
                                uint64_t* out_len) {
  if (out_len) *out_len = 0;
  std::vector<TransRec> recs;
  scan_transitions(path, &recs);
  if (recs.empty()) return nullptr;

  uint64_t high_water = 0;
  for (const TransRec& r : recs)
    if (r.env_steps > high_water) high_water = r.env_steps;

  // Drop records past the cutoff, then walk back from the tail until the
  // kept records cover max_rows (mirrors fill_replay_from_journal: only the
  // tail that can survive in the circular buffer is worth decoding).
  // kept may legitimately end up empty (cutoff excludes everything): the
  // high-water mark must still come back — zero rows, not nullptr — or the
  // resume-time double-journaling guard is lost.
  std::vector<const TransRec*> kept;
  uint64_t rows = 0;
  uint32_t obs_dim = recs.back().obs_dim;
  for (auto it = recs.rbegin(); it != recs.rend(); ++it) {
    if (cutoff_env_steps && it->env_steps > cutoff_env_steps) continue;
    if (it->obs_dim != obs_dim) continue;  // shape drift: skip defensively
    kept.push_back(&*it);
    rows += it->batch;
    if (max_rows && rows >= max_rows) break;
  }

  size_t head = 4 + 4 + 8;
  size_t total = head + ((size_t)obs_dim * 4 * 2 + 8) * rows;
  uint8_t* buf = static_cast<uint8_t*>(malloc(total));
  if (!buf) return nullptr;
  put_u32(buf, (uint32_t)rows);
  put_u32(buf + 4, obs_dim);
  put_u64(buf + 8, high_water);

  uint8_t* obs_dst = buf + head;
  uint8_t* act_dst = obs_dst + (size_t)rows * obs_dim * 4;
  uint8_t* rew_dst = act_dst + (size_t)rows * 4;
  uint8_t* next_dst = rew_dst + (size_t)rows * 4;
  // kept[] is newest-first; emit oldest-first so circular "newest wins"
  // semantics hold when the caller pushes in order.
  for (auto it = kept.rbegin(); it != kept.rend(); ++it) {
    const TransRec* r = *it;
    size_t ob = (size_t)r->batch * r->obs_dim * 4;
    const uint8_t* src = r->body.data();
    memcpy(obs_dst, src, ob);              obs_dst += ob;   src += ob;
    memcpy(act_dst, src, r->batch * 4);    act_dst += r->batch * 4; src += r->batch * 4;
    memcpy(rew_dst, src, r->batch * 4);    rew_dst += r->batch * 4; src += r->batch * 4;
    memcpy(next_dst, src, ob);             next_dst += ob;
  }
  if (out_len) *out_len = total;
  return buf;
}

// Open (create if absent) a journal for appending. Truncates any torn tail so
// appends continue from a clean record boundary — the same recovery contract
// as the Python backend. Returns an opaque handle, or nullptr on failure.
void* stj_open(const char* path, int fsync_each) {
  long valid = 0;
  FILE* probe = fopen(path, "rb");
  if (probe) {
    fclose(probe);
    valid = scan_file(path, nullptr);
    // truncate torn tail (ignore failure; appends would still be readable
    // up to the corruption point)
    FILE* rw = fopen(path, "rb+");
    if (rw) {
#if defined(_WIN32)
      fclose(rw);
#else
      if (ftruncate(fileno(rw), valid) != 0) { /* keep going */ }
      fclose(rw);
#endif
    }
  }
  FILE* fh = fopen(path, "ab");
  if (!fh) return nullptr;
  Journal* j = new Journal{fh, fsync_each != 0};
  return j;
}

// Append one payload. Returns 0 on success.
int stj_append(void* handle, const char* payload, uint32_t length) {
  Journal* j = static_cast<Journal*>(handle);
  if (!j || !j->fh) return 1;
  uint8_t header[8];
  put_u32(header, length);
  put_u32(header + 4, crc32_of(reinterpret_cast<const uint8_t*>(payload), length));
  if (fwrite(header, 1, 8, j->fh) != 8) return 2;
  if (length > 0 && fwrite(payload, 1, length, j->fh) != length) return 3;
  if (fflush(j->fh) != 0) return 4;
#if !defined(_WIN32)
  if (j->fsync_each && fsync(fileno(j->fh)) != 0) return 5;
#endif
  return 0;
}

void stj_close(void* handle) {
  Journal* j = static_cast<Journal*>(handle);
  if (!j) return;
  if (j->fh) fclose(j->fh);
  delete j;
}

// Read every intact record's payload, newline-delimited, into a malloc'd
// buffer (caller frees with stj_free). *out_len receives the byte count.
// Returns nullptr when the file is missing/empty.
void* stj_read_all(const char* path, uint64_t* out_len) {
  std::string out;
  scan_file(path, &out);
  if (out.empty()) { if (out_len) *out_len = 0; return nullptr; }
  void* buf = malloc(out.size());
  if (!buf) { if (out_len) *out_len = 0; return nullptr; }
  memcpy(buf, out.data(), out.size());
  if (out_len) *out_len = out.size();
  return buf;
}

void stj_free(void* buf) { free(buf); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Async journal writer: a background thread drains a bounded in-memory queue
// into the framed log, so the training loop's per-chunk journal append is a
// memcpy instead of a synchronous multi-MB write+flush (the "replay/
// persistence bandwidth without starving the step loop" concern, SURVEY.md
// §7.4 — the role LevelDB's own background write path plays for the
// reference's journal). Durability window == queue depth: a crash loses at
// most the queued-but-unwritten records, which the resume-time high-water
// logic already tolerates (missing tail ⇒ fewer warm-start rows, never
// corruption — frames are written whole by one thread).

namespace {

struct AsyncWriter {
  Journal* j = nullptr;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_submit;  // worker waits: queue non-empty / stop
  std::condition_variable cv_space;   // producers wait: room / drained
  std::deque<std::string> queue;
  size_t queued_bytes = 0;
  size_t max_bytes = 0;
  bool stop = false;
  bool idle = true;                   // worker drained and wrote everything
  int error = 0;                      // first write error, sticky
};

void writer_loop(AsyncWriter* w) {
  std::vector<std::string> batch;
  for (;;) {
    bool poisoned;
    {
      std::unique_lock<std::mutex> lk(w->mu);
      w->cv_submit.wait(lk, [&] { return w->stop || !w->queue.empty(); });
      if (w->queue.empty() && w->stop) return;
      while (!w->queue.empty()) {
        batch.push_back(std::move(w->queue.front()));
        w->queue.pop_front();
      }
      w->queued_bytes = 0;
      w->idle = false;
      poisoned = w->error != 0;
    }
    w->cv_space.notify_all();
    if (poisoned) {
      // After a write error the file may end in a partially-written frame;
      // the framed reader stops at that torn record, so any frame appended
      // past it would be silently invisible on recovery. Drain-and-drop so
      // the file ends exactly at the torn tail the recovery logic handles
      // (producers see the sticky error from submit and fail loudly).
      batch.clear();
      {
        std::lock_guard<std::mutex> lk(w->mu);
        w->idle = w->queue.empty();
      }
      w->cv_space.notify_all();
      continue;
    }
    int err = 0;
    for (const std::string& payload : batch) {
      uint8_t header[8];
      put_u32(header, (uint32_t)payload.size());
      put_u32(header + 4,
              crc32_of(reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size()));
      if (fwrite(header, 1, 8, w->j->fh) != 8) { err = 2; break; }
      if (!payload.empty() &&
          fwrite(payload.data(), 1, payload.size(), w->j->fh)
              != payload.size()) { err = 3; break; }
    }
    if (!err && fflush(w->j->fh) != 0) err = 4;
#if !defined(_WIN32)
    if (!err && w->j->fsync_each && fsync(fileno(w->j->fh)) != 0) err = 5;
#endif
    batch.clear();
    {
      std::lock_guard<std::mutex> lk(w->mu);
      if (err && !w->error) w->error = err;
      w->idle = w->queue.empty();
    }
    w->cv_space.notify_all();
  }
}

}  // namespace

extern "C" {

// Open an async writer over a journal (torn-tail recovery as stj_open).
// `max_queue_bytes` bounds producer-side memory; submit blocks when full.
void* stj_writer_open(const char* path, uint64_t max_queue_bytes,
                      int fsync_each) {
  void* jh = stj_open(path, fsync_each);
  if (!jh) return nullptr;
  AsyncWriter* w = new AsyncWriter;
  w->j = static_cast<Journal*>(jh);
  w->max_bytes = max_queue_bytes ? (size_t)max_queue_bytes : (64u << 20);
  w->worker = std::thread(writer_loop, w);
  return w;
}

// Enqueue one payload (copied). Blocks while the queue is over budget.
// Returns the sticky error code of the background writer (0 = ok).
int stj_writer_submit(void* handle, const char* payload, uint32_t length) {
  AsyncWriter* w = static_cast<AsyncWriter*>(handle);
  if (!w) return 1;
  {
    std::unique_lock<std::mutex> lk(w->mu);
    if (w->stop) return 1;
    // An empty queue always admits the payload, even one larger than the
    // whole budget — otherwise a single oversized record (big transition
    // batches) would wait on a predicate that can never become true.
    w->cv_space.wait(lk, [&] {
      return w->queued_bytes == 0 ||
             w->queued_bytes + length <= w->max_bytes || w->error;
    });
    if (w->error) return w->error;
    w->queue.emplace_back(payload, payload + length);
    w->queued_bytes += length;
    w->idle = false;
  }
  w->cv_submit.notify_one();
  return 0;
}

// Block until everything submitted so far is written and flushed.
int stj_writer_flush(void* handle) {
  AsyncWriter* w = static_cast<AsyncWriter*>(handle);
  if (!w) return 1;
  std::unique_lock<std::mutex> lk(w->mu);
  w->cv_space.wait(lk, [&] { return (w->idle && w->queue.empty()) || w->error; });
  return w->error;
}

// Flush, join the worker, close the file. Returns the sticky error code.
int stj_writer_close(void* handle) {
  AsyncWriter* w = static_cast<AsyncWriter*>(handle);
  if (!w) return 1;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->stop = true;
  }
  w->cv_submit.notify_one();
  if (w->worker.joinable()) w->worker.join();
  int err = w->error;
  stj_close(w->j);
  delete w;
  return err;
}

}  // extern "C"
