// Native wire hot path: the fleet's HTTP/1.1 parse/render state
// machines as a CPython extension (module name: stwire).
//
// This file is the C twin of sharetrade_tpu/fleet/proto.py — the
// sans-IO protocol core — with the EXACT same event semantics:
// Content-Length-only framing, MAX_HEAD/MAX_BODY refusal before
// buffering, torn reads at any byte offset, pipelining, last-wins
// lower-cased header dicts, the HTTP/1.0-vs-1.1 keep-alive folding,
// and byte-identical render_request/render_response output. The
// Python parsers survive as the differential oracle
// (tests/test_fleet_wire.py replays seeded corpora through both and
// requires identical event streams and identical ProtocolError
// statuses).
//
// Binding contract (lint check 18):
// - the ONLY Python module that loads this extension is
//   fleet/proto.py (the backend dispatch seam);
// - the byte-level parse and render cores run with the GIL RELEASED
//   (Py_BEGIN_ALLOW_THREADS pairing below), so the evloop's selector
//   thread stops serializing against engine-dispatch callbacks and
//   loadgen threads while it frames bytes;
// - the extension holds REFERENCES to proto.py's Request / Response /
//   ProtocolError classes (configure() below) instead of defining its
//   own, so events and exceptions are the same Python types under
//   both backends — `except proto.ProtocolError` and isinstance
//   checks never see a backend difference.
//
// Error-detail fidelity: the detail strings replicate proto.py's
// f-strings including Python's repr() of the offending bytes/str, so
// the differential tests can compare .detail, not just .status.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr size_t MAX_HEAD_BYTES = 16384;
constexpr long long MAX_BODY_BYTES = 1LL << 26;

// ---- Python-repr replicas (for ProtocolError detail parity) --------

bool needs_double_quote(const std::string &s) {
  return s.find('\'') != std::string::npos &&
         s.find('"') == std::string::npos;
}

// Python bytes.__repr__: b'...' (double quotes iff ' present, " not).
std::string bytes_repr(const std::string &s) {
  char quote = needs_double_quote(s) ? '"' : '\'';
  std::string out = "b";
  out += quote;
  char hex[8];
  for (unsigned char c : s) {
    if (c == static_cast<unsigned char>(quote) || c == '\\') {
      out += '\\';
      out += static_cast<char>(c);
    } else if (c == '\t') {
      out += "\\t";
    } else if (c == '\n') {
      out += "\\n";
    } else if (c == '\r') {
      out += "\\r";
    } else if (c >= 0x20 && c < 0x7f) {
      out += static_cast<char>(c);
    } else {
      std::snprintf(hex, sizeof hex, "\\x%02x", c);
      out += hex;
    }
  }
  out += quote;
  return out;
}

// Python str.__repr__ over a latin-1 string: printable latin-1 stays
// literal (the result is later decoded latin-1 into the detail str);
// C0/C1 controls, DEL, NBSP and SOFT HYPHEN escape as \xHH, matching
// CPython's unicode printability rules for the latin-1 range.
std::string str_repr_latin1(const std::string &s) {
  char quote = needs_double_quote(s) ? '"' : '\'';
  std::string out;
  out += quote;
  char hex[8];
  for (unsigned char c : s) {
    if (c == static_cast<unsigned char>(quote) || c == '\\') {
      out += '\\';
      out += static_cast<char>(c);
    } else if (c == '\t') {
      out += "\\t";
    } else if (c == '\n') {
      out += "\\n";
    } else if (c == '\r') {
      out += "\\r";
    } else if (c < 0x20 || c == 0x7f || (c >= 0x80 && c <= 0xa0) ||
               c == 0xad) {
      std::snprintf(hex, sizeof hex, "\\x%02x", c);
      out += hex;
    } else {
      out += static_cast<char>(c);
    }
  }
  out += quote;
  return out;
}

// ---- pure-C parse core (no Python API: runs GIL-free) --------------

struct Header {
  std::string name;     // lowered (latin-1 rules) unless needs_py_lower
  std::string raw_name; // original stripped bytes (for the 0xB5 case)
  std::string value;    // stripped, raw latin-1 bytes
  bool needs_py_lower;  // contains U+00B5 (lowers outside latin-1)
};

struct Msg {
  std::string method;
  std::string target;
  long long status = 0;
  std::vector<Header> headers;
  std::string body;
  bool keep_alive = true;
};

struct Err {
  bool set = false;
  int status = 400;
  std::string detail; // latin-1 bytes of the detail string
  void fail(const std::string &d) {
    set = true;
    detail = d;
  }
};

bool is_ascii_ws(unsigned char c) {
  return c == ' ' || (c >= 9 && c <= 13);
}

std::string strip_ascii(const std::string &s) {
  size_t b = 0, e = s.size();
  while (b < e && is_ascii_ws(s[b])) ++b;
  while (e > b && is_ascii_ws(s[e - 1])) --e;
  return s.substr(b, e - b);
}

// str.lower() restricted to latin-1: ASCII A-Z and U+00C0-U+00DE
// (minus the multiplication sign U+00D7) gain 0x20; U+00B5 (MICRO
// SIGN) lowers to U+03BC — OUTSIDE latin-1 — so such names defer to
// Python's str.lower at event-construction time for exactness.
void lower_latin1(const std::string &raw, std::string *out,
                  bool *needs_py) {
  *needs_py = false;
  out->clear();
  out->reserve(raw.size());
  for (unsigned char c : raw) {
    if (c == 0xb5) *needs_py = true;
    if ((c >= 'A' && c <= 'Z') ||
        (c >= 0xc0 && c <= 0xde && c != 0xd7)) {
      out->push_back(static_cast<char>(c + 0x20));
    } else {
      out->push_back(static_cast<char>(c));
    }
  }
}

bool ascii_ieq(const std::string &a, const char *b) {
  size_t n = std::strlen(b);
  if (a.size() != n) return false;
  for (size_t i = 0; i < n; ++i) {
    unsigned char c = a[i];
    if (c >= 'A' && c <= 'Z') c += 0x20;
    if (c != static_cast<unsigned char>(b[i])) return false;
  }
  return true;
}

// int(str(v).strip()) with Python's rules: unicode-whitespace strip
// (latin-1 subset), optional sign, ASCII decimal digits with single
// underscores BETWEEN digits. Returns 0 ok / 1 malformed /
// 2 negative / 3 over-limit; *canon is the canonical decimal (the
// {n} in proto.py's over-limit message).
int parse_content_length(const std::string &v, long long *out_n,
                         std::string *canon) {
  auto is_uws = [](unsigned char c) {
    return c == ' ' || (c >= 9 && c <= 13) || (c >= 0x1c && c <= 0x1f) ||
           c == 0x85 || c == 0xa0;
  };
  size_t b = 0, e = v.size();
  while (b < e && is_uws(v[b])) ++b;
  while (e > b && is_uws(v[e - 1])) --e;
  if (b == e) return 1;
  bool neg = false;
  size_t i = b;
  if (v[i] == '+' || v[i] == '-') {
    neg = v[i] == '-';
    ++i;
  }
  if (i == e) return 1;
  std::string digits;
  bool prev_digit = false;
  for (; i < e; ++i) {
    unsigned char c = v[i];
    if (c >= '0' && c <= '9') {
      digits += static_cast<char>(c);
      prev_digit = true;
    } else if (c == '_') {
      if (!prev_digit) return 1; // leading / doubled underscore
      prev_digit = false;
    } else {
      return 1;
    }
  }
  if (!prev_digit) return 1; // trailing underscore
  size_t z = 0;
  while (z + 1 < digits.size() && digits[z] == '0') ++z;
  std::string d = digits.substr(z);
  if (neg && d != "0") return 2; // int("-0") == 0, not negative
  *canon = d;
  if (d.size() > 18) return 3; // beyond long long: certainly > MAX
  long long n = 0;
  for (char c : d) n = n * 10 + (c - '0');
  *out_n = n;
  if (n > MAX_BODY_BYTES) return 3;
  return 0;
}

// int(bytes) for the status token: optional sign, ASCII digits with
// single underscores between digits. NO unicode-whitespace stripping
// (that is content_length's int(str.strip()) path, not this one) —
// and the token, produced by an ASCII-whitespace split, can hold no
// ASCII whitespace anyway. Returns false on Python's ValueError.
bool parse_int_token(const std::string &s, long long *out) {
  size_t i = 0, e = s.size();
  if (i == e) return false;
  bool neg = false;
  if (s[i] == '+' || s[i] == '-') {
    neg = s[i] == '-';
    ++i;
  }
  if (i == e) return false;
  bool prev_digit = false;
  long long n = 0;
  size_t digits = 0;
  for (; i < e; ++i) {
    unsigned char c = s[i];
    if (c >= '0' && c <= '9') {
      if (digits < 18) n = n * 10 + (c - '0');
      ++digits;
      prev_digit = true;
    } else if (c == '_') {
      if (!prev_digit) return false;
      prev_digit = false;
    } else {
      return false;
    }
  }
  if (!prev_digit) return false;
  if (digits > 18) return false; // beyond long long; no real status is
  *out = neg ? -n : n;
  return true;
}

void content_length_error(int rc, const std::string &raw_value,
                          const std::string &canon, Err *err) {
  if (rc == 1) {
    err->fail("malformed Content-Length " + str_repr_latin1(raw_value));
  } else if (rc == 2) {
    err->fail("negative Content-Length " + str_repr_latin1(raw_value));
  } else {
    err->fail("declared body of " + canon + " bytes exceeds the " +
              std::to_string(MAX_BODY_BYTES) + "-byte limit");
  }
}

// bytes.split() (any ASCII-whitespace run) with optional maxsplit.
std::vector<std::string> ws_split(const std::string &s, int maxsplit) {
  std::vector<std::string> out;
  size_t i = 0, n = s.size();
  while (i < n) {
    while (i < n && is_ascii_ws(s[i])) ++i;
    if (i >= n) break;
    if (maxsplit >= 0 && static_cast<int>(out.size()) == maxsplit) {
      out.push_back(s.substr(i));
      break;
    }
    size_t j = i;
    while (j < n && !is_ascii_ws(s[j])) ++j;
    out.push_back(s.substr(i, j - i));
    i = j;
  }
  return out;
}

std::vector<std::string> crlf_split(const std::string &s) {
  std::vector<std::string> out;
  size_t start = 0;
  for (;;) {
    size_t idx = s.find("\r\n", start);
    if (idx == std::string::npos) {
      out.push_back(s.substr(start));
      return out;
    }
    out.push_back(s.substr(start, idx - start));
    start = idx + 2;
  }
}

// _parse_headers: partition on ':', both sides ASCII-stripped, name
// lowered, LAST occurrence wins (resolved at dict build / lookup).
bool parse_header_lines(const std::vector<std::string> &lines,
                        size_t first, std::vector<Header> *out,
                        Err *err) {
  for (size_t i = first; i < lines.size(); ++i) {
    const std::string &line = lines[i];
    size_t colon = line.find(':');
    std::string raw_name =
        strip_ascii(colon == std::string::npos ? line
                                               : line.substr(0, colon));
    if (colon == std::string::npos || raw_name.empty()) {
      err->fail("malformed header line " + bytes_repr(line));
      return false;
    }
    Header h;
    h.raw_name = raw_name;
    lower_latin1(raw_name, &h.name, &h.needs_py_lower);
    h.value = strip_ascii(line.substr(colon + 1));
    out->push_back(std::move(h));
  }
  return true;
}

// headers.get(name): last-wins over the parse order.
const Header *find_header(const std::vector<Header> &headers,
                          const char *lowered) {
  for (size_t i = headers.size(); i > 0; --i) {
    if (headers[i - 1].name == lowered) return &headers[i - 1];
  }
  return nullptr;
}

struct WireCore {
  bool is_request;
  std::string buf;
  bool have_head = false;
  Msg head; // parsed head awaiting its body
  size_t need = 0;

  explicit WireCore(bool req) : is_request(req) {}

  bool pending() const { return !buf.empty() || have_head; }

  // Returns false on protocol error (err set); completed messages are
  // appended to *out in arrival order.
  bool feed(const char *data, size_t n, std::vector<Msg> *out,
            Err *err) {
    buf.append(data, n);
    for (;;) {
      if (!have_head) {
        size_t idx = buf.find("\r\n\r\n");
        if (idx == std::string::npos) {
          if (buf.size() > MAX_HEAD_BYTES) {
            err->fail("header block exceeds " +
                      std::to_string(MAX_HEAD_BYTES) + " bytes");
            return false;
          }
          return true;
        }
        if (idx > MAX_HEAD_BYTES) {
          err->fail("header block exceeds " +
                    std::to_string(MAX_HEAD_BYTES) + " bytes");
          return false;
        }
        std::string head_bytes = buf.substr(0, idx);
        buf.erase(0, idx + 4); // consumed before parse, like proto.py
        head = Msg();
        if (!(is_request ? parse_request_head(head_bytes, err)
                         : parse_response_head(head_bytes, err))) {
          return false;
        }
        have_head = true;
      }
      if (buf.size() < need) return true;
      head.body = buf.substr(0, need);
      buf.erase(0, need);
      have_head = false;
      out->push_back(std::move(head));
      head = Msg();
    }
  }

  bool parse_request_head(const std::string &head_bytes, Err *err) {
    std::vector<std::string> lines = crlf_split(head_bytes);
    std::vector<std::string> parts = ws_split(lines[0], -1);
    if (parts.size() != 3) {
      err->fail("malformed request line " + bytes_repr(lines[0]));
      return false;
    }
    const std::string &version = parts[2];
    if (version.compare(0, 7, "HTTP/1.") != 0) {
      err->fail("unsupported version " + bytes_repr(version));
      return false;
    }
    if (!parse_header_lines(lines, 1, &head.headers, err)) return false;
    const Header *conn = find_header(head.headers, "connection");
    if (version == "HTTP/1.0") {
      head.keep_alive = conn != nullptr && ascii_ieq(conn->value,
                                                     "keep-alive");
    } else {
      head.keep_alive = conn == nullptr || !ascii_ieq(conn->value,
                                                      "close");
    }
    head.method = parts[0];
    head.target = parts[1];
    const Header *cl = find_header(head.headers, "content-length");
    need = 0;
    if (cl != nullptr) {
      long long n = 0;
      std::string canon;
      int rc = parse_content_length(cl->value, &n, &canon);
      if (rc != 0) {
        content_length_error(rc, cl->value, canon, err);
        return false;
      }
      need = static_cast<size_t>(n);
    }
    return true;
  }

  bool parse_response_head(const std::string &head_bytes, Err *err) {
    std::vector<std::string> lines = crlf_split(head_bytes);
    std::vector<std::string> parts = ws_split(lines[0], 2);
    if (parts.size() < 2 ||
        parts[0].compare(0, 7, "HTTP/1.") != 0) {
      err->fail("malformed status line " + bytes_repr(lines[0]));
      return false;
    }
    long long status = 0;
    if (!parse_int_token(parts[1], &status)) {
      err->fail("malformed status line " + bytes_repr(lines[0]));
      return false;
    }
    head.status = status;
    if (!parse_header_lines(lines, 1, &head.headers, err)) return false;
    const Header *cl = find_header(head.headers, "content-length");
    if (cl == nullptr) {
      err->fail("response without Content-Length on a keep-alive "
                "connection");
      return false;
    }
    long long n = 0;
    std::string canon2;
    int body_rc = parse_content_length(cl->value, &n, &canon2);
    if (body_rc != 0) {
      content_length_error(body_rc, cl->value, canon2, err);
      return false;
    }
    need = static_cast<size_t>(n);
    return true;
  }
};

// ---- Python binding ------------------------------------------------

PyObject *g_request_cls = nullptr;
PyObject *g_response_cls = nullptr;
PyObject *g_protocol_error = nullptr;

int raise_protocol_error(const Err &err) {
  if (g_protocol_error == nullptr) {
    PyErr_SetString(PyExc_RuntimeError,
                    "stwire.configure() was never called");
    return -1;
  }
  PyObject *detail = PyUnicode_DecodeLatin1(err.detail.data(),
                                            err.detail.size(), nullptr);
  if (detail == nullptr) return -1;
  PyObject *args = PyTuple_Pack(1, detail);
  Py_DECREF(detail);
  if (args == nullptr) return -1;
  PyObject *kwargs = Py_BuildValue("{s:i}", "status", err.status);
  if (kwargs == nullptr) {
    Py_DECREF(args);
    return -1;
  }
  PyObject *exc = PyObject_Call(g_protocol_error, args, kwargs);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  if (exc == nullptr) return -1;
  PyErr_SetObject(g_protocol_error, exc);
  Py_DECREF(exc);
  return -1;
}

PyObject *headers_to_dict(const std::vector<Header> &headers) {
  PyObject *dict = PyDict_New();
  if (dict == nullptr) return nullptr;
  for (const Header &h : headers) {
    PyObject *key;
    if (h.needs_py_lower) {
      // U+00B5 lowers outside latin-1: defer to str.lower for the
      // exact CPython mapping.
      PyObject *raw = PyUnicode_DecodeLatin1(h.raw_name.data(),
                                             h.raw_name.size(), nullptr);
      if (raw == nullptr) {
        Py_DECREF(dict);
        return nullptr;
      }
      key = PyObject_CallMethod(raw, "lower", nullptr);
      Py_DECREF(raw);
    } else {
      key = PyUnicode_DecodeLatin1(h.name.data(), h.name.size(),
                                   nullptr);
    }
    if (key == nullptr) {
      Py_DECREF(dict);
      return nullptr;
    }
    PyObject *value = PyUnicode_DecodeLatin1(h.value.data(),
                                             h.value.size(), nullptr);
    if (value == nullptr) {
      Py_DECREF(key);
      Py_DECREF(dict);
      return nullptr;
    }
    int rc = PyDict_SetItem(dict, key, value); // last-wins, like proto
    Py_DECREF(key);
    Py_DECREF(value);
    if (rc < 0) {
      Py_DECREF(dict);
      return nullptr;
    }
  }
  return dict;
}

PyObject *build_event(bool is_request, const Msg &msg) {
  PyObject *headers = headers_to_dict(msg.headers);
  if (headers == nullptr) return nullptr;
  PyObject *body = PyBytes_FromStringAndSize(msg.body.data(),
                                             static_cast<Py_ssize_t>(
                                                 msg.body.size()));
  if (body == nullptr) {
    Py_DECREF(headers);
    return nullptr;
  }
  PyObject *event = nullptr;
  if (is_request) {
    PyObject *method = PyUnicode_DecodeLatin1(msg.method.data(),
                                              msg.method.size(), nullptr);
    PyObject *target =
        method == nullptr
            ? nullptr
            : PyUnicode_DecodeLatin1(msg.target.data(),
                                     msg.target.size(), nullptr);
    if (target != nullptr) {
      event = PyObject_CallFunctionObjArgs(
          g_request_cls, method, target, headers, body,
          msg.keep_alive ? Py_True : Py_False, nullptr);
    }
    Py_XDECREF(method);
    Py_XDECREF(target);
  } else {
    PyObject *status = PyLong_FromLongLong(msg.status);
    if (status != nullptr) {
      event = PyObject_CallFunctionObjArgs(g_response_cls, status,
                                           headers, body, nullptr);
      Py_DECREF(status);
    }
  }
  Py_DECREF(headers);
  Py_DECREF(body);
  return event;
}

struct ParserObject {
  PyObject_HEAD
  WireCore *core;
};

extern PyTypeObject RequestParserType;
extern PyTypeObject ResponseParserType;

int parser_init(PyObject *self, PyObject *args, PyObject *kwargs) {
  static const char *kwlist[] = {nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, ":Parser",
                                   const_cast<char **>(kwlist))) {
    return -1;
  }
  ParserObject *p = reinterpret_cast<ParserObject *>(self);
  delete p->core;
  p->core = new WireCore(Py_TYPE(self) == &RequestParserType);
  return 0;
}

void parser_dealloc(PyObject *self) {
  ParserObject *p = reinterpret_cast<ParserObject *>(self);
  delete p->core;
  p->core = nullptr;
  Py_TYPE(self)->tp_free(self);
}

PyObject *parser_feed(PyObject *self, PyObject *args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "y*:feed", &view)) return nullptr;
  ParserObject *p = reinterpret_cast<ParserObject *>(self);
  if (p->core == nullptr || g_request_cls == nullptr) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_RuntimeError,
                    "stwire parser used before configure()");
    return nullptr;
  }
  std::vector<Msg> msgs;
  Err err;
  bool ok;
  // The framing core touches only C buffers: release the GIL so the
  // selector thread's parse overlaps engine callbacks and loadgen.
  Py_BEGIN_ALLOW_THREADS
  ok = p->core->feed(static_cast<const char *>(view.buf),
                     static_cast<size_t>(view.len), &msgs, &err);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&view);
  if (!ok) {
    raise_protocol_error(err);
    return nullptr;
  }
  PyObject *out = PyList_New(static_cast<Py_ssize_t>(msgs.size()));
  if (out == nullptr) return nullptr;
  for (size_t i = 0; i < msgs.size(); ++i) {
    PyObject *event = build_event(p->core->is_request, msgs[i]);
    if (event == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), event);
  }
  return out;
}

PyObject *parser_pending_bytes(PyObject *self, PyObject *) {
  ParserObject *p = reinterpret_cast<ParserObject *>(self);
  if (p->core != nullptr && p->core->pending()) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

PyMethodDef parser_methods[] = {
    {"feed", parser_feed, METH_VARARGS,
     "Feed any slice of the byte stream; returns every message "
     "COMPLETED by it, in order (proto.py feed contract)."},
    {"pending_bytes", parser_pending_bytes, METH_NOARGS,
     "True if buffered bytes of an incomplete message are held."},
    {nullptr, nullptr, 0, nullptr},
};

#pragma GCC diagnostic push
#pragma GCC diagnostic ignored "-Wmissing-field-initializers"
PyTypeObject RequestParserType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "stwire.RequestParser",        // tp_name
    sizeof(ParserObject),          // tp_basicsize
    0,                             // tp_itemsize
    parser_dealloc,                // tp_dealloc
};

PyTypeObject ResponseParserType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "stwire.ResponseParser",       // tp_name
    sizeof(ParserObject),          // tp_basicsize
    0,                             // tp_itemsize
    parser_dealloc,                // tp_dealloc
};
#pragma GCC diagnostic pop

// ---- renderers -----------------------------------------------------

// str(obj) encoded latin-1 into *out; false (exception set) on a
// non-latin-1 char — the same UnicodeEncodeError class proto.py's
// .encode("latin-1") raises.
bool obj_to_latin1(PyObject *obj, std::string *out) {
  PyObject *text = PyObject_Str(obj);
  if (text == nullptr) return false;
  PyObject *raw = PyUnicode_AsLatin1String(text);
  Py_DECREF(text);
  if (raw == nullptr) return false;
  out->assign(PyBytes_AS_STRING(raw),
              static_cast<size_t>(PyBytes_GET_SIZE(raw)));
  Py_DECREF(raw);
  return true;
}

// (headers or {}).items() in insertion order; false on exception.
bool collect_header_pairs(
    PyObject *headers,
    std::vector<std::pair<std::string, std::string>> *out) {
  if (headers == nullptr || headers == Py_None) return true;
  int truthy = PyObject_IsTrue(headers);
  if (truthy < 0) return false;
  if (truthy == 0) return true;
  PyObject *items = PyObject_CallMethod(headers, "items", nullptr);
  if (items == nullptr) return false;
  PyObject *fast = PySequence_Fast(items, "headers.items() is not "
                                          "iterable");
  Py_DECREF(items);
  if (fast == nullptr) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *pair = PySequence_Fast_GET_ITEM(fast, i);
    PyObject *key = PySequence_GetItem(pair, 0);
    PyObject *value = key ? PySequence_GetItem(pair, 1) : nullptr;
    std::string k, v;
    bool ok = value != nullptr && obj_to_latin1(key, &k) &&
              obj_to_latin1(value, &v);
    Py_XDECREF(key);
    Py_XDECREF(value);
    if (!ok) {
      Py_DECREF(fast);
      return false;
    }
    out->emplace_back(std::move(k), std::move(v));
  }
  Py_DECREF(fast);
  return true;
}

PyObject *assemble(const std::vector<std::string> &head,
                   const char *body, size_t body_len) {
  std::string wire;
  // Pure byte assembly — GIL released (all inputs are C strings).
  Py_BEGIN_ALLOW_THREADS
  size_t total = 2 + body_len; // final "\r\n" + body
  for (const std::string &line : head) total += line.size() + 2;
  wire.reserve(total);
  for (const std::string &line : head) {
    wire += line;
    wire += "\r\n";
  }
  wire += "\r\n";
  wire.append(body, body_len);
  Py_END_ALLOW_THREADS
  return PyBytes_FromStringAndSize(wire.data(),
                                   static_cast<Py_ssize_t>(wire.size()));
}

PyObject *wire_render_request(PyObject *, PyObject *args,
                              PyObject *kwargs) {
  static const char *kwlist[] = {"method", "target", "host", "body",
                                 "headers", nullptr};
  PyObject *method, *target, *host;
  Py_buffer body = {};
  PyObject *headers = nullptr;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "OOO|y*O:render_request",
          const_cast<char **>(kwlist), &method, &target, &host, &body,
          &headers)) {
    return nullptr;
  }
  PyObject *result = nullptr;
  std::string m, t, h;
  std::vector<std::pair<std::string, std::string>> extra;
  if (obj_to_latin1(method, &m) && obj_to_latin1(target, &t) &&
      obj_to_latin1(host, &h) && collect_header_pairs(headers, &extra)) {
    std::vector<std::string> head;
    head.push_back(m + " " + t + " HTTP/1.1");
    head.push_back("Host: " + h);
    head.push_back("Content-Length: " +
                   std::to_string(body.obj ? body.len : 0));
    for (const auto &kv : extra) {
      head.push_back(kv.first + ": " + kv.second);
    }
    result = assemble(head,
                      body.obj ? static_cast<const char *>(body.buf)
                               : "",
                      body.obj ? static_cast<size_t>(body.len) : 0);
  }
  if (body.obj) PyBuffer_Release(&body);
  return result;
}

const char *reason_for(long long status) {
  switch (status) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 429: return "Too Many Requests";
    case 500: return "Internal Server Error";
    case 503: return "Service Unavailable";
    case 504: return "Gateway Timeout";
    default: return "Unknown";
  }
}

PyObject *wire_render_response(PyObject *, PyObject *args,
                               PyObject *kwargs) {
  static const char *kwlist[] = {"status", "body", "content_type",
                                 "keep_alive", "extra_headers", nullptr};
  long long status;
  Py_buffer body = {};
  const char *content_type = "application/json";
  int keep_alive = 1;
  PyObject *extra_headers = nullptr;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "Ly*|s$pO:render_response",
          const_cast<char **>(kwlist), &status, &body, &content_type,
          &keep_alive, &extra_headers)) {
    return nullptr;
  }
  PyObject *result = nullptr;
  std::vector<std::pair<std::string, std::string>> extra;
  if (collect_header_pairs(extra_headers, &extra)) {
    std::vector<std::string> head;
    head.push_back("HTTP/1.1 " + std::to_string(status) + " " +
                   reason_for(status));
    head.push_back(std::string("Content-Type: ") + content_type);
    head.push_back("Content-Length: " + std::to_string(body.len));
    if (!keep_alive) head.push_back("Connection: close");
    for (const auto &kv : extra) {
      head.push_back(kv.first + ": " + kv.second);
    }
    result = assemble(head, static_cast<const char *>(body.buf),
                      static_cast<size_t>(body.len));
  }
  PyBuffer_Release(&body);
  return result;
}

PyObject *wire_configure(PyObject *, PyObject *args) {
  PyObject *request_cls, *response_cls, *protocol_error;
  if (!PyArg_ParseTuple(args, "OOO:configure", &request_cls,
                        &response_cls, &protocol_error)) {
    return nullptr;
  }
  Py_INCREF(request_cls);
  Py_INCREF(response_cls);
  Py_INCREF(protocol_error);
  Py_XDECREF(g_request_cls);
  Py_XDECREF(g_response_cls);
  Py_XDECREF(g_protocol_error);
  g_request_cls = request_cls;
  g_response_cls = response_cls;
  g_protocol_error = protocol_error;
  Py_RETURN_NONE;
}

PyMethodDef module_methods[] = {
    {"configure", wire_configure, METH_VARARGS,
     "configure(Request, Response, ProtocolError): hand the extension "
     "proto.py's event/exception classes so both backends emit the "
     "same Python types."},
    {"render_request",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(wire_render_request)),
     METH_VARARGS | METH_KEYWORDS,
     "Byte-identical twin of proto.render_request."},
    {"render_response",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(wire_render_response)),
     METH_VARARGS | METH_KEYWORDS,
     "Byte-identical twin of proto.render_response."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef stwire_module = {
    PyModuleDef_HEAD_INIT,
    "stwire",
    "Native HTTP/1.1 parse/render for the fleet wire (the C twin of "
    "sharetrade_tpu/fleet/proto.py; loaded ONLY through proto.py's "
    "backend dispatch).",
    -1,
    module_methods,
    nullptr, nullptr, nullptr, nullptr,
};

} // namespace

PyMODINIT_FUNC PyInit_stwire(void) {
  RequestParserType.tp_flags = Py_TPFLAGS_DEFAULT;
  RequestParserType.tp_doc =
      "Server side: bytes from a client connection -> Request events.";
  RequestParserType.tp_methods = parser_methods;
  RequestParserType.tp_init = parser_init;
  RequestParserType.tp_new = PyType_GenericNew;
  ResponseParserType.tp_flags = Py_TPFLAGS_DEFAULT;
  ResponseParserType.tp_doc =
      "Client side: bytes from a server connection -> Response events.";
  ResponseParserType.tp_methods = parser_methods;
  ResponseParserType.tp_init = parser_init;
  ResponseParserType.tp_new = PyType_GenericNew;
  if (PyType_Ready(&RequestParserType) < 0) return nullptr;
  if (PyType_Ready(&ResponseParserType) < 0) return nullptr;
  PyObject *mod = PyModule_Create(&stwire_module);
  if (mod == nullptr) return nullptr;
  Py_INCREF(&RequestParserType);
  if (PyModule_AddObject(mod, "RequestParser",
                         reinterpret_cast<PyObject *>(
                             &RequestParserType)) < 0) {
    Py_DECREF(&RequestParserType);
    Py_DECREF(mod);
    return nullptr;
  }
  Py_INCREF(&ResponseParserType);
  if (PyModule_AddObject(mod, "ResponseParser",
                         reinterpret_cast<PyObject *>(
                             &ResponseParserType)) < 0) {
    Py_DECREF(&ResponseParserType);
    Py_DECREF(mod);
    return nullptr;
  }
  return mod;
}
