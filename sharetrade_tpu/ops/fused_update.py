"""Fused optimizer update: grad-upcast + moment update + param update (+
optional compute-dtype recast) in ONE pass over each parameter leaf.

The optax pair every learner used to call —

    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)

— materializes the intermediate ``updates`` tree (and, under bf16_mixed,
an explicitly upcast grads tree before it) between two library calls. On
TPU that is O(params) of avoidable HBM round-trips per update; at the
update cadences this framework runs (every env step for qlearn/DQN, every
minibatch for PPO) the optimizer's byte traffic sits on the hot path the
roofline telemetry measured memory-bound. This module fuses the whole
update into one pass per leaf:

- **TPU**: a Pallas kernel per leaf (`pallas_guide.md` tiling: leaves
  flatten to (rows, 128) lanes, gridded in VMEM-sized row blocks) reading
  the raw (possibly bf16) gradient, the f32 master param and the f32
  moments, and writing the new master + moments — optionally also the
  bf16 compute recast of the updated param (``emit_compute``). The
  learners do not consume that third output yet: their next boundary
  re-casts the masters through ``PrecisionPolicy.cast_compute`` (one
  O(params) read, dwarfed by activation traffic at every tier this repo
  runs), because threading the copy would put a second weight tree in
  the scan carry / TrainState shape. ``emit_compute`` is the seam for
  the TPU follow-up where that read is worth eliminating; it is
  compiled by tools/smoke_compile.py and pinned by tests either way.
- **elsewhere** (the CPU test/dev tier): the same arithmetic as plain jnp
  ops inside the caller's jit — XLA fuses the chain into one elementwise
  pass per leaf, so the fallback is semantically identical and leaves no
  Pallas dependency on non-TPU backends.

Numerics contract (pinned by tests/test_precision.py): the op order
REPLICATES optax's exactly — ``scale_by_rss`` / ``scale_by_adam`` /
``sgd`` followed by ``scale_by_learning_rate`` and ``apply_updates`` — so
fp32 results are BIT-IDENTICAL to the optax pair, and bf16_mixed differs
only by the gradient's bf16 quantization (grads upcast before any
arithmetic; moments and params stay f32). The optimizer STATE is the
optax state pytree itself (``ScaleByRssState`` / ``ScaleByAdamState``
namedtuples from ``optimizer.init``), so checkpoints and the fallback
path interchange freely.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from sharetrade_tpu.config import LearnerConfig

#: optax defaults replicated here (build_optimizer constructs with these).
ADAGRAD_EPS = 1e-7
ADAM_B1, ADAM_B2, ADAM_EPS = 0.9, 0.999, 1e-8

_LANE = 128
_BLOCK_ROWS = 256          # (256, 128) f32 blocks: 128 KiB per operand


# ---------------------------------------------------------------------------
# per-leaf math (shared verbatim by the XLA fallback and the Pallas kernels:
# ONE definition so the two paths cannot drift)
# ---------------------------------------------------------------------------

def _adagrad_leaf(p, g, s, *, lr, compute_dtype):
    """optax ``adagrad``: scale_by_rss + scale_by_learning_rate +
    apply_updates, in optax's exact op order."""
    g = g.astype(jnp.float32)  # precision-cast-ok: THE fused grad upcast
    s_new = g * g + s
    inv = jnp.where(s_new > 0, jax.lax.rsqrt(s_new + ADAGRAD_EPS), 0.0)
    p_new = p + (inv * g) * (-lr)
    return p_new, (s_new,), p_new.astype(compute_dtype)


def _adam_leaf(p, g, mu, nu, *, lr, bias1, bias2, compute_dtype):
    """optax ``adam``: scale_by_adam (bias corrections precomputed from the
    incremented count by the caller — they are scalars shared across
    leaves) + scale_by_learning_rate + apply_updates."""
    g = g.astype(jnp.float32)  # precision-cast-ok: THE fused grad upcast
    mu_new = (1.0 - ADAM_B1) * g + ADAM_B1 * mu
    nu_new = (1.0 - ADAM_B2) * (g * g) + ADAM_B2 * nu
    mu_hat = mu_new / bias1
    nu_hat = nu_new / bias2
    u = mu_hat / (jnp.sqrt(nu_hat + 0.0) + ADAM_EPS)
    p_new = p + u * (-lr)
    return p_new, (mu_new, nu_new), p_new.astype(compute_dtype)


def _sgd_leaf(p, g, *, lr, compute_dtype):
    g = g.astype(jnp.float32)  # precision-cast-ok: THE fused grad upcast
    p_new = p + g * (-lr)
    return p_new, (), p_new.astype(compute_dtype)


# ---------------------------------------------------------------------------
# Pallas kernels (TPU): one fused VMEM pass per row block
# ---------------------------------------------------------------------------

def _kernel(leaf_fn, n_state, emit_compute, scalar_names, static_hyper,
            *refs):
    """One (row-block) program: read p/g/state blocks, run the SHARED leaf
    math, write the new p/state (+ optional compute recast). Traced
    per-step scalars (adam's bias corrections) arrive through an SMEM
    operand — a traced value must be a kernel input, never a closure."""
    if scalar_names:
        scal_ref, *refs = refs
        hyper = {name: scal_ref[i] for i, name in enumerate(scalar_names)}
    else:
        hyper = {}
    p_ref, g_ref = refs[0], refs[1]
    state_in = refs[2:2 + n_state]
    outs = refs[2 + n_state:]
    p_new, state_new, p_c = leaf_fn(
        p_ref[:], g_ref[:], *(r[:] for r in state_in),
        **static_hyper, **hyper)
    outs[0][:] = p_new
    for ref, val in zip(outs[1:1 + n_state], state_new):
        ref[:] = val
    if emit_compute:
        outs[1 + n_state][:] = p_c


def _pallas_leaf(leaf_fn, n_state, p, g, state_leaves, *, compute_dtype,
                 emit_compute, static_hyper, scalar_hyper,
                 interpret=False):
    """Run one leaf's fused update as a Pallas program over (rows, 128)
    blocks. Leaves flatten to lanes and pad to full blocks; padded tail
    elements compute garbage that is sliced off (no cross-element data
    flow in any supported optimizer, so padding never contaminates).
    ``interpret`` runs the kernel in Pallas interpret mode — the CPU test
    path for kernel logic (tiling legality still needs a real TPU compile,
    tools/smoke_compile.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.size
    rows = -(-n // _LANE)
    pad_rows = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    grid = pad_rows // _BLOCK_ROWS

    def prep(x):
        flat = x.reshape(-1)
        flat = jnp.pad(flat, (0, pad_rows * _LANE - n))
        return flat.reshape(pad_rows, _LANE)

    scalar_names = tuple(sorted(scalar_hyper))
    operands = []
    in_specs = []
    if scalar_names:
        operands.append(jnp.stack(
            [scalar_hyper[k].astype(jnp.float32) for k in scalar_names]))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    operands += [prep(p), prep(g)] + [prep(s) for s in state_leaves]
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0))
    in_specs += [spec] * (2 + n_state)
    out_shapes = [jax.ShapeDtypeStruct((pad_rows, _LANE), jnp.float32)
                  for _ in range(1 + n_state)]
    if emit_compute:
        out_shapes.append(
            jax.ShapeDtypeStruct((pad_rows, _LANE), compute_dtype))
    kernel = functools.partial(
        _kernel, leaf_fn, n_state, emit_compute, scalar_names,
        dict(static_hyper, compute_dtype=compute_dtype))
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=tuple([spec] * len(out_shapes)),
        out_shape=tuple(out_shapes),
        interpret=interpret,
    )(*operands)

    def unprep(x):
        return x.reshape(-1)[:n].reshape(p.shape)

    p_new = unprep(outs[0])
    state_new = tuple(unprep(o) for o in outs[1:1 + n_state])
    p_c = unprep(outs[1 + n_state]) if emit_compute else None
    return p_new, state_new, p_c


def _use_pallas_default() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# pytree-level fused apply
# ---------------------------------------------------------------------------

def fused_apply(optimizer_name: str, lr: float, grads: Any, opt_state: Any,
                params: Any, *, compute_dtype=jnp.float32,
                emit_compute: bool = False,
                use_pallas: bool | None = None,
                interpret: bool = False):
    """One fused pass over the parameter pytree.

    Returns ``(new_params, new_opt_state[, new_compute_params])`` — the
    third element only when ``emit_compute`` (the bf16 weight copy for the
    next forward, written by the same kernel pass). ``opt_state`` is the
    optax state from ``build_optimizer(...).init(params)`` and the
    returned state has the identical structure, so fused and optax paths
    (and their checkpoints) interchange freely. Raw (possibly bf16) grads
    go in; the upcast happens inside the pass."""
    if use_pallas is None:
        use_pallas = _use_pallas_default()
    lr = float(lr)

    static_hyper = {"lr": lr}
    scalar_hyper: dict[str, Any] = {}
    if optimizer_name == "adagrad":
        leaf_fn, n_state = _adagrad_leaf, 1
        state_of = lambda st: (st[0].sum_of_squares,)
        rebuild = lambda st, leaves: (
            st[0]._replace(sum_of_squares=leaves[0]), *st[1:])
    elif optimizer_name == "adam":
        leaf_fn, n_state = _adam_leaf, 2
        # Bias corrections are per-STEP scalars (safe_int32_increment +
        # 1 - b^t, optax's exact formulation) — computed once out here,
        # not per leaf, exactly as scale_by_adam shares them. They are
        # TRACED values, so the Pallas path feeds them through SMEM.
        count = opt_state[0].count
        count_inc = jnp.where(
            count < jnp.iinfo(jnp.int32).max, count + 1, count)
        scalar_hyper = {
            "bias1": 1.0 - ADAM_B1 ** count_inc.astype(jnp.float32),
            "bias2": 1.0 - ADAM_B2 ** count_inc.astype(jnp.float32),
        }
        state_of = lambda st: (st[0].mu, st[0].nu)
        rebuild = lambda st, leaves: (
            st[0]._replace(count=count_inc, mu=leaves[0], nu=leaves[1]),
            *st[1:])
    elif optimizer_name == "sgd":
        leaf_fn, n_state = _sgd_leaf, 0
        state_of = lambda st: ()
        rebuild = lambda st, leaves: st
    else:
        raise ValueError(
            f"fused update does not support optimizer {optimizer_name!r}; "
            "set precision.fused_update='off' for custom optimizers")

    state_trees = state_of(opt_state)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_state = [treedef.flatten_up_to(t) for t in state_trees]

    new_p, new_state, new_pc = [], [[] for _ in range(n_state)], []
    for i, (p, g) in enumerate(zip(flat_p, flat_g)):
        leaves = tuple(t[i] for t in flat_state)
        # Pallas needs tiled 2-D blocks; scalars and tiny leaves stay on
        # the (identical-math) fused XLA path.
        if (use_pallas or interpret) and p.size >= _LANE:
            out = _pallas_leaf(leaf_fn, n_state, p, g, leaves,
                               compute_dtype=compute_dtype,
                               emit_compute=emit_compute,
                               static_hyper=static_hyper,
                               scalar_hyper=scalar_hyper,
                               interpret=interpret)
        else:
            out = leaf_fn(p, g, *leaves, compute_dtype=compute_dtype,
                          **static_hyper, **scalar_hyper)
        new_p.append(out[0])
        for j, s in enumerate(out[1]):
            new_state[j].append(s)
        new_pc.append(out[2])

    params_new = jax.tree_util.tree_unflatten(treedef, new_p)
    state_new = rebuild(
        opt_state,
        [jax.tree_util.tree_unflatten(treedef, s) for s in new_state])
    if emit_compute:
        return params_new, state_new, jax.tree_util.tree_unflatten(
            treedef, new_pc)
    return params_new, state_new


def fused_supported(cfg: LearnerConfig) -> bool:
    """Whether the learner's optimizer has a fused implementation (the
    update-path builder falls back to the optax pair otherwise)."""
    return cfg.optimizer in ("adagrad", "adam", "sgd")
