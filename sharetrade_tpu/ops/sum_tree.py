"""Fixed-shape sum-tree for on-device prioritized replay sampling.

The data structure behind PER (Schaul et al., arxiv 1511.05952), built for
the jitted chunk: a complete binary tree over a power-of-two leaf array,
stored as one array PER LEVEL — ``levels[0]`` the ``(L,)`` leaves up to
``levels[depth]`` the ``(1,)`` root (total mass) — so priority update →
stratified sample → TD-error write-back all happen inside the compiled
(mega)chunk with zero host round-trips and no dynamic shapes.

Why level-split instead of the textbook flat ``(2L,)`` heap layout: the
update path scatter-writes one level at a time, and XLA materializes a
scatter as a copy of the array it touches — on the flat layout every one
of the ``log2(L)`` ancestor writes copies the WHOLE tree (measured 2.4x
on the reference-shape DQN chunk), while per-level arrays copy just the
touched level, ``2L`` bytes total per update instead of ``2L·log2(L)``.

Two operations, both ``lax``-only:

- :func:`set_priorities` — batched leaf writes followed by a bottom-up
  ANCESTOR-PATH refresh: ``log2(L)`` rounds of "recompute each touched
  parent as the sum of its two (already-updated) children" — scatter-SET
  semantics, so duplicate indices (two strata hitting one leaf, masked
  rows aliasing a live slot) write identical values instead of
  double-adding deltas, and every touched node is *exactly* the pairwise
  sum of its children afterwards — the total-mass property the tests pin.
- :func:`sample_stratified` — inverse-CDF descent for a whole batch at
  once: stratum ``i`` draws its mass from ``[i/B, (i+1)/B) * total`` and
  walks root→leaf in ``log2(L)`` vectorized steps. Zero-priority
  (masked / never-written) leaves carry no mass and are unreachable,
  with a deterministic max-priority fallback for the float-boundary edge
  where a stratum's residual mass lands exactly on an empty right
  subtree.

The host side of the replay data plane — segment rotation, recovery,
durable IO — lives in ``data/transitions.py`` and the orchestrator's
consumer thread (``_journal_transitions`` / ``_warm_start_replay``);
``tools/lint_hot_loop.py`` check 9 keeps host calls out of this module.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class SumTree:
    """``levels[0]`` = ``(L,)`` leaf priorities, ``levels[k]`` = the
    ``(L/2^k,)`` internal sums, ``levels[-1]`` = the ``(1,)`` root."""

    levels: tuple

    @property
    def num_leaves(self) -> int:
        return self.levels[0].shape[0]

    @property
    def total(self) -> jax.Array:
        return self.levels[-1][0]

    @property
    def leaves(self) -> jax.Array:
        return self.levels[0]


def leaf_count(capacity: int) -> int:
    """Next power of two >= capacity (>= 1)."""
    if capacity < 1:
        raise ValueError(f"sum-tree capacity must be >= 1, got {capacity}")
    return 1 << (capacity - 1).bit_length() if capacity > 1 else 1


def from_leaves(leaves: jax.Array) -> SumTree:
    """Build the whole tree from a leaf array (O(2L) — the out-of-band
    reseed path for resume warm starts, not the per-step update)."""
    levels = [jnp.asarray(leaves, jnp.float32)]
    while levels[-1].shape[0] > 1:
        levels.append(levels[-1].reshape(-1, 2).sum(axis=1))
    return SumTree(levels=tuple(levels))


def create(capacity: int) -> SumTree:
    """All-zero tree: every leaf massless, nothing sampleable yet."""
    return from_leaves(jnp.zeros((leaf_count(capacity),), jnp.float32))


def set_priorities(tree: SumTree, idx: jax.Array, priority: jax.Array,
                   mask: jax.Array | None = None) -> SumTree:
    """Batched leaf update: ``leaves[idx[i]] = priority[i]`` where
    ``mask[i]`` (unmasked rows leave their slot untouched — they write the
    slot's CURRENT value, so a masked row aliasing a live slot is a
    no-op). Ancestors refresh along the touched root-paths only: each
    level scatter-SETs ``parent = left_child + right_child`` from the
    already-updated level below, so duplicate indices write identical
    values (never double-added deltas) and the child-sum invariant holds
    exactly at every touched node."""
    idx = idx.astype(jnp.int32)
    priority = priority.astype(jnp.float32)
    if mask is not None:
        priority = jnp.where(mask, priority, tree.levels[0][idx])
    levels = list(tree.levels)
    levels[0] = levels[0].at[idx].set(priority)
    pos = idx
    for k in range(1, len(levels)):
        pos = pos // 2
        levels[k] = levels[k].at[pos].set(
            levels[k - 1][2 * pos] + levels[k - 1][2 * pos + 1])
    return SumTree(levels=tuple(levels))


def sample_stratified(tree: SumTree, key: jax.Array,
                      batch: int) -> tuple[jax.Array, jax.Array]:
    """Stratified inverse-CDF sample of ``batch`` leaves ∝ priority.

    Stratum ``i`` draws target mass ``(i + u_i)/batch * total`` with
    ``u_i ~ U[0,1)``, then every stratum descends the tree in lockstep:
    at each of ``log2(L)`` levels go left when the residual mass fits the
    left subtree, else subtract it and go right. Returns ``(idx, probs)``
    — leaf indices and their normalized sampling probabilities
    ``p_leaf / total`` (the IS-weight input). All-zero trees return index
    0 with probability 0; callers gate on readiness."""
    levels = tree.levels
    total = tree.total
    strata = (jnp.arange(batch, dtype=jnp.float32)
              + jax.random.uniform(key, (batch,))) / batch
    mass = strata * total
    node = jnp.zeros((batch,), jnp.int32)
    for k in range(len(levels) - 2, -1, -1):      # root-1 down to leaves
        left = 2 * node
        left_sum = levels[k][left]
        go_left = mass < left_sum
        node = jnp.where(go_left, left, left + 1)
        mass = jnp.where(go_left, mass, mass - left_sum)
    # Float-boundary fallback: residual mass can land exactly on an empty
    # right subtree and reach a zero leaf; remap those strata onto the
    # max-priority leaf (deterministic, never a masked slot when any live
    # slot exists).
    leaf_p = levels[0][node]
    fallback = jnp.argmax(levels[0]).astype(jnp.int32)
    idx = jnp.where(leaf_p > 0, node, fallback)
    probs = levels[0][idx] / jnp.maximum(total, jnp.float32(1e-30))
    return idx, probs


def is_weights(probs: jax.Array, size: jax.Array,
               beta: jax.Array) -> jax.Array:
    """Importance-sampling weights ``(N * P(i))^-beta``, normalized by the
    batch max (the standard PER stabilization) — zero-probability rows
    (unready buffer, masked strata) get weight 0, never inf."""
    n = jnp.maximum(size.astype(jnp.float32), 1.0)
    safe = jnp.maximum(probs, jnp.float32(1e-30))
    w = jnp.where(probs > 0, (n * safe) ** (-beta), 0.0)
    return w / jnp.maximum(jnp.max(w), jnp.float32(1e-30))
