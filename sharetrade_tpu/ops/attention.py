"""Causal multi-head attention: Pallas flash kernel + XLA fallback.

The transformer tick-series policy (BASELINE.json config 5) attends over price
windows. On TPU the forward pass runs as a Pallas flash-attention kernel —
blocked online softmax, O(T) VMEM instead of the O(T²) score matrix in HBM —
following the playbook in /opt/skills/guides/pallas_guide.md (grid/BlockSpec
tiling, fori_loop over K blocks, broadcasted_iota masks).

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
pass recomputes attention with the XLA reference implementation. Forward
(rollout-heavy RL: thousands of policy evaluations per update) gets the
kernel; the update path pays one rematerialized T² softmax, which at tick-
window lengths is well inside VMEM-friendly territory. A fused Pallas
backward is a later optimization, not a semantic change.

Shapes: (batch, heads, seq, head_dim) throughout. Sequence and head_dim are
padded to TPU tile multiples inside the wrapper (lane = 128, guide §Tiling);
zero-padded K columns are masked to -inf, zero-padded D columns contribute
nothing to QKᵀ and are sliced off the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_Q = 128
BLOCK_K = 128
LANE = 128

_NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None):
    """Plain XLA attention — the numeric ground truth for the kernel."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        scores = jnp.where(col <= row, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  sm_scale: float, kv_len: int, kv_pad: int):
    """One (batch*head, q-block) program: online-softmax over K blocks.

    ``kv_len`` is the true key count (padding columns beyond it are masked);
    ``kv_pad`` is the padded extent the loop tiles over.
    """
    q_block = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)

    num_k_blocks = pl.cdiv(kv_pad, block_k)
    if causal:
        # Blocks entirely above the causal frontier contribute nothing.
        last_row = (qi + 1) * q_block - 1
        num_k_blocks = jnp.minimum(num_k_blocks, pl.cdiv(last_row + 1, block_k))

    row_ids = qi * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 0)

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (bq, bk)

        col_ids = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, block_k), 1)
        mask = col_ids < kv_len  # padding columns are not real keys
        if causal:
            mask = mask & (col_ids <= row_ids)
        s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((q_block, head_dim), jnp.float32)
    m0 = jnp.full((q_block,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q_block,), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, num_k_blocks, body, (acc0, m0, l0))

    # Fully-masked (padding) query rows have l == 0; emit zeros, not NaNs.
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, causal: bool, sm_scale: float, interpret: bool):
    batch, heads, seq_len, head_dim = q.shape
    kv_len = k.shape[2]
    if causal and kv_len != seq_len:
        # Causal alignment between unequal q/kv lengths is ambiguous
        # (prefix vs suffix); refuse rather than guess.
        raise ValueError(
            f"causal attention requires q_len == kv_len, got {seq_len} vs {kv_len}")

    qp = _pad_to(_pad_to(q, 2, BLOCK_Q), 3, LANE)
    kp = _pad_to(_pad_to(k, 2, BLOCK_K), 3, LANE)
    vp = _pad_to(_pad_to(v, 2, BLOCK_K), 3, LANE)
    d_pad = qp.shape[-1]  # post-padding width (a LANE multiple, any head_dim)
    qp = qp.reshape(batch * heads, -1, d_pad)
    kp = kp.reshape(batch * heads, -1, d_pad)
    vp = vp.reshape(batch * heads, -1, d_pad)
    bh, t_pad, _ = qp.shape
    kv_pad = kp.shape[1]

    kernel = functools.partial(
        _flash_kernel, block_k=BLOCK_K, causal=causal,
        sm_scale=sm_scale, kv_len=kv_len, kv_pad=kv_pad)

    out = pl.pallas_call(
        kernel,
        grid=(bh, t_pad // BLOCK_Q),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d_pad), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
        interpret=interpret,
    )(qp, kp, vp)

    out = out.reshape(batch, heads, t_pad, d_pad)
    return out[:, :, :seq_len, :head_dim]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, sm_scale, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, interpret)


def _flash_fwd_rule(q, k, v, causal, sm_scale, interpret):
    return _flash_forward(q, k, v, causal, sm_scale, interpret), (q, k, v)


def _flash_bwd_rule(causal, sm_scale, interpret, residuals, g):
    # Rematerialized backward through the XLA reference (see module docstring).
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale),
        q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: float | None = None,
                    use_pallas: bool | None = None):
    """Causal MHA over (batch, heads, seq, head_dim).

    ``use_pallas=None`` auto-selects: the kernel on TPU, the XLA reference
    elsewhere (the unit suite runs the kernel through the Pallas interpreter
    separately — tests/test_ops.py — so both paths stay covered).
    """
    if q.ndim != 4:
        raise ValueError(f"expected (batch, heads, seq, head_dim), got {q.shape}")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not use_pallas:
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, causal, sm_scale, interpret)
