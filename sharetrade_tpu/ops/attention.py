"""Causal multi-head attention: Pallas flash kernel + XLA fallback.

The transformer tick-series policy (BASELINE.json config 5) attends over price
windows. On TPU the forward pass runs as a Pallas flash-attention kernel —
blocked online softmax, O(T) VMEM instead of the O(T²) score matrix in HBM —
following the playbook in /opt/skills/guides/pallas_guide.md (grid/BlockSpec
tiling, fori_loop over K blocks, broadcasted_iota masks).

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` with FUSED Pallas
backward kernels (the standard flash-attention backward): the forward saves
only the per-row logsumexp (O(T) residual instead of the T² probability
matrix), and two kernels recompute score blocks on the fly — one tiled over
query blocks producing dQ, one tiled over key blocks producing dK/dV — so
the backward never materializes T² in HBM either.

Shapes: (batch, heads, seq, head_dim) throughout. Sequence and head_dim are
padded to TPU tile multiples inside the wrapper (lane = 128, guide §Tiling);
zero-padded K columns are masked to -inf, zero-padded D columns contribute
nothing to QKᵀ and are sliced off the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sharetrade_tpu.config import ConfigError

BLOCK_Q = 128
BLOCK_K = 128
LANE = 128

_NEG_INF = -1e30


def _dot(a, b):
    """MXU matmul with f32 accumulation. For bf16 operands the precision is
    pinned to DEFAULT (native single-pass bf16): a globally-configured
    "highest" precision (the test suite pins it for f32 parity) has no bf16
    meaning and crashes Mosaic's matmul lowering."""
    precision = (jax.lax.Precision.DEFAULT
                 if a.dtype == jnp.bfloat16 or b.dtype == jnp.bfloat16
                 else None)
    return jnp.dot(a, b, preferred_element_type=jnp.float32,
                   precision=precision)


def _block_size(padded: int) -> int:
    """Adaptive tiling: when the (128-padded) extent is a 256 multiple, use
    256-wide blocks — short sequences (the 202-token tick window pads to 256)
    then run one block per program, collapsing the K loop and the q-block
    grid dimension whose overhead dominates these shapes. Other extents keep
    the classic 128 tiles (a block must divide the padded extent)."""
    return 256 if padded % 256 == 0 else 128


def reference_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                        local_window: int | None = None):
    """Plain XLA attention — the numeric ground truth for the kernel.

    ``local_window=W`` restricts each query row p to the band of keys
    ``(p-W, p]`` — sliding-window (banded) causal attention: a query sees
    exactly the W keys ending at itself, so a sliding price window can be
    attended inside one long sequence without reprocessing it per step.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if local_window is not None and not causal:
        raise ConfigError("local_window requires causal attention")
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        mask = col <= row
        if local_window is not None:
            mask = mask & (col > row - local_window)
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, sm_scale: float, kv_len: int, kv_pad: int,
                  local_window: int | None):
    """One (batch*head, q-block) program: online-softmax over K blocks.

    ``kv_len`` is the true key count (padding columns beyond it are masked);
    ``kv_pad`` is the padded extent the loop tiles over. ``local_window=W``
    bands the causal mask to keys ``(row-W, row]`` and skips K blocks
    entirely below the band, so compute is O(T·W) instead of O(T²).
    """
    q_block = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    qi = pl.program_id(1)

    # Inputs stay in their native dtype (bf16 rides the MXU single-pass);
    # accumulation and softmax run in f32 via preferred_element_type.
    q = q_ref[0]  # (block_q, d)

    first_k_block = 0
    num_k_blocks = pl.cdiv(kv_pad, block_k)
    if causal:
        # Blocks entirely above the causal frontier contribute nothing.
        last_row = (qi + 1) * q_block - 1
        num_k_blocks = jnp.minimum(num_k_blocks, pl.cdiv(last_row + 1, block_k))
    if local_window is not None:
        # Blocks entirely below the band contribute nothing either.
        first_row = qi * q_block
        first_k_block = jnp.maximum(
            0, (first_row - local_window + 1) // block_k)

    row_ids = qi * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 0)

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = _dot(q, k_blk.T) * sm_scale  # (bq, bk)

        col_ids = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, block_k), 1)
        mask = col_ids < kv_len  # padding columns are not real keys
        if causal:
            mask = mask & (col_ids <= row_ids)
        if local_window is not None:
            mask = mask & (col_ids > row_ids - local_window)
        s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + _dot(p.astype(v_blk.dtype), v_blk)
        return acc, m_new, l_new

    acc0 = jnp.zeros((q_block, head_dim), jnp.float32)
    m0 = jnp.full((q_block,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q_block,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(first_k_block, num_k_blocks, body,
                                  (acc0, m0, l0))

    # Fully-masked (padding) query rows have l == 0; emit zeros, not NaNs.
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # Per-row logsumexp of the (scaled, masked) scores — the O(T) residual
    # the backward kernels rebuild probabilities from: p = exp(s - lse).
    # Stored broadcast across an 8-row sublane axis: TPU block shapes need
    # the last two dims divisible by (8, 128), so a flat (1, block_q) row
    # is not a legal block (pallas_guide.md §Tiling).
    lse_row = jnp.where(l > 0, m + jnp.log(l_safe), 0.0)
    lse_ref[0] = jnp.broadcast_to(lse_row[None, :], (8, q_block))


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _pad_inputs(q, k, v):
    """Pad q/k/v to tile multiples and collapse (batch, heads)."""
    batch, heads = q.shape[:2]
    qp = _pad_to(_pad_to(q, 2, BLOCK_Q), 3, LANE)
    kp = _pad_to(_pad_to(k, 2, BLOCK_K), 3, LANE)
    vp = _pad_to(_pad_to(v, 2, BLOCK_K), 3, LANE)
    d_pad = qp.shape[-1]  # post-padding width (a LANE multiple, any head_dim)
    qp = qp.reshape(batch * heads, -1, d_pad)
    kp = kp.reshape(batch * heads, -1, d_pad)
    vp = vp.reshape(batch * heads, -1, d_pad)
    return qp, kp, vp, d_pad


def _flash_forward(q, k, v, causal, sm_scale, local_window, interpret):
    """Returns ``(out, lse)`` — lse is the backward's O(T) residual."""
    batch, heads, seq_len, head_dim = q.shape
    kv_len = k.shape[2]
    if causal and kv_len != seq_len:
        # Causal alignment between unequal q/kv lengths is ambiguous
        # (prefix vs suffix); refuse rather than guess.
        raise ConfigError(
            f"causal attention requires q_len == kv_len, got {seq_len} vs {kv_len}")

    qp, kp, vp, d_pad = _pad_inputs(q, k, v)
    bh, t_pad, _ = qp.shape
    kv_pad = kp.shape[1]
    block_q, block_k = _block_size(t_pad), _block_size(kv_pad)

    if local_window is not None and kv_pad * d_pad > _STREAM_KV_ELEMS:
        # Banded long sequence: stream K/V one block per grid step — VMEM
        # holds O(block + window) regardless of sequence length.
        out, lse = _banded_forward(
            qp, kp, vp, d_pad, (kv_len, block_q, block_k), sm_scale,
            local_window, interpret)
        out = out.reshape(batch, heads, t_pad, d_pad)[:, :, :seq_len, :head_dim]
        lse = lse.reshape(batch, heads, 8, t_pad)[:, :, 0, :seq_len]
        return out, lse

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal,
        sm_scale=sm_scale, kv_len=kv_len, kv_pad=kv_pad,
        local_window=local_window)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, t_pad), jnp.float32),
        ),
        interpret=interpret,
    )(qp, kp, vp)

    out = out.reshape(batch, heads, t_pad, d_pad)[:, :, :seq_len, :head_dim]
    lse = lse.reshape(batch, heads, 8, t_pad)[:, :, 0, :seq_len]
    return out, lse


# ---------------------------------------------------------------------------
# Streaming banded kernels: when local_window is set, K/V stream through VMEM
# one block per grid step (a third grid axis walks the band) instead of the
# whole padded K/V staging per program. VMEM then holds O(block + window)
# regardless of sequence length, so episode-mode replay spans are bounded by
# HBM, not by the ~16 MB VMEM (the full-KV kernels above keep serving the
# local_window=None paths, which genuinely need all keys).
#
# The band for query block i spans key rows [i*bq - W + 1, (i+1)*bq - 1]:
# at most cdiv(bq + W - 1, bk) + 1 key blocks — a STATIC count, so the grid
# axis has fixed extent and out-of-range steps (clamped by the index_map)
# are masked via virtual-vs-clipped block-index comparison.
#
# Short sequences stay on the full-KV kernels (streaming's extra grid steps
# cost ~20% there); the dispatch threshold is the per-tensor K/V element
# count beyond which full staging approaches the VMEM budget.

_STREAM_KV_ELEMS = 1 << 19          # 512k elems ≈ 2 MB f32 per K/V tensor


def _band_extent(window: int, span_block: int, other_block: int,
                 num_other_blocks: int) -> int:
    return min(num_other_blocks, -(-(span_block + window - 1) // other_block) + 1)


def _band_first_k(i, block_q: int, block_k: int, window: int):
    """First key block of query block ``i``'s band — the ONE definition the
    index_maps and the in-kernel virtual/clipped masks must share."""
    return jnp.maximum(0, (i * block_q - window + 1) // block_k)


def _band_first_q(i, block_q: int, block_k: int):
    """First query block that can see key block ``i`` (causal lower bound)
    — shared by the dkv kernel and its q/lse/delta index_maps."""
    return (i * block_k) // block_q


def _band_k_index(block_q: int, block_k: int, window: int,
                  num_k_blocks: int):
    """BlockSpec index_map walking query block ``i``'s band at step ``j``."""
    def index(b, i, j):
        return (b, jnp.minimum(_band_first_k(i, block_q, block_k, window) + j,
                               num_k_blocks - 1), 0)
    return index


def _flash_banded_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                             acc_ref, m_ref, l_ref, *, block_k: int,
                             sm_scale: float, kv_len: int,
                             num_k_blocks: int, window: int,
                             band_blocks: int):
    q_block = q_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    virtual = _band_first_k(qi, q_block, block_k, window) + j
    clipped = jnp.minimum(virtual, num_k_blocks - 1)  # what index_map fetched

    q = q_ref[0]
    k_blk = k_ref[0]
    v_blk = v_ref[0]
    s = _dot(q, k_blk.T) * sm_scale
    row_ids = qi * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 0)
    col_ids = clipped * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 1)
    mask = ((col_ids < kv_len) & (col_ids <= row_ids)
            & (col_ids > row_ids - window) & (virtual == clipped))
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[0]
    l_prev = l_ref[0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, None])
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc_ref[...] = (acc_ref[...] * alpha[:, None]
                    + _dot(p.astype(v_blk.dtype), v_blk))
    m_ref[...] = jnp.broadcast_to(m_new[None, :], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[None, :], l_ref.shape)

    @pl.when(j == band_blocks - 1)
    def _finish():
        l = l_ref[0]
        m = m_ref[0]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_row = jnp.where(l > 0, m + jnp.log(l_safe), 0.0)
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], (8, q_block))


def _banded_forward(qp, kp, vp, d_pad, seq_params, sm_scale, window,
                    interpret):
    """Streaming-banded forward over padded (bh, t_pad, d_pad) inputs."""
    bh, t_pad, _ = qp.shape
    kv_pad = kp.shape[1]
    kv_len, block_q, block_k = seq_params
    num_k_blocks = kv_pad // block_k
    band_blocks = _band_extent(window, block_q, block_k, num_k_blocks)

    k_index = _band_k_index(block_q, block_k, window, num_k_blocks)

    kernel = functools.partial(
        _flash_banded_fwd_kernel, block_k=block_k, sm_scale=sm_scale,
        kv_len=kv_len, num_k_blocks=num_k_blocks, window=window,
        band_blocks=band_blocks)
    return pl.pallas_call(
        kernel,
        grid=(bh, t_pad // block_q, band_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), k_index),
            pl.BlockSpec((1, block_k, d_pad), k_index),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), qp.dtype),
            jax.ShapeDtypeStruct((bh, 8, t_pad), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d_pad), jnp.float32),
            pltpu.VMEM((8, block_q), jnp.float32),
            pltpu.VMEM((8, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float, kv_len: int, kv_pad: int,
                         local_window: int | None):
    """dQ, tiled over query blocks: dq = Σ_kb (p∘(dpᵀv − δ))·scale @ k."""
    q_block = q_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0]                                # (bq, d) native dtype
    do = do_ref[0]                              # (bq, d)
    # lse/delta arrive broadcast over an 8-row sublane axis — the same
    # (8, 128)-legality workaround the forward uses to store lse (see
    # _flash_kernel); row 0 carries the real values.
    lse = lse_ref[0][0]                         # (bq,)
    delta = delta_ref[0][0]                     # (bq,)
    row_ids = qi * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 0)

    first_k_block = 0
    num_k_blocks = pl.cdiv(kv_pad, block_k)
    if causal:
        last_row = (qi + 1) * q_block - 1
        num_k_blocks = jnp.minimum(num_k_blocks, pl.cdiv(last_row + 1, block_k))
    if local_window is not None:
        first_k_block = jnp.maximum(
            0, (qi * q_block - local_window + 1) // block_k)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = _dot(q, k_blk.T) * sm_scale
        col_ids = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, block_k), 1)
        mask = col_ids < kv_len
        if causal:
            mask = mask & (col_ids <= row_ids)
        if local_window is not None:
            mask = mask & (col_ids > row_ids - local_window)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = _dot(do, v_blk.T)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        return dq + _dot(ds, k_blk)

    dq0 = jnp.zeros((q_block, q_ref.shape[2]), jnp.float32)
    dq = jax.lax.fori_loop(first_k_block, num_k_blocks, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float, kv_len: int, t_pad: int,
                          local_window: int | None):
    """dK/dV, tiled over key blocks: dv = Σ_qb pᵀ·do; dk = Σ_qb dsᵀ·q·scale."""
    block_k = k_ref.shape[1]
    kb = pl.program_id(1)

    k_blk = k_ref[0]                            # (bk, d) native dtype
    v_blk = v_ref[0]
    col_ids = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    col_valid = col_ids < kv_len

    num_q_blocks = t_pad // block_q
    # Causal: query blocks strictly before this key block see none of it.
    qb_start = _band_first_q(kb, block_q, block_k) if causal else 0
    qb_end = num_q_blocks
    if local_window is not None:
        # Banded: key c is seen only by queries p ≤ c + W - 1; blocks past
        # that frontier contribute nothing.
        last_q_row = (kb + 1) * block_k - 1 + local_window - 1
        qb_end = jnp.minimum(num_q_blocks, pl.cdiv(last_q_row + 1, block_q))

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]

        s = _dot(q_blk, k_blk.T) * sm_scale
        mask = col_valid
        if causal:
            row_ids = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (col_ids <= row_ids)
            if local_window is not None:
                mask = mask & (col_ids > row_ids - local_window)
        p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)

        dv = dv + _dot(p.astype(do_blk.dtype).T, do_blk)
        dp = _dot(do_blk, v_blk.T)
        ds = (p * (dp - delta_blk[:, None]) * sm_scale).astype(q_blk.dtype)
        dk = dk + _dot(ds.T, q_blk)
        return dk, dv

    zeros = jnp.zeros((block_k, k_ref.shape[2]), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, qb_end, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_banded_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dq_acc_ref, *, block_k: int,
                            sm_scale: float, kv_len: int, num_k_blocks: int,
                            window: int, band_blocks: int):
    q_block = q_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    virtual = _band_first_k(qi, q_block, block_k, window) + j
    clipped = jnp.minimum(virtual, num_k_blocks - 1)

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][0]
    delta = delta_ref[0][0]
    k_blk = k_ref[0]
    v_blk = v_ref[0]
    s = _dot(q, k_blk.T) * sm_scale
    row_ids = qi * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 0)
    col_ids = clipped * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (q_block, block_k), 1)
    mask = ((col_ids < kv_len) & (col_ids <= row_ids)
            & (col_ids > row_ids - window) & (virtual == clipped))
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
    dp = _dot(do, v_blk.T)
    ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
    dq_acc_ref[...] = dq_acc_ref[...] + _dot(ds, k_blk)

    @pl.when(j == band_blocks - 1)
    def _finish():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _flash_banded_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                             block_q: int, sm_scale: float, kv_len: int,
                             num_q_blocks: int, window: int,
                             band_blocks: int):
    block_k = k_ref.shape[1]
    kb = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    virtual = _band_first_q(kb, block_q, block_k) + j
    clipped = jnp.minimum(virtual, num_q_blocks - 1)

    k_blk = k_ref[0]
    v_blk = v_ref[0]
    q_blk = q_ref[0]
    do_blk = do_ref[0]
    lse_blk = lse_ref[0][0]
    delta_blk = delta_ref[0][0]

    s = _dot(q_blk, k_blk.T) * sm_scale
    row_ids = clipped * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    col_ids = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = ((col_ids < kv_len) & (col_ids <= row_ids)
            & (col_ids > row_ids - window) & (virtual == clipped))
    p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
    dv_acc_ref[...] = dv_acc_ref[...] + _dot(p.astype(do_blk.dtype).T, do_blk)
    dp = _dot(do_blk, v_blk.T)
    ds = (p * (dp - delta_blk[:, None]) * sm_scale).astype(q_blk.dtype)
    dk_acc_ref[...] = dk_acc_ref[...] + _dot(ds.T, q_blk)

    @pl.when(j == band_blocks - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _banded_backward(qp, kp, vp, gp, lse_p, delta, d_pad, seq_params,
                     sm_scale, window, interpret):
    """Streaming-banded dQ and dK/dV over padded (bh, …) inputs."""
    bh, t_pad, _ = qp.shape
    kv_pad = kp.shape[1]
    kv_len, block_q, block_k = seq_params
    num_k_blocks = kv_pad // block_k
    num_q_blocks = t_pad // block_q

    k_index = _band_k_index(block_q, block_k, window, num_k_blocks)

    band_k = _band_extent(window, block_q, block_k, num_k_blocks)
    dq_kernel = functools.partial(
        _flash_banded_dq_kernel, block_k=block_k, sm_scale=sm_scale,
        kv_len=kv_len, num_k_blocks=num_k_blocks, window=window,
        band_blocks=band_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, num_q_blocks, band_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), k_index),
            pl.BlockSpec((1, block_k, d_pad), k_index),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d_pad), qp.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, gp, lse_p, delta)

    def q_index(b, i, j):
        return (b, jnp.minimum(_band_first_q(i, block_q, block_k) + j,
                               num_q_blocks - 1), 0)

    def qrow_index(b, i, j):
        return (b, 0, jnp.minimum(_band_first_q(i, block_q, block_k) + j,
                                  num_q_blocks - 1))

    band_q = _band_extent(window, block_k, block_q, num_q_blocks)
    dkv_kernel = functools.partial(
        _flash_banded_dkv_kernel, block_q=block_q, sm_scale=sm_scale,
        kv_len=kv_len, num_q_blocks=num_q_blocks, window=window,
        band_blocks=band_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, num_k_blocks, band_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), q_index),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d_pad), q_index),
            pl.BlockSpec((1, 8, block_q), qrow_index),
            pl.BlockSpec((1, 8, block_q), qrow_index),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, kv_pad, d_pad), kp.dtype),
            jax.ShapeDtypeStruct((bh, kv_pad, d_pad), vp.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, gp, lse_p, delta)
    return dq, dk, dv


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale, local_window,
                    interpret):
    batch, heads, seq_len, head_dim = q.shape
    kv_len = k.shape[2]

    qp, kp, vp, d_pad = _pad_inputs(q, k, v)
    bh, t_pad, _ = qp.shape
    kv_pad = kp.shape[1]
    gp = _pad_to(_pad_to(g, 2, BLOCK_Q), 3, LANE).reshape(bh, t_pad, d_pad)
    # δ = rowsum(dO ∘ O): cheap elementwise — plain XLA, padded with zeros so
    # padding query rows contribute nothing in the kernels.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = _pad_to(delta, 2, BLOCK_Q).reshape(bh, t_pad)
    lse_p = _pad_to(lse, 2, BLOCK_Q).reshape(bh, t_pad)
    # Sublane-broadcast to (bh, 8, t_pad): a flat (1, block_q) block over a
    # (bh, t_pad) array violates Mosaic's (8, 128) block-divisibility rule
    # whenever bh > 1 — the forward's lse output hit the same wall and stores
    # the broadcast layout; the backward reads row 0 back out.
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, t_pad))
    lse_p = jnp.broadcast_to(lse_p[:, None, :], (bh, 8, t_pad))

    block_q, block_k = _block_size(t_pad), _block_size(kv_pad)

    if local_window is not None and kv_pad * d_pad > _STREAM_KV_ELEMS:
        dq, dk, dv = _banded_backward(
            qp, kp, vp, gp, lse_p, delta, d_pad,
            (kv_len, block_q, block_k), sm_scale, local_window, interpret)
        dq = dq.reshape(batch, heads, t_pad, d_pad)[:, :, :seq_len, :head_dim]
        dk = dk.reshape(batch, heads, kv_pad, d_pad)[:, :, :kv_len, :head_dim]
        dv = dv.reshape(batch, heads, kv_pad, d_pad)[:, :, :kv_len, :head_dim]
        return dq, dk, dv

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_k=block_k, causal=causal,
        sm_scale=sm_scale, kv_len=kv_len, kv_pad=kv_pad,
        local_window=local_window)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, t_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, gp, lse_p, delta)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, causal=causal,
        sm_scale=sm_scale, kv_len=kv_len, t_pad=t_pad,
        local_window=local_window)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, kv_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, t_pad, d_pad), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, t_pad, d_pad), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 8, t_pad), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 8, t_pad), lambda b, j: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d_pad), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, kv_pad, d_pad), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_pad, d_pad), v.dtype),
        ),
        interpret=interpret,
    )(qp, kp, vp, gp, lse_p, delta)

    dq = dq.reshape(batch, heads, t_pad, d_pad)[:, :, :seq_len, :head_dim]
    dk = dk.reshape(batch, heads, kv_pad, d_pad)[:, :, :kv_len, :head_dim]
    dv = dv.reshape(batch, heads, kv_pad, d_pad)[:, :, :kv_len, :head_dim]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, sm_scale, local_window, interpret):
    out, _ = _flash_forward(q, k, v, causal, sm_scale, local_window, interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, sm_scale, local_window, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, local_window, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, sm_scale, local_window, interpret, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                           local_window, interpret)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: float | None = None,
                    local_window: int | None = None,
                    use_pallas: bool | None = None):
    """Causal MHA over (batch, heads, seq, head_dim).

    ``local_window=W`` restricts each query to the W-key band ending at
    itself (sliding-window attention, Mistral-style), letting a sliding
    price window be attended inside ONE long sequence. Compute and the
    K-block loop skip everything outside the band, so cost is O(T·W)
    rather than O(T²).

    ``use_pallas=None`` auto-selects: the kernel on TPU, the XLA reference
    elsewhere (the unit suite runs the kernel through the Pallas interpreter
    separately — tests/test_ops.py — so both paths stay covered).
    """
    if q.ndim != 4:
        raise ConfigError(f"expected (batch, heads, seq, head_dim), got {q.shape}")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if local_window is not None:
        if not causal:
            raise ConfigError("local_window requires causal attention")
        if local_window < 1:
            raise ConfigError(f"local_window must be >= 1, got {local_window}")
        if local_window >= q.shape[2]:
            local_window = None    # band covers everything: plain causal
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not use_pallas:
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                   local_window=local_window)
    interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, causal, sm_scale, local_window, interpret)
