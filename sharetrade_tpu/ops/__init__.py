"""Custom TPU ops (Pallas kernels) with XLA fallbacks.

The reference's only "ops layer" is libtensorflow's CPU kernels behind JNI
(reference build.sbt:21). Here the hot ops are hand-written for the TPU memory
hierarchy where XLA's fusion isn't enough; everything falls back to pure-XLA
implementations off-TPU so the unit suite runs on the CPU mesh.
"""

from sharetrade_tpu.ops.attention import flash_attention, reference_attention  # noqa: F401
