"""Bounded async readback pipeline — host consumption off the dispatch path.

The reference is a parameter-server trainer whose workers never wait on the
aggregator (SURVEY.md §7.4 "Queryability"); Podracer-style JAX architectures
(Anakin/Sebulba, arXiv:2104.06272) and MSRL's dataflow fragments
(arXiv:2210.00882) get their throughput from the same inversion: device
compute streams ahead while a host-side consumer absorbs results. This
module is that seam for the orchestrator's hot loop
(``runtime.async_pipeline``): the dispatcher issues megachunks back-to-back
and hands each materialization boundary's device buffers to ONE background
consumer thread through a bounded queue; the consumer performs the entire
readback + host-processing block (metric rows, flight recorder, journaling,
fault hooks, snapshot updates) strictly in chunk order.

Contracts the orchestrator builds on:

- **Order**: a single consumer thread pops FIFO, so rows / journal records /
  fault hooks observe exactly the chunk order of the synchronous path.
- **Backpressure**: the queue is bounded (``runtime.pipeline_depth``), so
  HBM held by in-flight readback buffers is bounded and dispatch stalls
  (``pipeline_stall``) rather than racing ahead unboundedly.
- **Fault propagation**: an exception raised while consuming is stored (not
  swallowed) and ``error`` is visible to the dispatcher BEFORE it commits
  the next megachunk; the original exception object is re-raised on the
  dispatcher thread so the supervision decider sees the true type. Chunk
  attribution rides the orchestrator's ``_committed_idx`` (advanced per row
  by the consumer, exactly like the synchronous loop's ``chunk_idx``).
- **Drain barrier**: ``drain()`` blocks until every boundary enqueued at
  call time has been consumed (or the consumer faulted) — the exactness
  gate before episode-completion checks, ``get_avg``/``get_std`` snapshot
  reads, and checkpoint/eval cadence decisions. Called from the consumer
  thread itself (a fault hook querying the orchestrator) it is a no-op,
  never a deadlock.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, NamedTuple


class Boundary(NamedTuple):
    """One materialization boundary handed from dispatcher to consumer."""

    base: int             #: first chunk index covered by this readback
    k: int                #: fused chunk count (1 on the exact path)
    metrics: Any          #: stacked (K, ...) device metric buffers
    transitions: Any      #: stacked transition batch (DQN journaling) | None
    heals_mark: int       #: agent_heals at dispatch (stale-report guard)
    chunks_covered: int   #: chunks since the previous boundary (timer input)


_SHUTDOWN = object()


class AsyncPipeline:
    """Bounded queue + one consumer thread; see the module docstring.

    ``consume`` is called with each :class:`Boundary` and returns the
    boundary metric row; ``attn_check(row)`` (optional) decides whether the
    row needs a dispatcher-side action (heal, cadence, completion) — if so
    the ``attention`` event is set and the dispatcher drains and acts.
    ``span`` (optional) is an ``obs.span``-shaped factory used for the
    ``queue_wait`` consumer-idle spans.
    """

    def __init__(self, depth: int, consume: Callable[[Boundary], dict], *,
                 attn_check: Callable[[dict], bool] | None = None,
                 span: Callable[..., Any] | None = None,
                 name: str = "readback-consumer"):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._consume = consume
        self._attn_check = attn_check
        self._span = span
        self._cond = threading.Condition()
        self._closing = False
        self.enqueued = 0         #: boundaries accepted by put/try_put
        self.processed = 0        #: boundaries consumed (or discarded)
        self.error: BaseException | None = None
        self.last_row: dict | None = None
        self.attention = threading.Event()
        #: Every boundary row that flagged attention, in chunk order, as
        #: (row, heals_mark, end_chunk_idx) — the dispatcher acts on EACH
        #: (not just the newest), so cadence crossings that land on
        #: consecutive boundaries are never coalesced into one action, and
        #: a fault raised while acting is attributed to end_chunk_idx (the
        #: synchronous loop's chunk_idx at that boundary), not to however
        #: far ahead the dispatcher has run.
        self._attn_rows: list[tuple[dict, int, int]] = []
        self.max_depth_seen = 0   #: high-water queue occupancy (tests)
        self.stalls = 0           #: times the dispatcher blocked on put
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    # -- dispatcher side -------------------------------------------------

    def try_put(self, b: Boundary) -> bool:
        """Non-blocking enqueue; False when the queue is full (caller then
        records a stall and falls back to the blocking :meth:`put`)."""
        if self.error is not None or self._closing:
            return True     # accept-and-drop stance: error handling is the
                            # dispatcher's next top-of-loop action anyway
        try:
            self._q.put_nowait(b)
        except queue.Full:
            return False
        self._account_enqueue()
        return True

    def put(self, b: Boundary, *, stop: threading.Event | None = None,
            timeout_s: float = 0.05) -> bool:
        """Blocking enqueue with backpressure. Returns False (item dropped)
        when the consumer faulted or ``stop`` was set while waiting — the
        dispatcher's top-of-loop error handling takes over. A call that
        actually waited on a full queue counts one ``stalls``."""
        stalled = False
        try:
            while True:
                if self.error is not None or self._closing:
                    return False
                if stop is not None and stop.is_set():
                    return False
                try:
                    self._q.put(b, timeout=timeout_s)
                except queue.Full:
                    stalled = True
                    continue
                self._account_enqueue()
                return True
        finally:
            if stalled:
                with self._cond:
                    self.stalls += 1

    def _account_enqueue(self) -> None:
        with self._cond:
            self.enqueued += 1
            self.max_depth_seen = max(self.max_depth_seen, self._q.qsize())

    def qsize(self) -> int:
        return self._q.qsize()

    def take_attention(self) -> list[tuple[dict, int, int]]:
        """Pop (and clear) the attention-flagged boundary rows, in chunk
        order. Call after :meth:`drain` — the consumer is idle then, so the
        list is complete for everything enqueued before the barrier."""
        with self._cond:
            rows, self._attn_rows = self._attn_rows, []
            return rows

    # -- barriers --------------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until every boundary enqueued at call time is consumed.
        Returns False on timeout or a consumer fault (the fault itself is
        surfaced via ``error``). No-op from the consumer thread itself (a
        fault hook calling back into the orchestrator must not deadlock)."""
        if threading.current_thread() is self._thread:
            return True
        deadline = time.monotonic() + timeout_s
        with self._cond:
            target = self.enqueued
            while self.processed < target and self.error is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
            return self.error is None

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Stop the consumer: anything still queued is DISCARDED (callers
        that need the rows drain first), the thread joins. Idempotent."""
        with self._cond:
            if self._closing:
                self._thread.join(timeout_s)
                return
            self._closing = True
        self._q.put(_SHUTDOWN)   # consumer discards queued items first
        self._thread.join(timeout_s)

    # -- consumer thread -------------------------------------------------

    def _loop(self) -> None:
        while True:
            if self._span is not None:
                # Consumer-idle time: a long queue_wait span means the
                # pipeline is starved (dispatch-bound) — the healthy state.
                with self._span("queue_wait", depth=self._q.qsize()):
                    item = self._q.get()
            else:
                item = self._q.get()
            if item is _SHUTDOWN:
                with self._cond:
                    self._cond.notify_all()
                return
            if self.error is not None or self._closing:
                # Stale boundary (post-fault / post-shutdown): the restore
                # path rewinds state and re-materializes these chunks.
                self._mark_processed()
                continue
            try:
                row = self._consume(item)
            except BaseException as exc:   # noqa: BLE001 — supervision food
                with self._cond:
                    self.error = exc
                    self.processed += 1
                    self._cond.notify_all()
                self.attention.set()
                continue
            # Attention MUST be visible before `processed` ticks: drain()
            # returns the instant processed catches up, and a dispatcher
            # that checks the flag right after a drain barrier has to see
            # this row's verdict — flagging after the tick opens a window
            # where the completion row is processed but unflagged, and the
            # dispatcher issues one overshoot chunk past the episode end.
            if self._attn_check is not None and self._attn_check(row):
                with self._cond:
                    self._attn_rows.append(
                        (row, item.heals_mark, item.base + item.k))
                self.attention.set()
            with self._cond:
                self.last_row = row
                self.processed += 1
                self._cond.notify_all()

    def _mark_processed(self) -> None:
        with self._cond:
            self.processed += 1
            self._cond.notify_all()
