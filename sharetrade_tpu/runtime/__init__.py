"""Runtime: lifecycle FSM, orchestration, supervision (L4/L5).

Reference: TrainerRouterActor + BackoffSupervisor + the ShareTradeHelper
driver loop (SURVEY.md §3.1, §3.5), re-designed as a host-side orchestrator
over a compiled device loop (§7.2's architectural inversion).
"""

from sharetrade_tpu.runtime.lifecycle import (  # noqa: F401
    Lifecycle,
    Phase,
    QueryReply,
    ReplyState,
)
from sharetrade_tpu.runtime.orchestrator import (  # noqa: F401
    DEFAULT_ERROR_POLICY,
    ESCALATE,
    RESTART,
    RESUME,
    STOP,
    Orchestrator,
    run_end_to_end,
)
