"""Lifecycle state machine — the router's four-phase protocol, actor-free.

Reference: TrainerRouterActor's ``context.become`` chain
``awaitingTrainingData → trainingDataPresent → trained → completed``
(TrainerRouterActor.scala:68-130) with the reply ADT
``NoTrainingDataReceived / NotComputed / TrainingNotCompleted / Completed /
Result(x)`` (:15-34). Here the same protocol is an explicit enum + a
``QueryReply`` value, and "stashing" ``StartTraining`` until data arrives
(:75-76) is a recorded intent flag the orchestrator honors.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass


class Phase(enum.Enum):
    AWAITING_DATA = "awaiting_data"    # awaitingTrainingData
    READY = "ready"                    # trainingDataPresent
    TRAINING = "training"              # children training (trained accumulate)
    TRAINED = "trained"                # all workers reported Trained
    COMPLETED = "completed"            # results served (terminal in reference)
    FAILED = "failed"                  # restart budget exhausted (new: explicit)


class ReplyState(enum.Enum):
    """Reply vocabulary of the reference protocol (TrainerRouterActor.scala:22-33)."""

    NO_TRAINING_DATA = "NoTrainingDataReceived"
    NOT_COMPUTED = "NotComputed"
    TRAINING_NOT_COMPLETED = "TrainingNotCompleted"
    COMPLETED = "Completed"
    RESULT = "Result"


@dataclass(frozen=True)
class QueryReply:
    state: ReplyState
    value: float | None = None

    @property
    def ok(self) -> bool:
        return self.state is ReplyState.RESULT

    def __repr__(self) -> str:  # Result(123.4) / NotComputed
        if self.state is ReplyState.RESULT:
            return f"Result({self.value})"
        return self.state.value


_TRANSITIONS: dict[Phase, set[Phase]] = {
    Phase.AWAITING_DATA: {Phase.READY, Phase.FAILED},
    Phase.READY: {Phase.TRAINING, Phase.AWAITING_DATA, Phase.FAILED},
    Phase.TRAINING: {Phase.TRAINED, Phase.READY, Phase.FAILED},
    Phase.TRAINED: {Phase.COMPLETED, Phase.READY, Phase.FAILED},
    # COMPLETED may re-arm via Initialise (TrainerChildActor.scala:57-59).
    Phase.COMPLETED: {Phase.READY, Phase.FAILED},
    Phase.FAILED: {Phase.READY},
}


class Lifecycle:
    """Thread-safe phase holder with legal-transition enforcement.

    ``on_transition`` (settable post-construction) observes every phase
    change as ``on_transition(old, new)`` — invoked OUTSIDE the lock so an
    observer that queries the lifecycle (the obs flight recorder / trace
    markers) can never deadlock it.
    """

    def __init__(self) -> None:
        self._phase = Phase.AWAITING_DATA
        self._lock = threading.Lock()
        self.start_requested = False  # the "stashed StartTraining" flag
        self.on_transition = None     # callable (old, new) | None

    @property
    def phase(self) -> Phase:
        with self._lock:
            return self._phase

    def to(self, new: Phase) -> None:
        with self._lock:
            if new is self._phase:
                return
            if new not in _TRANSITIONS[self._phase]:
                raise RuntimeError(
                    f"illegal lifecycle transition {self._phase.value} "
                    f"-> {new.value}")
            old, self._phase = self._phase, new
        self._notify(old, new)

    def force(self, new: Phase) -> None:
        with self._lock:
            if new is self._phase:
                return
            old, self._phase = self._phase, new
        self._notify(old, new)

    def _notify(self, old: Phase, new: Phase) -> None:
        if self.on_transition is not None:
            try:
                self.on_transition(old, new)
            except Exception:
                pass    # observability must never break the FSM
