"""The training orchestrator — L4/L5 of the reference, actor-free.

What the reference spreads across ShareTradeHelper (driver poll loop),
TrainerRouterActor (broadcast + lifecycle + aggregation + supervision) and
BackoffSupervisor wrappers (SURVEY.md §3.1, §3.5), this one host-side object
owns:

- the lifecycle FSM (awaiting-data → ready → training → trained/completed),
  with StartTraining stashing (TrainerRouterActor.scala:75-76);
- the chunked device loop: the agent's jitted ``step`` advances
  ``chunk_steps`` env steps per host visit; between chunks the host snapshots
  metrics, so ``get_avg``/``get_std`` answer **without stopping the device**
  (the reference interrupts trained workers with ask(GetPortfolio);
  SURVEY.md §7.4 "Queryability"). With ``runtime.megachunk_factor`` K > 1
  the host visit itself amortizes: K chunks fuse into one device-resident
  lax.scan (agents/base.py ``megachunk_step``), per-chunk metrics stack into
  a (K, ...) buffer read back with ONE batched ``jax.device_get`` at
  megachunk boundaries, and the loop falls back to K=1 dispatches near the
  episode threshold so the exact-completion gate keeps its semantics;
- supervision: a failing chunk triggers exponential-backoff restart from the
  latest checkpoint (initial 3 s, cap 60 s, jitter 0.2 — the reference's
  Backoff.onFailure envelope, TrainerRouterActor.scala:46-52) up to
  ``max_restarts``, then FAILED (the Escalate arm of its decider);
- checkpoint cadence: every ``checkpoint_every_updates`` updates — the
  reference's intended-but-stubbed every-500 (QDecisionPolicyActor.scala:74);
- a typed error policy — the reference's OneForOneStrategy decider maps
  exception classes to Resume/Restart/Stop/Escalate
  (TrainerRouterActor.scala:53-58); ``error_policy`` maps exception types to
  the same four verbs (resume = keep state and continue; restart =
  backoff + restore from checkpoint; stop = mark FAILED; escalate = re-raise);
- test seams: ``step_override`` replaces the compiled step (the overridable
  ``train()`` seam, TrainerRouterActorSpec.scala:144-153) and
  ``fault_hook`` injects failures mid-run (the PoisonPill chaos seam,
  :97-115).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from sharetrade_tpu.agents import build_agent
from sharetrade_tpu.agents.base import Agent, TrainState, megachunk_step
from sharetrade_tpu.checkpoint import CheckpointManager
from sharetrade_tpu.config import ConfigError, FrameworkConfig
from sharetrade_tpu.env import trading
from sharetrade_tpu.env.portfolio import make_portfolio_env
from sharetrade_tpu.obs import build_obs
from sharetrade_tpu.parallel import build_mesh, make_parallel_step
from sharetrade_tpu.runtime.lifecycle import Lifecycle, Phase, QueryReply, ReplyState
from sharetrade_tpu.runtime.pipeline import AsyncPipeline, Boundary
from sharetrade_tpu.utils.logging import EventLog, get_logger
from sharetrade_tpu.utils.metrics import MetricsRegistry
from sharetrade_tpu.utils.profiling import StepTimer, Tracer

log = get_logger("runtime.orchestrator")

#: Shared no-op context for un-sampled / obs-disabled span sites.
_NULL_CTX = contextlib.nullcontext()


#: Supervision verbs (the Akka directive vocabulary).
RESUME, RESTART, STOP, ESCALATE = "resume", "restart", "stop", "escalate"

#: Default decider, mirroring TrainerRouterActor.scala:53-58
#: (ArithmeticException→Resume, NullPointer→Restart, IllegalArgument→Stop,
#: anything else→Escalate... except here unknown errors Restart, because on
#: TPU transient device errors are the common case and restart-from-
#: checkpoint is the designed recovery path). The Stop verb is scoped to
#: ConfigError, not all ValueError: a bad config can never heal by
#: restarting, but a transient in-loop ValueError (a JAX tracing/shape
#: error from a restored-then-retraced step) deserves the restart path
#: instead of permanently failing the run.
DEFAULT_ERROR_POLICY: dict[type, str] = {
    ArithmeticError: RESUME,
    AttributeError: RESTART,
    ConfigError: STOP,
    KeyboardInterrupt: ESCALATE,
}


def _metric_rows(host: dict, k: int) -> list[dict[str, float]]:
    """Split one batched megachunk readback into its K per-chunk rows.

    ``host`` holds host-side arrays: scalars for a single chunk (k == 1),
    ``(K,)``-stacked values for a fused megachunk — the scan-stacked metric
    buffer of agents/base.py ``megachunk_step``."""
    if k == 1:
        return [{key: float(v) for key, v in host.items()}]
    return [{key: float(v[i]) for key, v in host.items()} for i in range(k)]


def _start_readback(*trees) -> None:
    """Kick off non-blocking device→host DMA for every array leaf
    (``copy_to_host_async`` — the async-checkpoint D2H trick applied to the
    metric/transition buffers). By the time the pipeline consumer calls its
    blocking ``device_get``, the bytes are usually already on the host; on
    backends without the method the consumer's device_get simply blocks on
    the CONSUMER thread — still off the dispatch critical path."""
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "copy_to_host_async"):
                try:
                    leaf.copy_to_host_async()
                except Exception:   # fallback documented above
                    return


class Orchestrator:
    def __init__(self, cfg: FrameworkConfig, *,
                 mesh=None,
                 checkpoints: CheckpointManager | None = None,
                 event_log: EventLog | None = None,
                 step_override: Callable[[TrainState], tuple[TrainState, dict]] | None = None,
                 fault_hook: Callable[[int, dict], None] | None = None,
                 error_policy: dict[type, str] | None = None):
        # Tuned-profile resolution (tuning.py): registered knobs still at
        # their defaults take the per-host profile's values; explicit
        # config wins; a fingerprint-mismatched profile raises loudly
        # (ProfileError is ConfigError = STOP territory). Idempotent, so
        # a cfg the CLI already resolved passes through unchanged.
        from sharetrade_tpu.tuning import apply_profile
        cfg = apply_profile(cfg)
        self.cfg = cfg
        self.mesh = mesh
        if cfg.runtime.megachunk_factor < 1:
            # A bad factor can never heal by restarting — same class of
            # error as any other impossible composition, so it fails at
            # construction (the supervision decider's STOP verb territory).
            raise ConfigError(
                "runtime.megachunk_factor must be >= 1, got "
                f"{cfg.runtime.megachunk_factor}")
        if cfg.runtime.pipeline_depth < 1:
            # Same class as a bad megachunk factor: an impossible
            # composition that restarting can never heal — STOP territory.
            raise ConfigError(
                "runtime.pipeline_depth must be >= 1, got "
                f"{cfg.runtime.pipeline_depth}")
        if (cfg.runtime.megachunk_factor > 1
                and cfg.runtime.metrics_every_chunks
                % cfg.runtime.megachunk_factor != 0):
            # Not an error — sampling quantizes UP to the next megachunk
            # boundary (rows are delivered late-but-complete from the
            # stacked buffer) — but worth a line in the log so a surprised
            # operator finds the interaction documented in config.py.
            log.info(
                "metrics_every_chunks=%d is not a multiple of "
                "megachunk_factor=%d; metric samples land on megachunk "
                "boundaries (rounded up)",
                cfg.runtime.metrics_every_chunks,
                cfg.runtime.megachunk_factor)
        self.lifecycle = Lifecycle()
        # Precision policy (precision.py): validated at construction (a bad
        # mode is STOP territory). The agents own the training-side casts;
        # the orchestrator applies the same policy to the eval forwards and
        # stamps the mode into checkpoint metadata (restore refuses a
        # mode-mismatched store with a loud error instead of letting flax
        # silently deserialize the wrong dtypes).
        from sharetrade_tpu.precision import policy_from_config
        self._precision = policy_from_config(cfg.precision)
        self.metrics = MetricsRegistry(
            max_points=cfg.obs.max_metric_points)
        # Telemetry (obs/): inert facade when cfg.obs.enabled is False —
        # zero files, span() hands back a shared null context. All of the
        # hot-loop instrumentation below rides the metrics_every_chunks
        # sampling cadence and reads only host values that the batched
        # megachunk readback already materialized (no new device syncs).
        self.obs = build_obs(cfg, self.metrics, mesh=mesh)
        # Training-side mergeable histograms (obs/hist.py; ISSUE 11): the
        # per-boundary chunk wall time and the inter-dispatch gap as
        # fixed-bucket distributions, exported through metrics.prom next
        # to the serve tier's stage histograms — the fleet-mergeable form
        # of what bench_async_pipeline measures from trace spans. Obs-
        # gated: the default obs-off hot loop stays structurally
        # instrumentation-free (one None check per dispatch).
        self._h_chunk_seconds = self._h_dispatch_gap = None
        if cfg.obs.enabled:
            from sharetrade_tpu.obs.hist import SECONDS_BOUNDS, Histogram
            self._h_chunk_seconds = self.metrics.attach_histogram(
                "train_chunk_seconds", Histogram(bounds=SECONDS_BOUNDS))
            self._h_dispatch_gap = self.metrics.attach_histogram(
                "train_dispatch_gap_ms", Histogram())
        self.checkpoints = checkpoints or CheckpointManager(
            cfg.runtime.checkpoint_dir, keep=cfg.runtime.keep_checkpoints,
            fsync=cfg.checkpoint.fsync,
            precision_mode=cfg.precision.mode)
        if getattr(self.checkpoints, "precision_mode", None) is None:
            # Injected managers join the run's precision contract the same
            # way they join its metrics/tracer below.
            self.checkpoints.precision_mode = cfg.precision.mode
        if getattr(self.checkpoints, "metrics", None) is None:
            # Restore walk-back counters (ckpt_restore_fallbacks_total,
            # ckpt_quarantined_total) land in the run's registry and flow
            # out through the obs MetricsExporter like every other counter.
            self.checkpoints.metrics = self.metrics
        self.events = event_log or EventLog(None)
        if self.obs.enabled:
            # Structured run events double into the flight ring (the tap),
            # lifecycle transitions mark the trace timeline, and checkpoint
            # save/restore phases span it from whichever thread writes.
            self.events.mirror = self._obs_event_tap
            self.lifecycle.on_transition = self._obs_phase_tap
            if getattr(self.checkpoints, "tracer", None) is None:
                self.checkpoints.tracer = self.obs.tracer
        self.tracer = Tracer(cfg.runtime.profile_dir)
        self._step_override = step_override
        self._fault_hook = fault_hook
        self._error_policy = (DEFAULT_ERROR_POLICY if error_policy is None
                              else error_policy)

        self.agent: Agent | None = None
        self.env = None  # TradingEnv once data arrives
        self._ts: TrainState | None = None
        self._step_fn = None
        self._mega_fn = None   # K-chunk fused program (megachunk_factor > 1)
        self._eval_fn = None   # cached jitted greedy-eval program
        self._snapshot: dict[str, float] = {}
        self._snapshot_lock = threading.Lock()
        # Guards the donated step dispatch vs concurrent _ts readers
        # (evaluate()'s snapshot): held only across the non-blocking
        # dispatch + reassignment, never across device execution.
        self._step_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Preemption (SIGTERM/SIGINT via cli train, or any caller's
        # request_preempt): the dispatcher honors it at the next megachunk
        # boundary — drain, emergency tag_preempt checkpoint, journal flush,
        # flight dump — inside runtime.preempt_grace_s. ``preempted`` is the
        # caller-visible outcome flag (the CLI maps it to a distinct exit
        # code).
        self._preempt = threading.Event()
        self._preempt_deadline: float | None = None
        self.preempted = False
        #: Whether the preemption drain actually published tag_preempt —
        #: the CLI's "emergency checkpoint: written" claim keys off this,
        #: not off preemption having been attempted.
        self.preempt_saved = False
        self.restarts = 0
        self.agent_heals = 0   # per-agent row respawns (partial_recovery)
        self._best_eval: float | None = None  # lazily seeded from tag_best
        self._best_eval_lock = threading.Lock()
        self.episode = 0
        self.last_error: BaseException | None = None
        # Async readback pipeline (runtime.async_pipeline): live only while
        # a supervised run is in flight; _committed_idx is the consumer's
        # per-row progress cursor (== the synchronous loop's chunk_idx),
        # read by the dispatcher for fault attribution and drain math.
        self._pl: AsyncPipeline | None = None
        self._committed_idx = 0
        self._timer: StepTimer | None = None
        self._last_ckpt_updates = 0
        #: Stats of the most recent run's pipeline (max queue depth seen,
        #: dispatcher stalls) — kept after shutdown for tests/benchmarks.
        self.pipeline_stats: dict[str, int] = {}
        self._transitions_journal = None
        self._journal_high_water = 0  # env_steps already journaled
        self._journal_rows_since_compact = 0
        # Actor/learner disaggregation (distrib/): the learner tails every
        # actor's transitions journal between megachunks and splices the
        # new rows into its device replay buffer — per-actor cursors are
        # the last-ingested env-step stamps (monotone per journal, so a
        # restarted actor resumes cleanly past them). DQN-only: the other
        # algos have no replay buffer to feed.
        self._actor_cursors: dict[str, int] = {}
        self._last_ingest_updates = 0
        # num_actors gates too: with no pool (plain ``cli train``) the
        # cadence must not force pipeline-drain boundaries every
        # ingest_every_updates just to glob an empty actors dir.
        # ingest_without_pool bypasses that gate for the fleet flywheel:
        # SERVED SESSIONS write the journals there (fleet/flywheel.py),
        # so there is data to tail with no ActorPool in this process.
        self._ingest_enabled = ((cfg.distrib.num_actors > 0
                                 or cfg.distrib.ingest_without_pool)
                                and cfg.distrib.ingest_every_updates > 0
                                and cfg.learner.algo == "dqn")
        # Adaptive ingest cadence (tuning.adaptive_ingest — the online
        # half of ROADMAP item 5 on the learner side): the LIVE cadence
        # the boundary checks read. The configured value is the BASE;
        # the controller backs off (doubling, up to 8x base) after
        # consecutive all-dry ticks — a caught-up learner must not keep
        # paying a pipeline-drain boundary + header-peek scan of every
        # actor journal each `base` updates for nothing — and snaps back
        # to base the moment rows arrive; a tick that reads a FULL
        # per-actor window (backlog: the actors are outrunning the
        # learner, the N=4 ingest-collapse signature) tightens below
        # base (halving, down to base/4) so the backlog streams in
        # sooner. Every move is bounded, visible (gauge + counter +
        # flight event) and inert without a pool.
        self._ingest_every = max(1, cfg.distrib.ingest_every_updates)
        self._ingest_base = self._ingest_every
        self._adaptive_ingest = (self._ingest_enabled
                                 and cfg.tuning.adaptive_ingest)
        self._ingest_dry_streak = 0
        if self._ingest_enabled:
            self.metrics.record("ingest_every_updates_current",
                                float(self._ingest_every))
        if cfg.learner.algo == "dqn" and cfg.learner.journal_replay:
            import os
            from sharetrade_tpu.data.service import _open_journal
            path = os.path.join(cfg.data.journal_dir, "transitions.journal")
            self._transitions_journal = None
            if cfg.data.journal_segment_records > 0:
                # Bounded journal: segment rotation + retirement
                # (data.journal_segment_records). Rotation lives in the
                # Python backend — the C++ async writer appends to one
                # file, so it is bypassed here; group-commit watermarks
                # still apply per segment.
                from sharetrade_tpu.data.journal import Journal
                self._transitions_journal = Journal(
                    path,
                    fsync_every_records=cfg.data.journal_fsync_every_records,
                    fsync_interval_s=cfg.data.journal_fsync_interval_s,
                    segment_records=cfg.data.journal_segment_records)
            elif cfg.data.async_transition_writer and cfg.data.use_native_journal:
                # Hot-path appends drain through the C++ background thread;
                # the step loop never blocks on journal IO.
                from sharetrade_tpu.data.native import (
                    AsyncNativeJournal, async_writer_available)
                if async_writer_available():
                    self._transitions_journal = AsyncNativeJournal(path)
            if self._transitions_journal is None:
                # Group-commit knobs (data.journal_fsync_*): consumer-side
                # appends batch in memory and hit the disk (write + fsync)
                # on a count/interval watermark instead of one flush per
                # chunk — the Python-backend half of taking journaling off
                # the dispatch critical path (the C++ async writer above
                # already batches in its background thread).
                self._transitions_journal = _open_journal(
                    path, prefer_native=cfg.data.use_native_journal,
                    fsync_every_records=cfg.data.journal_fsync_every_records,
                    fsync_interval_s=cfg.data.journal_fsync_interval_s)

    # ------------------------------------------------------------------
    # telemetry taps (obs/): wired only when cfg.obs.enabled
    # ------------------------------------------------------------------

    def _obs_event_tap(self, kind: str, payload: dict) -> None:
        self.obs.record("event", event=kind, **payload)

    def _obs_phase_tap(self, old: Phase, new: Phase) -> None:
        self.obs.record("lifecycle", frm=old.value, to=new.value)
        self.obs.tracer.instant(f"phase:{new.value}")

    # ------------------------------------------------------------------
    # protocol: SendTrainingData (TrainerRouterActor.scala:77-81)
    # ------------------------------------------------------------------

    def send_training_data(self, prices: np.ndarray | Any, *,
                           resume: bool = False) -> None:
        """Build the env + agent from a price series — 1-D for the
        single-asset env, (A, T) for the multi-asset portfolio env. With
        ``resume=True`` the latest checkpoint (params, optimizer, RNG, env
        cursors) is restored instead of a fresh init — the user-facing
        continuation of the crash-recovery path (SURVEY.md §7.1 item 7)."""
        prices = np.asarray(prices)
        if prices.ndim == 2 and prices.shape[0] > 1:
            self.env = make_portfolio_env(
                prices, window=self.cfg.env.window,
                initial_budget=self.cfg.env.initial_budget,
                initial_shares=self.cfg.env.initial_shares)
        else:
            self.env = trading.make_trading_env(
                prices.reshape(-1), window=self.cfg.env.window,
                initial_budget=self.cfg.env.initial_budget,
                initial_shares=self.cfg.env.initial_shares)
        self.agent = build_agent(self.cfg, self.env, mesh=self.mesh)
        self._build_step()
        self._eval_fn = None   # env/model changed: retrace on next evaluate
        template = self.agent.init(jax.random.PRNGKey(self.cfg.seed))
        self._capture_roofline_fallback(template)
        if resume:
            state, step, saved_meta = self._restore_for_resume(template)
            horizon = self.env.num_steps
            max_cursor = int(np.max(np.asarray(state.env_state.t)))
            if max_cursor > horizon:
                # A shorter series would freeze every agent past the new
                # horizon and the completion arithmetic could never fire.
                raise ValueError(
                    f"checkpoint env cursor ({max_cursor}) exceeds the new "
                    f"series horizon ({horizon}); resume needs the same or a "
                    f"longer price series")
            self._ts = self._place(self._warm_start_replay(state))
            # Recover the episode index from the checkpoint metadata; the
            # env_steps//horizon heuristic is the fallback for pre-metadata
            # checkpoints (it overcounts once per-agent heals inflate the
            # step count, which is why the index is persisted). Clamp to
            # episodes-1 either way: the FINAL checkpoint of a completed run
            # is written after the episode counter increments past the last
            # episode, and resuming it unclamped would set a completion
            # threshold ((episode+1) x horizon) that frozen agents can never
            # reach — an infinite chunk spin.
            saved_episode = saved_meta.get("episode")
            raw = (int(saved_episode) if saved_episode is not None
                   else int(state.env_steps) // horizon)
            self.episode = max(0, min(raw, self.cfg.runtime.episodes - 1))
            from sharetrade_tpu.agents.base import agent_health
            ok = np.asarray(jax.device_get(agent_health(state.env_state)))
            t = np.asarray(state.env_state.t)
            # HEALTHY cursors only: a run completed via the stranded-rows-
            # excluded gate (partial_recovery off) carries a quarantined
            # row frozen BELOW the horizon; counting it would skip the
            # re-arm and reintroduce the spin for exactly that resume.
            # ALL rows stranded counts as done too — no live cursor can
            # advance, and the re-arm's fresh state is the only recovery
            # (restoring the same poisoned checkpoint can't be).
            done_cursors = (not bool(ok.any())
                            or int(np.min(t[ok])) >= horizon)
            if (done_cursors and int(state.env_steps)
                    < (self.episode + 1) * horizon):
                # Resumed the final checkpoint of a COMPLETED episode while
                # the config asks for more passes (runtime.episodes raised):
                # every live cursor is frozen at the horizon, so without a
                # re-arm the run would spin chunks forever waiting for a
                # completion threshold frozen agents can never advance
                # toward. Re-arm the next episode in place — fresh env
                # cursors/carry (which also respawns any stranded row),
                # learned params/opt/env_steps kept (the Initialise→Train
                # cycle, TrainerChildActor.scala:57-59). (If heals inflated
                # env_steps past the threshold instead, the normal
                # completion gate re-arms on the first chunk.)
                log.info("resumed a %s with episodes=%d; re-arming "
                         "episode %d",
                         "completed episode" if ok.any()
                         else "checkpoint with every row stranded "
                              "(mid-episode progress discarded)",
                         self.cfg.runtime.episodes, self.episode)
                self._reset_episode()
            log.info("resumed from checkpoint step=%d "
                     "(env cursor %d, %d updates, episode %d)", step,
                     int(state.env_state.t[0]), int(state.updates),
                     self.episode)
            self.events.emit("resumed", step=step)
        else:
            if self._transitions_journal is not None:
                # A fresh run must not inherit another run's experience: the
                # journal is truncated, not appended to (warm starts would
                # otherwise seed the buffer with off-distribution data). The
                # high-water mark resets with it — the new run's env_steps
                # restart at zero and must journal from the first chunk.
                self._transitions_journal.compact([])
                self._journal_high_water = 0
            # Fresh state counts episodes from zero; a stale episode index
            # from a previous run would push the completion threshold to
            # (episode+1) x horizon — unreachable for frozen envs.
            self.episode = 0
            self._ts = self._place(template)
        self.lifecycle.to(Phase.READY)
        self.events.emit("training_data_received",
                         episode_steps=self.env.num_steps)
        # Honor a stashed StartTraining (reference stash/unstashAll, :75-76).
        # The stash is consumed: later send_training_data calls (a fresh
        # retrain on the same orchestrator) must not silently auto-start.
        if self.lifecycle.start_requested:
            self.lifecycle.start_requested = False
            self.start_training(
                background=getattr(self, "_stashed_background", True))

    def _build_step(self) -> None:
        factor = self.cfg.runtime.megachunk_factor
        self._mega_fn = None
        # Roofline capture (obs.roofline): seed the analytic FLOP model for
        # the cross-check, and hand the compile-time capture hook to the
        # program constructors. All of this runs at BUILD time — the
        # capture itself is one extra AOT lowering per program, and the
        # run-time gauge math rides the pipeline consumer (_host_process).
        roofline = (self.obs.roofline if self._step_override is None
                    else None)
        if roofline is not None:
            roofline.steps_per_chunk = self.cfg.runtime.chunk_steps
            roofline.precision_mode = self.cfg.precision.mode
            try:
                from sharetrade_tpu.utils.flops import (
                    train_flops_per_agent_step)
                roofline.analytic_flops_per_chunk = (
                    train_flops_per_agent_step(self.cfg, self.env.obs_dim)
                    * self.cfg.parallel.num_workers
                    * self.cfg.runtime.chunk_steps)
            except Exception:   # no analytic model: capture still runs
                log.exception("analytic FLOP model unavailable; roofline "
                              "cross-check disabled")
        cost_hook = roofline.capture if roofline is not None else None
        # Async-pipeline donation carve-out, CPU runtime only: the pipeline
        # consumer's device_get runs CONCURRENTLY with the dispatcher's
        # donating dispatch, and on the CPU runtime that combination
        # corrupts the heap (segfaults in unrelated threads once restores
        # interleave — the exact hazard the CPU megachunk carve-out below
        # already documents; reproduced by the supervision tests with the
        # pipeline on). Accelerator backends keep donation: concurrent D2H
        # against a donating dispatch is the designed overlap there (same
        # pattern as CheckpointManager.save_async).
        async_on = (self.cfg.runtime.async_pipeline
                    and self._step_override is None)
        if self._step_override is not None:
            # Host-side test seam: an arbitrary Python callable cannot be
            # traced into a lax.scan, so megachunks are unavailable and the
            # loop runs its K=1 path regardless of megachunk_factor.
            self._place = lambda ts: ts
            self._step_fn = self._step_override
        elif self.mesh is not None:
            # A tp axis in the mesh shards parameters via the Megatron
            # suffix rules (column/row splits for the MLP and transformer
            # block projections); without rules a tp axis would silently
            # replicate params, making the public surface's tensor
            # parallelism a no-op.
            from sharetrade_tpu.parallel import mlp_tp_rules
            model_axis = self.cfg.parallel.model_axis
            rules = (mlp_tp_rules(model_axis)
                     if model_axis in self.mesh.axis_names else None)
            # Both programs (and _place, _reset_episode, _heal_agents and
            # the checkpoint-restore path through it) resolve their specs
            # from the same canonical train_state_shardings tree, so a
            # restored or warm-started state lands on exactly the layout
            # the compiled step's in_shardings expect — no involuntary
            # reshard on the first chunk after a recovery.
            constrain = self.cfg.parallel.shard_constraints
            from sharetrade_tpu.parallel.mesh import is_cpu_mesh
            donate = not (async_on and is_cpu_mesh(self.mesh))
            self._place, self._step_fn = make_parallel_step(
                self.agent, self.mesh, data_axis=self.cfg.parallel.data_axis,
                param_rules=rules, constrain=constrain, donate=donate,
                cost_hook=cost_hook)
            if factor > 1:
                # The K-chunk scan composes INSIDE the pjit boundary (one
                # partitioned program), so ICI collectives stay fused across
                # inner chunks; the single-chunk program above remains the
                # exact path near episode thresholds.
                _, self._mega_fn = make_parallel_step(
                    self.agent, self.mesh,
                    data_axis=self.cfg.parallel.data_axis,
                    param_rules=rules, megachunk_factor=factor,
                    constrain=constrain, donate=donate,
                    cost_hook=cost_hook)
        else:
            self._place = lambda ts: ts
            # Donated input, matching the mesh path: the previous chunk's
            # TrainState is dead the moment the next step executes, halving
            # the state's HBM footprint (matters at the d>=1024 tier:
            # params+opt+replay double-buffered otherwise). Failure paths
            # are covered — _ensure_live_state restores when a raise leaves
            # donated-dead buffers behind, and save_async snapshots to host
            # before the next chunk can free them. Known trade (same as the
            # mesh path has always made): a RESUME-verb error raised from
            # INSIDE the step can no longer resume-in-place — the input was
            # donated — so it recovers via checkpoint restore, losing at
            # most checkpoint_every_updates updates instead of none (the
            # bound holds from chunk 0: _run_supervised writes a baseline
            # checkpoint before the first chunk). Under the async pipeline
            # on the CPU backend donation is carved out (see above) — the
            # cost is one extra live TrainState, on the host-memory
            # fallback path only.
            donate = ((0,) if not (async_on
                                   and jax.default_backend() == "cpu")
                      else ())
            self._step_fn = jax.jit(self.agent.step, donate_argnums=donate)
            if factor > 1:
                # NO donation on the CPU-fallback megachunk: donating the
                # TrainState into the fused lax.scan corrupts the heap on
                # the CPU runtime (use-after-free that surfaces as segfaults
                # in unrelated threads once checkpoint restores interleave
                # with megachunk dispatches — reproduced by the supervision
                # tests). The cost is one extra live TrainState per K chunks
                # on the fallback path only; the mesh/pjit path above keeps
                # donation, where HBM double-buffering actually matters.
                self._mega_fn = jax.jit(
                    megachunk_step(self.agent.step, factor))

    def _capture_roofline_fallback(self, template: TrainState) -> None:
        """Compile-time roofline capture for the MESHLESS build paths —
        the mesh path captures through ``jit_parallel_step``'s
        ``cost_hook`` (parallel/sharding.py), but the CPU-fallback
        programs are plain ``jax.jit`` wrappers built in
        :meth:`_build_step`, so their costs are recorded here, against
        the same template the first dispatch will see. Build-time only;
        a capture failure is swallowed inside RooflineCapture."""
        roofline = self.obs.roofline
        if (roofline is None or self.mesh is not None
                or self._step_override is not None):
            return
        roofline.capture(self._step_fn, (template,), megachunk_factor=1)
        if self._mega_fn is not None:
            roofline.capture(
                self._mega_fn, (template,),
                megachunk_factor=self.cfg.runtime.megachunk_factor)

    # ------------------------------------------------------------------
    # protocol: StartTraining (TrainerRouterActor.scala:86-88)
    # ------------------------------------------------------------------

    def start_training(self, *, background: bool = True) -> None:
        if self.lifecycle.phase is Phase.AWAITING_DATA:
            self.lifecycle.start_requested = True  # stashed until data
            self._stashed_background = background
            log.info("StartTraining stashed until training data arrives")
            return
        if self.lifecycle.phase not in (Phase.READY, Phase.COMPLETED,
                                        Phase.TRAINED, Phase.FAILED):
            log.info("already training; ignoring StartTraining")
            return
        if self.lifecycle.phase is not Phase.READY:
            self.initialise()
        self.lifecycle.to(Phase.TRAINING)
        self._stop.clear()
        if background:
            self._thread = threading.Thread(
                target=self._run_supervised, name="trainer", daemon=True)
            self._thread.start()
        else:
            self._run_supervised()

    # protocol: Initialise (TrainerChildActor.scala:57-59) — re-arm for a
    # fresh episode keeping learned parameters.
    def initialise(self) -> None:
        if self.agent is None or self._ts is None:
            return
        self._reset_episode()
        self.lifecycle.to(Phase.READY)

    # ------------------------------------------------------------------
    # the supervised device loop (BackoffSupervisor + Terminated respawn)
    # ------------------------------------------------------------------

    def _run_supervised(self) -> None:
        """The dispatcher: issues (mega)chunks and makes state-mutating
        decisions. With ``runtime.async_pipeline`` on, EVERY blocking host
        sync of the steady state — the batched ``device_get`` readback and
        the whole host_process block (metric rows, flight recorder,
        journaling, fault hooks, snapshot) — runs on the pipeline's
        consumer thread (:meth:`_host_process`), so the inter-megachunk
        dispatch gap no longer includes host time; the dispatcher drains
        the pipeline (a strict barrier) before the exact-completion K=1
        fallback, episode completion, heal/NaN supervision and
        checkpoint/eval cadence actions (:meth:`_boundary_actions`), and a
        consumer fault propagates here before the next megachunk commits
        state. With the knob off (or under ``step_override``) the same two
        methods run inline — the pre-pipeline synchronous path, byte-
        identical behavior."""
        rt = self.cfg.runtime
        horizon = self.env.num_steps
        chunk_idx = 0
        self._last_ckpt_updates = 0  # reference guards iteration != 0 (:74)
        # Sampled metrics (config.RuntimeConfig.metrics_every_chunks): a
        # per-chunk float(np.asarray(v)) is a device round-trip that
        # serializes the dispatch pipeline — bench.py documents that exact
        # readback as ~4x on tunneled links. Between samples, chunks
        # dispatch back-to-back; every decision below (fault detection,
        # snapshot, eval/ckpt cadence, completion) runs on sampled chunks,
        # with completion made exact by a host-side env_steps upper bound
        # (each chunk advances the cumulative counter by AT MOST
        # chunk_steps) that forces per-chunk sampling near the episode
        # threshold. A fault_hook (the reference's mock seam) implies
        # per-chunk sampling so injected faults surface on the chunk that
        # raised them.
        metrics_every = (1 if self._fault_hook is not None
                         else max(1, rt.metrics_every_chunks))
        # Device-resident megachunks (config.RuntimeConfig.megachunk_factor):
        # K consecutive chunks fused into ONE compiled lax.scan, so the host
        # pays one dispatch per K chunks instead of K — the lever against
        # the ~0.1 s per-dispatch floor on tunneled links. Per-chunk metrics
        # come back as a stacked (K, ...) buffer read with ONE batched
        # device_get; near the episode threshold the loop falls back to the
        # K=1 exact path below. _build_step leaves _mega_fn None for the
        # host-side step_override seam.
        mega = rt.megachunk_factor if self._mega_fn is not None else 1
        timer = StepTimer(rt.chunk_steps, self.cfg.parallel.num_workers,
                          max_history=self.cfg.obs.max_timer_history or None)
        self._timer = timer   # the consumer's tick handle (_host_process)
        obs = self.obs
        self.tracer.start()
        # ONE batched readback seeds both the baseline-checkpoint label and
        # the env-step completion bound (formerly two scalar device_gets —
        # tools/lint_hot_loop.py keeps stray per-scalar syncs out).
        updates0, env_steps0 = (
            int(v) for v in jax.device_get(  # hot-loop-sync-ok: once, before the first chunk
                (self._ts.updates, self._ts.env_steps)))
        # Baseline checkpoint before the first chunk (async; skipped when
        # one already exists or checkpointing is off): with donated step
        # inputs, a failure INSIDE a step can never resume in place — it
        # restores from the latest checkpoint — and without this save the
        # pre-first-cadence window would restore-to-nothing and silently
        # reinitialize, discarding warm-start/resume state. This makes the
        # "lose at most checkpoint_every_updates updates" bound true from
        # chunk 0.
        # "Exists" is not enough — steps() lists damaged dirs so the
        # walk-back can quarantine them; the baseline must be saved unless
        # an INTACT checkpoint could actually serve a restore (one hash of
        # the newest checkpoint, once per run start).
        has_intact = getattr(self.checkpoints, "any_intact",
                             lambda: self.checkpoints.latest_step()
                             is not None)
        if rt.checkpoint_every_updates > 0 and not has_intact():
            self.checkpoints.save_async(
                updates0, self._ts,
                metadata={"episode": self.episode, "env_steps": env_steps0})
        timer.tick()
        last_env_steps: int | None = env_steps0
        chunks_since = 0   # chunks since the last materialization decision
        chunks_ahead = 0   # chunks dispatched past the last boundary row SEEN
        # Inter-dispatch gap histogram (obs-gated): end of one dispatch
        # call to the start of the next — the dispatch-floor signal
        # bench_async_pipeline derives from trace spans, kept here as a
        # mergeable distribution. Reset to None across recoveries so a
        # backoff sleep never counts as a "gap". ONE helper pair shared
        # by the sync and prefetch dispatch sites: both paths must stamp
        # identically for train_dispatch_gap_ms to mean one distribution.
        last_dispatch_end: float | None = None

        def _note_dispatch_gap() -> None:
            if (self._h_dispatch_gap is not None
                    and last_dispatch_end is not None):
                self._h_dispatch_gap.observe(
                    (time.perf_counter() - last_dispatch_end) * 1e3)

        def _stamp_dispatch_end() -> None:
            nonlocal last_dispatch_end
            if self._h_dispatch_gap is not None:
                last_dispatch_end = time.perf_counter()
        self._committed_idx = 0
        # Double-buffered dispatch (runtime.double_buffer_dispatch; sync
        # path only — the async pipeline subsumes it): the (metrics, K,
        # agent_heals-at-dispatch) of a megachunk already issued while its
        # predecessor's rows are read back and processed. The heals mark
        # lets the health check recognize a STALE unhealthy_workers report:
        # rows computed before a boundary heal still carry the quarantined
        # row, and re-healing it would find no bad rows and spuriously
        # escalate to a full restart.
        pending: tuple[dict, int, int] | None = None
        # Async readback pipeline (runtime.async_pipeline, default on): the
        # dispatcher below never blocks on a readback — each materialization
        # boundary's device buffers go to the consumer thread, which runs
        # _host_process strictly in chunk order. Forced off under the
        # step_override test seam (lockstep semantics) alongside megachunks.
        pl: AsyncPipeline | None = None
        if rt.async_pipeline and self._step_override is None:
            self.pipeline_stats = {}
            pl = AsyncPipeline(
                rt.pipeline_depth, self._host_process,
                attn_check=self._row_needs_attention,
                span=obs.span if obs.enabled else None)
        self._pl = pl
        # Chunk position of the boundary row _boundary_actions is acting on
        # in the attention path — a supervision raise from there (NaN loss,
        # heal escalation) is attributed to ITS boundary, not to however
        # far ahead the dispatcher has dispatched (sync-path parity).
        acting_chunk: int | None = None
        try:
          while not self._stop.is_set():
            try:
                acting_chunk = None
                if self._preempt.is_set():
                    # Megachunk-boundary preemption point: every committed
                    # state lands here between dispatches, so the emergency
                    # checkpoint below captures a coherent boundary state.
                    self._preempt_shutdown(pl)
                    return
                if pl is not None and (pl.error is not None
                                       or pl.attention.is_set()):
                    # A consumer fault, or a boundary row that needs a
                    # dispatcher-side action (heal / cadence / completion):
                    # drain so every queued readback lands in order, then
                    # act on the newest boundary row — the drain barrier
                    # that keeps supervision and completion exact.
                    pl.drain()
                    pl.attention.clear()
                    if pl.error is not None:
                        # True-chunk attribution: the consumer's committed
                        # cursor stopped AT the failing chunk, exactly where
                        # the synchronous loop's chunk_idx would be.
                        chunk_idx = self._committed_idx
                        raise pl.error
                    if pl.last_row is not None:
                        last_env_steps = int(pl.last_row["env_steps"])
                        chunks_ahead = chunk_idx - self._committed_idx
                    # Act on EVERY flagged row, in chunk order — cadence
                    # crossings on consecutive boundaries each get their
                    # action (eval/checkpoint), exactly like the
                    # synchronous path's per-boundary decision block.
                    for row, mark, end_idx in pl.take_attention():
                        acting_chunk = end_idx
                        ret = self._boundary_actions(row, mark, horizon)
                        if ret == "completed":
                            return
                        if ret == "rearmed":
                            break   # later rows predate the re-arm
                    continue
                if last_env_steps is None:  # after any recovery path
                    last_env_steps = int(
                        jax.device_get(self._ts.env_steps))  # hot-loop-sync-ok: once per recovery, not per chunk
                    chunks_since = 0
                    chunks_ahead = 0
                threshold = horizon * (self.episode + 1)
                if pending is not None:
                    metrics, k, heals_mark = pending
                    pending = None
                else:
                    heals_mark = self.agent_heals
                    # Fuse K chunks ONLY when even the env-step UPPER BOUND
                    # after K more chunks stays strictly below the episode
                    # threshold (each chunk advances the counter by at most
                    # chunk_steps): no inner chunk can hit the completion
                    # gate, so near episode ends the loop degrades to K=1
                    # dispatches and the gate keeps its exact semantics.
                    can_fuse = (mega > 1
                                and (last_env_steps + (chunks_ahead + mega)
                                     * rt.chunk_steps) < threshold)
                    if (pl is not None and mega > 1 and not can_fuse
                            and chunks_ahead > 0):
                        # Drain barrier BEFORE the K=1 exact fallback: the
                        # fusion guard ran on an upper bound that staled
                        # while boundaries were in flight; refresh from the
                        # drained consumer row — often fusion is still
                        # legal, and the completion math is exact again.
                        # Only a refresh that actually MOVED the bound
                        # re-enters the loop: un-materialized fast-path
                        # chunks have no row to reclaim, and looping on
                        # them would spin forever — they fall through to
                        # the K=1 exact path below.
                        if (pl.drain() and pl.error is None
                                and pl.last_row is not None):
                            refreshed = (int(pl.last_row["env_steps"]),
                                         chunk_idx - self._committed_idx)
                            if refreshed != (last_env_steps, chunks_ahead):
                                last_env_steps, chunks_ahead = refreshed
                                continue    # re-enter: attention first
                    k = mega if can_fuse else 1
                    # Obs spans ride the SAMPLING cadence, not the chunk
                    # cadence: only the dispatch whose readback will
                    # materialize this sample is timed, so between samples
                    # the fast path stays span-free (the <2% overhead
                    # budget, bench_obs_overhead). The predicate mirrors
                    # the sample decision below — chunk-count cadence, the
                    # near-threshold exact path, or a transitions journal
                    # (journaled runs materialize every chunk).
                    sampling = obs.enabled and (
                        chunks_since + k >= metrics_every
                        or self._transitions_journal is not None
                        or (last_env_steps + (chunks_ahead + k)
                            * rt.chunk_steps) >= threshold)
                    _note_dispatch_gap()
                    with (obs.span("dispatch", chunk=chunk_idx, k=k)
                          if sampling else _NULL_CTX), self.tracer.span(
                            f"train_chunk_{chunk_idx}"
                            + (f"_x{k}" if k > 1 else "")):
                        # The step lock fences evaluate()'s state snapshot
                        # from this donating dispatch; dispatch is
                        # non-blocking so the lock is held microseconds,
                        # not the chunk.
                        with self._step_lock:
                            ts, metrics = (self._mega_fn if k > 1
                                           else self._step_fn)(self._ts)
                            # Commit the new state BEFORE any hook can
                            # raise: the mesh/accelerator paths donate their
                            # input (old state already dead), and the non-
                            # donating CPU megachunk paths must still never
                            # re-dispatch a superseded state after a hook
                            # fault. Do NOT assume donation on every path —
                            # the CPU fused-scan carve-outs (_build_step,
                            # sharding.py) exist to avoid a use-after-free.
                            self._ts = ts
                    _stamp_dispatch_end()
                transitions = metrics.pop("transitions", None)
                chunks_since += k
                chunks_ahead += k
                est_env_steps = min(
                    last_env_steps + chunks_ahead * rt.chunk_steps, threshold)
                if (chunks_since < metrics_every and transitions is None
                        and est_env_steps < threshold):
                    chunk_idx += k
                    continue        # fast path: no host materialization
                if pl is not None:
                    # Hand the boundary to the consumer: start the D2H copy
                    # without blocking, enqueue (backpressure when the
                    # bounded queue is full — in-flight HBM stays bounded),
                    # and keep dispatching. Readback + the entire
                    # host_process block happen on the consumer thread.
                    _start_readback(metrics, transitions)
                    boundary = Boundary(chunk_idx, k, metrics, transitions,
                                        heals_mark, chunks_since)
                    if not pl.try_put(boundary):
                        with (obs.span("pipeline_stall", chunk=chunk_idx,
                                       depth=pl.depth)
                              if obs.enabled else _NULL_CTX):
                            ok = pl.put(boundary, stop=self._stop)
                        self.metrics.inc("pipeline_stalls_total")
                        if not ok:
                            continue   # fault/stop while blocked: top of
                                       # loop takes over
                    self.metrics.record("pipeline_queue_depth", pl.qsize())
                    chunk_idx += k
                    chunks_since = 0
                    if (est_env_steps >= threshold
                            or self._fault_hook is not None):
                        # Drain barrier for the exact completion gate: the
                        # upper bound says this boundary MAY finish the
                        # episode; wait for its true row (the consumer
                        # flags attention when it actually completes).
                        # A fault_hook keeps the SAME barrier on every
                        # boundary — the chaos seam's contract is dispatch-
                        # synchronous state (hooks mutate self._ts in the
                        # supervision tests), so the hook still runs on the
                        # consumer (fault propagation is exercised) but the
                        # dispatcher never runs ahead of it.
                        if (pl.drain() and pl.error is None
                                and pl.last_row is not None):
                            last_env_steps = int(pl.last_row["env_steps"])
                            chunks_ahead = chunk_idx - self._committed_idx
                    continue
                if (rt.double_buffer_dispatch and k > 1
                        and transitions is None and self._fault_hook is None
                        and (last_env_steps + (chunks_ahead + k)
                             * rt.chunk_steps) < threshold):
                    # Cruise-regime double buffering (sync path): issue
                    # megachunk k+1 BEFORE blocking on this one's readback,
                    # so the D2H metric transfer below overlaps device
                    # compute (the async-checkpoint D2H overlap applied to
                    # the metrics path). Guarded exactly like the fused
                    # dispatch (no inner chunk of the in-flight program can
                    # complete the episode), and off when transitions are
                    # journaled (durability) or a fault_hook is installed
                    # (the chaos seam needs dispatch-synchronous state).
                    # Consequence, documented in config.py: fault detection
                    # and the checkpoint/eval cadence act on a state one
                    # in-flight megachunk ahead of the rows being read.
                    # The span covers the chunks the prefetch advances
                    # (chunk_idx + k onward) so the trace keeps one
                    # train_chunk_* entry per dispatch, not just the first.
                    # The obs dispatch span mirrors that (this block only
                    # runs at materialization boundaries, so it is already
                    # on the sampled path).
                    _note_dispatch_gap()
                    with (obs.span("dispatch", chunk=chunk_idx + k, k=k,
                                   prefetch=True)
                          if obs.enabled else _NULL_CTX), self.tracer.span(
                            f"train_chunk_{chunk_idx + k}_x{k}"):
                        with self._step_lock:
                            ts, ahead = self._mega_fn(self._ts)
                            self._ts = ts
                    _stamp_dispatch_end()
                    pending = (ahead, k, self.agent_heals)
                # Synchronous path: readback + host processing inline (the
                # pre-pipeline behavior, byte-identical).
                metrics = self._host_process(Boundary(
                    chunk_idx, k, metrics, transitions, heals_mark,
                    chunks_since))
                chunk_idx = self._committed_idx
                last_env_steps = int(metrics["env_steps"])
                chunks_since = 0
                chunks_ahead = 0
                ret = self._boundary_actions(metrics, heals_mark, horizon)
                if ret == "completed":
                    return
            except Exception as exc:  # supervision decider
                last_env_steps = None   # resync after any recovery path
                pending = None          # in-flight megachunk is now stale
                last_dispatch_end = None  # recovery/backoff is not a "gap"
                pipeline_fault = pl is not None and exc is pl.error
                if pl is not None:
                    # Quiesce and replace the pipeline: boundaries still
                    # queued were computed from state the restore below
                    # rewinds — they are stale, and the fresh run segment
                    # re-materializes those chunks.
                    pl.shutdown()
                    self._record_pipeline_stats(pl)
                    pl = AsyncPipeline(
                        rt.pipeline_depth, self._host_process,
                        attn_check=self._row_needs_attention,
                        span=obs.span if obs.enabled else None)
                    self._pl = pl
                # Attribution: a consumer fault belongs to the chunk the
                # consumer committed last; a supervision raise from the
                # attention path belongs to the boundary row it was acting
                # on (the dispatcher may be several megachunks ahead of
                # both); any other dispatcher-local fault keeps its own
                # position (the consumer can only be behind it).
                if pipeline_fault:
                    chunk_idx = self._committed_idx
                elif acting_chunk is not None:
                    chunk_idx = acting_chunk
                else:
                    chunk_idx = max(chunk_idx, self._committed_idx)
                self.last_error = exc
                verb = self._decide(exc)
                self.events.emit("worker_failed", error=repr(exc), verb=verb,
                                 restarts=self.restarts + 1)
                # Forensic bundle BEFORE any recovery mutates state: the
                # ring holds the last-capacity chunk rows (its newest
                # chunk_metrics entry is the failing chunk — rows are
                # recorded before the hooks that raise on them),
                # lifecycle transitions, run events and WARNING+ logs.
                obs.dump_flight(reason="supervision", error=repr(exc),
                                verb=verb, restarts=self.restarts,
                                episode=self.episode, next_chunk=chunk_idx)
                if verb == RESUME:
                    log.warning("resuming after %r (policy: resume)", exc)
                    self._ensure_live_state()
                    timer.rebase()   # exclude the failed chunk's time
                    continue
                if verb == STOP:
                    self.lifecycle.force(Phase.FAILED)
                    self.tracer.stop()
                    obs.flush()
                    log.error("stopping after %r (policy: stop)", exc)
                    return
                if verb == ESCALATE:
                    self.lifecycle.force(Phase.FAILED)
                    self.tracer.stop()
                    obs.flush()
                    raise
                self.restarts += 1
                self.metrics.inc("restarts_total")
                if self.restarts > rt.max_restarts:
                    self.lifecycle.force(Phase.FAILED)
                    self.tracer.stop()
                    obs.flush()
                    log.error("restart budget exhausted: %r", exc)
                    return
                delay = min(rt.backoff_initial_s * 2 ** (self.restarts - 1),
                            rt.backoff_max_s)
                delay *= 1.0 + random.uniform(-rt.backoff_jitter,
                                              rt.backoff_jitter)
                log.warning("chunk failed (%r); restart %d/%d in %.2fs",
                            exc, self.restarts, rt.max_restarts, delay)
                with obs.span("supervision_recovery",
                              restart=self.restarts) \
                        if obs.enabled else _NULL_CTX:
                    if self._wait_backoff(delay):
                        return
                    self._restore_or_reinit()
                # Exclude the failed chunk + backoff + restore from the
                # next throughput sample.
                timer.rebase()
        finally:
            self._pl = None
            if pl is not None:
                pl.shutdown()
                self._record_pipeline_stats(pl)

    def _record_pipeline_stats(self, pl: AsyncPipeline) -> None:
        self.pipeline_stats = {
            "max_depth_seen": max(
                self.pipeline_stats.get("max_depth_seen", 0),
                pl.max_depth_seen),
            "boundaries": (self.pipeline_stats.get("boundaries", 0)
                           + pl.processed),
        }

    # ------------------------------------------------------------------
    # preemption (SIGTERM/SIGINT): drain, emergency checkpoint, exit
    # ------------------------------------------------------------------

    def request_preempt(self) -> None:
        """Ask the run to preempt: the training thread drains and writes the
        ``tag_preempt`` emergency checkpoint at its next megachunk boundary
        (:meth:`_preempt_shutdown`), then returns. Installed as the
        SIGTERM/SIGINT action by ``cli train``; safe to call from
        signal-handler context (it only sets an Event). The grace deadline
        anchors HERE — at notice time, not at the boundary the dispatcher
        eventually reaches — so a long in-flight megachunk eats into the
        budget instead of extending it past the fleet's follow-up KILL."""
        if not self._preempt.is_set():
            self._preempt_deadline = (time.monotonic()
                                      + self.cfg.runtime.preempt_grace_s)
        self._preempt.set()

    def _wait_backoff(self, delay: float) -> bool:
        """Backoff sleep that wakes EARLY on preemption — the restart
        backoff must not eat the ``runtime.preempt_grace_s`` budget (the
        loop top then runs the preemption drain against the restored
        state). Returns True when stop was requested."""
        deadline = time.monotonic() + delay
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._preempt.is_set():
                return False
            if self._stop.wait(min(remaining, 0.1)):
                return True

    def _preempt_shutdown(self, pl: AsyncPipeline | None) -> None:
        """The preemption drain, run on the training thread at a megachunk
        boundary, inside ``runtime.preempt_grace_s``: queued readbacks drain
        in order (their metric rows and journal appends commit), in-flight
        async checkpoint writes land, an emergency ``tag_preempt``
        checkpoint with full resume metadata (updates / env_steps / episode)
        is written, the journal group-commit batch hits the disk, and the
        flight recorder dumps with reason ``"preemption"``. Never raises — a
        failure here degrades durability but must not convert a preemption
        into a supervision restart that burns the remaining grace."""
        obs = self.obs
        grace = self.cfg.runtime.preempt_grace_s
        # Anchored at request_preempt time: boundary latency (a long
        # in-flight megachunk) already consumed part of the budget.
        deadline = self._preempt_deadline or (time.monotonic() + grace)
        log.warning("preemption requested; draining for an emergency "
                    "checkpoint (%.1fs of the %.1fs grace left)",
                    max(0.0, deadline - time.monotonic()), grace)
        saved = False
        with (obs.span("preemption_drain", grace_s=grace)
              if obs.enabled else _NULL_CTX):
            try:
                if pl is not None:
                    pl.drain(timeout_s=max(0.5,
                                           deadline - time.monotonic()))
                self._ensure_live_state()
                updates, env_steps = (int(v) for v in jax.device_get(
                    (self._ts.updates, self._ts.env_steps)))
                self.checkpoints.wait_pending(
                    timeout=max(0.5, deadline - time.monotonic()))
                self.checkpoints.save_tagged(
                    "preempt", self._ts,
                    metadata={"updates": updates, "env_steps": env_steps,
                              "episode": self.episode, "preempted": True})
                saved = True
                # Durability-critical work strictly BEFORE any telemetry
                # write: a failing obs volume must not skip the journal
                # batch flush or the event-log record.
                flush = getattr(self._transitions_journal, "flush", None)
                if flush is not None:
                    flush()
                self.events.emit("preempted", updates=updates,
                                 env_steps=env_steps, episode=self.episode)
                log.warning("emergency checkpoint tag_preempt written "
                            "(updates=%d, env_steps=%d, episode=%d)",
                            updates, env_steps, self.episode)
            except Exception:
                log.exception("preemption drain failed; exiting with "
                              "whatever was already durable")
        try:
            # Telemetry is inside its own no-raise envelope too: an IO
            # error here must not convert the preemption into a
            # supervision restart that burns the remaining grace.
            if saved:
                obs.tracer.instant("emergency_checkpoint",
                                   updates=updates, env_steps=env_steps)
            obs.dump_flight(reason="preemption", episode=self.episode,
                            restarts=self.restarts)
            self.tracer.stop()
            obs.flush()
        except Exception:
            log.exception("preemption telemetry flush failed")
        self.preempt_saved = saved
        self.preempted = True

    def _host_process(self, b: Boundary) -> dict[str, float]:
        """The consumer half: ONE batched readback for the whole megachunk
        (the stacked (K, ...) metric rows and, for DQN journaling, the
        stacked transition batch cross together), then the per-row host
        work — flight-ring records, journal appends, fault hooks, metric
        stream, snapshot — strictly in chunk order. Runs on the pipeline's
        consumer thread under ``runtime.async_pipeline`` (every blocking
        call here is off the dispatch critical path), inline on the
        dispatcher otherwise. ``self._committed_idx`` advances per row and
        is the fault-attribution cursor either way."""
        obs = self.obs
        self._committed_idx = b.base
        with (obs.span("readback", chunk=b.base, k=b.k)
              if obs.enabled else _NULL_CTX):
            host, host_tr = jax.device_get((b.metrics, b.transitions))  # hot-loop-sync-ok: consumer-side batched megachunk readback, off the dispatch path
        with (obs.span("host_process", chunk=b.base, k=b.k)
              if obs.enabled else _NULL_CTX):
            rows = _metric_rows(host, b.k)
            for i, row in enumerate(rows):
                if obs.enabled:
                    # Into the flight ring BEFORE the fault hook / health
                    # checks that can raise on this row: at dump time the
                    # ring's newest chunk_metrics entry IS the failing
                    # chunk.
                    obs.record("chunk_metrics", chunk=b.base + i, **row)
                if host_tr is not None:
                    self._journal_transitions(
                        jax.tree.map(lambda a: a[i], host_tr)
                        if b.k > 1 else host_tr,
                        int(row["env_steps"]))
                if self._fault_hook is not None:
                    # Per inner chunk with its TRUE chunk index: a fault
                    # landing mid-megachunk surfaces at the boundary but is
                    # attributed (and, on raise, retried) at the chunk that
                    # raised it.
                    self._fault_hook(b.base + i, row)
                self._committed_idx = b.base + i + 1
                if i + 1 < b.k:
                    # Inner (non-boundary) rows keep the per-chunk metric
                    # stream complete — delivered late, at the boundary;
                    # snapshot/supervision/cadence read the boundary row,
                    # which subsumes them (quarantine and counters are
                    # monotone within a megachunk).
                    self.metrics.record_many(row)
            metrics = rows[-1]
            metrics.update(self._timer.tick(b.chunks_covered))
            if (self._h_chunk_seconds is not None
                    and metrics.get("chunk_seconds")):
                # Consumer-thread histogram of the sampled per-chunk wall
                # time (obs/hist.py): the mergeable distribution behind
                # the chunk_seconds gauge — host floats only, no sync.
                self._h_chunk_seconds.observe(metrics["chunk_seconds"])
            if obs.roofline is not None:
                # Live roofline gauges (mfu / achieved_tflops / hbm_gbps):
                # static compiled costs divided by the sampled per-chunk
                # wall time — consumer-thread math on already-host values,
                # never a device sync, never the dispatcher.
                obs.roofline.on_boundary(
                    k=b.k, chunk_seconds=metrics.get("chunk_seconds"))
            with self._snapshot_lock:
                self._snapshot = metrics
            self.metrics.record_many(metrics)
            return metrics

    def _row_needs_attention(self, row: dict[str, float]) -> bool:
        """Consumer-side hint: does this boundary row need a DISPATCHER
        action (heal, NaN supervision, eval/checkpoint cadence, episode
        completion)? Over-triggering is harmless — the dispatcher drains
        and re-evaluates the exact conditions in _boundary_actions — so the
        reads here tolerate benign races with dispatcher-owned state."""
        rt = self.cfg.runtime
        unhealthy = row.get("unhealthy_workers", 0)
        if rt.partial_recovery and unhealthy > 0:
            return True
        if rt.partial_recovery and not np.isfinite(row.get("loss", 0.0)):  # hot-loop-sync-ok: consumer thread, host floats
            return True
        if (not rt.partial_recovery
                and unhealthy >= self.cfg.parallel.num_workers):
            return True
        updates = int(row.get("updates", 0))
        last = self._last_ckpt_updates
        for every in (rt.eval_every_updates, rt.checkpoint_every_updates):
            if every > 0 and updates // every > last // every:
                return True
        if self._ingest_enabled:
            # Live cadence (adaptive ingest): benign race with the
            # dispatcher's adjustments — over-triggering just drains and
            # re-evaluates, like every other attention hint here.
            every = self._ingest_every
            if updates // every > self._last_ingest_updates // every:
                return True
        return (int(row.get("env_steps", 0))
                >= self.env.num_steps * (self.episode + 1))

    def _boundary_actions(self, metrics: dict[str, float], heals_mark: int,
                          horizon: int) -> str | None:
        """Dispatcher-side decisions on a boundary row: per-agent healing,
        NaN supervision (raises feed the decider), eval/checkpoint cadence,
        and the episode-completion gate. Runs inline on the synchronous
        path; under the async pipeline it runs only after a drain barrier,
        so the row is the newest and the live state corresponds to it.
        Returns "completed" (terminal — caller returns), "rearmed" (episode
        re-armed), or None."""
        rt = self.cfg.runtime
        timer = self._timer
        obs = self.obs
        workers = self.cfg.parallel.num_workers
        if (rt.partial_recovery
                and metrics.get("unhealthy_workers", 0) > 0
                # Stale report from a pre-heal in-flight megachunk (double
                # buffering / pipeline depth): the row was already respawned
                # at the previous boundary; the next fresh megachunk
                # re-reports if the fault actually persists.
                and heals_mark == self.agent_heals):
            # Quarantined rows detected: respawn just those agents
            # (the reference's one-dead-child heal). Raising falls
            # through to the supervision decider -> full restore.
            # A recurring fault must not heal->re-poison->heal
            # forever: past the heal budget it escalates to the
            # restart path, whose max_restarts bounds availability.
            if (self.agent_heals >= rt.max_agent_heals
                    or not self._heal_agents()):
                raise RuntimeError(
                    f"{int(metrics['unhealthy_workers'])} agent(s) "
                    "non-finite and beyond row respawn "
                    f"(heals used: {self.agent_heals}/"
                    f"{rt.max_agent_heals})")
        if (rt.partial_recovery
                and not np.isfinite(metrics.get("loss", 0.0))):
            # Poison reached the shared loss (and so the params on
            # the next update): beyond any row respawn — full
            # checkpoint restore via the supervision path.
            raise RuntimeError("non-finite training loss "
                               "(shared state poisoned)")

        updates = int(metrics.get("updates", 0))
        if (self._ingest_enabled
                and updates // self._ingest_every
                > self._last_ingest_updates // self._ingest_every):
            # Actor-feed ingest (distrib/): contained like the periodic
            # eval below — a torn actor journal or a transient read error
            # is an ingest miss, not a training fault; the next cadence
            # tick retries from the same cursors.
            try:
                self.ingest_actor_feeds()
            except Exception:
                log.exception("actor-feed ingest failed; "
                              "training continues")
            self._last_ingest_updates = updates
        if (rt.eval_every_updates > 0
                and updates // rt.eval_every_updates
                > self._last_ckpt_updates // rt.eval_every_updates):
            # Periodic greedy eval between chunks: feeds the
            # event-log learning curve and (keep_best_eval) the
            # retained-best checkpoint during long unattended runs.
            # Contained: an eval/retention failure (e.g. disk full
            # in save_tagged) is an observability loss, not a
            # training fault — it must not consume a restart or
            # roll the healthy run back to a checkpoint.
            try:
                self.evaluate()
            except Exception:
                log.exception("periodic evaluation failed; "
                              "training continues")
        if (rt.checkpoint_every_updates > 0
                and updates // rt.checkpoint_every_updates
                > self._last_ckpt_updates // rt.checkpoint_every_updates):
            # Async: device->host DMA overlaps the next chunk.
            # The episode index rides the metadata: env_steps alone
            # can't recover it once per-agent heals inflate the step
            # count past horizon-per-episode.
            self.checkpoints.save_async(
                updates, self._ts,
                # env_steps rides along for the crash-soak/journal
                # consistency checks and the resume-source comparison
                # (tag_preempt vs latest step checkpoint).
                metadata={"episode": self.episode,
                          "env_steps": int(metrics.get("env_steps", 0))})
            self.metrics.inc("checkpoints_total")
            self.events.emit("checkpoint", updates=updates)
        self._last_ckpt_updates = updates

        # env_steps is cumulative across episodes (the epsilon ramp
        # input), so episode N completes at (N+1) x horizon. With
        # per-agent healing, a respawned row restarts its episode
        # mid-run and may still be training when the step count
        # crosses the threshold — completion additionally waits for
        # every worker's cursor to reach the horizon (the reference
        # completes only when all 10 children report Trained,
        # including replacements, TrainerRouterActor.scala:114,125).
        done_steps = (int(metrics.get("env_steps", 0))
                      >= horizon * (self.episode + 1))
        # With partial_recovery off, a quarantined row can never be
        # respawned: it would strand the all-trained gate forever
        # (the learners' on-device quarantine is unconditional), so
        # stranded rows count as excluded — the run completes
        # without them, like a dead child nobody respawns.
        stranded = (0.0 if rt.partial_recovery
                    else metrics.get("unhealthy_workers", 0.0))
        all_trained = (metrics.get("trained_workers", float(workers))
                       + stranded >= workers)
        if done_steps and all_trained:
            self.episode += 1
            self.metrics.inc("episodes_completed_total")
            if self.episode < rt.episodes:
                # Re-arm for another pass over the history, keeping
                # learned parameters (the Initialise→Train cycle,
                # TrainerChildActor.scala:57-59).
                self.events.emit("episode_completed",
                                 episode=self.episode)
                self._reset_episode()
                return "rearmed"
            self.checkpoints.wait_pending(timeout=60)
            self.checkpoints.save(
                updates, self._ts,
                metadata={"episode": self.episode,
                          "env_steps": int(metrics.get("env_steps", 0))})
            # Completion is a durability point: group-commit batches (and
            # the C++ async writer's queue) drain to disk before the run
            # reports COMPLETED, so a reader of the journal file sees every
            # journaled chunk the moment the lifecycle says done.
            flush = getattr(self._transitions_journal, "flush", None)
            if flush is not None:
                flush()
            self.lifecycle.to(Phase.TRAINED)
            self.lifecycle.to(Phase.COMPLETED)
            self.tracer.stop()
            self.events.emit("training_completed",
                             env_steps=int(metrics["env_steps"]),
                             episodes=self.episode,
                             **timer.summary())
            obs.flush()   # trace + final metrics drain durable now
            log.info("training completed at %d env steps", horizon)
            return "completed"
        if (not rt.partial_recovery
                and metrics.get("unhealthy_workers", 0) >= workers):
            # Every row non-finite with healing disabled AND the run
            # not complete: the unconditional on-device quarantine
            # freezes every cursor, so no further progress is
            # possible — route through the supervision path instead
            # of spinning chunks forever. (Checked AFTER the
            # completion gate: a run whose last chunk both finishes
            # the episode and poisons every row still completes via
            # the stranded-rows-excluded path above.)
            raise RuntimeError(
                "all agent rows non-finite (partial_recovery off); "
                "no further progress is possible")
        return None

    def _reset_episode(self) -> None:
        """Fresh env cursors/carry/RNG for the next episode; parameters,
        optimizer state, update counter, AND the cumulative env-step count
        carry over (env_steps drives the epsilon exploration ramp — resetting
        it would replay ~1000 fully-random steps into a learned policy)."""
        fresh = self.agent.init(
            jax.random.PRNGKey(self.cfg.seed + self.episode))
        self._ts = self._place(fresh.replace(
            params=self._ts.params, opt_state=self._ts.opt_state,
            updates=self._ts.updates, env_steps=self._ts.env_steps,
            # DQN keeps its replay buffer and target net across episodes.
            extras=self._ts.extras))

    def _ensure_live_state(self) -> None:
        """A failure inside the donated-input step can leave self._ts holding
        deleted buffers; resume-in-place is then impossible and we fall back
        to restore."""
        leaves = jax.tree.leaves(self._ts)
        if any(getattr(l, "is_deleted", lambda: False)() for l in leaves):
            log.warning("state was donated into the failed step; restoring")
            self._restore_or_reinit()

    def _decide(self, exc: BaseException) -> str:
        for etype, verb in self._error_policy.items():
            if isinstance(exc, etype):
                return verb
        return RESTART

    def _heal_agents(self) -> bool:
        """Respawn poisoned agent ROWS in place — the reference's per-worker
        heal (one dead child replaced while the other nine keep training,
        TrainerRouterActor.scala:141-146) translated to vectorized agents.

        The learners' on-device quarantine (base.healthy_mask) guarantees a
        non-finite row never reached the shared parameters, so recovery is
        local: splice a fresh env cursor + model carry into the bad rows
        (params/optimizer/RNG/step counters untouched) and let the respawned
        agents retrain their episode — the reference's re-fired
        StartTraining (:116-120). Survivors lose nothing; completion waits
        for the respawned rows (the all_trained gate).

        Trunk-rollout models (the episode-mode transformer) share one
        representative agent's price windows and carry across the batch
        (agents/rollout.py agent-invariance), so their respawned rows CANNOT
        restart at cursor 0 — a healthy-but-desynced row could be elected
        representative and corrupt every agent's windows. Instead they
        rejoin AT the survivors' cursor: a fresh wallet spliced in at the
        representative's env cursor, with the representative's carry (the
        trunk/K-V cache is action-independent, so every lockstep row's carry
        is identical — the respawned row's "recomputed" carry already exists
        on a healthy neighbor). The respawned agent trades the remainder of
        the episode; survivors lose nothing; lockstep is preserved. This is
        the round-3 exemption removed — previously one poisoned flagship row
        rolled the WHOLE run back to the last checkpoint.

        Returns False — caller falls back to checkpoint restore — when the
        damage exceeds a row respawn: shared params/opt non-finite (the
        quarantine was breached), EVERY row bad (device-level corruption),
        or no bad rows found (the fault is elsewhere)."""
        if self._step_override is not None or self.agent is None:
            return False
        from sharetrade_tpu.agents.base import election_health
        ts = self._ts
        # THE shared row-health predicate (also used to elect the shared-
        # trunk representative in agents/rollout.py): env state AND model
        # carry finite, per row.
        ok = np.asarray(jax.device_get(election_health(ts.env_state,
                                                       ts.carry)))
        bad = ~ok
        if not bad.any() or bad.all():
            return False
        shared = jax.device_get((ts.params, ts.opt_state))
        if not all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(shared)):
            return False
        fresh = self.agent.init(jax.random.PRNGKey(
            self.cfg.seed + 7919 * (self.agent_heals + 1)))

        def splice(cur, new):
            m = bad.reshape((-1,) + (1,) * (np.asarray(cur).ndim - 1))
            return jnp.where(m, new, cur)

        fresh_env, fresh_carry = fresh.env_state, fresh.carry
        if getattr(self.agent.model, "apply_rollout_trunk", None) is not None:
            # Lockstep rejoin (see docstring): fresh wallet at the
            # representative healthy row's cursor, carry copied from it.
            rep = int(np.flatnonzero(ok)[0])
            fresh_env = fresh_env.replace(
                t=jnp.broadcast_to(ts.env_state.t[rep],
                                   fresh_env.t.shape))
            fresh_carry = jax.tree.map(
                lambda c: jnp.broadcast_to(c[rep:rep + 1],
                                           c.shape).astype(c.dtype),
                ts.carry)
        self._ts = self._place(ts.replace(
            env_state=jax.tree.map(splice, ts.env_state, fresh_env),
            carry=jax.tree.map(splice, ts.carry, fresh_carry)))
        self.agent_heals += 1
        self.metrics.inc("heals_total")
        idx = [int(i) for i in np.flatnonzero(bad)]
        log.warning("respawned poisoned agent row(s) %s in place "
                    "(heal %d; params untouched)", idx, self.agent_heals)
        self.events.emit("agents_healed", agents=idx,
                         heals=self.agent_heals)
        return True

    def _restore_or_reinit(self) -> None:
        """Restore the latest INTACT checkpoint — the manager verifies each
        candidate (checksums, deserializability, finite shared leaves),
        quarantines damaged ones and walks back — else restart the episode
        from scratch: respawn-and-retrain (TrainerRouterActor.scala:116-120,
        141-146). "All corrupt" raises CheckpointCorruptError, a
        FileNotFoundError subclass, so it lands on the same reinit arm as
        "none saved yet" — a run never strands on damaged newest bytes."""
        template = self.agent.init(jax.random.PRNGKey(self.cfg.seed))
        self.checkpoints.wait_pending(timeout=60)  # pick up in-flight saves
        try:
            state, step = self.checkpoints.restore(template)
            self._surface_restore_fallback()
            self._ts = self._place(self._warm_start_replay(state))
            self.events.emit("restored", step=step)
        except FileNotFoundError:
            self._ts = self._place(self._warm_start_replay(template))
            self.events.emit("reinitialized")

    def _surface_restore_fallback(self) -> None:
        """A restore that had to walk back past quarantined checkpoints is
        a supervision-visible fact, not just a manager log line: the event
        log records which steps were skipped and why (the counters —
        ckpt_restore_fallbacks_total / ckpt_quarantined_total — already
        flowed through the manager's metrics hook)."""
        report = getattr(self.checkpoints, "last_restore_report", None) or {}
        skipped = report.get("skipped")
        if skipped:
            self.events.emit(
                "restore_fallback", step=report.get("step"),
                skipped=[[int(s), reason] for s, reason in skipped])

    def _restore_for_resume(self, template: TrainState
                            ) -> tuple[TrainState, int, dict]:
        """``--resume`` source selection: prefer the ``tag_preempt``
        emergency checkpoint when it is at least as new (by update count)
        as the newest VERIFIED step checkpoint — it was written AFTER the
        last cadence save, at the exact megachunk boundary the preempted
        run stopped on. Falls back to the verified step-checkpoint
        walk-back when the tag is absent, older, or quarantined; and
        symmetrically, when the step walk-back lands BELOW the tag's
        update count (the unverified ``latest_step`` number was inflated
        by a checkpoint verification rejected), the intact emergency
        checkpoint is re-preferred. Returns ``(state, step_label,
        metadata)``."""
        pmeta = self.checkpoints.tagged_metadata("preempt")
        tag_hint = int(pmeta.get("updates", -1)) if pmeta else -1
        latest = self.checkpoints.latest_step()

        def tag_candidate() -> tuple[TrainState, int, dict] | None:
            """Verified tag_preempt restore; None when absent or every
            copy (primary + .old) was quarantined by verification."""
            try:
                state, meta = self.checkpoints.restore_tagged(
                    template, "preempt")
            except FileNotFoundError:
                return None
            return state, int(meta.get("updates", 0)), meta

        def accept(t: tuple[TrainState, int, dict]
                   ) -> tuple[TrainState, int, dict]:
            log.info("resuming from preemption checkpoint (updates=%d)",
                     t[1])
            self.events.emit("resumed_from_preempt", updates=t[1])
            return t

        tag = None
        if pmeta is not None and (latest is None or tag_hint >= latest):
            tag = tag_candidate()
            # Compare the ACTUALLY-restored metadata, not the hint: a
            # corrupt primary makes restore_tagged serve the .old crash-
            # window copy, which can be older than a step checkpoint.
            if tag is not None and (latest is None or tag[1] >= latest):
                return accept(tag)
        try:
            state, step = self.checkpoints.restore(template)
        except FileNotFoundError:
            # Steps gone or ALL corrupt: an intact emergency checkpoint —
            # even one OLDER than the (now-quarantined) step numbers that
            # suppressed the preference above — beats stranding the run.
            if tag is None and pmeta is not None:
                tag = tag_candidate()
            if tag is not None:
                return accept(tag)
            raise
        self._surface_restore_fallback()
        # The VERIFIED metadata rides the restore report — re-reading
        # meta.json here would be redundant IO plus a window for an
        # unverified copy to diverge from what restore just checksummed.
        report = getattr(self.checkpoints, "last_restore_report", None) or {}
        meta = report.get("meta") or self.checkpoints.metadata(step)
        if pmeta is not None and tag is None and tag_hint > step:
            # The step side's number was inflated by a checkpoint that the
            # walk-back quarantined; the emergency checkpoint may now be
            # the freshest intact state after all.
            tag = tag_candidate()
        if tag is not None and tag[1] > step:
            return accept(tag)
        if tag is not None:
            log.warning(
                "preemption checkpoint restored at updates=%d is older "
                "than step checkpoint %d; using the step checkpoint",
                tag[1], step)
        return state, step, meta

    # ------------------------------------------------------------------
    # journal-backed replay (learner.journal_replay; SURVEY.md §7.4)
    # ------------------------------------------------------------------

    def _journal_transitions(self, transitions, env_steps: int) -> None:
        """Host-side append of one chunk's transition batch to the durable
        event log. Arrays arrive as (T, B, ...) from the scanned chunk;
        frozen (episode-complete) agent rows are filtered by the validity
        mask before writing. Chunks replayed after a restore (RNG restored,
        identical data) are skipped via the env-step high-water mark so a
        heal never double-journals."""
        if transitions is None or self._transitions_journal is None:
            return
        if env_steps <= self._journal_high_water:
            return
        self._journal_high_water = env_steps
        from sharetrade_tpu.data.transitions import append_transitions
        valid = np.asarray(transitions["valid"]).reshape(-1)
        if not valid.any():
            return
        flat = {k: np.asarray(v).reshape((-1,) + np.asarray(v).shape[2:])
                for k, v in transitions.items() if k != "valid"}
        # Packed binary records (data/transitions.py): ~5x smaller than the
        # JSON encoding and decoded on recovery by one C++/numpy pass.
        append_transitions(
            self._transitions_journal, flat["obs"][valid],
            flat["action"][valid], flat["reward"][valid],
            flat["next_obs"][valid], env_steps=env_steps)
        # Bound the journal: once a buffer's worth of NEW rows accumulated,
        # drop records older than the recoverable tail (2x capacity keeps a
        # full buffer recoverable at any resume cutoff inside the last
        # capacity rows). Record boundaries/stamps survive compaction, so
        # cutoff filtering stays exact. With segment rotation on
        # (data.journal_segment_records) compaction is segment-granular:
        # whole sealed segments older than the horizon are deleted —
        # never a rewrite of live data, never a segment newer than the
        # horizon — and the journal_segments / journal_compacted_bytes
        # telemetry tracks the bound.
        capacity = self.cfg.learner.replay_capacity
        self._journal_rows_since_compact += int(valid.sum())
        segmented = self.cfg.data.journal_segment_records > 0
        if self._journal_rows_since_compact >= capacity:
            if segmented:
                from sharetrade_tpu.data.transitions import (
                    retire_transition_segments)
                retired, freed = retire_transition_segments(
                    self._transitions_journal, 2 * capacity)
                if freed:
                    self.metrics.inc("journal_compacted_bytes_total", freed)
                if retired:
                    self.metrics.inc("journal_segments_retired_total",
                                     retired)
            else:
                from sharetrade_tpu.data.transitions import (
                    compact_transitions)
                compact_transitions(self._transitions_journal, 2 * capacity)
            self._journal_rows_since_compact = 0
        if segmented:
            from sharetrade_tpu.data.journal import segment_paths
            self.metrics.record(
                "journal_segments",
                len(segment_paths(self._transitions_journal.path)) + 1)

    def ingest_actor_feeds(self) -> int:
        """Feed-driven ingest — the learner half of actor/learner
        disaggregation (distrib/): tail every actor's transitions journal
        under ``distrib.actor_dir`` for rows STAMPED past the per-actor
        cursor, splice them into the live device replay buffer
        (oldest-first circular pushes, exactly the ``_warm_start_replay``
        fill path), and reseed PER priorities at the stored max (the
        priorities were never journaled — same contract as a resume).

        Membership is ELASTIC by construction: the journal set is
        re-discovered from the filesystem every call, so an actor that
        joined mid-run starts being ingested at its first committed
        record and a dead actor simply stops producing — the learner
        never needs to know the pool's membership, only its data. Runs on
        the dispatcher thread at a drained boundary (``_boundary_actions``
        cadence ``distrib.ingest_every_updates``), so no dispatch is in
        flight; the step lock fences ``evaluate()`` racers exactly like
        every other state mutation. Returns the rows ingested."""
        if not self._ingest_enabled or self._ts is None:
            return 0
        import glob
        import os
        from sharetrade_tpu.agents.dqn import (
            fill_replay_from_arrays, reseed_per_priorities)
        from sharetrade_tpu.data.transitions import read_new_transitions
        from sharetrade_tpu.distrib.actor import TRANSITIONS_FILE
        root = self.cfg.distrib.actor_dir
        max_rows = (self.cfg.distrib.ingest_max_rows
                    or self.cfg.learner.replay_capacity)
        total = 0
        backlog = False
        per_actor: dict[str, int] = {}
        for path in sorted(glob.glob(
                os.path.join(root, "*", TRANSITIONS_FILE))):
            actor_id = os.path.basename(os.path.dirname(path))
            cursor = self._actor_cursors.get(actor_id, 0)
            try:
                out = read_new_transitions(path, cursor, max_rows)
            except OSError:
                log.exception("actor feed %s unreadable; skipping this "
                              "ingest tick", path)
                continue
            if out is None:
                continue
            obs, action, reward, next_obs, high_water = out
            rows = int(obs.shape[0])
            if rows >= max_rows:
                # A FULL window means the reader truncated: this actor's
                # journal holds more committed rows than one tick may
                # splice — the backlog signal the adaptive cadence
                # tightens on (the rest streams across later ticks, the
                # read_new_transitions oldest-first contract).
                backlog = True
            if rows:
                if obs.shape[1] != self.env.obs_dim:
                    log.error(
                        "actor feed %s obs_dim %d != learner obs_dim %d; "
                        "refusing the rows (actor running a different "
                        "env config?)", path, obs.shape[1],
                        self.env.obs_dim)
                    self._actor_cursors[actor_id] = max(cursor, high_water)
                    continue
                with self._step_lock:
                    extras = self._ts.extras
                    extras = extras.replace(
                        replay=fill_replay_from_arrays(
                            extras.replay, obs, action, reward, next_obs))
                    self._ts = self._ts.replace(extras=extras)
                total += rows
                per_actor[actor_id] = rows
                self.metrics.inc(
                    f"actor_rows_ingested_total_{actor_id}", rows)
            # The cursor advances to the scanned high-water even when no
            # rows were kept (all filtered): stamps are monotone, so
            # nothing committed is ever skipped by advancing.
            self._actor_cursors[actor_id] = max(cursor, high_water)
        if total:
            with self._step_lock:
                # ONE tree rebuild per ingest tick, not per journal
                # (no-op for uniform extras).
                self._ts = self._ts.replace(
                    extras=reseed_per_priorities(self._ts.extras))
            self.metrics.inc("distrib_rows_ingested_total", total)
            self.metrics.record("distrib_actor_feeds", len(per_actor))
            self.events.emit("actor_feed_ingest", rows=total,
                             actors=sorted(per_actor))
            log.info("ingested %d actor transition rows (%s)", total,
                     ", ".join(f"{k}:{v}"
                               for k, v in sorted(per_actor.items())))
        self._adapt_ingest_cadence(total, backlog)
        return total

    #: Adaptive-cadence bounds, as factors of the configured base
    #: cadence: backoff doubles up to base*8 (dry feeds), tightening
    #: halves down to max(1, base/4) (backlog). Class attributes so the
    #: fake-clock tests and the bench name the same contract.
    INGEST_BACKOFF_MAX_FACTOR = 8
    INGEST_TIGHTEN_DIV = 4
    #: Consecutive all-dry ticks before the first backoff step: one dry
    #: tick is a scheduling phase artifact, three is a caught-up learner.
    INGEST_DRY_TICKS = 3

    def _adapt_ingest_cadence(self, rows: int, backlog: bool) -> None:
        """One bounded AIMD step of the live ingest cadence (see the
        ``_ingest_every`` construction comment for the policy). Runs on
        the dispatcher thread right after an ingest tick — the only
        writer of ``_ingest_every``."""
        if not self._adaptive_ingest:
            return
        base = self._ingest_base
        every = self._ingest_every
        new = every
        reason = None
        if rows == 0:
            self._ingest_dry_streak += 1
            if (self._ingest_dry_streak >= self.INGEST_DRY_TICKS
                    and every < base * self.INGEST_BACKOFF_MAX_FACTOR):
                new = min(base * self.INGEST_BACKOFF_MAX_FACTOR, every * 2)
                reason = "feeds_dry"
        else:
            self._ingest_dry_streak = 0
            if backlog:
                floor = max(1, base // self.INGEST_TIGHTEN_DIV)
                if every > floor:
                    new = max(floor, every // 2)
                    reason = "backlog"
            elif every > base:
                # Data is flowing again after a dry backoff: snap back
                # to the configured cadence in one step (a gradual walk
                # down would under-ingest for several boundaries).
                new = base
                reason = "recovered"
        if new == every:
            return
        self._ingest_every = new
        self.metrics.inc("ingest_adjustments_total")
        self.metrics.record("ingest_every_updates_current", float(new))
        self.obs.record("ingest_cadence_adjust", reason=reason,
                        every=new, base=base, rows=rows,
                        backlog=backlog)
        log.info("adaptive ingest cadence: every %d -> %d updates (%s)",
                 every, new, reason)

    def _warm_start_replay(self, state: TrainState) -> TrainState:
        """Rebuild the DQN replay buffer from the transitions journal. The
        journal sees every chunk as it happens while checkpoints lag by the
        save cadence, so after a crash the journal is the fresher (and
        durable) source of truth — the event-sourcing recovery pattern the
        reference applies to price data (SharePriceGetter.scala:55-62),
        applied to experience."""
        if self._transitions_journal is None:
            return state
        from sharetrade_tpu.agents.dqn import (
            ReplayBuffer, fill_replay_from_arrays, fill_replay_from_events)
        from sharetrade_tpu.data.transitions import read_tail_transitions
        capacity = self.cfg.learner.replay_capacity
        cutoff = int(state.env_steps)
        # Legacy JSON "transitions" events (older logs — a pre-rotation
        # journal may carry them INTO its first sealed segment, so the
        # scan covers every segment); binary records are skipped by
        # replay() and decoded below. This stays bounded: segment
        # retirement caps the whole journal near the 2x-capacity horizon,
        # and the binary fast path below walks only the tail segments
        # newest-first (the bounded-recovery fix).
        events = [e for e in self._transitions_journal.replay()
                  if e.get("type") == "transitions"]
        # Packed binary tail (the fast path): one C++/numpy pass returns the
        # capacity-bounded arrays plus the journal's env-step high water.
        # Fill only up to the restored state's env-step count: the chunks
        # between checkpoint and crash re-run with restored RNG and push
        # identical transitions themselves — filling them here too would
        # double-count them in the live buffer. cutoff=0 (fresh init) keeps
        # nothing but still recovers the high-water mark. journal= makes
        # the reader quiesce group-commit/async-writer buffers first, so
        # every append that returned is visible to the tail walk.
        tail = read_tail_transitions(self._transitions_journal.path,
                                     capacity if cutoff > 0 else 1,
                                     cutoff_env_steps=cutoff,
                                     journal=self._transitions_journal)
        # Recover the journaling high-water mark so chunks replayed between
        # the restored checkpoint and the crash point aren't re-journaled.
        self._journal_high_water = max(
            [self._journal_high_water]
            + [e.get("env_steps", 0) for e in events]
            + ([tail[4]] if tail is not None else []))
        fresh = ReplayBuffer.create(capacity, self.env.obs_dim)
        warm = fill_replay_from_events(
            fresh, [e for e in events if e.get("env_steps", 0) <= cutoff])
        if tail is not None and cutoff > 0:
            warm = fill_replay_from_arrays(warm, *tail[:4])
        if int(warm.size) == 0:
            return state            # nothing journaled yet: keep as restored
        log.info("warm-started replay buffer with %d journaled transitions",
                 int(warm.size))
        self.events.emit("replay_warm_started", size=int(warm.size))
        from sharetrade_tpu.agents.dqn import reseed_per_priorities
        # PER mode: priorities are not journaled — the recovered rows
        # re-enter the sum-tree at the checkpointed max priority (no-op
        # for uniform extras).
        return state.replace(extras=reseed_per_priorities(
            state.extras.replace(replay=warm)))

    # ------------------------------------------------------------------
    # queries (IsEverythingDone / GetAvg / GetStd; ShareTradeHelper.scala:35-39)
    # ------------------------------------------------------------------

    def is_everything_done(self) -> QueryReply:
        phase = self.lifecycle.phase
        if phase is Phase.AWAITING_DATA:
            return QueryReply(ReplyState.NO_TRAINING_DATA)
        if phase in (Phase.READY, Phase.TRAINING):
            return QueryReply(ReplyState.TRAINING_NOT_COMPLETED)
        if phase is Phase.FAILED:
            return QueryReply(ReplyState.NOT_COMPUTED)
        return QueryReply(ReplyState.COMPLETED)

    def _drain_pipeline(self) -> None:
        """Barrier for external readers: wait until every boundary enqueued
        so far has been consumed, so ``get_avg``/``get_std``/``snapshot``
        answer from the newest processed chunk — the async pipeline must
        not make queries staler than the synchronous path's sampling
        cadence already allows. No-op when no pipeline is live, from the
        consumer thread itself, or after a consumer fault (the supervision
        path owns recovery)."""
        pl = self._pl
        if pl is not None:
            pl.drain(timeout_s=30.0)

    def _stat(self, key: str, *, trained_only: bool = False) -> QueryReply:
        self._drain_pipeline()
        phase = self.lifecycle.phase
        if phase is Phase.AWAITING_DATA:
            return QueryReply(ReplyState.NO_TRAINING_DATA)
        if phase is Phase.FAILED:
            # A dead run must not serve its stale pre-failure snapshot as a
            # RESULT — the reference's protocol has no reply arm for "here is
            # a number from a run that died" (TrainerRouterActor.scala:15-34),
            # and is_everything_done() already answers NOT_COMPUTED here.
            return QueryReply(ReplyState.NOT_COMPUTED)
        with self._snapshot_lock:
            snap = dict(self._snapshot)
        if trained_only:
            # Reference GetAvg semantics: average only the workers that
            # FINISHED training (it asks the trained list, nobody else —
            # TrainerRouterActor.scala:84-95,137-139). NotComputed until at
            # least one agent's episode cursor reached the horizon.
            if snap.get("trained_workers", 0.0) < 1.0:
                return QueryReply(ReplyState.NOT_COMPUTED)
            key = f"{key}_trained"
        value = snap.get(key)
        if value is None:
            return QueryReply(ReplyState.NOT_COMPUTED)
        # Mid-run replies use the latest chunk snapshot — progressive stats
        # over all agents by default; ``trained_only`` reproduces the
        # reference's completed-workers-at-time-t observable.
        return QueryReply(ReplyState.RESULT, value)

    def get_avg(self, *, trained_only: bool | None = None) -> QueryReply:
        if trained_only is None:
            trained_only = self.cfg.runtime.query_trained_only
        return self._stat("portfolio_mean", trained_only=trained_only)

    def get_std(self, *, trained_only: bool | None = None) -> QueryReply:
        if trained_only is None:
            trained_only = self.cfg.runtime.query_trained_only
        return self._stat("portfolio_std", trained_only=trained_only)

    def snapshot(self) -> dict[str, float]:
        self._drain_pipeline()
        with self._snapshot_lock:
            return dict(self._snapshot)

    def evaluate(self) -> dict[str, float]:
        """Greedy-policy evaluation: replay the episode with argmax actions,
        no exploration, no updates — the measurement the reference never
        separates from training (its portfolio avg mixes ~10% random actions
        even at full epsilon, QDecisionPolicyActor.scala:58-62). Runs one
        scan on the current params; training state is untouched.

        With ``runtime.keep_best_eval`` the evaluated state is retained as
        the ``best`` tagged checkpoint whenever it improves on the best
        eval seen (across resumes — the tag's own metadata seeds the bar):
        on-policy training can find the strategy and then collapse, and
        without retention the collapsed policy is what a user ships."""
        if self.agent is None or self._ts is None:
            raise RuntimeError("no training data / state")
        # Snapshot the state under the step lock (_snapshot_ts): both step
        # paths donate their input, so an external evaluate() racing the
        # training thread's next dispatch could otherwise read donated-dead
        # buffers ("Array has been deleted").
        ts = self._snapshot_ts()
        result = self._evaluate_params(ts.params)
        # The greedy-eval curve lands in the event log so learning progress
        # is auditable after the run (the reference's only observable is the
        # final avg, ShareTradeHelper.scala:46; this is the per-policy
        # learning signal it never records).
        self.events.emit("evaluation", updates=int(ts.updates), **result)
        if self.cfg.runtime.keep_best_eval:
            # Locked check-then-act: the training thread's periodic eval
            # (runtime.eval_every_updates) and a caller thread's explicit
            # evaluate() can race here, and an unguarded compare would let
            # a worse policy overwrite a better tag_best.
            with self._best_eval_lock:
                if self._best_eval is None:
                    prior = self.checkpoints.tagged_metadata("best")
                    self._best_eval = (float(prior["eval_portfolio"])
                                       if prior else float("-inf"))
                if result["eval_portfolio"] > self._best_eval:
                    self._best_eval = result["eval_portfolio"]
                    self.checkpoints.save_tagged(
                        "best", ts,
                        metadata={"eval_portfolio": result["eval_portfolio"],
                                  "updates": int(ts.updates)})
                    self.events.emit(
                        "best_eval_retained",
                        eval_portfolio=result["eval_portfolio"],
                        updates=int(ts.updates))
        return result

    def evaluate_best(self) -> dict[str, float]:
        """Greedy evaluation of the RETAINED best policy (the ``best``
        tagged checkpoint written by :meth:`evaluate` under
        ``runtime.keep_best_eval``) — what a user should ship when the live
        policy has collapsed past its discovery peak. Training state is
        untouched; raises FileNotFoundError when nothing was retained."""
        if self.agent is None or self._ts is None:
            raise RuntimeError("no training data / state")
        template = self.agent.init(jax.random.PRNGKey(self.cfg.seed))
        state, meta = self.checkpoints.restore_tagged(template, "best")
        result = self._evaluate_params(self._place(state).params)
        result["eval_updates"] = float(meta.get("updates", -1))
        return result

    def _evaluate_params(self, params) -> dict[str, float]:
        env = self.env
        horizon = env.num_steps
        # Evaluate in the precision the policy TRAINS in (the compute copy
        # of the fp32 masters — identity in fp32 mode): the shipped
        # numbers should describe the network as it actually runs, and a
        # master-dtype eval would retrace the cached program besides.
        params = self._precision.cast_compute(params)

        # The jitted eval program is cached on the orchestrator (jit caches
        # by function identity — a fresh lambda per call would retrace the
        # full-episode program on every evaluate(), tens of seconds at
        # larger models); send_training_data invalidates it. Both branches
        # are params -> (final_env_state, rewards) so params never freeze
        # into the cached closure.
        if self._eval_fn is None:
            # Evaluate the exact network that was trained (the agent carries
            # its model) — rebuilding from config here would silently
            # evaluate a different architecture whenever a custom model was
            # injected. Resolved only on a cache miss.
            model = self.agent.model
            if model is None:
                from sharetrade_tpu.models import build_model
                from sharetrade_tpu.agents import _HEADS  # registry heads
                model = build_model(self.cfg.model, self.env.obs_dim,
                                    head=_HEADS[self.cfg.learner.algo],
                                    num_actions=self.env.num_actions,
                                    num_assets=self.env.num_assets)
            from sharetrade_tpu.agents.rollout import (
                supports_precomputed_trunk)
            if supports_precomputed_trunk(model, env):
                # Precomputed-trunk greedy replay: the whole episode's
                # trunk is one banded pass (prices are action-independent),
                # vs horizon sequential one-token cache-attention steps —
                # the same inversion the training rollout uses
                # (agents/rollout.py).
                from sharetrade_tpu.agents.rollout import (
                    greedy_rollout_precomputed)
                self._eval_fn = jax.jit(
                    lambda p: greedy_rollout_precomputed(model, env, p))
            else:
                precision = self._precision

                def greedy_scan(p):
                    def body(carry, _):
                        state, model_carry = carry
                        obs = env.observe(state)
                        out, model_carry = model.apply(p, obs, model_carry)
                        action = jnp.argmax(out.logits).astype(jnp.int32)
                        new_state, reward = env.step(state, action)
                        return (new_state, model_carry), reward

                    # The carry seed follows the compute dtype (identity in
                    # fp32): a recurrent model fed bf16 weights writes a
                    # bf16 carry, and an f32 seed would flip the scan
                    # carry's dtype on the first iteration.
                    carry0 = precision.cast_carry(model.init_carry(), model)
                    (final, _), rewards = jax.lax.scan(
                        body, (env.reset(), carry0), None,
                        length=horizon)
                    return final, rewards

                self._eval_fn = jax.jit(greedy_scan)

        final, rewards = self._eval_fn(params)
        return {
            "eval_portfolio": float(env.portfolio_value(final)),
            "eval_reward_sum": float(jnp.sum(rewards)),
        }

    # ------------------------------------------------------------------

    def wait(self, timeout: float | None = None) -> bool:
        """Join the training thread (the driver's poll loop, minus polling)."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        # Queued save_async writes must land before teardown: a stop right
        # after a cadence save would otherwise silently drop it (the writer
        # is a daemon thread — process exit kills it mid-write, and the
        # atomic protocol would roll that checkpoint back to nothing).
        self.checkpoints.wait_pending(timeout=60)
        if self._transitions_journal is not None:
            self._transitions_journal.close()
            self._transitions_journal = None
        # Telemetry teardown LAST: the final exporter drain and trace flush
        # see everything the run wrote, including its shutdown events.
        self.obs.close()

    def _snapshot_ts(self) -> TrainState:
        """Copy the live TrainState under the step lock. Both step paths
        DONATE their input, so any reader racing the training thread's
        next dispatch could observe freed buffers; while the lock is held
        no donating dispatch can be enqueued, and the copies own their
        buffers afterwards. Raises when the state is mid-recovery (a
        failed donated step left dead buffers behind) — the caller should
        retry after the supervision path restores."""
        with self._step_lock:
            if any(getattr(l, "is_deleted", lambda: False)()
                   for l in jax.tree.leaves(self._ts)):
                raise RuntimeError(
                    "training state is recovering from a failed step; "
                    "retry shortly")
            return jax.tree.map(
                lambda x: jnp.copy(x) if hasattr(x, "devices") else x,
                self._ts)

    @property
    def train_state(self) -> TrainState | None:
        """A SNAPSHOT of the live training state (safe against the donated
        step consuming the original buffers mid-read); None before data."""
        if self._ts is None:
            return None
        if self._thread is None or not self._thread.is_alive():
            return self._ts          # no concurrent dispatch: zero-copy
        return self._snapshot_ts()


def run_end_to_end(cfg: FrameworkConfig, prices, *, use_mesh: bool = False,
                   background: bool = False) -> Orchestrator:
    """The ShareTradeHelper main flow: data → orchestrator → train →
    aggregate (ShareTradeHelper.scala:14-48), in one call."""
    mesh = build_mesh(cfg.parallel) if use_mesh else None
    orch = Orchestrator(cfg, mesh=mesh)
    orch.start_training(background=True)   # stashed: data not sent yet
    orch.send_training_data(prices)        # unstashes and launches
    if not background:
        orch.wait()
    return orch
