"""Recurrent (LSTM) actor-critic policy.

No equivalent exists in the reference (its "memory" is the 201-price sliding
window re-fed every step, SURVEY.md §5 "Long-context"); this is the
forward-looking PPO+LSTM configuration from BASELINE.json config 4. The carry
``(h, c)`` threads through the same ``lax.scan`` that carries the env state,
so recurrence costs no extra host round-trips.

The cell computes all four gates as ONE fused (obs+hidden) x 4*hidden matmul —
a single MXU-friendly contraction instead of eight small ones.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.models.core import (Model, ModelOut, compute_dtype,
                                        dense, dense_init)


def lstm_policy(obs_dim: int = 203, hidden_dim: int = 200, num_actions: int = 3,
                *, dtype=jnp.float32) -> Model:
    def init(key):
        k_in, k_cell, k_pi, k_v = jax.random.split(key, 4)
        return {
            "input": dense_init(k_in, obs_dim, hidden_dim, dtype=dtype),
            # fused gate weights: [x ; h] -> (i, f, g, o), each hidden_dim wide
            "gates": dense_init(k_cell, 2 * hidden_dim, 4 * hidden_dim, dtype=dtype),
            "policy": dense_init(k_pi, hidden_dim, num_actions, scale=0.01, dtype=dtype),
            "value": dense_init(k_v, hidden_dim, 1, dtype=dtype),
        }

    def init_carry():
        zeros = jnp.zeros((hidden_dim,), dtype)
        return (zeros, zeros)

    def apply(params, obs, carry):
        # Compute in the handed-in params' dtype (masters or the precision
        # policy's bf16 copy); ``dtype`` above governs only the master init
        # and the carry seed (the policy casts the carry at construction).
        h_prev, c_prev = carry
        x = jax.nn.relu(dense(params["input"],
                              obs.astype(compute_dtype(params))))
        gates = dense(params["gates"], jnp.concatenate([x, h_prev]))
        i, f, g, o = jnp.split(gates, 4)
        c = jax.nn.sigmoid(f + 1.0) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        logits = dense(params["policy"], h).astype(jnp.float32)
        value = dense(params["value"], h).astype(jnp.float32)[0]
        return ModelOut(logits=logits, value=value), (h, c)

    return Model(init=init, apply=apply, init_carry=init_carry,
                 obs_dim=obs_dim, num_actions=num_actions, name="lstm")
