"""Policy networks (L2 compute core).

Reference: the single inline TF graph in QDecisionPolicyActor.scala:38-50.
Here the model zoo is a registry keyed by ``ModelConfig.kind`` so learners are
model-agnostic (SURVEY.md §7.1 item 3: one policy/learner interface covering
the BASELINE.json config ladder).
"""

from __future__ import annotations

import jax.numpy as jnp

from sharetrade_tpu.config import ModelConfig
from sharetrade_tpu.models.core import Model, ModelOut, dense, dense_init  # noqa: F401
from sharetrade_tpu.models.lstm import lstm_policy
from sharetrade_tpu.models.mlp import ac_mlp, q_mlp
from sharetrade_tpu.models.transformer import transformer_policy

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def build_model(cfg: ModelConfig, obs_dim: int, *, head: str = "ac",
                parity: bool = False, num_actions: int | None = None) -> Model:
    """Construct the policy network for ``cfg.kind``.

    ``head="q"`` selects the Q-value head (valid for MLP only — the reference
    network); ``head="ac"`` selects actor-critic heads. ``parity=True`` (with
    kind=mlp, head=q) reproduces the reference graph bit-for-bit in
    architecture: constant 0.1 biases, ReLU output, stddev-1 init.
    ``num_actions`` overrides the config (multi-asset envs widen the head).
    """
    dtype = _DTYPES[cfg.dtype]
    actions = cfg.num_actions if num_actions is None else num_actions
    if cfg.kind == "mlp":
        if head == "q":
            return q_mlp(obs_dim, cfg.hidden_dim, actions,
                         parity=parity, dtype=dtype)
        return ac_mlp(obs_dim, cfg.hidden_dim, actions, dtype=dtype)
    if cfg.kind == "lstm":
        return lstm_policy(obs_dim, cfg.hidden_dim, actions, dtype=dtype)
    if cfg.kind == "transformer":
        return transformer_policy(
            obs_dim, actions, num_layers=cfg.num_layers,
            num_heads=cfg.num_heads, head_dim=cfg.head_dim, dtype=dtype)
    raise ValueError(f"unknown model kind {cfg.kind!r}")
