"""Policy networks (L2 compute core).

Reference: the single inline TF graph in QDecisionPolicyActor.scala:38-50.
Here the model zoo is a registry keyed by ``ModelConfig.kind`` so learners are
model-agnostic (SURVEY.md §7.1 item 3: one policy/learner interface covering
the BASELINE.json config ladder).
"""

from __future__ import annotations

import jax.numpy as jnp

from sharetrade_tpu.config import ConfigError, ModelConfig
from sharetrade_tpu.models.core import Model, ModelOut, dense, dense_init  # noqa: F401
from sharetrade_tpu.models.lstm import lstm_policy
from sharetrade_tpu.models.mlp import ac_mlp, q_mlp
from sharetrade_tpu.models.transformer import transformer_policy

#: Master-weight dtypes a config may request. ``bfloat16`` is DELIBERATELY
#: absent: the old whole-model cast put params, gradients AND optimizer
#: accumulators in bf16 with no warning — the convergence-hostile
#: configuration the precision policy (precision.py) replaces. The
#: migration error below names the new knob.
_DTYPES = {"float32": jnp.float32}

_BF16_MIGRATION = (
    "model.dtype='bfloat16' has been removed: the whole-model cast "
    "silently put optimizer state and master weights in bf16 (a "
    "convergence-hostile configuration). Set precision.mode='bf16_mixed' "
    "instead — bf16 compute with fp32 master weights, f32 matmul "
    "accumulation, and f32 optimizer updates (see README 'Precision "
    "policy'). Model params now always initialize as fp32 masters; the "
    "precision policy casts the compute copy at each update boundary.")


def _validate_moe_dispatch(cfg: ModelConfig, ep_mesh) -> None:
    """MoE dispatch validation shared by the window and episode branches."""
    if cfg.moe_dispatch not in ("psum", "a2a"):
        raise ConfigError(
            f"unknown model.moe_dispatch {cfg.moe_dispatch!r} "
            "(expected 'psum' or 'a2a')")
    if cfg.moe_dispatch == "a2a" and cfg.moe_experts:
        if not cfg.moe_top_k:
            raise ConfigError(
                "model.moe_dispatch='a2a' is a top-k dispatch pattern; "
                "set model.moe_top_k>0 (the dense-mask top-1 scheme has "
                "no capacity buffers to all_to_all)")
        if ep_mesh is None:
            raise ConfigError(
                "model.moe_dispatch='a2a' needs a mesh with an 'ep' "
                "axis (set parallel.mesh_shape, e.g. "
                "{\"dp\": 2, \"ep\": 4})")


def build_model(cfg: ModelConfig, obs_dim: int, *, head: str = "ac",
                parity: bool = False, num_actions: int | None = None,
                mesh=None, num_assets: int = 1) -> Model:
    """Construct the policy network for ``cfg.kind``.

    ``head="q"`` selects the Q-value head (valid for MLP only — the reference
    network); ``head="ac"`` selects actor-critic heads. ``parity=True`` (with
    kind=mlp, head=q) reproduces the reference graph bit-for-bit in
    architecture: constant 0.1 biases, ReLU output, stddev-1 init.
    ``num_actions`` overrides the config (multi-asset envs widen the head).
    ``mesh`` enables the partitioned transformer paths: ``cfg.attention=
    "ring"`` rings attention over its sp axis; ``cfg.pipeline_blocks``
    pipelines the blocks over its pp axis. ``num_assets`` > 1 selects the
    window transformer's per-asset-block tokenization over the portfolio
    observation layout (episode mode stays single-asset — PARITY.md).
    """
    if cfg.dtype == "bfloat16":
        raise ConfigError(_BF16_MIGRATION)
    if cfg.dtype not in _DTYPES:
        raise ConfigError(f"unknown model.dtype {cfg.dtype!r}; "
                          f"choose from {sorted(_DTYPES)} "
                          "(low precision is precision.mode's job)")
    dtype = _DTYPES[cfg.dtype]
    actions = cfg.num_actions if num_actions is None else num_actions
    if cfg.seq_mode not in ("window", "episode"):
        raise ConfigError(f"unknown model.seq_mode {cfg.seq_mode!r}")
    if cfg.seq_mode == "episode" and cfg.kind != "transformer":
        raise ConfigError(
            f"model.seq_mode='episode' is a transformer mode; "
            f"model.kind={cfg.kind!r} would silently ignore it")
    if cfg.remat_blocks and not (cfg.kind == "transformer"
                                 and cfg.seq_mode == "episode"):
        raise ConfigError(
            "model.remat_blocks applies to the episode-mode transformer's "
            "banded replay only; other models would silently ignore it — "
            "use learner.remat for the window/fold replay paths")
    if cfg.kind == "mlp":
        if head == "q":
            return q_mlp(obs_dim, cfg.hidden_dim, actions,
                         parity=parity, dtype=dtype)
        return ac_mlp(obs_dim, cfg.hidden_dim, actions, dtype=dtype)
    if cfg.kind == "lstm":
        return lstm_policy(obs_dim, cfg.hidden_dim, actions, dtype=dtype)
    if cfg.kind == "tcn":
        if num_assets > 1:
            # Same loud boundary the episode transformer gets: a TCN built
            # over the portfolio layout would silently convolve asset-1
            # prices, the budget, and the share counts as one window.
            raise ConfigError(
                "model.kind='tcn' is single-asset (PARITY.md); use the "
                "window transformer, mlp, or lstm for multi-asset "
                "portfolios")
        from sharetrade_tpu.models.tcn import tcn_policy
        return tcn_policy(obs_dim, actions, channels=cfg.hidden_dim,
                          dtype=dtype)
    if cfg.kind == "transformer":
        attention_fn = None
        pp_mesh = None
        batch_axis = (  # agent batch rides dp when the mesh has it
            "dp" if mesh is not None and "dp" in mesh.axis_names else None)
        # A non-TPU mesh (the virtual-CPU test/dryrun client) can't lower the
        # Pallas kernel; the XLA reference path is numerically identical.
        from sharetrade_tpu.parallel.mesh import (
            has_shard_map_axis as _has_shard_map_axis, mesh_platform)
        use_pallas = (False if mesh is not None
                      and mesh_platform(mesh) != "tpu" else None)
        if cfg.seq_mode == "episode":
            if num_assets > 1:
                raise ConfigError(
                    "model.seq_mode='episode' is single-asset: its shared-"
                    "trunk design amortizes ONE tick stream across the "
                    "agent batch (see PARITY.md); use seq_mode='window' "
                    "for multi-asset portfolios")
            if cfg.attention not in ("flash", "ring"):
                raise ConfigError(
                    "model.seq_mode='episode' supports attention='flash' "
                    "(local banded) or 'ring' (the sp halo exchange — "
                    "episode mode's sequence-parallel scheme); ulysses is "
                    "window-mode only")
            episode_attention = None
            if cfg.attention == "ring":
                if mesh is None or "sp" not in mesh.axis_names:
                    raise ConfigError(
                        "model.attention='ring' needs a mesh with an 'sp' "
                        "axis (set parallel.mesh_shape, e.g. "
                        "{\"dp\": 2, \"sp\": 4})")
                if cfg.pipeline_blocks:
                    raise ConfigError(
                        "model.attention='ring' + model.pipeline_blocks is "
                        "unsupported (no sp attention inside a pipeline "
                        "stage); pick one partitioning")
                from sharetrade_tpu.parallel.episode_sp import (
                    halo_banded_attention_sharded)
                episode_attention = halo_banded_attention_sharded(
                    mesh, seq_axis="sp", batch_axis=batch_axis,
                    use_pallas=use_pallas)
            ep_pp_mesh = None
            if cfg.pipeline_blocks:
                if mesh is None or "pp" not in mesh.axis_names:
                    raise ConfigError(
                        "model.pipeline_blocks needs a mesh with a 'pp' "
                        "axis (set parallel.mesh_shape, e.g. "
                        "{\"dp\": 2, \"pp\": 4})")
                ep_pp_mesh = mesh
            ep_mesh = (mesh if cfg.moe_experts and mesh is not None
                       and "ep" in mesh.axis_names else None)
            _validate_moe_dispatch(cfg, ep_mesh)
            from sharetrade_tpu.models.transformer_episode import (
                episode_transformer_policy)
            return episode_transformer_policy(
                obs_dim, actions, num_layers=cfg.num_layers,
                num_heads=cfg.num_heads, head_dim=cfg.head_dim, dtype=dtype,
                use_pallas=use_pallas, attention_fn=episode_attention,
                pp_mesh=ep_pp_mesh, pp_batch_axis=batch_axis,
                moe_experts=cfg.moe_experts, ep_mesh=ep_mesh,
                moe_top_k=cfg.moe_top_k,
                moe_capacity_factor=cfg.moe_capacity_factor,
                moe_dispatch=cfg.moe_dispatch,
                remat_blocks=cfg.remat_blocks,
                # The carry→series seam pin applies exactly where a
                # shard_map-partitioned path (sp halo attention, ep MoE
                # dispatch) can propagate a transposed-mesh spec backward
                # onto the dp-sharded hist carry; meshes without those
                # axes compile clean already and keep their exact
                # programs (mesh.has_shard_map_axis — the same scope
                # predicate as PPO's rollout→update seam).
                seam_mesh=(mesh if _has_shard_map_axis(mesh) else None))
        if cfg.attention in ("ring", "ulysses"):
            if mesh is None or "sp" not in mesh.axis_names:
                raise ConfigError(
                    f"model.attention={cfg.attention!r} needs a mesh with an "
                    "'sp' axis (set parallel.mesh_shape, e.g. "
                    "{\"dp\": 2, \"sp\": 4})")
            if cfg.attention == "ring":
                from sharetrade_tpu.parallel.ring_attention import (
                    ring_attention_sharded)
                attention_fn = ring_attention_sharded(
                    mesh, seq_axis="sp", batch_axis=batch_axis)
            else:
                from sharetrade_tpu.parallel.ulysses import (
                    ulysses_attention_sharded)
                attention_fn = ulysses_attention_sharded(
                    mesh, seq_axis="sp", batch_axis=batch_axis,
                    use_pallas=use_pallas)
        elif cfg.attention != "flash":
            raise ConfigError(f"unknown model.attention {cfg.attention!r}")
        if cfg.pipeline_blocks:
            if mesh is None or "pp" not in mesh.axis_names:
                raise ConfigError(
                    "model.pipeline_blocks needs a mesh with a 'pp' axis "
                    "(set parallel.mesh_shape, e.g. {\"dp\": 2, \"pp\": 4})")
            if cfg.attention != "flash":
                raise ConfigError(
                    f"model.attention={cfg.attention!r} + "
                    "model.pipeline_blocks is unsupported (nested "
                    "shard_maps); pick one partitioning")
            pp_mesh = mesh
        # Experts shard over ep when the mesh has that axis; otherwise the
        # expert bank runs single-device (still trainable — the mechanism's
        # reachability doesn't depend on the mesh).
        ep_mesh = (mesh if cfg.moe_experts and mesh is not None
                   and "ep" in mesh.axis_names else None)
        _validate_moe_dispatch(cfg, ep_mesh)
        return transformer_policy(
            obs_dim, actions, num_layers=cfg.num_layers,
            num_heads=cfg.num_heads, head_dim=cfg.head_dim, dtype=dtype,
            use_pallas=use_pallas, attention_fn=attention_fn,
            pp_mesh=pp_mesh, pp_batch_axis=batch_axis,
            moe_experts=cfg.moe_experts, ep_mesh=ep_mesh,
            moe_top_k=cfg.moe_top_k,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_dispatch=cfg.moe_dispatch, num_assets=num_assets)
    raise ConfigError(f"unknown model kind {cfg.kind!r}")
