"""MLP policy networks.

``q_mlp`` reproduces the reference's network exactly
(QDecisionPolicyActor.scala:38-50):

    h1 = relu(x @ w1 + 0.1)      w1: (203, 200), RandomNormal init
    q  = relu(h1 @ w2 + 0.1)     w2: (200, 3),   RandomNormal init

Two faithful oddities, kept behind ``parity=True`` (the default matches the
reference so numeric-parity tests are possible; ``parity=False`` gives the
conventional variant):

- the biases are *constants* (``tf.constant(0.1)``), not trained variables —
  only w1/w2 receive gradients;
- the output layer is ReLU'd, clamping Q-values at 0.

``ac_mlp`` is the actor-critic generalization (policy logits + value head)
used by the PG/A2C/PPO learners (SURVEY.md §7.1 item 3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.models.core import (Model, ModelOut, compute_dtype,
                                        dense, dense_init)


def q_mlp(obs_dim: int = 203, hidden_dim: int = 200, num_actions: int = 3,
          *, parity: bool = True, dtype=jnp.float32) -> Model:
    """The reference Q-network. ``parity=True`` = constant 0.1 biases +
    ReLU output + stddev-1.0 normal init (QDecisionPolicyActor.scala:41-47)."""

    scale = 1.0 if parity else None

    def init(key):
        k1, k2 = jax.random.split(key)
        p1 = dense_init(k1, obs_dim, hidden_dim, scale=scale, dtype=dtype)
        p2 = dense_init(k2, hidden_dim, num_actions, scale=scale, dtype=dtype)
        if parity:
            # Constant biases: drop them from the trainable pytree entirely;
            # apply() adds the 0.1 inline (reference b1/b2 are tf.constant).
            p1 = {"w": p1["w"]}
            p2 = {"w": p2["w"]}
        return {"layer1": p1, "layer2": p2}

    def apply(params, obs, carry):
        # Compute in the dtype of the params actually handed in (the fp32
        # masters, or the precision policy's bf16 copy) — the build-time
        # ``dtype`` governs only the master init above.
        dtype = compute_dtype(params)
        x = obs.astype(dtype)
        if parity:
            h = jax.nn.relu(
                jnp.dot(x, params["layer1"]["w"], preferred_element_type=jnp.float32)
                .astype(dtype) + jnp.asarray(0.1, dtype)
            )
            q = jax.nn.relu(
                jnp.dot(h, params["layer2"]["w"], preferred_element_type=jnp.float32)
                .astype(dtype) + jnp.asarray(0.1, dtype)
            )
        else:
            h = jax.nn.relu(dense(params["layer1"], x))
            q = dense(params["layer2"], h)  # no output ReLU: unclamped Q-values
        out = ModelOut(logits=q.astype(jnp.float32), value=jnp.float32(0.0))
        return out, carry

    return Model(init=init, apply=apply, obs_dim=obs_dim,
                 num_actions=num_actions, name="q_mlp")


def ac_mlp(obs_dim: int = 203, hidden_dim: int = 200, num_actions: int = 3,
           *, dtype=jnp.float32) -> Model:
    """Two-layer torso with separate policy and value heads."""

    def init(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {
            "torso1": dense_init(k1, obs_dim, hidden_dim, dtype=dtype),
            "torso2": dense_init(k2, hidden_dim, hidden_dim, dtype=dtype),
            "policy": dense_init(k3, hidden_dim, num_actions, scale=0.01, dtype=dtype),
            "value": dense_init(k4, hidden_dim, 1, dtype=dtype),
        }

    def apply(params, obs, carry):
        x = obs.astype(compute_dtype(params))
        h = jax.nn.relu(dense(params["torso1"], x))
        h = jax.nn.relu(dense(params["torso2"], h))
        logits = dense(params["policy"], h).astype(jnp.float32)
        value = dense(params["value"], h).astype(jnp.float32)[0]
        return ModelOut(logits=logits, value=value), carry

    return Model(init=init, apply=apply, obs_dim=obs_dim,
                 num_actions=num_actions, name="ac_mlp")
