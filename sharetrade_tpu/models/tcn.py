"""Temporal convolutional tick policy (TCN).

A third sequence-model family beside the LSTM and the transformer: stacked
dilated CAUSAL 1-D convolutions over the tokenized price window, receptive
field doubling per block until it covers the whole window. On TPU the
channels-last convolutions lower to MXU matmuls (an NWC conv with C_in x
C_out filters is a batched matmul per tap), so the whole forward is
MXU-resident with no recurrence — unlike the LSTM there is no sequential
carry, and unlike the transformer there is no O(W^2) score matrix at all.

The reference has one model (the 203->200->3 MLP,
QDecisionPolicyActor.scala:38-47); the model zoo generalizes it (SURVEY.md
§7.1 item 3). The TCN shares the window-mode transformer's tokenization
(scale-invariant per-tick features; models/transformer.py) and the
episode-mode head design (portfolio injected at the head,
models/transformer_episode.py): market features come from the conv stack's
last position, then a learned projection of (budget, shares) joins before
the policy/value heads.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from sharetrade_tpu.models.core import (
    Model, ModelOut, compute_dtype, dense, dense_init, portfolio_features,
    tick_window_features)

KERNEL = 3


def default_num_blocks(window: int) -> int:
    """Blocks needed for the dilated receptive field 1 + (K-1)*(2^B - 1)
    to cover ``window`` (shared with the FLOP accounting, utils/flops.py)."""
    return max(1, math.ceil(
        math.log2(max((window - 1) / (KERNEL - 1) + 1, 2))))


def _conv_init(key, kernel: int, c_in: int, c_out: int, dtype):
    """He-normal (W, I, O) filter + bias."""
    std = math.sqrt(2.0 / (kernel * c_in))
    w = jax.random.normal(key, (kernel, c_in, c_out), dtype) * jnp.asarray(
        std, dtype)
    return {"w": w, "b": jnp.zeros((c_out,), dtype)}


def _causal_conv(p, x, dilation: int):
    """(B, W, C_in) -> (B, W, C_out), left-padded so position t sees only
    positions <= t (standard causal dilated conv)."""
    pad = (KERNEL - 1) * dilation
    if x.dtype == jnp.bfloat16:
        # No preferred_element_type on the bf16 path: conv's TRANSPOSE rule
        # (unlike dot_general's) rebuilds a conv between the bf16 primal
        # and the f32 cotangent of the pre-cast output and rejects the
        # dtype mix — a trace-time TypeError under value_and_grad. A plain
        # bf16 conv differentiates cleanly, and the TPU MXU accumulates
        # bf16 convolutions in f32 internally regardless.
        out = jax.lax.conv_general_dilated(
            x, p["w"], window_strides=(1,), padding=[(pad, 0)],
            rhs_dilation=(dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        return out + p["b"]
    out = jax.lax.conv_general_dilated(
        x, p["w"], window_strides=(1,), padding=[(pad, 0)],
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=jnp.float32)
    return out.astype(x.dtype) + p["b"]


def tcn_policy(obs_dim: int = 203, num_actions: int = 3, *,
               channels: int = 64, num_blocks: int | None = None,
               dtype=jnp.float32) -> Model:
    """Build the TCN policy (``ModelConfig.kind="tcn"``).

    ``num_blocks=None`` auto-sizes the stack so the dilated receptive field
    ``1 + (K-1)*(2^B - 1)`` covers the whole price window.
    """
    window = obs_dim - 2
    if num_blocks is None:
        num_blocks = default_num_blocks(window)

    def init(key):
        keys = jax.random.split(key, 4 + 2 * num_blocks)
        params = {
            "embed": dense_init(keys[0], 3, channels, dtype=dtype),
            "port": dense_init(keys[1], 3, channels, scale=0.02, dtype=dtype),
            "policy": dense_init(keys[2], channels, num_actions, scale=0.01,
                                 dtype=dtype),
            "value": dense_init(keys[3], channels, 1, dtype=dtype),
            "blocks": [],
        }
        for i in range(num_blocks):
            params["blocks"].append({
                "conv": _conv_init(keys[4 + 2 * i], KERNEL, channels,
                                   channels, dtype),
                "mix": dense_init(keys[5 + 2 * i], channels, channels,
                                  scale=0.02, dtype=dtype),
            })
        return params

    def apply_batch(params, obs, carry):
        # Compute dtype follows the handed-in params (masters or the
        # precision policy's bf16 copy); build-time ``dtype`` = master init.
        dtype = compute_dtype(params)
        tokens = tick_window_features(obs, window)               # (B, W, 3)
        x = dense(params["embed"], tokens.astype(dtype))         # (B, W, C)
        for i, blk in enumerate(params["blocks"]):
            h = jax.nn.gelu(_causal_conv(blk["conv"], x, dilation=2 ** i))
            x = x + dense(blk["mix"], h)
        summary = x[:, -1]                                       # (B, C)
        port = portfolio_features(
            obs[:, window], obs[:, window + 1], obs[:, window - 1])
        summary = summary + dense(params["port"], port.astype(dtype))
        logits = dense(params["policy"], summary).astype(jnp.float32)
        value = dense(params["value"], summary).astype(jnp.float32)[:, 0]
        return ModelOut(logits=logits, value=value,
                        aux=jnp.float32(0.0)), carry

    def apply(params, obs, carry):
        outs, carry = apply_batch(params, obs[None], carry)
        return ModelOut(logits=outs.logits[0], value=outs.value[0],
                        aux=outs.aux), carry

    return Model(init=init, apply=apply, apply_batch=apply_batch,
                 obs_dim=obs_dim, num_actions=num_actions, name="tcn")
