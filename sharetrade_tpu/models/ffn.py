"""The transformer block's FFN half: dense MLP or mixture-of-experts.

One dispatch helper shared by BOTH transformer families (window mode,
models/transformer.py; episode mode, models/transformer_episode.py) so the
MoE routing variants — dense-mask top-1, capacity top-k, their ep-sharded
psum forms, and the token-sharded all_to_all dispatch (parallel/moe.py) —
cannot drift between them. The reference has a single dense 2-layer MLP and
no MoE at all (SURVEY.md §2.2 lists EP as absent); this is the forward-
looking expert-parallel capability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.models.core import dense


def ffn_apply(blk: dict, h: jax.Array, *, moe_experts: int = 0,
              ep_mesh=None, ep_axis: str = "ep", moe_top_k: int = 0,
              moe_capacity_factor: float = 1.25,
              moe_dispatch: str = "psum",
              batch_axis: str | None = None):
    """Apply the block's FFN to ``h`` (..., d) under the residual's LN2.

    Returns ``(y, aux)`` — ``y`` matches ``h``'s shape; ``aux`` is the MoE
    load-balance loss (0.0 for the dense path), which models surface via
    ``ModelOut.aux`` and learners weight by ``LearnerConfig.aux_loss_coef``
    (essential for the dropping schemes, where a collapsed gate silently
    zeroes overflow tokens).
    """
    if not moe_experts:
        return (dense(blk["mlp_out"], jax.nn.gelu(dense(blk["mlp_in"], h))),
                jnp.float32(0.0))
    from sharetrade_tpu.parallel import moe as moe_lib
    d_model = h.shape[-1]
    flat = h.reshape(-1, d_model)
    if moe_top_k:          # capacity-bucketed top-k dispatch
        if ep_mesh is not None and moe_dispatch == "a2a":
            # Token-sharded all_to_all dispatch: pad the token count to a
            # multiple of ep (pad rows are marked invalid — no buffer
            # slots, no balance-stat contribution), slice real rows back.
            ep = ep_mesh.shape[ep_axis]
            n = flat.shape[0]
            pad = (-n) % ep
            y, aux = moe_lib.moe_apply_topk_a2a(
                blk["moe"],
                jnp.pad(flat, ((0, pad), (0, 0))) if pad else flat,
                ep_mesh, axis=ep_axis, top_k=moe_top_k,
                capacity_factor=moe_capacity_factor,
                n_valid=n if pad else None)
            y = y[:n] if pad else y
        elif ep_mesh is not None:
            y, aux = moe_lib.moe_apply_topk_sharded(
                blk["moe"], flat, ep_mesh, axis=ep_axis,
                top_k=moe_top_k, capacity_factor=moe_capacity_factor,
                batch_axis=batch_axis)
        else:
            y, aux = moe_lib.moe_apply_topk(
                blk["moe"], flat, top_k=moe_top_k,
                capacity_factor=moe_capacity_factor)
    elif ep_mesh is not None:
        y, aux = moe_lib.moe_apply_sharded(
            blk["moe"], flat, ep_mesh, axis=ep_axis, batch_axis=batch_axis)
    else:
        y, aux = moe_lib.moe_apply(blk["moe"], flat)
    return y.reshape(h.shape), aux
