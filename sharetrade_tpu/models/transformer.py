"""Transformer tick-series policy (BASELINE.json config 5).

Treats the observation's price window as a *sequence* instead of a flat
feature vector — the long-context capability the reference lacks entirely
(SURVEY.md §5: windows iterated, never modeled as sequences). Each tick
becomes a token carrying (price, log-return, position); the (budget, shares)
portfolio scalars are appended as a final summary token whose output embedding
feeds the policy/value heads. Causal attention runs through the Pallas flash
kernel on TPU (sharetrade_tpu/ops/attention.py).

Prices are normalized by the window's last price so the policy is
scale-invariant across decades of price levels (the 1992 MSFT window differs
from 2015's by an order of magnitude).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.models.core import Model, ModelOut, dense, dense_init
from sharetrade_tpu.ops.attention import flash_attention


def _layer_norm(x, scale, bias, eps=1e-6):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def transformer_policy(obs_dim: int = 203, num_actions: int = 3, *,
                       num_layers: int = 2, num_heads: int = 4,
                       head_dim: int = 64, mlp_ratio: int = 4,
                       dtype=jnp.float32, use_pallas: bool | None = None) -> Model:
    window = obs_dim - 2           # price ticks; final token holds the portfolio
    seq_len = window + 1
    d_model = num_heads * head_dim

    def init(key):
        keys = jax.random.split(key, 4 + 6 * num_layers)
        params = {
            "embed": dense_init(keys[0], 3, d_model, dtype=dtype),
            "pos": jax.random.normal(keys[1], (seq_len, d_model), dtype) * 0.02,
            "policy": dense_init(keys[2], d_model, num_actions, scale=0.01, dtype=dtype),
            "value": dense_init(keys[3], d_model, 1, dtype=dtype),
            "blocks": [],
            "final_ln": {"scale": jnp.ones((d_model,), dtype),
                         "bias": jnp.zeros((d_model,), dtype)},
        }
        for i in range(num_layers):
            k = keys[4 + 6 * i: 4 + 6 * (i + 1)]
            params["blocks"].append({
                "ln1": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
                "qkv": dense_init(k[0], d_model, 3 * d_model, dtype=dtype),
                "proj": dense_init(k[1], d_model, d_model,
                                   scale=0.02 / max(num_layers, 1), dtype=dtype),
                "ln2": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
                "mlp_in": dense_init(k[2], d_model, mlp_ratio * d_model, dtype=dtype),
                "mlp_out": dense_init(k[3], mlp_ratio * d_model, d_model,
                                      scale=0.02 / max(num_layers, 1), dtype=dtype),
            })
        return params

    def tokenize(obs):
        """(B, obs_dim) -> (B, seq, 3) token features."""
        prices = obs[:, :window].astype(jnp.float32)
        budget, shares = obs[:, window], obs[:, window + 1]
        anchor = jnp.maximum(prices[:, -1:], 1e-6)               # (B, 1)
        rel = prices / anchor - 1.0
        logp = jnp.log(jnp.maximum(prices, 1e-6))
        log_ret = jnp.concatenate(
            [jnp.zeros_like(logp[:, :1]), logp[:, 1:] - logp[:, :-1]], axis=1)
        tick_tokens = jnp.stack(
            [rel, log_ret, jnp.zeros_like(rel)], axis=-1)        # (B, window, 3)
        portfolio_token = jnp.stack(
            [budget / (anchor[:, 0] * 100.0), shares / 100.0,
             jnp.ones_like(budget)], axis=-1)                    # (B, 3)
        return jnp.concatenate([tick_tokens, portfolio_token[:, None, :]], axis=1)

    def apply_batch(params, obs, carry):
        """Native batched forward: the whole agent batch rides one flash
        kernel call per layer with a batch*heads grid — no batch-1 programs
        (the round-1 pathology: per-agent vmapped kernel invocations)."""
        bsz = obs.shape[0]
        tokens = tokenize(obs).astype(dtype)
        x = dense(params["embed"], tokens) + params["pos"]       # (B, seq, d)
        for blk in params["blocks"]:
            h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
            qkv = dense(blk["qkv"], h).reshape(
                bsz, seq_len, 3, num_heads, head_dim)
            # kernel expects (batch, heads, seq, head_dim)
            q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
            attn = flash_attention(q, k, v, causal=True, use_pallas=use_pallas)
            attn = attn.transpose(0, 2, 1, 3).reshape(
                bsz, seq_len, d_model).astype(dtype)
            x = x + dense(blk["proj"], attn)
            h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
            x = x + dense(blk["mlp_out"], jax.nn.gelu(dense(blk["mlp_in"], h)))
        summary = _layer_norm(x[:, -1], params["final_ln"]["scale"],
                              params["final_ln"]["bias"])
        logits = dense(params["policy"], summary).astype(jnp.float32)
        value = dense(params["value"], summary).astype(jnp.float32)[:, 0]
        return ModelOut(logits=logits, value=value), carry

    def apply(params, obs, carry):
        outs, carry = apply_batch(params, obs[None], carry)
        return ModelOut(logits=outs.logits[0], value=outs.value[0]), carry

    return Model(init=init, apply=apply, apply_batch=apply_batch,
                 obs_dim=obs_dim, num_actions=num_actions, name="transformer")
