"""Transformer tick-series policy (BASELINE.json config 5).

Treats the observation's price window as a *sequence* instead of a flat
feature vector — the long-context capability the reference lacks entirely
(SURVEY.md §5: windows iterated, never modeled as sequences). Each tick
becomes a token carrying (price, log-return, position); the (budget, shares)
portfolio scalars are appended as a final summary token whose output embedding
feeds the policy/value heads. Causal attention runs through the Pallas flash
kernel on TPU (sharetrade_tpu/ops/attention.py).

Prices are normalized by the window's last price so the policy is
scale-invariant across decades of price levels (the 1992 MSFT window differs
from 2015's by an order of magnitude).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sharetrade_tpu.config import ConfigError

from sharetrade_tpu.models.core import (
    Model, ModelOut, compute_dtype, dense, dense_init, portfolio_features,
    tick_window_features)
from sharetrade_tpu.models.ffn import ffn_apply
from sharetrade_tpu.ops.attention import flash_attention


def _layer_norm(x, scale, bias, eps=1e-6):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def transformer_policy(obs_dim: int = 203, num_actions: int = 3, *,
                       num_layers: int = 2, num_heads: int = 4,
                       head_dim: int = 64, mlp_ratio: int = 4,
                       dtype=jnp.float32, use_pallas: bool | None = None,
                       attention_fn=None, pp_mesh=None, pp_axis: str = "pp",
                       pp_batch_axis: str | None = None,
                       moe_experts: int = 0, ep_mesh=None,
                       ep_axis: str = "ep", moe_top_k: int = 0,
                       moe_capacity_factor: float = 1.25,
                       moe_dispatch: str = "psum",
                       num_assets: int = 1) -> Model:
    """``attention_fn(q, k, v) -> out`` overrides the local flash kernel —
    the sequence-parallel hook (e.g. ``ring_attention_sharded`` binds a mesh
    so attention rings over the sp axis, parallel/ring_attention.py).

    ``pp_mesh`` pipelines the transformer blocks over that mesh's
    ``pp_axis`` (GPipe microbatch schedule, parallel/pipeline.py): one block
    per stage, so ``num_layers`` must equal the pp size. Blocks are then
    stored stacked (leading dim = num_layers) so stage i's slice shards onto
    pp-device i. ``pp_batch_axis`` names the mesh axis the agent batch is
    sharded over (usually "dp") so microbatches keep that sharding.

    ``num_assets`` > 1 tokenizes the multi-asset portfolio observation
    (env/portfolio.py: A windows ++ budget ++ A share counts) as A
    per-asset blocks of [window tick tokens | portfolio token], each block
    tagged with a learned asset embedding; positions tile per block and the
    policy/value summary averages the A portfolio-token outputs. At A=1
    this degenerates EXACTLY to the single-asset layout (same parameters,
    same sequence), so checkpoints stay compatible."""
    if num_assets < 1:
        raise ConfigError(f"num_assets must be >= 1, got {num_assets}")
    window = (obs_dim - 1 - num_assets) // num_assets
    if num_assets * window + 1 + num_assets != obs_dim:
        raise ConfigError(
            f"obs_dim={obs_dim} does not match the {num_assets}-asset "
            f"portfolio layout (A*window + 1 + A)")
    seq_len = num_assets * (window + 1)
    d_model = num_heads * head_dim
    if attention_fn is None:
        attention_fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, use_pallas=use_pallas)
    if pp_mesh is not None and pp_mesh.shape[pp_axis] != num_layers:
        raise ConfigError(
            f"pipeline_blocks needs num_layers == pp size "
            f"({num_layers} != {pp_mesh.shape[pp_axis]})")
    if moe_experts and pp_mesh is not None:
        raise ConfigError("pipeline_blocks + moe_experts is unsupported "
                         "(nested shard_maps); pick one partitioning")

    def init(key):
        # The asset embedding (A>1 only) draws from an extra TRAILING key:
        # split(key, n) is prefix-stable in n, so single-asset configs
        # reproduce the exact same weights per seed as before the
        # multi-asset feature existed.
        keys = jax.random.split(
            key, 4 + 6 * num_layers + (1 if num_assets > 1 else 0))
        params = {
            "embed": dense_init(keys[0], 3, d_model, dtype=dtype),
            # Within-block positions, tiled per asset block at apply time
            # (A=1: exactly the old full-sequence table).
            "pos": jax.random.normal(
                keys[1], (window + 1, d_model), dtype) * 0.02,
            "policy": dense_init(keys[2], d_model, num_actions, scale=0.01, dtype=dtype),
            "value": dense_init(keys[3], d_model, 1, dtype=dtype),
            "blocks": [],
            "final_ln": {"scale": jnp.ones((d_model,), dtype),
                         "bias": jnp.zeros((d_model,), dtype)},
        }
        if num_assets > 1:
            params["asset"] = jax.random.normal(
                keys[-1], (num_assets, d_model), dtype) * 0.02
        for i in range(num_layers):
            k = keys[4 + 6 * i: 4 + 6 * (i + 1)]
            block = {
                "ln1": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
                "qkv": dense_init(k[0], d_model, 3 * d_model, dtype=dtype),
                "proj": dense_init(k[1], d_model, d_model,
                                   scale=0.02 / max(num_layers, 1), dtype=dtype),
                "ln2": {"scale": jnp.ones((d_model,), dtype),
                        "bias": jnp.zeros((d_model,), dtype)},
            }
            if moe_experts:
                from sharetrade_tpu.parallel.moe import init_moe_params
                block["moe"] = init_moe_params(
                    k[2], moe_experts, d_model, mlp_ratio * d_model,
                    dtype=dtype)
            else:
                block["mlp_in"] = dense_init(
                    k[2], d_model, mlp_ratio * d_model, dtype=dtype)
                block["mlp_out"] = dense_init(
                    k[3], mlp_ratio * d_model, d_model,
                    scale=0.02 / max(num_layers, 1), dtype=dtype)
            params["blocks"].append(block)
        if pp_mesh is not None:
            # Stacked layout (leading dim = stages) so stage i's slice lands
            # on pp-device i through the pipeline shard_map.
            params["blocks"] = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *params["blocks"])
        return params

    def block_apply(blk, x):
        """One pre-LN transformer block over (B, T, d) tokens.

        Returns ``(x, aux)`` — aux is the block's MoE load-balance loss
        (0.0 for dense-FFN blocks), surfaced so training can regularize the
        gate: with capacity dispatch (moe_top_k>0) an unbalanced gate
        overflows expert buffers and silently zeroes dropped tokens.
        """
        bsz, t = x.shape[0], x.shape[1]
        # Compute dtype follows the handed-in block params (masters or the
        # precision policy's bf16 copy), not the build-time closure.
        dtype = compute_dtype(blk)
        h = _layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = dense(blk["qkv"], h).reshape(bsz, t, 3, num_heads, head_dim)
        # attention expects (batch, heads, seq, head_dim)
        q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
        attn = attention_fn(q, k, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(
            bsz, t, d_model).astype(dtype)
        x = x + dense(blk["proj"], attn)
        h = _layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        y, aux = ffn_apply(
            blk, h, moe_experts=moe_experts, ep_mesh=ep_mesh,
            ep_axis=ep_axis, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor,
            moe_dispatch=moe_dispatch, batch_axis=pp_batch_axis)
        return x + y, aux

    def tokenize(obs):
        """(B, obs_dim) -> (B, seq, 3): per-asset blocks of shared tick
        features plus that asset's portfolio token (budget, its shares,
        its window anchor — the flag channel is the tick features' zero
        one). A=1 reproduces the single-asset layout exactly."""
        b = obs.shape[0]
        windows = obs[:, :num_assets * window].reshape(b, num_assets, window)
        budget = obs[:, num_assets * window]
        shares = obs[:, num_assets * window + 1:]                # (B, A)
        ticks = tick_window_features(
            windows.reshape(b * num_assets, window), window
        ).reshape(b, num_assets, window, 3)
        port = portfolio_features(
            jnp.broadcast_to(budget[:, None], shares.shape), shares,
            windows[:, :, -1])                                   # (B, A, 3)
        blocks = jnp.concatenate([ticks, port[:, :, None, :]], axis=2)
        return blocks.reshape(b, seq_len, 3)

    def apply_batch(params, obs, carry):
        """Native batched forward: the whole agent batch rides one flash
        kernel call per layer with a batch*heads grid — no batch-1 programs
        (the round-1 pathology: per-agent vmapped kernel invocations)."""
        bsz = obs.shape[0]
        tokens = tokenize(obs).astype(compute_dtype(params))
        pos = jnp.tile(params["pos"], (num_assets, 1))           # (seq, d)
        x = dense(params["embed"], tokens) + pos                 # (B, seq, d)
        if num_assets > 1:
            x = x + jnp.repeat(params["asset"], window + 1, axis=0)
        aux = jnp.float32(0.0)
        if pp_mesh is None:
            for blk in params["blocks"]:
                x, blk_aux = block_apply(blk, x)
                aux = aux + blk_aux
        else:
            from sharetrade_tpu.parallel.pipeline import pipeline_apply
            from jax.sharding import PartitionSpec as P
            # GPipe microbatches over the agent batch: M = stages when the
            # batch divides evenly (bubble (S-1)/(M+S-1)), else one batch.
            stages = num_layers
            m = stages if bsz % stages == 0 else 1
            mb = x.reshape((m, bsz // m) + x.shape[1:])
            b_axis = pp_batch_axis
            if b_axis is not None and (bsz // m) % pp_mesh.shape[b_axis]:
                b_axis = None   # odd batch (e.g. eval's batch-1): replicate
            # moe + pipeline_blocks is rejected at construction, so pipelined
            # stages never carry an aux term to drop.
            mb = pipeline_apply(
                lambda blk, t: block_apply(blk, t)[0], params["blocks"], mb,
                pp_mesh, axis=pp_axis, mb_spec=P(None, b_axis))
            x = mb.reshape((bsz,) + mb.shape[2:])
        # Summary = mean over the A portfolio tokens' outputs (A=1: the
        # final token, the original readout).
        port_idx = (jnp.arange(num_assets) + 1) * (window + 1) - 1
        summary = _layer_norm(jnp.mean(x[:, port_idx], axis=1),
                              params["final_ln"]["scale"],
                              params["final_ln"]["bias"])
        logits = dense(params["policy"], summary).astype(jnp.float32)
        value = dense(params["value"], summary).astype(jnp.float32)[:, 0]
        return ModelOut(logits=logits, value=value,
                        aux=aux / max(num_layers, 1)), carry

    def apply(params, obs, carry):
        outs, carry = apply_batch(params, obs[None], carry)
        return ModelOut(logits=outs.logits[0], value=outs.value[0],
                        aux=outs.aux), carry

    return Model(init=init, apply=apply, apply_batch=apply_batch,
                 obs_dim=obs_dim, num_actions=num_actions, name="transformer")
